// PyTorch custom ops backed by the native engine.
//
// Role parity: horovod/torch/mpi_ops_v2.cc — ops registered with the
// dispatcher whose kernels enqueue into the shared coordinator.  Loaded
// with torch.ops.load_library; horovod_tpu.torch routes its collectives
// through torch.ops.hvd.* when available (native engine + toolchain),
// keeping the numpy/ctypes path as the fallback.  Because these are
// dispatcher ops, torch.compile-traced graphs carry them as calls
// instead of graph breaks.

#include <cstring>
#include <string>

#include <torch/library.h>
#include <ATen/ATen.h>

#include "engine.h"

extern "C" void* hvd_engine_handle();

namespace {

bool MapDtype(at::ScalarType t, hvd::DataType* out) {
  switch (t) {
    case at::kFloat:
      *out = hvd::DataType::FLOAT32;
      return true;
    case at::kDouble:
      *out = hvd::DataType::FLOAT64;
      return true;
    case at::kHalf:
      *out = hvd::DataType::FLOAT16;
      return true;
    case at::kBFloat16:
      *out = hvd::DataType::BFLOAT16;
      return true;
    case at::kInt:
      *out = hvd::DataType::INT32;
      return true;
    case at::kLong:
      *out = hvd::DataType::INT64;
      return true;
    case at::kByte:
      *out = hvd::DataType::UINT8;
      return true;
    case at::kChar:
      *out = hvd::DataType::INT8;
      return true;
    case at::kBool:
      *out = hvd::DataType::BOOL;
      return true;
    default:
      return false;
  }
}

hvd::Engine* EngineOrThrow() {
  auto* eng = static_cast<hvd::Engine*>(hvd_engine_handle());
  TORCH_CHECK(eng != nullptr,
              "horovod_tpu native engine is not initialized");
  return eng;
}

hvd::TensorShape ShapeOf(const at::Tensor& t) {
  hvd::TensorShape s;
  for (auto d : t.sizes()) s.dims.push_back(d);
  if (s.dims.empty()) s.dims.push_back(1);  // 0-d lift, ctypes parity
  return s;
}

void WaitOrThrow(hvd::Engine* eng, int64_t h) {
  hvd::StatusType st = eng->handles().Wait(h);
  std::string reason;
  if (st != hvd::StatusType::OK) {
    auto* state = eng->handles().Get(h);
    reason = state != nullptr && !state->status.reason.empty()
                 ? state->status.reason
                 : "collective failed";
  }
  eng->handles().Release(h);
  TORCH_CHECK(reason.empty(), reason);
}

at::Tensor Allreduce(const at::Tensor& input, std::string tensor_name,
                     int64_t reduce_op, double prescale, double postscale,
                     int64_t ps_id, int64_t ps_size) {
  auto* eng = EngineOrThrow();
  at::Tensor out = input.is_contiguous() ? input.clone()
                                         : input.contiguous();
  hvd::DataType dt;
  TORCH_CHECK(MapDtype(out.scalar_type(), &dt),
              "unsupported dtype for engine allreduce");
  std::string err;
  int64_t h = eng->EnqueueAllreduce(
      tensor_name, out.data_ptr(), ShapeOf(out), dt,
      static_cast<hvd::ReduceOp>(reduce_op), prescale, postscale, &err,
      static_cast<int32_t>(ps_id), static_cast<int32_t>(ps_size));
  TORCH_CHECK(h >= 0, err);
  WaitOrThrow(eng, h);
  return out;
}

// In-place variant: reduces directly into the caller's tensor (parity:
// hvd.allreduce_ — mpi_ops_v2.cc's DoAllreduce writes the output in
// place).
at::Tensor& AllreduceInplace(at::Tensor& input, std::string tensor_name,
                             int64_t reduce_op, double prescale,
                             double postscale, int64_t ps_id,
                             int64_t ps_size) {
  auto* eng = EngineOrThrow();
  TORCH_CHECK(input.is_contiguous(),
              "in-place allreduce needs a contiguous tensor");
  hvd::DataType dt;
  TORCH_CHECK(MapDtype(input.scalar_type(), &dt),
              "unsupported dtype for engine allreduce");
  std::string err;
  int64_t h = eng->EnqueueAllreduce(
      tensor_name, input.data_ptr(), ShapeOf(input), dt,
      static_cast<hvd::ReduceOp>(reduce_op), prescale, postscale, &err,
      static_cast<int32_t>(ps_id), static_cast<int32_t>(ps_size));
  TORCH_CHECK(h >= 0, err);
  WaitOrThrow(eng, h);
  return input;
}

at::Tensor Broadcast(const at::Tensor& input, std::string tensor_name,
                     int64_t root_rank, int64_t ps_id, int64_t ps_size) {
  auto* eng = EngineOrThrow();
  at::Tensor out = input.is_contiguous() ? input.clone()
                                         : input.contiguous();
  hvd::DataType dt;
  TORCH_CHECK(MapDtype(out.scalar_type(), &dt),
              "unsupported dtype for engine broadcast");
  std::string err;
  int64_t h = eng->EnqueueBroadcast(
      tensor_name, out.data_ptr(), ShapeOf(out), dt,
      static_cast<int32_t>(root_rank), &err, static_cast<int32_t>(ps_id),
      static_cast<int32_t>(ps_size));
  TORCH_CHECK(h >= 0, err);
  WaitOrThrow(eng, h);
  return out;
}

at::Tensor Allgather(const at::Tensor& input, std::string tensor_name,
                     int64_t ps_id, int64_t ps_size) {
  auto* eng = EngineOrThrow();
  at::Tensor in = input.contiguous();
  hvd::DataType dt;
  TORCH_CHECK(MapDtype(in.scalar_type(), &dt),
              "unsupported dtype for engine allgather");
  std::string err;
  int64_t h = eng->EnqueueAllgather(
      tensor_name, in.data_ptr(), ShapeOf(in), dt, &err,
      static_cast<int32_t>(ps_id), static_cast<int32_t>(ps_size));
  TORCH_CHECK(h >= 0, err);
  hvd::StatusType st = eng->handles().Wait(h);
  auto* state = eng->handles().Get(h);
  if (st != hvd::StatusType::OK || state == nullptr) {
    std::string reason = state != nullptr && !state->status.reason.empty()
                             ? state->status.reason
                             : "allgather failed";
    eng->handles().Release(h);
    TORCH_CHECK(false, reason);
  }
  // Negotiated first-dim size: rows derive from dims[1:] (zero-row
  // contributions included).
  int64_t row = 1;
  for (size_t i = 1; i < in.sizes().size(); ++i) row *= in.size(i);
  int64_t elem = in.element_size();
  int64_t total_rows =
      elem > 0 && row > 0
          ? static_cast<int64_t>(state->result.size()) / (elem * row)
          : 0;
  std::vector<int64_t> shape(in.sizes().begin(), in.sizes().end());
  if (shape.empty()) shape.push_back(1);
  shape[0] = total_rows;
  at::Tensor out = at::empty(shape, in.options());
  std::memcpy(out.data_ptr(), state->result.data(), state->result.size());
  eng->handles().Release(h);
  return out;
}

}  // namespace

TORCH_LIBRARY(hvd, m) {
  m.def(
      "allreduce(Tensor input, str tensor_name, int reduce_op, "
      "float prescale, float postscale, int ps_id, int ps_size) "
      "-> Tensor");
  m.def(
      "allreduce_(Tensor(a!) input, str tensor_name, int reduce_op, "
      "float prescale, float postscale, int ps_id, int ps_size) "
      "-> Tensor(a!)");
  m.def(
      "broadcast(Tensor input, str tensor_name, int root_rank, "
      "int ps_id, int ps_size) -> Tensor");
  m.def(
      "allgather(Tensor input, str tensor_name, int ps_id, "
      "int ps_size) -> Tensor");
}

TORCH_LIBRARY_IMPL(hvd, CPU, m) {
  m.impl("allreduce", Allreduce);
  m.impl("allreduce_", AllreduceInplace);
  m.impl("broadcast", Broadcast);
  m.impl("allgather", Allgather);
}
