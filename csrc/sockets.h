// Framed TCP helpers for the controller and CPU data plane.
//
// Frame = u8 tag, u32 LE length, payload — the format defined in
// horovod_tpu/utils/socketutil.py.  The data plane needs full-duplex
// exchange (ring steps send and receive simultaneously); Exchange() runs a
// poll() loop over the two directions so large messages cannot deadlock on
// kernel socket buffers (the Python engine uses a sender thread for the
// same reason, cpu_backend.py:41-46).
#pragma once

#include <cstddef>
#include <cstdint>
#include <stdexcept>
#include <string>
#include <vector>

namespace hvd {

constexpr uint8_t kTagRequestList = 1;
constexpr uint8_t kTagResponseList = 2;
constexpr uint8_t kTagData = 3;
// Tags 4-9 are reserved by the Python engine's control-plane
// extensions (KV tunneling, heartbeats, and the collective-abort
// agreement: abort-report / probe / probe-ack / abort-verdict — see
// horovod_tpu/utils/socketutil.py and common/wire.py).  The native
// engine never sends or expects them; do not reuse the numbers.

struct SocketError : std::runtime_error {
  explicit SocketError(const std::string& what) : std::runtime_error(what) {}
};

// Blocking send of one frame (loops over partial writes / EINTR).
void SendFrame(int fd, uint8_t tag, const void* payload, size_t len);

// Blocking receive of one full frame; returns tag, fills payload.
uint8_t RecvFrame(int fd, std::vector<uint8_t>* payload);

// True if a full read() on fd would not block right now (data available).
bool Readable(int fd, int timeout_ms);

// Full-duplex: send one kTagData frame to send_fd while receiving one
// kTagData frame from recv_fd.  Either fd may be -1 to skip a direction;
// both may be the same fd (pairwise partner exchange).
void Exchange(int send_fd, const void* sbuf, size_t slen, int recv_fd,
              std::vector<uint8_t>* rbuf);

// Like Exchange, but receives directly into a caller buffer of exactly
// rlen bytes; throws if the incoming frame length differs.
void ExchangeInto(int send_fd, const void* sbuf, size_t slen, int recv_fd,
                  void* rbuf, size_t rlen);

// Concurrent send of the same kTagData frame to many peers (broadcast
// root).  Poll-driven round-robin, so no per-peer thread is needed.
void MultiSend(const std::vector<int>& fds, const void* buf, size_t len);

void SetNoDelay(int fd);

}  // namespace hvd
