// Autotuning of coordination-loop knobs: GP regression + expected
// improvement over (fusion threshold, cycle time, cache on/off), scored
// by allreduced bytes/sec.
//
// Role parity: horovod/common/parameter_manager.cc/.h +
// optim/bayesian_optimization.cc + optim/gaussian_process.cc (there:
// Eigen + L-BFGS; here: hand-rolled Cholesky + candidate sweep — sample
// counts are tens, dimensions ≤ 3).  The Python twin
// (horovod_tpu/autotune/) is the executable spec; only rank 0 runs the
// tuner, so the two implementations never need bit-identical decisions.
#pragma once

#include <cstdint>
#include <random>
#include <string>
#include <vector>

namespace hvd {

// GP posterior over f: [0,1]^d -> R, RBF kernel.  The length-scale is
// selected per Fit by maximum marginal likelihood over a log grid
// (parity: the reference's L-BFGS MLE, gaussian_process.cc:44+; twin of
// the python engine's autotune/gaussian_process.py) — pass a positive
// length_scale to pin it instead.
class GaussianProcess {
 public:
  GaussianProcess(double length_scale = 0.0, double signal_variance = 1.0,
                  double noise_variance = 1e-4)
      : fit_ls_(length_scale <= 0.0),
        ls_(length_scale <= 0.0 ? 0.25 : length_scale),
        sv_(signal_variance), nv_(noise_variance) {}

  void Fit(const std::vector<std::vector<double>>& x,
           const std::vector<double>& y);
  // Posterior mean and stddev at one point (de-standardized).
  void Predict(const std::vector<double>& x, double* mean,
               double* stddev) const;
  // Scale of the standardized targets (1.0 before the first Fit).
  double y_std() const { return y_std_; }

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;
  // Cholesky + weights for the current ls_; returns the log marginal
  // likelihood (GPML eq. 2.30).  Always finite: a near-non-PD kernel is
  // clamped (diagonal floored at 1e-12), which naturally scores badly
  // against better-conditioned candidates rather than needing a
  // sentinel.
  double Factor(const std::vector<std::vector<double>>& x,
                const std::vector<double>& yn);

  bool fit_ls_;
  double ls_, sv_, nv_;
  std::vector<std::vector<double>> x_;
  std::vector<double> alpha_;
  std::vector<double> chol_;  // lower triangular, row-major n×n
  double y_mean_ = 0, y_std_ = 1;
};

class BayesianOptimization {
 public:
  explicit BayesianOptimization(int dim, double xi = 0.01,
                                uint32_t seed = 0, int n_candidates = 512)
      : dim_(dim), xi_(xi), rng_(seed), n_candidates_(n_candidates) {}

  void AddSample(const std::vector<double>& x, double y);
  std::vector<double> Best() const;
  std::vector<double> NextSample();
  double ExpectedImprovement(const std::vector<double>& x) const;
  bool empty() const { return ys_.empty(); }

 private:
  int dim_;
  double xi_;
  std::mt19937 rng_;
  int n_candidates_;
  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;
  GaussianProcess gp_;
};

struct TunedParams {
  int64_t fusion_threshold = 64 << 20;
  double cycle_time_s = 0.005;
  bool cache_enabled = true;
  bool hierarchical_allreduce = false;
  bool hierarchical_allgather = false;
  int64_t ring_segment_bytes = 0;
};

// Rank-0 tuner: feed allreduced bytes, get knob updates to broadcast.
class ParameterManager {
 public:
  struct Options {
    bool tune_fusion = true;
    bool tune_cycle = true;
    bool tune_cache = true;
    // Only meaningful on hierarchical topologies; the engine gates these
    // on local_size>1 && cross_size>1 before constructing the manager.
    bool tune_hier_allreduce = false;
    bool tune_hier_allgather = false;
    int warmup_samples = 3;
    int max_samples = 20;
    double sample_duration_s = 0.5;
    std::string log_path;
  };

  ParameterManager(const TunedParams& initial, const Options& opts);
  ~ParameterManager();

  // Returns true when *out holds new params to apply + broadcast.
  bool RecordBytes(int64_t nbytes, double now_s, TunedParams* out);
  bool done() const { return done_; }
  const TunedParams& current() const { return current_; }

 private:
  std::vector<double> ParamsToX(const TunedParams& p) const;
  TunedParams XToParams(const std::vector<double>& x) const;
  void Log(int sample, double score);

  TunedParams current_;
  Options opts_;
  std::vector<std::string> dims_;
  BayesianOptimization bo_;
  std::vector<double> current_x_;
  int warmup_left_;
  int samples_ = 0;
  int64_t bytes_ = 0;
  double sample_start_s_ = -1;
  bool done_ = false;
  void* log_file_ = nullptr;  // FILE*
};

}  // namespace hvd
