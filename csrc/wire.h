// Binary wire codec for controller messages.
//
// Role parity: horovod/common/wire/message.fbs + message.cc (the reference
// uses FlatBuffers).  The layout here is the hand-rolled little-endian
// encoding specified in horovod_tpu/common/wire.py — THE TWO MUST MATCH;
// both engines speak this format on the same sockets.
//
// The Python engine additionally defines collective-abort agreement
// payloads (AbortReport / ProbeAck / AbortVerdict, wire.py) carried on
// reserved control tags 6-9 (sockets.h), and the serving admission
// broadcast (ServeDelta, wire.py) on reserved tag 10.  They have no C++
// mirror: the native engine ignores HVD_COLLECTIVE_TIMEOUT and never
// hosts horovod_tpu.serving — both only take effect on PyEngine gangs
// (runtime_py.py).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "types.h"

namespace hvd {

// What one rank wants to do with one named tensor.
// Parity: message.h:47-100 + prescale/postscale from the torch v2 path.
struct Request {
  int32_t request_rank = 0;
  RequestType request_type = RequestType::ALLREDUCE;
  DataType tensor_type = DataType::FLOAT32;
  std::string tensor_name;
  int32_t root_rank = -1;
  std::string device = "cpu";
  TensorShape tensor_shape;
  ReduceOp reduce_op = ReduceOp::SUM;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  // Process-set scoping (0 = the global set); set_size lets the
  // coordinator wait for exactly the members.
  int32_t process_set_id = 0;
  int32_t process_set_size = 0;
};

// What every rank must now execute, in identical order.
// Parity: message.h:132-192.
struct Response {
  ResponseType response_type = ResponseType::ERROR;
  std::vector<std::string> tensor_names;
  std::string error_message;
  std::vector<std::string> devices;
  DataType tensor_type = DataType::FLOAT32;
  std::vector<int64_t> tensor_sizes;
  // Allreduce execution parameters negotiated from the requests; fusion
  // only merges responses where these match.
  ReduceOp reduce_op = ReduceOp::SUM;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  // For allreduce: the exact negotiated dims of each fused tensor, one
  // per tensor_names entry — authoritative on every rank, which keeps
  // response-cache parameters coherent (see engine.h ResponseCache).
  std::vector<TensorShape> tensor_shapes;
  // Process-set scoping: non-members skip the response entirely.
  int32_t process_set_id = 0;
};

// A response-cache hit event: this rank is ready to re-run the cached
// response for `name` at cache position `position` (see
// horovod_tpu/common/response_cache.py for the protocol).
struct CacheHit {
  std::string name;
  uint32_t position = 0;
};

// List frames end with an optional u32 `epoch` trailer — the elastic
// membership epoch (horovod_tpu/elastic; 0 when absent).  The native
// engine only ever runs at epoch 0 (elastic requires the Python engine;
// Engine raises before construction otherwise) but both codecs carry
// the trailer so the layout spec in horovod_tpu/common/wire.py and this
// header stay in lockstep.
std::vector<uint8_t> EncodeRequestList(const std::vector<Request>& reqs,
                                       bool shutdown,
                                       const std::vector<CacheHit>& hits = {},
                                       uint32_t epoch = 0);
// Returns false on malformed input.  `epoch` (optional out) receives
// the trailer, 0 when the frame predates it.
bool DecodeRequestList(const uint8_t* data, size_t len,
                       std::vector<Request>* out, bool* shutdown,
                       std::vector<CacheHit>* hits,
                       uint32_t* epoch = nullptr);

// Autotuner knob broadcast riding the response stream (parity: rank-0
// Params bcast, parameter_manager.cc via controller.cc:33-47).
struct WireParams {
  bool present = false;
  int64_t fusion_threshold = 0;
  double cycle_time_s = 0;
  bool cache_enabled = true;
  bool hierarchical_allreduce = false;
  bool hierarchical_allgather = false;
  int64_t ring_segment_bytes = 0;
};

std::vector<uint8_t> EncodeResponseList(
    const std::vector<Response>& resps, bool shutdown,
    const std::vector<uint32_t>& hit_positions = {},
    const std::vector<std::string>& resend_names = {},
    const WireParams& params = {}, uint32_t epoch = 0);
bool DecodeResponseList(const uint8_t* data, size_t len,
                        std::vector<Response>* out, bool* shutdown,
                        std::vector<uint32_t>* hit_positions,
                        std::vector<std::string>* resend_names,
                        WireParams* params, uint32_t* epoch = nullptr);

// -- recovery-ladder framing (HVD_WIRE_CRC=1; wire.py mirror) ----------
//
// Control tags 11-13 are reserved by the Python engine's data-plane
// recovery ladder (utils/ladder.py): kTagNack = 11 (u32 expected_seq),
// kTagResume = 12 and kTagFailover = 13 (i32 rank, u32 expected_seq,
// u32 epoch).  On a CRC-armed link every kTagData frame additionally
// ends with an 8-byte trailer INSIDE the frame payload:
//
//   DataTrailer := u32 seq, u32 crc
//   crc = CRC-32 (reflected polynomial 0xEDB88320, the zlib/IEEE one)
//         over the payload bytes, then over the 4 LE seq bytes.
//
// The native engine does not implement the ladder yet; it MUST NOT join
// a gang running HVD_WIRE_CRC=1 (the knob is rejected at Engine
// construction, like HVD_COLLECTIVE_TIMEOUT is ignored).  WireCrc32 is
// provided so the future native path validates identically to
// wire.py's data_crc().
constexpr uint8_t kTagNack = 11;
constexpr uint8_t kTagResume = 12;
constexpr uint8_t kTagFailover = 13;
constexpr size_t kDataTrailerBytes = 8;

// Control tags 14-15 are reserved by the Python engine's gang-wide
// tracing clock sync (HVD_TRACE=1; telemetry/trace.py):
// kTagClockPing = 14 (i64 t0_ns, u32 epoch), kTagClockPong = 15
// (i64 t0_ns, i64 t_coord_ns, u32 epoch).  Like the abort tags, these
// frames never reach a native engine — a traced gang must be
// all-Python (docs/timeline.md "Gang-wide tracing").
constexpr uint8_t kTagClockPing = 14;
constexpr uint8_t kTagClockPong = 15;

// Control tags 16-17 are reserved by the Python engine's always-on
// flight recorder (telemetry/blackbox.py): kTagBlackbox = 16 (u32
// epoch; coordinator asks a live worker for its ring) and
// kTagBlackboxDump = 17 (i32 rank, u32 epoch, u32 len, len bytes of
// UTF-8 JSON — the same document blackbox_rank<r>.json would hold).
// Like the abort tags these frames never reach a native engine; the
// coordinator simply gets no dump from one.
constexpr uint8_t kTagBlackbox = 16;
constexpr uint8_t kTagBlackboxDump = 17;

// Control tags 18-21 are reserved by the Python engine's hierarchical
// control tree and epoch fencing (runtime_py.py; docs/fault_tolerance.md
// "Hierarchical control plane, fencing, and quorum"):
// kTagTreeUp = 18 (u32 epoch, u32 n, { i32 rank, u8 tag, u32 len,
// bytes }[n] — a per-host sub-coordinator's aggregate of its children's
// control frames), kTagTreeDown = 19 (i32 target_rank, u8 tag, u32 len,
// bytes — a root frame routed through the sub-coordinator; -1 fans out
// to every child), kTagReparent = 20 (i32 rank, i32 old_parent,
// u32 epoch — an orphaned child adopting itself back to the root), and
// kTagFence = 21 (u32 stale_epoch, u32 current_epoch — typed rejection
// of a stale-epoch sender).  A native engine never joins a tree: a
// multi-host Python gang only builds one among Python ranks, so like
// the abort tags these frames never reach this decoder.
constexpr uint8_t kTagTreeUp = 18;
constexpr uint8_t kTagTreeDown = 19;
constexpr uint8_t kTagReparent = 20;
constexpr uint8_t kTagFence = 21;

// CRC-32 (zlib polynomial), seed 0 — matches Python's zlib.crc32.
uint32_t WireCrc32(const uint8_t* data, size_t len, uint32_t crc = 0);

// crc-over-payload-then-seq, exactly wire.py data_crc().
uint32_t DataCrc(const uint8_t* payload, size_t len, uint32_t seq);

}  // namespace hvd
