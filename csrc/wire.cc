#include "wire.h"

#include <cstring>

namespace hvd {
namespace {

// Little-endian primitive writers.  x86/ARM targets are all LE; we still
// write bytewise so the codec is endian-agnostic.
void PutU8(std::vector<uint8_t>& b, uint8_t v) { b.push_back(v); }

void PutU32(std::vector<uint8_t>& b, uint32_t v) {
  for (int i = 0; i < 4; ++i) b.push_back((v >> (8 * i)) & 0xff);
}

void PutI32(std::vector<uint8_t>& b, int32_t v) {
  PutU32(b, static_cast<uint32_t>(v));
}

void PutI64(std::vector<uint8_t>& b, int64_t v) {
  auto u = static_cast<uint64_t>(v);
  for (int i = 0; i < 8; ++i) b.push_back((u >> (8 * i)) & 0xff);
}

void PutF64(std::vector<uint8_t>& b, double v) {
  uint64_t u;
  std::memcpy(&u, &v, 8);
  for (int i = 0; i < 8; ++i) b.push_back((u >> (8 * i)) & 0xff);
}

void PutStr(std::vector<uint8_t>& b, const std::string& s) {
  PutU32(b, static_cast<uint32_t>(s.size()));
  b.insert(b.end(), s.begin(), s.end());
}

struct Reader {
  const uint8_t* data;
  size_t len;
  size_t off = 0;
  bool fail = false;

  bool Need(size_t n) {
    if (off + n > len) {
      fail = true;
      return false;
    }
    return true;
  }
  uint8_t U8() {
    if (!Need(1)) return 0;
    return data[off++];
  }
  uint32_t U32() {
    if (!Need(4)) return 0;
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v |= uint32_t(data[off + i]) << (8 * i);
    off += 4;
    return v;
  }
  int32_t I32() { return static_cast<int32_t>(U32()); }
  int64_t I64() {
    if (!Need(8)) return 0;
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v |= uint64_t(data[off + i]) << (8 * i);
    off += 8;
    return static_cast<int64_t>(v);
  }
  double F64() {
    uint64_t u = static_cast<uint64_t>(I64());
    double v;
    std::memcpy(&v, &u, 8);
    return v;
  }
  std::string Str() {
    uint32_t n = U32();
    if (!Need(n)) return "";
    std::string s(reinterpret_cast<const char*>(data + off), n);
    off += n;
    return s;
  }
};

void EncodeRequest(const Request& r, std::vector<uint8_t>& b) {
  PutU8(b, static_cast<uint8_t>(r.request_type));
  PutI32(b, r.request_rank);
  PutU8(b, static_cast<uint8_t>(r.tensor_type));
  PutStr(b, r.tensor_name);
  PutI32(b, r.root_rank);
  PutStr(b, r.device);
  PutU8(b, static_cast<uint8_t>(r.reduce_op));
  PutF64(b, r.prescale_factor);
  PutF64(b, r.postscale_factor);
  PutU8(b, static_cast<uint8_t>(r.tensor_shape.dims.size()));
  for (auto d : r.tensor_shape.dims) PutI64(b, d);
  PutI32(b, r.process_set_id);
  PutI32(b, r.process_set_size);
}

Request DecodeRequest(Reader& rd) {
  Request r;
  r.request_type = static_cast<RequestType>(rd.U8());
  r.request_rank = rd.I32();
  r.tensor_type = static_cast<DataType>(rd.U8());
  r.tensor_name = rd.Str();
  r.root_rank = rd.I32();
  r.device = rd.Str();
  r.reduce_op = static_cast<ReduceOp>(rd.U8());
  r.prescale_factor = rd.F64();
  r.postscale_factor = rd.F64();
  uint8_t ndim = rd.U8();
  for (uint8_t i = 0; i < ndim; ++i) r.tensor_shape.dims.push_back(rd.I64());
  r.process_set_id = rd.I32();
  r.process_set_size = rd.I32();
  return r;
}

void EncodeResponse(const Response& r, std::vector<uint8_t>& b) {
  PutU8(b, static_cast<uint8_t>(r.response_type));
  PutU8(b, static_cast<uint8_t>(r.tensor_type));
  PutU32(b, static_cast<uint32_t>(r.tensor_names.size()));
  for (auto& nm : r.tensor_names) PutStr(b, nm);
  PutStr(b, r.error_message);
  PutU32(b, static_cast<uint32_t>(r.devices.size()));
  for (auto& d : r.devices) PutStr(b, d);
  PutU32(b, static_cast<uint32_t>(r.tensor_sizes.size()));
  for (auto s : r.tensor_sizes) PutI64(b, s);
  PutU8(b, static_cast<uint8_t>(r.reduce_op));
  PutF64(b, r.prescale_factor);
  PutF64(b, r.postscale_factor);
  PutU32(b, static_cast<uint32_t>(r.tensor_shapes.size()));
  for (auto& s : r.tensor_shapes) {
    PutU8(b, static_cast<uint8_t>(s.dims.size()));
    for (auto d : s.dims) PutI64(b, d);
  }
  PutI32(b, r.process_set_id);
}

Response DecodeResponse(Reader& rd) {
  Response r;
  r.response_type = static_cast<ResponseType>(rd.U8());
  r.tensor_type = static_cast<DataType>(rd.U8());
  uint32_t n_names = rd.U32();
  for (uint32_t i = 0; i < n_names && !rd.fail; ++i)
    r.tensor_names.push_back(rd.Str());
  r.error_message = rd.Str();
  uint32_t n_dev = rd.U32();
  for (uint32_t i = 0; i < n_dev && !rd.fail; ++i)
    r.devices.push_back(rd.Str());
  uint32_t n_sizes = rd.U32();
  for (uint32_t i = 0; i < n_sizes && !rd.fail; ++i)
    r.tensor_sizes.push_back(rd.I64());
  r.reduce_op = static_cast<ReduceOp>(rd.U8());
  r.prescale_factor = rd.F64();
  r.postscale_factor = rd.F64();
  uint32_t n_shapes = rd.U32();
  for (uint32_t i = 0; i < n_shapes && !rd.fail; ++i) {
    TensorShape s;
    uint8_t ndim = rd.U8();
    for (uint8_t j = 0; j < ndim; ++j) s.dims.push_back(rd.I64());
    r.tensor_shapes.push_back(std::move(s));
  }
  r.process_set_id = rd.I32();
  return r;
}

}  // namespace

std::vector<uint8_t> EncodeRequestList(const std::vector<Request>& reqs,
                                       bool shutdown,
                                       const std::vector<CacheHit>& hits,
                                       uint32_t epoch) {
  std::vector<uint8_t> b;
  PutU8(b, shutdown ? 1 : 0);
  PutU32(b, static_cast<uint32_t>(reqs.size()));
  for (auto& r : reqs) EncodeRequest(r, b);
  PutU32(b, static_cast<uint32_t>(hits.size()));
  for (auto& h : hits) {
    PutStr(b, h.name);
    PutU32(b, h.position);
  }
  PutU32(b, epoch);
  return b;
}

bool DecodeRequestList(const uint8_t* data, size_t len,
                       std::vector<Request>* out, bool* shutdown,
                       std::vector<CacheHit>* hits, uint32_t* epoch) {
  Reader rd{data, len};
  *shutdown = rd.U8() != 0;
  uint32_t n = rd.U32();
  for (uint32_t i = 0; i < n && !rd.fail; ++i)
    out->push_back(DecodeRequest(rd));
  uint32_t n_hits = rd.U32();
  for (uint32_t i = 0; i < n_hits && !rd.fail; ++i) {
    CacheHit h;
    h.name = rd.Str();
    h.position = rd.U32();
    hits->push_back(std::move(h));
  }
  // Optional epoch trailer (0 on frames that predate it).
  uint32_t e = (!rd.fail && rd.off + 4 <= rd.len) ? rd.U32() : 0;
  if (epoch) *epoch = e;
  return !rd.fail;
}

std::vector<uint8_t> EncodeResponseList(
    const std::vector<Response>& resps, bool shutdown,
    const std::vector<uint32_t>& hit_positions,
    const std::vector<std::string>& resend_names, const WireParams& params,
    uint32_t epoch) {
  std::vector<uint8_t> b;
  PutU8(b, shutdown ? 1 : 0);
  PutU32(b, static_cast<uint32_t>(resps.size()));
  for (auto& r : resps) EncodeResponse(r, b);
  PutU32(b, static_cast<uint32_t>(hit_positions.size()));
  for (auto p : hit_positions) PutU32(b, p);
  PutU32(b, static_cast<uint32_t>(resend_names.size()));
  for (auto& nm : resend_names) PutStr(b, nm);
  PutU8(b, params.present ? 1 : 0);
  if (params.present) {
    PutI64(b, params.fusion_threshold);
    PutF64(b, params.cycle_time_s);
    PutU8(b, params.cache_enabled ? 1 : 0);
    PutU8(b, params.hierarchical_allreduce ? 1 : 0);
    PutU8(b, params.hierarchical_allgather ? 1 : 0);
    PutI64(b, params.ring_segment_bytes);
  }
  PutU32(b, epoch);
  return b;
}

bool DecodeResponseList(const uint8_t* data, size_t len,
                        std::vector<Response>* out, bool* shutdown,
                        std::vector<uint32_t>* hit_positions,
                        std::vector<std::string>* resend_names,
                        WireParams* params, uint32_t* epoch) {
  Reader rd{data, len};
  *shutdown = rd.U8() != 0;
  uint32_t n = rd.U32();
  for (uint32_t i = 0; i < n && !rd.fail; ++i)
    out->push_back(DecodeResponse(rd));
  uint32_t n_hits = rd.U32();
  for (uint32_t i = 0; i < n_hits && !rd.fail; ++i)
    hit_positions->push_back(rd.U32());
  uint32_t n_resend = rd.U32();
  for (uint32_t i = 0; i < n_resend && !rd.fail; ++i)
    resend_names->push_back(rd.Str());
  params->present = rd.U8() != 0;
  if (params->present) {
    params->fusion_threshold = rd.I64();
    params->cycle_time_s = rd.F64();
    params->cache_enabled = rd.U8() != 0;
    params->hierarchical_allreduce = rd.U8() != 0;
    params->hierarchical_allgather = rd.U8() != 0;
    params->ring_segment_bytes = rd.I64();
  }
  // Optional epoch trailer (0 on frames that predate it).
  uint32_t e = (!rd.fail && rd.off + 4 <= rd.len) ? rd.U32() : 0;
  if (epoch) *epoch = e;
  return !rd.fail;
}

// -- recovery-ladder framing (HVD_WIRE_CRC=1; see wire.h) --------------

namespace {

// Table-driven CRC-32, reflected polynomial 0xEDB88320 (the zlib/IEEE
// CRC) — must produce exactly Python's zlib.crc32 for the same bytes.
const uint32_t* Crc32Table() {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
      table[i] = c;
    }
    init = true;
  }
  return table;
}

}  // namespace

uint32_t WireCrc32(const uint8_t* data, size_t len, uint32_t crc) {
  const uint32_t* table = Crc32Table();
  crc = ~crc;
  for (size_t i = 0; i < len; ++i)
    crc = table[(crc ^ data[i]) & 0xff] ^ (crc >> 8);
  return ~crc;
}

uint32_t DataCrc(const uint8_t* payload, size_t len, uint32_t seq) {
  uint32_t crc = WireCrc32(payload, len, 0);
  uint8_t s[4] = {static_cast<uint8_t>(seq & 0xff),
                  static_cast<uint8_t>((seq >> 8) & 0xff),
                  static_cast<uint8_t>((seq >> 16) & 0xff),
                  static_cast<uint8_t>((seq >> 24) & 0xff)};
  return WireCrc32(s, 4, crc);
}

}  // namespace hvd
