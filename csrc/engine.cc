#include "engine.h"

#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>

#include "kernels.h"
#include "sockets.h"

namespace hvd {
namespace {

double NowS() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int64_t Mod(int64_t a, int64_t m) { return ((a % m) + m) % m; }

// NCCL-style near-equal chunking (parity: cpu_backend._chunk_bounds).
std::vector<int64_t> ChunkBounds(int64_t n, int parts) {
  int64_t base = n / parts, rem = n % parts;
  std::vector<int64_t> bounds{0};
  for (int i = 0; i < parts; ++i)
    bounds.push_back(bounds.back() + base + (i < rem ? 1 : 0));
  return bounds;
}

const char* OpName(RequestType t) {
  switch (t) {
    case RequestType::ALLREDUCE: return "ALLREDUCE";
    case RequestType::ALLGATHER: return "ALLGATHER";
    case RequestType::BROADCAST: return "BROADCAST";
    case RequestType::JOIN: return "JOIN";
    case RequestType::ALLTOALL: return "ALLTOALL";
    case RequestType::BARRIER: return "BARRIER";
    case RequestType::REDUCESCATTER: return "REDUCESCATTER";
  }
  return "?";
}

const char* DtypeName(DataType t) {
  switch (t) {
    case DataType::UINT8: return "UINT8";
    case DataType::INT8: return "INT8";
    case DataType::UINT16: return "UINT16";
    case DataType::INT16: return "INT16";
    case DataType::INT32: return "INT32";
    case DataType::INT64: return "INT64";
    case DataType::FLOAT16: return "FLOAT16";
    case DataType::FLOAT32: return "FLOAT32";
    case DataType::FLOAT64: return "FLOAT64";
    case DataType::BOOL: return "BOOL";
    case DataType::BFLOAT16: return "BFLOAT16";
    case DataType::FLOAT8_E4M3: return "FLOAT8_E4M3";
    case DataType::FLOAT8_E5M2: return "FLOAT8_E5M2";
  }
  return "?";
}

}  // namespace

// ---------------------------------------------------------------------------
// HandleManager
// ---------------------------------------------------------------------------

int64_t HandleManager::Allocate() {
  std::lock_guard<std::mutex> lk(mu_);
  int64_t h = next_++;
  states_[h];  // default-construct pending state
  return h;
}

void HandleManager::MarkDone(int64_t h, Status status,
                             std::vector<uint8_t> result,
                             std::vector<int64_t> splits) {
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = states_.find(h);
    if (it == states_.end()) return;
    it->second.done = true;
    it->second.status = std::move(status);
    it->second.result = std::move(result);
    it->second.recv_splits = std::move(splits);
  }
  cv_.notify_all();
}

int HandleManager::Poll(int64_t h) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = states_.find(h);
  if (it == states_.end()) return -1;
  return it->second.done ? 1 : 0;
}

StatusType HandleManager::Wait(int64_t h) {
  std::unique_lock<std::mutex> lk(mu_);
  auto it = states_.find(h);
  if (it == states_.end()) return StatusType::INVALID_ARGUMENT;
  cv_.wait(lk, [&] { return states_[h].done; });
  return states_[h].status.type;
}

HandleState* HandleManager::Get(int64_t h) {
  std::lock_guard<std::mutex> lk(mu_);
  auto it = states_.find(h);
  return it == states_.end() ? nullptr : &it->second;
}

void HandleManager::Release(int64_t h) {
  std::lock_guard<std::mutex> lk(mu_);
  states_.erase(h);
}

// ---------------------------------------------------------------------------
// ResponseCache
// ---------------------------------------------------------------------------

bool ResponseCache::SameParams(const Request& a, const Request& b) {
  return a.tensor_type == b.tensor_type &&
         a.tensor_shape.dims == b.tensor_shape.dims &&
         a.reduce_op == b.reduce_op &&
         a.prescale_factor == b.prescale_factor &&
         a.postscale_factor == b.postscale_factor && a.device == b.device;
}

ResponseCache::State ResponseCache::Classify(const Request& req,
                                             uint32_t* position) {
  *position = 0;
  // Process-set ops bypass the cache (positions must stay coherent on
  // EVERY rank; non-members never see the set's traffic).
  if (!enabled() || req.request_type != RequestType::ALLREDUCE ||
      req.process_set_id)
    return MISS;
  auto it = by_name_.find(req.tensor_name);
  if (it == by_name_.end()) {
    ++misses;
    return MISS;
  }
  if (!SameParams(it->second.params, req)) {
    *position = it->second.position;
    return INVALID;
  }
  ++hits;
  *position = it->second.position;
  return HIT;
}

const Response* ResponseCache::GetByPosition(uint32_t pos) const {
  auto it = by_pos_.find(pos);
  return it == by_pos_.end() ? nullptr : &it->second->response;
}

const std::string* ResponseCache::NameAt(uint32_t pos) const {
  auto it = by_pos_.find(pos);
  return it == by_pos_.end() ? nullptr : &it->second->name;
}

bool ResponseCache::SynthesizeRequest(uint32_t pos, int rank,
                                      Request* out) const {
  auto it = by_pos_.find(pos);
  if (it == by_pos_.end()) return false;
  *out = it->second->params;
  out->request_rank = rank;
  return true;
}

void ResponseCache::Touch(uint32_t pos) {
  auto it = by_pos_.find(pos);
  if (it == by_pos_.end()) return;
  lru_.splice(lru_.end(), lru_, it->second->lru_it);
}

int64_t ResponseCache::PositionOf(const std::string& name) const {
  auto it = by_name_.find(name);
  return it == by_name_.end() ? -1 : static_cast<int64_t>(it->second.position);
}

void ResponseCache::Put(const Response& resp) {
  if (!enabled() || resp.response_type != ResponseType::ALLREDUCE ||
      !resp.error_message.empty() || resp.process_set_id)
    return;
  bool have_shapes = resp.tensor_shapes.size() == resp.tensor_names.size();
  for (size_t i = 0; i < resp.tensor_names.size(); ++i) {
    const auto& name = resp.tensor_names[i];
    TensorShape shape;
    if (have_shapes)
      shape = resp.tensor_shapes[i];
    else
      shape.dims = {resp.tensor_sizes[i]};

    Response single;
    single.response_type = ResponseType::ALLREDUCE;
    single.tensor_type = resp.tensor_type;
    single.tensor_names = {name};
    single.devices = resp.devices;
    single.tensor_sizes = {resp.tensor_sizes[i]};
    single.reduce_op = resp.reduce_op;
    single.prescale_factor = resp.prescale_factor;
    single.postscale_factor = resp.postscale_factor;
    single.tensor_shapes = {shape};

    Request params;
    params.request_type = RequestType::ALLREDUCE;
    params.tensor_type = resp.tensor_type;
    params.tensor_name = name;
    params.device = resp.devices.empty() ? "cpu" : resp.devices[0];
    params.reduce_op = resp.reduce_op;
    params.prescale_factor = resp.prescale_factor;
    params.postscale_factor = resp.postscale_factor;
    params.tensor_shape = std::move(shape);
    PutOne(name, std::move(single), std::move(params));
  }
}

void ResponseCache::PutOne(const std::string& name, Response resp,
                           Request params) {
  auto it = by_name_.find(name);
  if (it != by_name_.end()) {
    // In-place update keeps the position stable (shape changes re-cache
    // under the same position).
    it->second.response = std::move(resp);
    it->second.params = std::move(params);
    lru_.splice(lru_.end(), lru_, it->second.lru_it);
    return;
  }
  if (static_cast<int64_t>(by_name_.size()) >= capacity_) {
    const std::string victim = lru_.front();
    lru_.pop_front();
    auto vit = by_name_.find(victim);
    if (vit != by_name_.end()) {
      free_positions_.push_back(vit->second.position);
      by_pos_.erase(vit->second.position);
      by_name_.erase(vit);
    }
    ++evictions;
  }
  uint32_t pos;
  if (!free_positions_.empty()) {
    pos = free_positions_.front();
    free_positions_.erase(free_positions_.begin());
  } else {
    pos = next_position_++;
  }
  Entry e;
  e.name = name;
  e.position = pos;
  e.response = std::move(resp);
  e.params = std::move(params);
  e.lru_it = lru_.insert(lru_.end(), name);
  auto [nit, _] = by_name_.emplace(name, std::move(e));
  by_pos_[pos] = &nit->second;
}

// ---------------------------------------------------------------------------
// Engine lifecycle
// ---------------------------------------------------------------------------

Engine::Engine(const EngineConfig& cfg, std::vector<int> data_fds,
               std::vector<int> ctrl_fds)
    : cfg_(cfg), data_fds_(std::move(data_fds)), ctrl_fds_(std::move(ctrl_fds)) {
  for (int fd : data_fds_)
    if (fd >= 0) SetNoDelay(fd);
  for (int fd : ctrl_fds_)
    if (fd >= 0) SetNoDelay(fd);
  last_stall_check_s_ = NowS();
  cache_.SetCapacity(cfg.cache_capacity);
  if (cfg.rank == 0 && !cfg.timeline_path.empty())
    timeline_.Initialize(cfg.timeline_path, cfg.timeline_mark_cycles);
  if (cfg.autotune && cfg.rank == 0) {
    auto opts = cfg.autotune_opts;
    if (!HierarchicalTopologyOk()) {
      // A hierarchy knob is meaningless on a flat topology — tuning it
      // would waste GP samples on a no-op dimension.
      opts.tune_hier_allreduce = false;
      opts.tune_hier_allgather = false;
    }
    pm_ = std::make_unique<ParameterManager>(
        TunedParams{cfg.fusion_threshold, cfg.cycle_time_s,
                    cfg.cache_capacity > 0, cfg.hierarchical_allreduce,
                    cfg.hierarchical_allgather, cfg.ring_segment_bytes},
        opts);
  }
  bg_ = std::thread([this] { BackgroundLoop(); });
}

Engine::~Engine() { Shutdown(); }

void Engine::Shutdown() {
  bool expected = false;
  if (!shutdown_requested_.compare_exchange_strong(expected, true)) {
    if (bg_.joinable()) bg_.join();
    return;
  }
  // Negotiated shutdown (parity: controller.cc:116-130 — the shutdown
  // flag rides RequestList/ResponseList): the loop tells the
  // coordinator, whose next ResponseList stops every rank in the same
  // cycle, so no rank reads a socket its peer already closed.  Bounded:
  // if negotiation can't complete (peer already gone), force the local
  // loop down after the deadline.
  double deadline = NowS() + 10.0;
  while (!loop_exited_.load() && NowS() < deadline)
    std::this_thread::sleep_for(std::chrono::milliseconds(2));
  shutdown_.store(true);
  if (bg_.joinable()) bg_.join();
  timeline_.Shutdown();
  for (int fd : data_fds_)
    if (fd >= 0) ::close(fd);
  for (int fd : ctrl_fds_)
    if (fd >= 0) ::close(fd);
}

// ---------------------------------------------------------------------------
// Enqueue side (caller threads)
// ---------------------------------------------------------------------------

bool Engine::ClaimName(const std::string& name, std::string* err) {
  if (pending_names_.count(name)) {
    *err = "Requested a collective on a tensor with the same name as "
           "another tensor that is currently being processed: " +
           name;
    return false;
  }
  pending_names_.insert(name);
  return true;
}

void Engine::ReleaseName(const std::string& name) {
  std::lock_guard<std::mutex> lk(queue_mu_);
  pending_names_.erase(name);
}

int64_t Engine::Enqueue(TensorTableEntry entry, std::string* err) {
  std::lock_guard<std::mutex> lk(queue_mu_);
  if (aborted_.load() || shutdown_.load() || shutdown_requested_.load()) {
    *err = "horovod_tpu runtime has been shut down";
    return -1;
  }
  if (!ClaimName(entry.name, err)) return -1;
  entry.enqueue_s = NowS();
  int64_t h = entry.handle;
  request_queue_.push_back(entry.request);
  table_.emplace(entry.name, std::move(entry));
  return h;
}

int64_t Engine::EnqueueAllreduce(const std::string& name, void* buf,
                                 const TensorShape& shape, DataType dt,
                                 ReduceOp op, double prescale,
                                 double postscale, std::string* err,
                                 int32_t ps_id, int32_t ps_size) {
  TensorTableEntry e;
  e.name = name;
  e.data = static_cast<uint8_t*>(buf);
  e.nelems = shape.num_elements();
  e.handle = handles_.Allocate();
  e.request.request_rank = cfg_.rank;
  e.request.request_type = RequestType::ALLREDUCE;
  e.request.tensor_type = dt;
  e.request.tensor_name = name;
  e.request.tensor_shape = shape;
  e.request.reduce_op = op;
  e.request.prescale_factor = prescale;
  e.request.postscale_factor = postscale;
  e.request.process_set_id = ps_id;
  e.request.process_set_size = ps_size;
  return Enqueue(std::move(e), err);
}

int64_t Engine::EnqueueAllgather(const std::string& name, const void* buf,
                                 const TensorShape& shape, DataType dt,
                                 std::string* err, int32_t ps_id,
                                 int32_t ps_size) {
  TensorTableEntry e;
  e.name = name;
  e.data = static_cast<uint8_t*>(const_cast<void*>(buf));
  e.nelems = shape.num_elements();
  e.handle = handles_.Allocate();
  e.request.request_rank = cfg_.rank;
  e.request.request_type = RequestType::ALLGATHER;
  e.request.tensor_type = dt;
  e.request.tensor_name = name;
  e.request.tensor_shape = shape;
  e.request.process_set_id = ps_id;
  e.request.process_set_size = ps_size;
  return Enqueue(std::move(e), err);
}

int64_t Engine::EnqueueBroadcast(const std::string& name, void* buf,
                                 const TensorShape& shape, DataType dt,
                                 int root_rank, std::string* err,
                                 int32_t ps_id, int32_t ps_size) {
  if (root_rank < 0 || root_rank >= cfg_.size) {
    *err = "broadcast root rank " + std::to_string(root_rank) +
           " out of range [0, " + std::to_string(cfg_.size) + ")";
    return -1;
  }
  TensorTableEntry e;
  e.name = name;
  e.data = static_cast<uint8_t*>(buf);
  e.nelems = shape.num_elements();
  e.handle = handles_.Allocate();
  e.request.request_rank = cfg_.rank;
  e.request.request_type = RequestType::BROADCAST;
  e.request.tensor_type = dt;
  e.request.tensor_name = name;
  e.request.tensor_shape = shape;
  e.request.root_rank = root_rank;
  e.request.process_set_id = ps_id;
  e.request.process_set_size = ps_size;
  return Enqueue(std::move(e), err);
}

int64_t Engine::EnqueueAlltoall(const std::string& name, const void* buf,
                                const TensorShape& shape, DataType dt,
                                const std::vector<int64_t>& splits,
                                std::string* err, int32_t ps_id,
                                int32_t ps_size) {
  int n = ps_id ? ps_size : cfg_.size;
  if (n <= 0) {
    *err = "alltoall: invalid process_set_size " + std::to_string(ps_size);
    return -1;
  }
  if (!splits.empty()) {
    if (static_cast<int>(splits.size()) != n) {
      *err = "alltoall needs one split per participant (" +
             std::to_string(n) + ")";
      return -1;
    }
    int64_t total = 0;
    for (auto s : splits) total += s;
    if (shape.dims.empty() || total != shape.dims[0]) {
      *err = "splits must sum to dim 0";
      return -1;
    }
  } else if (!shape.dims.empty() && shape.dims[0] % n != 0) {
    *err = "alltoall without splits requires dim 0 divisible by the "
           "participant count";
    return -1;
  }
  TensorTableEntry e;
  e.name = name;
  e.data = static_cast<uint8_t*>(const_cast<void*>(buf));
  e.nelems = shape.num_elements();
  e.handle = handles_.Allocate();
  e.splits = splits;
  e.request.request_rank = cfg_.rank;
  e.request.request_type = RequestType::ALLTOALL;
  e.request.tensor_type = dt;
  e.request.tensor_name = name;
  e.request.tensor_shape = shape;
  e.request.process_set_id = ps_id;
  e.request.process_set_size = ps_size;
  return Enqueue(std::move(e), err);
}

void Engine::RegisterProcessSet(int32_t id, std::vector<int> ranks) {
  std::lock_guard<std::mutex> lk(process_sets_mu_);
  process_sets_[id] = std::move(ranks);
}

std::vector<int> Engine::ProcessSetRanks(int32_t id) {
  std::lock_guard<std::mutex> lk(process_sets_mu_);
  auto it = process_sets_.find(id);
  return it != process_sets_.end() ? it->second : std::vector<int>{};
}

std::pair<std::vector<int>, int> Engine::ResponseGroup(
    const Response& resp) {
  std::vector<int> group;
  int me = cfg_.rank;
  if (resp.process_set_id) {
    group = ProcessSetRanks(resp.process_set_id);
    me = static_cast<int>(
        std::find(group.begin(), group.end(), cfg_.rank) - group.begin());
  } else {
    for (int r = 0; r < cfg_.size; ++r) group.push_back(r);
  }
  return {std::move(group), me};
}

int64_t Engine::EnqueueReduceScatter(const std::string& name,
                                     const void* buf,
                                     const TensorShape& shape, DataType dt,
                                     ReduceOp op, std::string* err,
                                     int32_t ps_id, int32_t ps_size) {
  if (shape.dims.empty()) {
    *err = "reducescatter needs at least one dimension to scatter over "
           "(got a scalar)";
    return -1;
  }
  TensorTableEntry e;
  e.name = name;
  e.data = static_cast<uint8_t*>(const_cast<void*>(buf));
  e.nelems = shape.num_elements();
  e.handle = handles_.Allocate();
  e.request.request_rank = cfg_.rank;
  e.request.request_type = RequestType::REDUCESCATTER;
  e.request.tensor_type = dt;
  e.request.tensor_name = name;
  e.request.tensor_shape = shape;
  e.request.reduce_op = op;
  e.request.process_set_id = ps_id;
  e.request.process_set_size = ps_size;
  return Enqueue(std::move(e), err);
}

int Engine::Barrier(std::string* err, int32_t ps_id, int32_t ps_size) {
  TensorTableEntry e;
  int64_t c;
  if (ps_id == 0) {
    c = barrier_counter_.fetch_add(1);
    e.name = "__barrier." + std::to_string(c);
  } else {
    // Per-set counters; distinct name families keep a concurrent
    // global barrier from colliding in the duplicate-name guard.
    std::lock_guard<std::mutex> lk(process_sets_mu_);
    c = ps_barrier_counters_[ps_id]++;
    e.name = "__barrier.ps" + std::to_string(ps_id) + "." +
             std::to_string(c);
  }
  static int32_t zero = 0;
  e.data = reinterpret_cast<uint8_t*>(&zero);
  e.nelems = 1;
  e.handle = handles_.Allocate();
  e.request.request_rank = cfg_.rank;
  e.request.request_type = RequestType::BARRIER;
  e.request.tensor_name = e.name;
  e.request.tensor_type = DataType::INT32;
  e.request.process_set_id = ps_id;
  e.request.process_set_size = ps_size;
  int64_t h = Enqueue(std::move(e), err);
  if (h < 0) return -1;
  StatusType st = handles_.Wait(h);
  if (st != StatusType::OK && err) {
    HandleState* hs = handles_.Get(h);
    *err = (hs && !hs->status.reason.empty())
               ? hs->status.reason
               : "barrier failed (status " +
                     std::to_string(static_cast<int>(st)) + ")";
  }
  handles_.Release(h);
  return st == StatusType::OK ? 0 : -1;
}

void Engine::CacheStats(int64_t out[5]) {
  std::lock_guard<std::mutex> lk(cache_mu_);
  out[0] = cache_.hits;
  out[1] = cache_.misses;
  out[2] = cache_.evictions;
  out[3] = cache_.size();
  out[4] = cache_.capacity();
}

int Engine::Join() {
  int64_t h = handles_.Allocate();
  {
    std::lock_guard<std::mutex> lk(queue_mu_);
    joined_ = true;
    join_handle_ = h;
    Request req;
    req.request_rank = cfg_.rank;
    req.request_type = RequestType::JOIN;
    req.tensor_name = "__join__";
    request_queue_.push_back(req);
  }
  handles_.Wait(h);
  handles_.Release(h);
  return last_joined_rank_.load();
}

// ---------------------------------------------------------------------------
// Background loop
// ---------------------------------------------------------------------------

void Engine::BackgroundLoop() {
  try {
    while (!shutdown_.load()) {
      double t0 = NowS();
      timeline_.MarkCycleStart();
      if (!RunLoopOnce()) break;
      double dt = NowS() - t0;
      if (dt < cfg_.cycle_time_s) {
        auto us = static_cast<int64_t>((cfg_.cycle_time_s - dt) * 1e6);
        std::this_thread::sleep_for(std::chrono::microseconds(us));
      }
    }
  } catch (const std::exception& e) {
    // A peer closing its socket during an agreed teardown is part of
    // shutting down, not a failure worth alarming about.
    if (!shutdown_requested_.load() && !shutdown_.load())
      std::fprintf(stderr, "[hvd-core %d] background loop failed: %s\n",
                   cfg_.rank, e.what());
    Abort(e.what());
  }
  DrainOnShutdown();
  loop_exited_.store(true);
}

void Engine::DrainOnShutdown() {
  std::vector<TensorTableEntry> entries;
  int64_t jh;
  {
    std::lock_guard<std::mutex> lk(queue_mu_);
    for (auto& kv : table_) entries.push_back(std::move(kv.second));
    table_.clear();
    request_queue_.clear();
    jh = join_handle_;
    join_handle_ = -1;
  }
  for (auto& e : entries) {
    ReleaseName(e.name);
    if (e.handle >= 0)
      handles_.MarkDone(e.handle,
                        Status::Aborted("Horovod has been shut down."));
  }
  if (jh >= 0) handles_.MarkDone(jh, Status::OK());
}

bool Engine::RunLoopOnce() {
  std::vector<Request> msgs;
  {
    std::lock_guard<std::mutex> lk(queue_mu_);
    msgs.swap(request_queue_);
  }
  if (cfg_.rank == 0) return CoordinatorCycle(std::move(msgs));
  return WorkerCycle(std::move(msgs));
}

void Engine::ClassifyRequests(std::vector<Request> msgs,
                              std::vector<Request>* requests,
                              std::vector<CacheHit>* hit_events) {
  // Parity: the cache check at the top of ComputeResponseList
  // (controller.cc:171-200), adapted to explicit hit events.
  std::lock_guard<std::mutex> lk(cache_mu_);
  for (auto& req : msgs) {
    auto rit = resend_uncached_.find(req.tensor_name);
    if (rit != resend_uncached_.end()) {
      resend_uncached_.erase(rit);
      requests->push_back(std::move(req));
      continue;
    }
    uint32_t pos = 0;
    if (cache_classify_enabled_ &&
        cache_.Classify(req, &pos) == ResponseCache::HIT)
      hit_events->push_back({req.tensor_name, pos});
    else
      requests->push_back(std::move(req));
  }
}

void Engine::ApplyParams(const WireParams& p) {
  cfg_.fusion_threshold = p.fusion_threshold;
  cfg_.cycle_time_s = p.cycle_time_s;
  cfg_.hierarchical_allreduce = p.hierarchical_allreduce;
  cfg_.hierarchical_allgather = p.hierarchical_allgather;
  cfg_.ring_segment_bytes = p.ring_segment_bytes;
  std::lock_guard<std::mutex> lk(cache_mu_);
  cache_classify_enabled_ = p.cache_enabled;
}

void Engine::ExecuteCachedHits(const std::vector<uint32_t>& hit_positions) {
  if (hit_positions.empty()) return;
  std::vector<Response> cached;
  {
    std::lock_guard<std::mutex> lk(cache_mu_);
    for (auto p : hit_positions) {
      const Response* resp = cache_.GetByPosition(p);
      if (resp == nullptr) {
        // A missing position means this rank's cache diverged from the
        // coordinator's.  Executing the remaining hits would launch a
        // different collective sequence than the other ranks and hang
        // the whole job — fail fast instead.
        std::fprintf(stderr,
                     "[hvd-core %d] cache coherence violation: position %u "
                     "missing locally, aborting\n",
                     cfg_.rank, p);
        Abort("response cache coherence violation");
        return;
      }
      cache_.Touch(p);
      cached.push_back(*resp);  // copy: FuseResponses mutates its inputs
    }
  }
  for (auto& resp : FuseResponses(std::move(cached)))
    PerformResponse(resp, /*from_cache=*/true);
}

void Engine::ProcessResends(const std::vector<std::string>& resend_names) {
  std::lock_guard<std::mutex> lk(queue_mu_);
  std::lock_guard<std::mutex> clk(cache_mu_);
  for (auto& nm : resend_names) {
    auto it = table_.find(nm);
    if (it != table_.end()) {
      resend_uncached_.insert(nm);
      request_queue_.push_back(it->second.request);
    }
  }
}

bool Engine::WorkerCycle(std::vector<Request> msgs) {
  int ctrl = ctrl_fds_[0];
  std::vector<Request> requests;
  std::vector<CacheHit> hit_events;
  ClassifyRequests(std::move(msgs), &requests, &hit_events);
  bool want_shutdown = shutdown_requested_.load();
  bool send_failed = false;
  if (!requests.empty() || !hit_events.empty() || want_shutdown) {
    auto payload = EncodeRequestList(requests, want_shutdown, hit_events);
    try {
      SendFrame(ctrl, kTagRequestList, payload.data(), payload.size());
    } catch (const SocketError&) {
      // The coordinator may have closed right after broadcasting a
      // shutdown ResponseList; that frame can still be buffered on our
      // side — fall through to the drain, which exits gracefully on
      // it.  Only if no shutdown was in flight is this a real failure.
      send_failed = true;
    }
  }
  while (Readable(ctrl, 0)) {
    std::vector<uint8_t> payload;
    uint8_t tag = RecvFrame(ctrl, &payload);
    if (tag != kTagResponseList)
      throw SocketError("worker expected response list, got tag " +
                        std::to_string(tag));
    std::vector<Response> responses;
    std::vector<uint32_t> hit_positions;
    std::vector<std::string> resend;
    WireParams params;
    bool shutdown = false;
    if (!DecodeResponseList(payload.data(), payload.size(), &responses,
                            &shutdown, &hit_positions, &resend, &params))
      throw SocketError("malformed response list");
    // Apply BEFORE executing this frame's hits: the fusion threshold
    // shapes the fused launches, which must match on every rank.
    if (params.present) ApplyParams(params);
    ProcessResends(resend);
    ExecuteCachedHits(hit_positions);
    for (auto& resp : responses) PerformResponse(resp);
    if (shutdown) {
      shutdown_.store(true);
      return false;
    }
  }
  if (send_failed)  // no shutdown was in flight: genuine lost peer
    throw SocketError("lost connection to coordinator");
  return true;
}

void Engine::AbsorbRequest(const Request& req,
                           std::vector<std::string>* ready) {
  if (req.request_type == RequestType::JOIN) {
    joined_ranks_.insert(req.request_rank);
    last_joined_rank_.store(req.request_rank);
    // Tensors waiting only on joined ranks become ready (global-set
    // entries only; join never applies to process-set traffic).
    for (auto& kv : msg_table_) {
      if (!kv.second.requests.empty() &&
          kv.second.requests[0].process_set_id == 0 &&
          static_cast<int>(kv.second.requests.size()) ==
              cfg_.size - static_cast<int>(joined_ranks_.size())) {
        if (std::find(ready->begin(), ready->end(), kv.first) == ready->end())
          ready->push_back(kv.first);
      }
    }
    return;
  }
  // Table key: process-set requests are scoped by set id, so the same
  // tensor name may be in flight in two different sets at once.
  std::string key =
      req.process_set_id
          ? req.tensor_name + "@ps" + std::to_string(req.process_set_id)
          : req.tensor_name;
  auto& ent = msg_table_[key];
  if (timeline_.enabled()) {
    // Start on the FIRST request for this key — a process set may not
    // contain rank 0, and an End without a Start corrupts the trace.
    if (ent.requests.empty())
      timeline_.NegotiateStart(req.tensor_name, OpName(req.request_type));
    timeline_.NegotiateRankReady(req.tensor_name, req.request_rank);
  }
  if (ent.requests.empty()) ent.first_seen_s = NowS();
  ent.requests.push_back(req);
  // Process-set request: ready when every member is in (join is
  // global-set-only); global: all non-joined ranks.
  int full_at = req.process_set_id
                    ? req.process_set_size
                    : cfg_.size - static_cast<int>(joined_ranks_.size());
  if (static_cast<int>(ent.requests.size()) == full_at)
    ready->push_back(key);
}

bool Engine::CoordinatorCycle(std::vector<Request> msgs) {
  std::vector<std::string> ready;
  bool shutdown = shutdown_requested_.load();
  std::map<int, std::vector<std::string>> resend_by_rank;

  auto absorb_hit = [&](const std::string& name, uint32_t pos, int rank) {
    // A hit event stands for the full Request; rebuild it from our own
    // (coherent) cache and let it ride the ordinary message table.  If
    // our entry was evicted in flight, ask the sender to resend.
    std::lock_guard<std::mutex> lk(cache_mu_);
    const std::string* ent_name = cache_.NameAt(pos);
    Request req;
    if (ent_name == nullptr || *ent_name != name ||
        !cache_.SynthesizeRequest(pos, rank, &req)) {
      resend_by_rank[rank].push_back(name);
      return;
    }
    hit_ranks_[name].insert(rank);
    AbsorbRequest(req, &ready);
  };

  std::vector<Request> requests;
  std::vector<CacheHit> own_hits;
  ClassifyRequests(std::move(msgs), &requests, &own_hits);
  for (auto& req : requests) AbsorbRequest(req, &ready);
  for (auto& h : own_hits) absorb_hit(h.name, h.position, 0);
  for (int r = 1; r < cfg_.size; ++r) {
    int fd = ctrl_fds_[r];
    while (Readable(fd, 0)) {
      std::vector<uint8_t> payload;
      uint8_t tag = RecvFrame(fd, &payload);
      if (tag != kTagRequestList)
        throw SocketError("coordinator expected request list, got tag " +
                          std::to_string(tag));
      std::vector<Request> reqs;
      std::vector<CacheHit> peer_hits;
      bool peer_shutdown = false;
      if (!DecodeRequestList(payload.data(), payload.size(), &reqs,
                             &peer_shutdown, &peer_hits))
        throw SocketError("malformed request list");
      shutdown = shutdown || peer_shutdown;
      for (auto& req : reqs) AbsorbRequest(req, &ready);
      for (auto& h : peer_hits) absorb_hit(h.name, h.position, r);
    }
  }

  std::vector<Response> responses;
  std::vector<uint32_t> hit_positions;
  for (auto& key : ready) {
    auto it = msg_table_.find(key);
    if (it == msg_table_.end()) continue;
    auto reqs = std::move(it->second.requests);
    msg_table_.erase(it);
    const std::string& name = reqs[0].tensor_name;  // key may be scoped
    timeline_.NegotiateEnd(name);
    // Hits are global-set-only (key == name there); looking up by key
    // keeps a set-scoped completion from stealing a same-named global
    // tensor's hit record.
    std::set<int> hit_ranks;
    auto hit = hit_ranks_.find(key);
    if (hit != hit_ranks_.end()) {
      hit_ranks = std::move(hit->second);
      hit_ranks_.erase(hit);
    }
    bool all_hit = true;
    for (auto& r : reqs)
      if (!hit_ranks.count(r.request_rank)) {
        all_hit = false;
        break;
      }
    int64_t pos = -1;
    if (all_hit) {
      std::lock_guard<std::mutex> lk(cache_mu_);
      pos = cache_.PositionOf(name);
    }
    if (pos >= 0) {
      // Every contributor hit → all requests were synthesized from the
      // same cache entry → the negotiated response IS the cached one;
      // broadcast just the position.
      hit_positions.push_back(static_cast<uint32_t>(pos));
    } else {
      responses.push_back(ConstructResponse(name, reqs));
    }
  }

  if (static_cast<int>(joined_ranks_.size()) == cfg_.size) {
    Response join_resp;
    join_resp.response_type = ResponseType::JOIN;
    join_resp.tensor_sizes = {last_joined_rank_.load()};
    responses.push_back(join_resp);
    joined_ranks_.clear();
  }

  if (!cfg_.stall_check_disable) shutdown = CheckStalls() || shutdown;

  if (!responses.empty() || !hit_positions.empty() || !resend_by_rank.empty() ||
      have_pending_params_ || shutdown) {
    auto fused = FuseResponses(std::move(responses));
    WireParams wp;
    if (have_pending_params_) {
      wp.present = true;
      wp.fusion_threshold = pending_params_.fusion_threshold;
      wp.cycle_time_s = pending_params_.cycle_time_s;
      wp.cache_enabled = pending_params_.cache_enabled;
      wp.hierarchical_allreduce = pending_params_.hierarchical_allreduce;
      wp.hierarchical_allgather = pending_params_.hierarchical_allgather;
      wp.ring_segment_bytes = pending_params_.ring_segment_bytes;
      have_pending_params_ = false;
    }
    std::vector<uint8_t> shared;
    for (int r = 1; r < cfg_.size; ++r) {
      auto rit = resend_by_rank.find(r);
      if (rit != resend_by_rank.end()) {
        auto payload = EncodeResponseList(fused, shutdown, hit_positions,
                                          rit->second, wp);
        SendFrame(ctrl_fds_[r], kTagResponseList, payload.data(),
                  payload.size());
      } else {
        if (shared.empty())
          shared = EncodeResponseList(fused, shutdown, hit_positions, {}, wp);
        SendFrame(ctrl_fds_[r], kTagResponseList, shared.data(),
                  shared.size());
      }
    }
    // Same ordering contract as the workers: apply before fusing or
    // executing this frame's cached hits.
    if (wp.present) ApplyParams(wp);
    ExecuteCachedHits(hit_positions);
    for (auto& resp : fused) PerformResponse(resp);
    if (pm_ && !pm_->done()) {
      int64_t nbytes = 0;
      for (auto& r : fused)
        if (r.response_type == ResponseType::ALLREDUCE)
          for (auto s : r.tensor_sizes)
            nbytes += s * static_cast<int64_t>(ItemSize(r.tensor_type));
      {
        std::lock_guard<std::mutex> lk(cache_mu_);
        for (auto p : hit_positions) {
          const Response* c = cache_.GetByPosition(p);
          if (c)
            nbytes += c->tensor_sizes[0] *
                      static_cast<int64_t>(ItemSize(c->tensor_type));
        }
      }
      TunedParams next;
      if (pm_->RecordBytes(nbytes, NowS(), &next)) {
        pending_params_ = next;
        have_pending_params_ = true;
      }
    }
    if (shutdown) {
      shutdown_.store(true);
      return false;
    }
  }
  return true;
}

bool Engine::CheckStalls() {
  double now = NowS();
  if (now - last_stall_check_s_ < cfg_.stall_warn_s / 4) return false;
  last_stall_check_s_ = now;
  bool shutdown = false;
  for (auto& kv : msg_table_) {
    double waited = now - kv.second.first_seen_s;
    if (waited > cfg_.stall_warn_s) {
      std::string have;
      for (auto& r : kv.second.requests)
        have += std::to_string(r.request_rank) + " ";
      std::fprintf(stderr,
                   "[hvd-core 0] Stalled tensor %s: ready on ranks [ %s] "
                   "for %.0fs\n",
                   kv.first.c_str(), have.c_str(), waited);
      if (cfg_.stall_shutdown_s > 0 && waited > cfg_.stall_shutdown_s) {
        std::fprintf(stderr,
                     "[hvd-core 0] Stalled tensor %s exceeded shutdown "
                     "threshold; shutting down\n",
                     kv.first.c_str());
        shutdown = true;
      }
    }
  }
  return shutdown;
}

// ---------------------------------------------------------------------------
// Response construction + fusion (coordinator)
// ---------------------------------------------------------------------------

Response Engine::ConstructResponse(const std::string& name,
                                   const std::vector<Request>& reqs) {
  const Request& first = reqs[0];
  std::string err;
  auto mismatch = [&](auto pred) {
    for (auto& r : reqs)
      if (pred(r)) return true;
    return false;
  };

  if (mismatch([&](const Request& r) {
        return r.request_type != first.request_type;
      })) {
    err = "Mismatched collective operations for tensor " + name;
  } else if (mismatch([&](const Request& r) {
               return r.process_set_id != first.process_set_id ||
                      r.process_set_size != first.process_set_size;
             })) {
    err = "Mismatched process sets for tensor " + name;
  } else if (first.process_set_id &&
             first.request_type == RequestType::JOIN) {
    err = std::string(OpName(first.request_type)) +
          " does not support process sets (tensor " + name + ")";
  } else if (first.process_set_id &&
             first.request_type == RequestType::ALLREDUCE &&
             first.reduce_op == ReduceOp::ADASUM) {
    err = "Adasum is not supported with process sets (tensor " + name + ")";
  } else if (mismatch([&](const Request& r) {
               return r.tensor_type != first.tensor_type;
             })) {
    err = "Mismatched data types for tensor " + name + ": ";
    std::set<std::string> types;
    for (auto& r : reqs) types.insert(DtypeName(r.tensor_type));
    bool firstt = true;
    for (auto& t : types) {
      if (!firstt) err += ", ";
      err += t;
      firstt = false;
    }
  } else if (first.request_type == RequestType::ALLREDUCE) {
    if (mismatch([&](const Request& r) {
          return r.tensor_shape != first.tensor_shape;
        })) {
      err = "Mismatched allreduce tensor shapes for " + name;
    } else if (mismatch([&](const Request& r) {
                 return r.reduce_op != first.reduce_op;
               })) {
      err = "Mismatched reduce ops for tensor " + name;
    }
  } else if (first.request_type == RequestType::BROADCAST) {
    if (mismatch([&](const Request& r) {
          return r.root_rank != first.root_rank;
        })) {
      err = "Mismatched broadcast root ranks for " + name;
    } else if (mismatch([&](const Request& r) {
                 return r.tensor_shape != first.tensor_shape;
               })) {
      err = "Mismatched broadcast tensor shapes for " + name;
    } else if (first.process_set_id) {
      auto members = ProcessSetRanks(first.process_set_id);
      if (!members.empty() &&
          std::find(members.begin(), members.end(), first.root_rank) ==
              members.end()) {
        // Authoritative check (wrappers pre-check too): a non-member
        // root would skip while members block in RecvFrame.
        err = "broadcast root rank " + std::to_string(first.root_rank) +
              " is not a member of process set " +
              std::to_string(first.process_set_id) + " (tensor " + name +
              ")";
      }
    }
  } else if (first.request_type == RequestType::ALLGATHER) {
    for (auto& r : reqs) {
      if (r.tensor_shape.dims.size() != first.tensor_shape.dims.size() ||
          !std::equal(r.tensor_shape.dims.begin() + 1,
                      r.tensor_shape.dims.end(),
                      first.tensor_shape.dims.begin() + 1)) {
        err = "Mismatched allgather tensor shapes for " + name +
              ": all dimensions except the first must match";
        break;
      }
    }
  } else if (first.request_type == RequestType::REDUCESCATTER) {
    if (mismatch([&](const Request& r) {
          return r.tensor_shape != first.tensor_shape;
        })) {
      err = "Mismatched reducescatter tensor shapes for " + name;
    } else if (mismatch([&](const Request& r) {
                 return r.reduce_op != first.reduce_op;
               })) {
      err = "Mismatched reduce ops for tensor " + name;
    } else if (first.reduce_op == ReduceOp::ADASUM) {
      err = "Adasum is not defined for reducescatter (tensor " + name + ")";
    }
  }

  if (!err.empty()) {
    Response r;
    r.response_type = ResponseType::ERROR;
    r.tensor_names = {name};
    r.error_message = err;
    return r;
  }

  Response resp;
  resp.response_type = static_cast<ResponseType>(first.request_type);
  resp.tensor_names = {name};
  resp.tensor_type = first.tensor_type;
  resp.devices = {first.device};
  resp.process_set_id = first.process_set_id;
  if (first.request_type == RequestType::ALLREDUCE) {
    resp.tensor_sizes = {first.tensor_shape.num_elements()};
    resp.reduce_op = first.reduce_op;
    resp.prescale_factor = first.prescale_factor;
    resp.postscale_factor = first.postscale_factor;
    // Negotiated dims ride the response so cache parameters stay
    // coherent on every rank (incl. joined ranks' stand-ins).
    resp.tensor_shapes = {first.tensor_shape};
  } else if (first.request_type == RequestType::ALLGATHER) {
    // First-dim size per rank, rank order (0 for joined ranks); for a
    // process set, per member in member order.
    std::map<int, const Request*> by_rank;
    for (auto& r : reqs) by_rank[r.request_rank] = &r;
    std::vector<int> order;
    if (first.process_set_id) {
      auto members = ProcessSetRanks(first.process_set_id);
      if (members.empty()) {
        Response er;
        er.response_type = ResponseType::ERROR;
        er.tensor_names = {name};
        er.error_message =
            "process set " + std::to_string(first.process_set_id) +
            " is not registered on the coordinator (construct the "
            "ProcessSet on every rank)";
        return er;
      }
      order = members;
    } else {
      for (int r = 0; r < cfg_.size; ++r) order.push_back(r);
    }
    for (int r : order) {
      auto it = by_rank.find(r);
      resp.tensor_sizes.push_back(
          it != by_rank.end() ? it->second->tensor_shape.dims[0] : 0);
    }
  } else if (first.request_type == RequestType::BROADCAST) {
    resp.tensor_sizes = {first.root_rank};
  } else if (first.request_type == RequestType::REDUCESCATTER) {
    resp.tensor_sizes = {first.tensor_shape.num_elements()};
    resp.reduce_op = first.reduce_op;
    resp.tensor_shapes = {first.tensor_shape};
  }
  return resp;
}

std::vector<Response> Engine::FuseResponses(std::vector<Response> responses) {
  std::vector<Response> out;
  Response pending;
  bool have_pending = false;
  int64_t pending_bytes = 0;
  for (auto& r : responses) {
    bool fusable = r.response_type == ResponseType::ALLREDUCE &&
                   r.error_message.empty();
    if (!fusable) {
      if (have_pending) {
        out.push_back(std::move(pending));
        have_pending = false;
      }
      out.push_back(std::move(r));
      continue;
    }
    int64_t nbytes = 0;
    for (auto s : r.tensor_sizes) nbytes += s;
    nbytes *= static_cast<int64_t>(ItemSize(r.tensor_type));
    if (have_pending && pending.tensor_type == r.tensor_type &&
        pending.devices == r.devices && pending.reduce_op == r.reduce_op &&
        pending.prescale_factor == r.prescale_factor &&
        pending.postscale_factor == r.postscale_factor &&
        pending.process_set_id == r.process_set_id &&
        pending_bytes + nbytes <= cfg_.fusion_threshold) {
      pending.tensor_names.insert(pending.tensor_names.end(),
                                  r.tensor_names.begin(),
                                  r.tensor_names.end());
      pending.tensor_sizes.insert(pending.tensor_sizes.end(),
                                  r.tensor_sizes.begin(),
                                  r.tensor_sizes.end());
      pending.tensor_shapes.insert(pending.tensor_shapes.end(),
                                   r.tensor_shapes.begin(),
                                   r.tensor_shapes.end());
      pending_bytes += nbytes;
    } else {
      if (have_pending) out.push_back(std::move(pending));
      pending = std::move(r);
      have_pending = true;
      pending_bytes = nbytes;
    }
  }
  if (have_pending) out.push_back(std::move(pending));
  return out;
}

// ---------------------------------------------------------------------------
// Execution
// ---------------------------------------------------------------------------

std::vector<TensorTableEntry> Engine::GetEntries(const Response& resp) {
  // Parity: GetTensorEntriesFromResponse (tensor_queue.cc:72-117) — a
  // joined rank gets zero stand-ins.
  std::vector<TensorTableEntry> entries;
  std::lock_guard<std::mutex> lk(queue_mu_);
  for (size_t i = 0; i < resp.tensor_names.size(); ++i) {
    const auto& nm = resp.tensor_names[i];
    auto it = table_.find(nm);
    if (it != table_.end()) {
      entries.push_back(std::move(it->second));
      table_.erase(it);
    } else {
      TensorTableEntry e;
      e.name = nm;
      e.handle = -1;
      e.request.request_rank = cfg_.rank;
      e.request.tensor_type = resp.tensor_type;
      if (resp.response_type == ResponseType::ALLREDUCE) {
        int64_t n = resp.tensor_sizes[i];
        e.standin.assign(n * ItemSize(resp.tensor_type), 0);
        e.data = e.standin.data();
        e.nelems = n;
        e.request.tensor_shape.dims = {n};
      } else if (resp.response_type == ResponseType::REDUCESCATTER) {
        // Needs the negotiated shape — the scatter splits over dim 0,
        // so a flat stand-in would desync the ring chunk boundaries.
        const TensorShape& s = resp.tensor_shapes[i];
        int64_t n = s.num_elements();
        e.standin.assign(n * ItemSize(resp.tensor_type), 0);
        e.data = e.standin.data();
        e.nelems = n;
        e.request.tensor_shape = s;
      } else {
        e.nelems = 0;
        e.request.tensor_shape.dims = {0};
      }
      entries.push_back(std::move(e));
    }
  }
  return entries;
}

void Engine::PerformResponse(const Response& resp, bool from_cache) {
  if (resp.response_type == ResponseType::JOIN) {
    if (!resp.tensor_sizes.empty())
      last_joined_rank_.store(static_cast<int>(resp.tensor_sizes[0]));
    int64_t jh;
    {
      std::lock_guard<std::mutex> lk(queue_mu_);
      jh = join_handle_;
      join_handle_ = -1;
      joined_ = false;
    }
    if (jh >= 0) handles_.MarkDone(jh, Status::OK());
    return;
  }

  if (resp.response_type == ResponseType::ERROR) {
    for (const auto& nm : resp.tensor_names) {
      TensorTableEntry e;
      bool found = false;
      {
        std::lock_guard<std::mutex> lk(queue_mu_);
        auto it = table_.find(nm);
        if (it != table_.end()) {
          e = std::move(it->second);
          table_.erase(it);
          found = true;
        }
      }
      if (found) {
        ReleaseName(e.name);
        if (e.handle >= 0)
          handles_.MarkDone(e.handle,
                            Status::PreconditionError(resp.error_message));
      }
    }
    return;
  }

  if (resp.process_set_id && resp.response_type != ResponseType::ERROR) {
    // Process-set responses reach every rank in the response stream;
    // non-members simply skip (members always have the entries — join
    // is global-set-only, so no stand-ins here).
    auto members = ProcessSetRanks(resp.process_set_id);
    if (std::find(members.begin(), members.end(), cfg_.rank) ==
        members.end())
      return;
  }

  if (!from_cache && resp.response_type == ResponseType::ALLREDUCE) {
    // Populate the response cache BEFORE execution and regardless of
    // execution outcome: the put stores metadata only, and doing it
    // unconditionally in response-stream order is what keeps every
    // rank's cache (positions, LRU, evictions) coherent.
    std::lock_guard<std::mutex> lk(cache_mu_);
    cache_.Put(resp);
  }

  auto entries = GetEntries(resp);
  if (timeline_.enabled() && !resp.tensor_names.empty())
    timeline_.Start(resp.tensor_names[0],
                    OpName(static_cast<RequestType>(resp.response_type)));
  Status status = Status::OK();
  try {
    switch (resp.response_type) {
      case ResponseType::ALLREDUCE:
        DoAllreduce(entries, resp);
        break;
      case ResponseType::ALLGATHER:
        DoAllgather(entries, resp);
        break;
      case ResponseType::BROADCAST:
        DoBroadcast(entries, resp);
        break;
      case ResponseType::ALLTOALL:
        DoAlltoall(entries, resp);
        break;
      case ResponseType::REDUCESCATTER:
        DoReduceScatter(entries, resp);
        break;
      case ResponseType::BARRIER:
        DoBarrier(resp);
        for (auto& e : entries) {
          ReleaseName(e.name);
          if (e.handle >= 0) handles_.MarkDone(e.handle, Status::OK());
        }
        if (timeline_.enabled() && !resp.tensor_names.empty())
          timeline_.End(resp.tensor_names[0]);
        return;
      default:
        throw std::runtime_error("bad response type");
    }
  } catch (const std::exception& ex) {
    std::fprintf(stderr, "[hvd-core %d] collective %s failed: %s\n",
                 cfg_.rank, OpName(static_cast<RequestType>(resp.response_type)),
                 ex.what());
    status = Status::UnknownError(ex.what());
    for (auto& e : entries) {
      ReleaseName(e.name);
      if (e.handle >= 0) handles_.MarkDone(e.handle, status);
    }
    // Data-plane failure leaves sockets in an undefined protocol state.
    Abort(ex.what());
  }
  if (timeline_.enabled() && !resp.tensor_names.empty())
    timeline_.End(resp.tensor_names[0]);
}

void Engine::DoAllreduce(std::vector<TensorTableEntry>& entries,
                         const Response& resp) {
  DataType dt = resp.tensor_type;
  size_t isz = ItemSize(dt);
  // Op and scales come from the negotiated response — identical on every
  // rank, including joined ranks whose entries are zero stand-ins.
  ReduceOp op = resp.reduce_op;
  double prescale = resp.prescale_factor;
  double postscale = resp.postscale_factor;

  int64_t total = 0;
  for (auto& e : entries) total += e.nelems;

  uint8_t* flat;
  bool fused = entries.size() > 1;
  if (fused) {
    // Parity: MemcpyInFusionBuffer — one lazily grown persistent buffer.
    if (fusion_buffer_.size() < static_cast<size_t>(total) * isz)
      fusion_buffer_.resize(total * isz);
    flat = fusion_buffer_.data();
    int64_t off = 0;
    for (auto& e : entries) {
      std::memcpy(flat + off * isz, e.data, e.nelems * isz);
      off += e.nelems;
    }
  } else {
    flat = entries[0].data;  // in-place, zero copy
  }

  if (prescale != 1.0) ScaleInPlace(flat, total, dt, prescale);

  // Group = the full world, or the process set's members (subgroup
  // rings reuse the full mesh sockets; Adasum/hierarchical are
  // rejected for sets at negotiation).
  auto [group, me] = ResponseGroup(resp);

  if (op == ReduceOp::ADASUM) {
    AdasumFlat(flat, total, dt);
  } else if (!resp.process_set_id && cfg_.hierarchical_allreduce &&
             HierarchicalTopologyOk()) {
    HierarchicalAllreduceFlat(flat, total, dt, op);
  } else {
    RingAllreduceGroup(flat, total, dt, op, group, me);
  }

  if (op == ReduceOp::AVERAGE)
    AverageInPlace(flat, total, dt,
                   static_cast<int64_t>(group.size()));
  if (postscale != 1.0) ScaleInPlace(flat, total, dt, postscale);

  if (fused) {
    int64_t off = 0;
    for (auto& e : entries) {
      std::memcpy(e.data, flat + off * isz, e.nelems * isz);
      off += e.nelems;
    }
  }
  for (auto& e : entries) {
    ReleaseName(e.name);
    if (e.handle >= 0) handles_.MarkDone(e.handle, Status::OK());
  }
}

void Engine::RingAllreduceFlat(uint8_t* buf, int64_t nelems, DataType dt,
                               ReduceOp op) {
  // Parity: cpu_backend.ring_allreduce_flat — ring reduce-scatter +
  // ring allgather, chunk boundaries and combine order identical so the
  // two engines are bit-identical (they can share one job).
  std::vector<int> group(cfg_.size);
  for (int i = 0; i < cfg_.size; ++i) group[i] = i;
  RingAllreduceGroup(buf, nelems, dt, op, group, cfg_.rank);
}

void Engine::RingAllreduceGroup(uint8_t* buf, int64_t nelems, DataType dt,
                                ReduceOp op, const std::vector<int>& group,
                                int me) {
  int size = static_cast<int>(group.size());
  if (size == 1) return;
  size_t isz = ItemSize(dt);
  int right = data_fds_[group[Mod(me + 1, size)]];
  int left = data_fds_[group[Mod(me - 1, size)]];
  auto bounds = ChunkBounds(nelems, size);
  std::vector<uint8_t> tmp;

  // Phase 1: ring reduce-scatter.
  for (int step = 0; step < size - 1; ++step) {
    int64_t send_idx = Mod(me - step, size);
    int64_t recv_idx = Mod(me - step - 1, size);
    int64_t send_n = bounds[send_idx + 1] - bounds[send_idx];
    int64_t recv_n = bounds[recv_idx + 1] - bounds[recv_idx];
    tmp.resize(recv_n * isz);
    ExchangeInto(right, buf + bounds[send_idx] * isz, send_n * isz, left,
                 tmp.data(), recv_n * isz);
    CombineInto(buf + bounds[recv_idx] * isz, tmp.data(), recv_n, dt, op);
  }

  // Phase 2: ring allgather of the reduced chunks.
  for (int step = 0; step < size - 1; ++step) {
    int64_t send_idx = Mod(me + 1 - step, size);
    int64_t recv_idx = Mod(me - step, size);
    int64_t send_n = bounds[send_idx + 1] - bounds[send_idx];
    int64_t recv_n = bounds[recv_idx + 1] - bounds[recv_idx];
    ExchangeInto(right, buf + bounds[send_idx] * isz, send_n * isz, left,
                 buf + bounds[recv_idx] * isz, recv_n * isz);
  }
}

bool Engine::HierarchicalTopologyOk() const {
  // Requires the launcher's homogeneous block rank layout
  // (rank = cross_rank*local_size + local_rank) and a true two-level
  // shape.
  return cfg_.local_size > 1 && cfg_.cross_size > 1 &&
         cfg_.local_size * cfg_.cross_size == cfg_.size &&
         cfg_.rank == cfg_.cross_rank * cfg_.local_size + cfg_.local_rank;
}

std::vector<int> Engine::LocalGroup() const {
  std::vector<int> g(cfg_.local_size);
  for (int i = 0; i < cfg_.local_size; ++i)
    g[i] = cfg_.cross_rank * cfg_.local_size + i;
  return g;
}

std::vector<int> Engine::CrossGroup() const {
  std::vector<int> g(cfg_.cross_size);
  for (int k = 0; k < cfg_.cross_size; ++k)
    g[k] = k * cfg_.local_size + cfg_.local_rank;
  return g;
}

void Engine::HierarchicalAllreduceFlat(uint8_t* buf, int64_t nelems,
                                       DataType dt, ReduceOp op) {
  // Two-level TPU mapping of NCCLHierarchicalAllreduce
  // (nccl_operations.cc:163-363): reduce-scatter on the node-local ring,
  // allreduce the owned 1/local_size slice on the cross-node ring, then
  // allgather on the local ring — only 1/local_size of the bytes crosses
  // the slow fabric.  Chunk walk identical to cpu_backend so the two
  // engines stay bit-compatible in a mixed job.
  int L = cfg_.local_size;
  int li = cfg_.local_rank;
  size_t isz = ItemSize(dt);
  auto local = LocalGroup();
  int right = data_fds_[local[Mod(li + 1, L)]];
  int left = data_fds_[local[Mod(li - 1, L)]];
  auto bounds = ChunkBounds(nelems, L);
  std::vector<uint8_t> tmp;

  // Phase 1: local ring reduce-scatter.
  for (int step = 0; step < L - 1; ++step) {
    int64_t send_idx = Mod(li - step, L);
    int64_t recv_idx = Mod(li - step - 1, L);
    int64_t send_n = bounds[send_idx + 1] - bounds[send_idx];
    int64_t recv_n = bounds[recv_idx + 1] - bounds[recv_idx];
    tmp.resize(recv_n * isz);
    ExchangeInto(right, buf + bounds[send_idx] * isz, send_n * isz, left,
                 tmp.data(), recv_n * isz);
    CombineInto(buf + bounds[recv_idx] * isz, tmp.data(), recv_n, dt, op);
  }

  // Phase 2: cross-node ring allreduce of the fully-reduced owned chunk.
  int64_t own = Mod(li + 1, L);
  int64_t own_n = bounds[own + 1] - bounds[own];
  if (own_n > 0)
    RingAllreduceGroup(buf + bounds[own] * isz, own_n, dt, op, CrossGroup(),
                       cfg_.cross_rank);

  // Phase 3: local ring allgather.
  for (int step = 0; step < L - 1; ++step) {
    int64_t send_idx = Mod(li + 1 - step, L);
    int64_t recv_idx = Mod(li - step, L);
    int64_t send_n = bounds[send_idx + 1] - bounds[send_idx];
    int64_t recv_n = bounds[recv_idx + 1] - bounds[recv_idx];
    ExchangeInto(right, buf + bounds[send_idx] * isz, send_n * isz, left,
                 buf + bounds[recv_idx] * isz, recv_n * isz);
  }
}

void Engine::AdasumFlat(uint8_t* buf, int64_t nelems, DataType dt) {
  // Parity: cpu_backend._adasum_flat — recursive distance-doubling partner
  // exchange, fp64 accumulation, power-of-two world sizes.
  int size = cfg_.size, rank = cfg_.rank;
  if (size == 1) return;
  if (size & (size - 1))
    throw std::runtime_error("Adasum requires a power-of-two world size");
  std::vector<double> acc(nelems), other(nelems);
  ToF64(buf, acc.data(), nelems, dt);
  for (int k = 1; k < size; k *= 2) {
    int partner = rank ^ k;
    int fd = data_fds_[partner];
    ExchangeInto(fd, acc.data(), nelems * 8, fd, other.data(), nelems * 8);
    if (rank < partner) {
      AdasumPairF64(acc.data(), other.data(), acc.data(), nelems);
    } else {
      AdasumPairF64(other.data(), acc.data(), acc.data(), nelems);
    }
  }
  FromF64(acc.data(), buf, nelems, dt);
}

void Engine::DoAllgather(std::vector<TensorTableEntry>& entries,
                         const Response& resp) {
  if (!resp.process_set_id && cfg_.hierarchical_allgather &&
      HierarchicalTopologyOk()) {
    DoAllgatherHierarchical(entries, resp);
    return;
  }
  // Ragged ring allgatherv (parity: cpu_backend.allgather; displacement
  // logic parity: MPIAllgather, mpi_operations.cc:83-166).  For a
  // process set the ring walks the member list.
  auto [group, me] = ResponseGroup(resp);
  int size = static_cast<int>(group.size()), rank = me;
  for (auto& e : entries) {
    size_t isz = ItemSize(resp.tensor_type);
    struct Block {
      const uint8_t* ptr = nullptr;
      size_t len = 0;
      std::vector<uint8_t> own;
    };
    std::vector<Block> blocks(size);
    blocks[rank].ptr = e.data;
    blocks[rank].len = e.nelems * isz;
    if (size > 1) {
      int right = data_fds_[group[Mod(rank + 1, size)]];
      int left = data_fds_[group[Mod(rank - 1, size)]];
      for (int step = 0; step < size - 1; ++step) {
        int64_t send_idx = Mod(rank - step, size);
        int64_t recv_idx = Mod(rank - step - 1, size);
        std::vector<uint8_t> incoming;
        Exchange(right, blocks[send_idx].ptr, blocks[send_idx].len, left,
                 &incoming);
        blocks[recv_idx].own = std::move(incoming);
        blocks[recv_idx].ptr = blocks[recv_idx].own.data();
        blocks[recv_idx].len = blocks[recv_idx].own.size();
      }
    }
    size_t total = 0;
    for (auto& b : blocks) total += b.len;
    std::vector<uint8_t> result(total);
    size_t off = 0;
    for (auto& b : blocks) {
      if (b.len) std::memcpy(result.data() + off, b.ptr, b.len);
      off += b.len;
    }
    ReleaseName(e.name);
    if (e.handle >= 0)
      handles_.MarkDone(e.handle, Status::OK(), std::move(result));
  }
}

void Engine::DoAllgatherHierarchical(std::vector<TensorTableEntry>& entries,
                                     const Response& resp) {
  // Two-level allgatherv (role parity: MPIHierarchicalAllgather,
  // mpi_operations.cc:168-309 — there via a node-shared MPI window;
  // here via the node-local ring + a leaders-only cross ring):
  //   1. ragged ring allgatherv within the node → node block,
  //   2. local leaders exchange node blocks on the cross ring,
  //   3. leaders fan the full buffer out to their node (MultiSend).
  // Output ordering matches the flat path because the launcher's block
  // rank layout makes node blocks contiguous in global rank order.
  int L = cfg_.local_size, li = cfg_.local_rank, C = cfg_.cross_size;
  auto local = LocalGroup();
  for (auto& e : entries) {
    // Phase 1: node-local ragged ring allgatherv.
    struct Block {
      const uint8_t* ptr = nullptr;
      size_t len = 0;
      std::vector<uint8_t> own;
    };
    std::vector<Block> blocks(L);
    blocks[li].ptr = e.data;
    blocks[li].len = e.nelems * ItemSize(resp.tensor_type);
    int right = data_fds_[local[Mod(li + 1, L)]];
    int left = data_fds_[local[Mod(li - 1, L)]];
    for (int step = 0; step < L - 1; ++step) {
      int64_t send_idx = Mod(li - step, L);
      int64_t recv_idx = Mod(li - step - 1, L);
      std::vector<uint8_t> incoming;
      Exchange(right, blocks[send_idx].ptr, blocks[send_idx].len, left,
               &incoming);
      blocks[recv_idx].own = std::move(incoming);
      blocks[recv_idx].ptr = blocks[recv_idx].own.data();
      blocks[recv_idx].len = blocks[recv_idx].own.size();
    }
    size_t node_bytes = 0;
    for (auto& b : blocks) node_bytes += b.len;
    std::vector<uint8_t> node_block(node_bytes);
    size_t off = 0;
    for (auto& b : blocks) {
      if (b.len) std::memcpy(node_block.data() + off, b.ptr, b.len);
      off += b.len;
    }

    std::vector<uint8_t> result;
    if (li == 0) {
      // Phase 2: leaders' ragged ring allgatherv of node blocks.
      std::vector<Block> nblocks(C);
      int me = cfg_.cross_rank;
      nblocks[me].ptr = node_block.data();
      nblocks[me].len = node_block.size();
      if (C > 1) {
        int nright = data_fds_[Mod(me + 1, C) * L];
        int nleft = data_fds_[Mod(me - 1, C) * L];
        for (int step = 0; step < C - 1; ++step) {
          int64_t send_idx = Mod(me - step, C);
          int64_t recv_idx = Mod(me - step - 1, C);
          std::vector<uint8_t> incoming;
          Exchange(nright, nblocks[send_idx].ptr, nblocks[send_idx].len,
                   nleft, &incoming);
          nblocks[recv_idx].own = std::move(incoming);
          nblocks[recv_idx].ptr = nblocks[recv_idx].own.data();
          nblocks[recv_idx].len = nblocks[recv_idx].own.size();
        }
      }
      size_t total = 0;
      for (auto& b : nblocks) total += b.len;
      result.resize(total);
      off = 0;
      for (auto& b : nblocks) {
        if (b.len) std::memcpy(result.data() + off, b.ptr, b.len);
        off += b.len;
      }
      // Phase 3: fan out to the rest of the node.
      std::vector<int> others;
      for (int i = 1; i < L; ++i) others.push_back(data_fds_[local[i]]);
      MultiSend(others, result.data(), result.size());
    } else {
      uint8_t tag = RecvFrame(data_fds_[local[0]], &result);
      if (tag != kTagData)
        throw std::runtime_error("hierarchical allgather: bad frame tag");
    }
    ReleaseName(e.name);
    if (e.handle >= 0)
      handles_.MarkDone(e.handle, Status::OK(), std::move(result));
  }
}

void Engine::DoBroadcast(std::vector<TensorTableEntry>& entries,
                         const Response& resp) {
  int rank = cfg_.rank;
  // root is a GLOBAL rank; for a process set the fan-out covers the
  // member list only.
  auto [group, me_unused] = ResponseGroup(resp);
  (void)me_unused;
  int size = static_cast<int>(group.size());
  for (auto& e : entries) {
    int root = resp.tensor_sizes.empty()
                   ? e.request.root_rank
                   : static_cast<int>(resp.tensor_sizes[0]);
    size_t nbytes = e.nelems * ItemSize(resp.tensor_type);
    if (size > 1) {
      if (rank == root) {
        std::vector<int> others;
        for (int r : group)
          if (r != root) others.push_back(data_fds_[r]);
        MultiSend(others, e.data, nbytes);
      } else {
        std::vector<uint8_t> payload;
        uint8_t tag = RecvFrame(data_fds_[root], &payload);
        if (tag != kTagData)
          throw SocketError("broadcast expected data frame");
        // A joined stand-in has no caller buffer; the payload is dropped.
        if (e.data && e.nelems)
          std::memcpy(e.data, payload.data(),
                      std::min(payload.size(), nbytes));
      }
    }
    ReleaseName(e.name);
    if (e.handle >= 0) handles_.MarkDone(e.handle, Status::OK());
  }
}

void Engine::DoAlltoall(std::vector<TensorTableEntry>& entries,
                        const Response& resp) {
  // Pairwise exchange rounds (parity: cpu_backend.alltoall); for a
  // process set, partners walk the member list.
  auto [group, me] = ResponseGroup(resp);
  int size = static_cast<int>(group.size()), rank = me;
  for (auto& e : entries) {
    size_t isz = ItemSize(resp.tensor_type);
    int64_t dim0 = e.request.tensor_shape.dims.empty()
                       ? 0
                       : e.request.tensor_shape.dims[0];
    int64_t row_elems = dim0 > 0 ? e.nelems / dim0 : 0;
    size_t row_bytes = row_elems * isz;
    std::vector<int64_t> splits = e.splits;
    if (splits.empty()) {
      int64_t per = size > 0 ? dim0 / size : 0;
      splits.assign(size, per);
    }
    std::vector<int64_t> offs{0};
    for (auto s : splits) offs.push_back(offs.back() + s);

    std::vector<std::vector<uint8_t>> recv_blocks(size);
    std::vector<int64_t> recv_rows(size, 0);
    recv_blocks[rank].assign(
        e.data + offs[rank] * row_bytes,
        e.data + offs[rank + 1] * row_bytes);
    recv_rows[rank] = splits[rank];
    for (int step = 1; step < size; ++step) {
      int dst = Mod(rank + step, size);
      int src = Mod(rank - step, size);
      std::vector<uint8_t> incoming;
      Exchange(data_fds_[group[dst]], e.data + offs[dst] * row_bytes,
               splits[dst] * row_bytes, data_fds_[group[src]], &incoming);
      recv_rows[src] =
          row_bytes ? static_cast<int64_t>(incoming.size() / row_bytes) : 0;
      recv_blocks[src] = std::move(incoming);
    }
    size_t total = 0;
    for (auto& b : recv_blocks) total += b.size();
    std::vector<uint8_t> result(total);
    size_t off = 0;
    for (auto& b : recv_blocks) {
      if (!b.empty()) std::memcpy(result.data() + off, b.data(), b.size());
      off += b.size();
    }
    ReleaseName(e.name);
    if (e.handle >= 0)
      handles_.MarkDone(e.handle, Status::OK(), std::move(result),
                        std::move(recv_rows));
  }
}

void Engine::DoReduceScatter(std::vector<TensorTableEntry>& entries,
                             const Response& resp) {
  // Ring reduce-scatter over dim-0 row chunks (parity:
  // cpu_backend.reducescatter — identical walk, so mixed native/py jobs
  // stay bit-compatible).  The standard walk leaves rank r owning chunk
  // (r+1)%size; shifting the start by one virtual rank leaves it owning
  // chunk r, which is the API contract.
  auto [group, me] = ResponseGroup(resp);
  int size = static_cast<int>(group.size()), rank = me;
  DataType dt = resp.tensor_type;
  size_t isz = ItemSize(dt);
  ReduceOp op = resp.reduce_op;
  for (auto& e : entries) {
    const TensorShape& shape = e.request.tensor_shape;
    int64_t d0 = shape.dims[0];
    int64_t row_elems = d0 > 0 ? e.nelems / d0 : 0;
    auto row_bounds = ChunkBounds(d0, size);
    if (size == 1) {
      std::vector<uint8_t> result(e.data, e.data + e.nelems * isz);
      ReleaseName(e.name);
      if (e.handle >= 0)
        handles_.MarkDone(e.handle, Status::OK(), std::move(result));
      continue;
    }
    // Working copies of each row chunk (the caller's input buffer is
    // not mutated; the owned chunk becomes the handle result).
    std::vector<std::vector<uint8_t>> chunks(size);
    for (int i = 0; i < size; ++i) {
      int64_t lo = row_bounds[i] * row_elems;
      int64_t hi = row_bounds[i + 1] * row_elems;
      chunks[i].assign(e.data + lo * isz, e.data + hi * isz);
    }
    int right = data_fds_[group[Mod(rank + 1, size)]];
    int left = data_fds_[group[Mod(rank - 1, size)]];
    std::vector<uint8_t> tmp;
    for (int step = 0; step < size - 1; ++step) {
      int64_t send_idx = Mod(rank - 1 - step, size);
      int64_t recv_idx = Mod(rank - 2 - step, size);
      tmp.resize(chunks[recv_idx].size());
      ExchangeInto(right, chunks[send_idx].data(), chunks[send_idx].size(),
                   left, tmp.data(), tmp.size());
      CombineInto(chunks[recv_idx].data(), tmp.data(),
                  static_cast<int64_t>(chunks[recv_idx].size() / isz), dt,
                  op);
    }
    std::vector<uint8_t> result = std::move(chunks[rank]);
    if (op == ReduceOp::AVERAGE)
      AverageInPlace(result.data(),
                     static_cast<int64_t>(result.size() / isz), dt,
                     static_cast<int64_t>(size));
    ReleaseName(e.name);
    if (e.handle >= 0)
      handles_.MarkDone(e.handle, Status::OK(), std::move(result));
  }
}

void Engine::DoBarrier(const Response& resp) {
  int32_t zero = 0;
  auto [group, me] = ResponseGroup(resp);
  RingAllreduceGroup(reinterpret_cast<uint8_t*>(&zero), 1,
                     DataType::INT32, ReduceOp::SUM, group, me);
}

void Engine::Abort(const std::string& reason) {
  (void)reason;
  aborted_.store(true);
  shutdown_.store(true);
}

}  // namespace hvd
