"""PyTorch front-end: ``import horovod_tpu.torch as hvd``.

Role parity: ``horovod/torch/__init__.py`` — the classic Horovod torch
surface (init/rank/size, sync+async+in-place collectives, autograd
support, hook-driven ``DistributedOptimizer`` overlapping allreduce with
backward, ``broadcast_parameters`` / ``broadcast_optimizer_state`` /
``broadcast_object``, ``join``) on top of the horovod_tpu coordination
engine.  Eager torch tensors bridge zero-copy to the engine as numpy
views; there is no separate native extension because the engine itself
is the native core.
"""

from __future__ import annotations

import collections
from contextlib import contextmanager

import torch

from horovod_tpu.basics import (  # noqa: F401
    cache_stats,
    cross_rank,
    cross_size,
    cuda_built,
    gloo_built,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    rocm_built,
    shutdown,
    size,
    xla_built,
)
from horovod_tpu.torch.compression import Compression  # noqa: F401
from horovod_tpu.torch.mpi_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    grouped_allreduce,
    grouped_allreduce_async,
    join,
    poll,
    reducescatter,
    reducescatter_async,
    synchronize,
)
from horovod_tpu.common.types import ReduceOp


class _DistributedOptimizer(torch.optim.Optimizer):
    """Mixin applied to the user's optimizer class by
    ``DistributedOptimizer`` (parity: torch/__init__.py:38-222 — a
    dynamically created subclass with per-parameter grad-accumulator
    hooks that fire async allreduces during backward; ``step()`` is the
    synchronization barrier)."""

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1, op=ReduceOp.AVERAGE,
                 process_set=None):
        super(self.__class__, self).__init__(params)
        self._compression = compression
        self.op = op
        self.backward_passes_per_step = backward_passes_per_step
        self.process_set = process_set

        if named_parameters is not None:
            named_parameters = list(named_parameters)
        else:
            named_parameters = [
                (f"allreduce.noname.{i}.{j}", v)
                for i, group in enumerate(self.param_groups)
                for j, v in enumerate(group["params"])]
        # Parity checks (torch/__init__.py:60-86): names must be unique
        # and cover every parameter.
        if len({k for k, _ in named_parameters}) < len(named_parameters):
            raise ValueError(
                "parameter names in named_parameters must be unique")
        all_params = {v for group in self.param_groups
                      for v in group["params"]}
        named = {v for _, v in named_parameters}
        if all_params - named:
            raise ValueError(
                "named_parameters was specified, but one or more model "
                "parameters were not named")
        self._parameter_names = {v: k for k, v in named_parameters}
        self._handles = {}
        self._ctxs = {}
        self._requires_update = set()
        self._synchronized = False
        self._should_synchronize = True
        self._allreduce_delay = {}
        active = size() > 1 if self.process_set is None \
            else self.process_set.size() > 1
        if self.process_set is not None and \
                not self.process_set.included():
            raise ValueError(
                f"rank {rank()} is not a member of {self.process_set}; "
                "construct the optimizer only on member ranks")
        if active:
            self._register_hooks()

    # -- hooks ------------------------------------------------------------

    def _register_hooks(self):
        for param_group in self.param_groups:
            for p in param_group["params"]:
                if p.requires_grad:
                    self._requires_update.add(p)
                    self._allreduce_delay[p] = self.backward_passes_per_step
                    # The public post-accumulate hook (torch>=2.1) fires at
                    # the same point as the reference's grad-accumulator
                    # hook (torch/__init__.py:127-162).
                    p.register_post_accumulate_grad_hook(self._make_hook())

    def _make_hook(self):
        def hook(p):
            if p in self._handles and self._handles[p] is not None:
                if self._allreduce_delay[p] <= 0:
                    raise AssertionError(
                        "Gradients were computed more than "
                        "backward_passes_per_step times before call to "
                        "step(). Increase backward_passes_per_step to "
                        "accumulate gradients locally.")
            assert not p.grad.requires_grad
            assert self._allreduce_delay[p] > 0
            self._allreduce_delay[p] -= 1
            if self._allreduce_delay[p] == 0:
                self._handles[p] = self._allreduce_grad_async(p)
        return hook

    def _allreduce_grad_async(self, p):
        name = self._parameter_names[p]
        compressed, ctx = self._compression.compress(p.grad)
        self._ctxs[p] = ctx
        return allreduce_async(compressed, name=f"allreduce.{name}",
                               op=self.op,
                               process_set=self.process_set)

    # -- synchronization --------------------------------------------------

    def synchronize(self):
        """Waits for every outstanding gradient allreduce and writes the
        reduced values into param.grad (parity: __init__.py:164-201)."""
        missing = [p for p in self._requires_update
                   if p not in self._handles]
        for p in missing:
            if p.grad is None:
                p.grad = p.data.new_zeros(p.shape)
            self._handles[p] = self._allreduce_grad_async(p)
        for p, handle in self._handles.items():
            output = synchronize(handle)
            self._allreduce_delay[p] = self.backward_passes_per_step
            with torch.no_grad():
                p.grad.copy_(
                    self._compression.decompress(output, self._ctxs.pop(p)))
        self._handles.clear()
        self._synchronized = True

    @contextmanager
    def skip_synchronize(self):
        """Use when calling ``synchronize()`` manually before ``step()``
        (parity: __init__.py:203-214)."""
        self._should_synchronize = False
        try:
            yield
        finally:
            self._should_synchronize = True

    def step(self, closure=None):
        if self._should_synchronize:
            if self._synchronized:
                import warnings

                warnings.warn(
                    "optimizer.step() called without "
                    "optimizer.skip_synchronize() context after "
                    "optimizer.synchronize(). This can cause training "
                    "slowdown. You may want to consider using "
                    "optimizer.skip_synchronize() context if you use "
                    "optimizer.synchronize() in your code.")
            self.synchronize()
        self._synchronized = False
        return super(self.__class__, self).step(closure)

    def zero_grad(self, *args, **kwargs):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() "
                "but before optimizer.step() or optimizer.synchronize(). "
                "This is prohibited as it can cause a race condition.")
        return super(self.__class__, self).zero_grad(*args, **kwargs)


class _DistributedAdasumOptimizer(torch.optim.Optimizer):
    """Delta-model Adasum optimizer (parity: torch/__init__.py:224-392).

    Where the averaging optimizer combines *gradients*, Adasum's contract
    combines the *parameter deltas* the local optimizer produced: each
    rank applies its own ``step()``, the per-parameter deltas
    ``p - p_start`` are reduced with the scale-invariant Adasum operation
    (``ops/adasum.py``), and every rank resets to
    ``p_start + adasum(deltas)``.  The starting model is broadcast from
    rank 0 at construction so the deltas are taken from a common point.
    """

    def __init__(self, params, named_parameters, compression,
                 backward_passes_per_step=1):
        super(self.__class__, self).__init__(params)
        if backward_passes_per_step != 1:
            # The averaging wrapper implements this via gradient hooks;
            # the delta model has no hook to delay — accumulate by calling
            # backward() several times before step() instead.
            raise ValueError(
                "backward_passes_per_step > 1 is not supported with "
                "op=Adasum: call loss.backward() several times before "
                "optimizer.step() to accumulate gradients locally")
        self._compression = compression
        self._starting_models = {}
        names = dict(named_parameters or [])
        by_param = {v: k for k, v in names.items()}
        self._adasum_names = {}
        for i, group in enumerate(self.param_groups):
            for j, p in enumerate(group["params"]):
                self._adasum_names[p] = (
                    f"adasum.delta.{by_param[p]}" if p in by_param
                    else f"adasum.delta.{i}.{j}")
        if size() > 1:
            broadcast_parameters(
                [(nm, p) for p, nm in self._adasum_names.items()],
                root_rank=0)

    def step(self, closure=None):
        updated = [p for group in self.param_groups
                   for p in group["params"] if p.grad is not None]
        starts = {p: p.data.clone().detach() for p in updated}
        loss = super(self.__class__, self).step(closure)
        handles = []
        for p in updated:
            delta = p.data - starts[p]
            compressed, ctx = self._compression.compress(delta)
            h = allreduce_async(compressed, name=self._adasum_names[p],
                                op=ReduceOp.ADASUM)
            handles.append((p, h, ctx))
        with torch.no_grad():
            for p, h, ctx in handles:
                d = self._compression.decompress(synchronize(h), ctx)
                p.data.copy_(starts[p] + d)
        return loss


def DistributedOptimizer(optimizer, named_parameters=None,
                         compression=Compression.none,
                         backward_passes_per_step=1,
                         op=ReduceOp.AVERAGE, process_set=None):
    """Wraps a torch optimizer: gradient allreduce overlaps backward;
    ``step()`` synchronizes (parity: torch/__init__.py:394-449, same
    dynamic-subclass technique).  ``op=Adasum`` selects the delta-model
    wrapper (parity: the op switch in the reference factory).
    ``process_set`` scopes the gradient collectives to a subgroup
    (construct the optimizer on member ranks only)."""
    if op == ReduceOp.ADASUM:
        if process_set is not None:
            raise ValueError("Adasum does not support process sets")
        cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
                   dict(_DistributedAdasumOptimizer.__dict__))
        return cls(optimizer.param_groups, named_parameters, compression,
                   backward_passes_per_step)
    cls = type(optimizer.__class__.__name__, (optimizer.__class__,),
               dict(_DistributedOptimizer.__dict__))
    return cls(optimizer.param_groups, named_parameters, compression,
               backward_passes_per_step, op, process_set)


# ---------------------------------------------------------------------------
# state broadcast helpers
# ---------------------------------------------------------------------------


def broadcast_parameters(params, root_rank=0):
    """Broadcasts a ``state_dict()`` or iterable of (name, tensor) from
    root to all ranks, in place (parity: torch/__init__.py:451-481)."""
    if isinstance(params, dict):
        params = sorted(params.items())
    elif isinstance(params, collections.abc.Iterable):
        params = list(params)
    handles = []
    for name, p in params:
        if p is None:
            continue
        if torch.is_tensor(p):
            handles.append(broadcast_async_(p, root_rank,
                                            name=f"bp.{name}"))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcasts the optimizer state (momentum buffers, step counters,
    hyperparameters) from root (parity: torch/__init__.py:483-604 —
    tensors broadcast in place, scalars via broadcast_object)."""
    if isinstance(optimizer, torch.optim.LBFGS):
        raise ValueError("cannot broadcast torch.optim.LBFGS state")
    state_dict = optimizer.state_dict()
    if rank() == root_rank and not state_dict["state"]:
        # Reference behavior: initialize state on root by stepping with
        # zero gradients so there is something to broadcast.  Only the
        # root steps here, so a wrapped optimizer must skip its gradient
        # synchronization or it would launch a one-rank allreduce.
        for group in optimizer.param_groups:
            for p in group["params"]:
                if p.requires_grad and p.grad is None:
                    p.grad = p.data.new_zeros(p.shape)
        if hasattr(optimizer, "skip_synchronize"):
            with optimizer.skip_synchronize():
                optimizer.step()
        else:
            optimizer.step()
        state_dict = optimizer.state_dict()

    # Scalars (incl. param_group hyperparameters) travel as one pickled
    # object that also carries tensor metadata — non-root ranks may have
    # no state yet, so they learn shapes/dtypes from the root and
    # allocate receive buffers; tensors are then broadcast in place.
    if rank() == root_rank:
        meta = {"param_groups": state_dict["param_groups"], "state": {}}
        for pid, pstate in state_dict["state"].items():
            meta["state"][pid] = {}
            for key, value in pstate.items():
                if torch.is_tensor(value):
                    meta["state"][pid][key] = (
                        "tensor", value.dtype, tuple(value.shape))
                else:
                    meta["state"][pid][key] = ("scalar", value)
    else:
        meta = None
    meta = broadcast_object(meta, root_rank,
                            name="broadcast_optimizer_state")

    tensors = []
    new_state = {}
    own_state = state_dict["state"]
    for pid, pstate in meta["state"].items():
        new_state[pid] = {}
        for key, entry in pstate.items():
            if entry[0] == "tensor":
                _, dtype, shape = entry
                if rank() == root_rank:
                    t = own_state[pid][key]
                else:
                    t = torch.zeros(shape, dtype=dtype)
                tensors.append((f"opt.{pid}.{key}", t))
                new_state[pid][key] = t
            else:
                new_state[pid][key] = entry[1]
    broadcast_parameters(tensors, root_rank)
    if rank() != root_rank:
        optimizer.load_state_dict({"state": new_state,
                                   "param_groups": meta["param_groups"]})


def broadcast_object(obj, root_rank=0, name=None):
    """Serializes and broadcasts an arbitrary picklable object from root
    (parity: torch/__init__.py:607-648).  One implementation serves every
    front-end: the framework-agnostic pickle-over-broadcast in
    ``horovod_tpu.ops.eager`` (torch tensors pickle fine)."""
    from horovod_tpu.ops.eager import broadcast_object as _impl

    return _impl(obj, root_rank, name)
