"""On-demand build + load of the PyTorch custom-op library.

``csrc/torch_ops.cc`` registers ``torch.ops.hvd.allreduce`` /
``allreduce_`` / ``broadcast`` / ``allgather`` — dispatcher ops whose
kernels enqueue straight into the native C++ engine (the reference's
``torch/mpi_ops_v2.cc`` mechanism).  Built on demand against the
installed torch's headers via the shared machinery in
``horovod_tpu.common.native_build``; ``torch.compile`` traces carry the
ops as dispatcher calls.  Preconditions (native engine, env switch)
re-check per call; only genuine build/load failures latch.
``HVD_TORCH_NATIVE_OPS=0`` opts out; the numpy/ctypes path is always
the fallback.
"""

from __future__ import annotations

import os
import threading

from horovod_tpu.common import native_build

_lock = threading.Lock()
_loaded = False
_failed = False

SUPPORTED_DTYPES = frozenset({
    "torch.float32", "torch.float64", "torch.float16", "torch.bfloat16",
    "torch.int32", "torch.int64", "torch.uint8", "torch.int8",
    "torch.bool"})

_SO = os.path.join(native_build.LIB_DIR, "libhvd_torch_ops.so")


def available() -> bool:
    """True when ``torch.ops.hvd.*`` can serve this process's engine."""
    global _loaded, _failed
    if os.environ.get("HVD_TORCH_NATIVE_OPS", "1") == "0":
        return False
    if not native_build.native_engine_active():
        return False
    if _loaded or _failed:
        return _loaded
    with _lock:
        if _loaded or _failed:
            return _loaded
        try:
            _build_and_load()
            _loaded = True
        except Exception as e:
            _failed = True
            from horovod_tpu.utils.logging import get_logger

            get_logger().debug(f"torch native ops unavailable: {e}")
    return _loaded


def _build_and_load():
    import torch

    src = os.path.join(native_build.CSRC_DIR, "torch_ops.cc")
    if native_build.needs_build(src, _SO):
        import torch.utils.cpp_extension as ce

        abi = int(getattr(torch._C, "_GLIBCXX_USE_CXX11_ABI", True))
        torch_lib = os.path.join(os.path.dirname(torch.__file__), "lib")
        native_build.build(
            src, _SO,
            extra_flags=[*(f"-I{p}" for p in ce.include_paths()),
                         f"-D_GLIBCXX_USE_CXX11_ABI={abi}"],
            extra_links=[f"-L{torch_lib}", "-ltorch", "-ltorch_cpu",
                         "-lc10", f"-Wl,-rpath,{torch_lib}"])
    if not os.path.exists(_SO):
        raise RuntimeError(f"{_SO} not built and no sources to build it")
    torch.ops.load_library(_SO)
