"""Gradient compression for the PyTorch binding.

Parity: ``horovod/torch/compression.py:20-75`` — a Compressor interface
with ``none`` and ``fp16`` implementations operating on torch tensors.
``fp16`` here is IEEE half (torch-native), matching the reference; the
JAX-side ``horovod_tpu.ops.compression`` defaults to bfloat16 because
that is the TPU wire/MXU-native 16-bit type.
"""

from __future__ import annotations

import torch


class Compressor:
    """Interface: compress before the collective, decompress after."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Float tensors travel as fp16 and are restored to their original
    dtype afterwards; non-float tensors pass through untouched
    (parity: compression.py:47-61)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point and tensor.dtype != torch.float16:
            return tensor.to(torch.float16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tensor.to(ctx)
        return tensor


class Compression:
    """Optional gradient compression algorithms used during allreduce."""

    none = NoneCompressor
    fp16 = FP16Compressor
