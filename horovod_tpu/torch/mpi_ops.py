"""PyTorch collective ops over the horovod_tpu coordination engine.

Role parity: ``horovod/torch/mpi_ops.py`` (the Python surface) +
``horovod/torch/mpi_ops_v2.cc`` (handles, async enqueue) — sync, async,
and in-place variants of allreduce / allgather / broadcast / alltoall,
``poll``/``synchronize`` on integer handles, ``join``, and autograd
support.  Instead of a pybind11 extension the torch tensors bridge to
the engine through numpy views; the handle registry, name counters, and
op resolution are shared with the framework-agnostic eager layer
(``horovod_tpu.ops.eager``) so a handle from either front-end can be
synchronized by the other.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import torch

from horovod_tpu import basics
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.ops.eager import (
    _auto_name,
    _register,
    _resolve_op,
    poll,  # noqa: F401  (re-exported)
    synchronize,  # noqa: F401  (re-exported)
)

# Reference-named ReduceOp constants (mpi_ops.py re-exports these).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def _native_dispatch(tensor: torch.Tensor, process_set):
    """(True, ps_id, ps_size) when the C++ dispatcher ops
    (csrc/torch_ops.cc — torch.ops.hvd.*, the reference's mpi_ops_v2.cc
    mechanism) can serve this tensor; CPU tensors only (device tensors
    keep the host-staging numpy path)."""
    from horovod_tpu.torch import _native_ops

    if tensor.device.type != "cpu" or \
            str(tensor.dtype) not in _native_ops.SUPPORTED_DTYPES:
        return False, 0, 0
    if not _native_ops.available():
        return False, 0, 0
    ps_id, ps_size = 0, 0
    if process_set is not None:
        ps_id, ps_size = process_set.validate(basics.rank(),
                                              basics.size())
    return True, ps_id, ps_size


def _to_numpy(tensor: torch.Tensor) -> np.ndarray:
    t = tensor.detach()
    if t.device.type != "cpu":
        # Host staging for device tensors — the analog of the reference's
        # *CudaOnCPU op variants (torch/mpi_ops_v2.cc): copy to host,
        # run the collective there, and finalize() moves results back to
        # tensor.device.
        t = t.to("cpu")
    if not t.is_contiguous():
        t = t.contiguous()
    return np.ascontiguousarray(t.numpy())


def join() -> int:
    """Signals that this rank is out of data; blocks until every rank
    joins.  Returns the last joined rank (parity: mpi_ops.py:494-510)."""
    return basics._engine().join()


def barrier(process_set=None) -> None:
    basics._engine().barrier(process_set=process_set)


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------


def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    process_set=None) -> int:
    rop = _resolve_op(op, average)
    arr = _to_numpy(tensor)
    h = basics._engine().allreduce_async(
        _auto_name("torch.allreduce", name), arr, op=rop,
        prescale=prescale_factor, postscale=postscale_factor,
        process_set=process_set)

    def finalize(result):
        return torch.from_numpy(np.asarray(result)).reshape(tensor.shape) \
            .to(tensor.dtype).to(tensor.device)

    return _register(h, finalize)


def allreduce_async_(tensor, average=None, name=None, op=None,
                     prescale_factor=1.0, postscale_factor=1.0,
                     process_set=None) -> int:
    """In-place: the reduced values are written back into `tensor`."""
    rop = _resolve_op(op, average)
    arr = _to_numpy(tensor)
    h = basics._engine().allreduce_async(
        _auto_name("torch.allreduce", name), arr, op=rop,
        prescale=prescale_factor, postscale=postscale_factor,
        process_set=process_set)

    def finalize(result):
        # copy_ performs the host->device transfer itself; no pre-staging.
        out = torch.from_numpy(np.asarray(result)).reshape(tensor.shape) \
            .to(tensor.dtype)
        with torch.no_grad():
            tensor.copy_(out)
        return tensor

    return _register(h, finalize)


class _HorovodAllreduce(torch.autograd.Function):
    """Parity: mpi_ops.py HorovodAllreduce — the gradient of an
    allreduce is the same allreduce of the upstream gradient (over the
    same process set)."""

    @staticmethod
    def forward(ctx, tensor, average, name, op, prescale, postscale,
                process_set=None):
        ctx.average = average
        ctx.op = op
        ctx.prescale = prescale
        ctx.postscale = postscale
        ctx.process_set = process_set
        native, ps_id, ps_size = _native_dispatch(tensor, process_set)
        if native:
            # backward re-enters with name=None; draw from the same
            # noname counter the numpy path would
            nm = name if name is not None \
                else _auto_name("torch.allreduce", None)
            return torch.ops.hvd.allreduce(
                tensor, nm, int(_resolve_op(op, average)),
                float(prescale), float(postscale), ps_id, ps_size)
        return synchronize(allreduce_async(tensor, average, name, op,
                                           prescale, postscale,
                                           process_set))

    @staticmethod
    def backward(ctx, grad_output):
        reduced = _HorovodAllreduce.apply(
            grad_output, ctx.average, None, ctx.op, ctx.prescale,
            ctx.postscale, ctx.process_set)
        return reduced, None, None, None, None, None, None


def allreduce(tensor, average=None, name=None, compression=None, op=None,
              prescale_factor=1.0, postscale_factor=1.0,
              process_set=None) -> torch.Tensor:
    """Differentiable allreduce returning a new tensor."""
    from horovod_tpu.torch.compression import Compression

    compression = compression or Compression.none
    compressed, ctx = compression.compress(tensor)
    reduced = _HorovodAllreduce.apply(
        compressed, average, _auto_name("torch.allreduce", name), op,
        prescale_factor, postscale_factor, process_set)
    return compression.decompress(reduced, ctx)


def allreduce_(tensor, average=None, name=None, op=None,
               prescale_factor=1.0, postscale_factor=1.0,
               process_set=None) -> torch.Tensor:
    native, ps_id, ps_size = _native_dispatch(tensor, process_set)
    if native and tensor.is_contiguous():
        # The in-place dispatcher op reduces directly into the caller's
        # storage (mpi_ops_v2.cc parity).
        nm = name if name is not None \
            else _auto_name("torch.allreduce", None)
        return torch.ops.hvd.allreduce_(
            tensor, nm, int(_resolve_op(op, average)),
            float(prescale_factor), float(postscale_factor), ps_id,
            ps_size)
    return synchronize(allreduce_async_(tensor, average, name, op,
                                        prescale_factor, postscale_factor,
                                        process_set))


def grouped_allreduce_async(tensors, average=None, name=None,
                            op=None, process_set=None) -> list:
    base = _auto_name("torch.grouped", name)
    return [allreduce_async(t, average, f"{base}.{i}", op,
                            process_set=process_set)
            for i, t in enumerate(tensors)]


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      process_set=None) -> list:
    return [synchronize(h)
            for h in grouped_allreduce_async(tensors, average, name, op,
                                             process_set)]


# ---------------------------------------------------------------------------
# allgather
# ---------------------------------------------------------------------------


def allgather_async(tensor, name=None, process_set=None) -> int:
    arr = _to_numpy(tensor)
    h = basics._engine().allgather_async(
        _auto_name("torch.allgather", name), arr,
        process_set=process_set)
    tail_shape = tuple(tensor.shape[1:]) if tensor.dim() > 0 else ()

    def finalize(result):
        out = torch.from_numpy(np.asarray(result))
        if tail_shape:
            out = out.reshape(-1, *tail_shape)
        return out.to(tensor.dtype).to(tensor.device)

    return _register(h, finalize)


class _HorovodAllgather(torch.autograd.Function):
    """Parity: mpi_ops.py HorovodAllgather — backward allreduces the
    gradient (over the same process set) and narrows to this rank's
    segment.  First dims may differ per rank, so the true offset comes
    from gathering the per-rank sizes, like the reference's grad_fn."""

    @staticmethod
    def forward(ctx, tensor, name, process_set=None):
        ctx.dim0 = tensor.shape[0] if tensor.dim() > 0 else 1
        ctx.process_set = process_set
        native, ps_id, ps_size = _native_dispatch(tensor, process_set)
        if native:
            nm = name if name is not None \
                else _auto_name("torch.allgather", None)
            return torch.ops.hvd.allgather(tensor, nm, ps_id, ps_size)
        return synchronize(allgather_async(tensor, name, process_set))

    @staticmethod
    def backward(ctx, grad_output):
        ps = ctx.process_set
        grad_reduced = _HorovodAllreduce.apply(
            grad_output, None, None, ReduceOp.SUM, 1.0, 1.0, ps)
        sizes = synchronize(allgather_async(
            torch.tensor([ctx.dim0], dtype=torch.int64), None, ps))
        my_pos = ps.rank() if ps is not None else basics.rank()
        offset = int(sizes[:my_pos].sum())
        return grad_reduced.narrow(0, offset, ctx.dim0), None, None


def allgather(tensor, name=None, process_set=None) -> torch.Tensor:
    """Differentiable allgather: concatenation along dim 0 across ranks
    (first dims may differ per rank)."""
    return _HorovodAllgather.apply(tensor,
                                   _auto_name("torch.allgather", name),
                                   process_set)


def reducescatter_async(tensor, average=None, name=None, op=None,
                        process_set=None) -> int:
    """Reduce across ranks, scatter over dim 0 (rank r receives the r-th
    near-equal row chunk; the reference project added torch
    ``hvd.reducescatter`` right after the v0.19 line)."""
    rop = _resolve_op(op, average)
    if rop == ReduceOp.ADASUM:
        raise ValueError("reducescatter does not support op Adasum")
    if tensor.dim() == 0:
        raise ValueError(
            "reducescatter needs at least one dimension to scatter over "
            "(got a scalar)")
    arr = _to_numpy(tensor)
    h = basics._engine().reducescatter_async(
        _auto_name("torch.reducescatter", name), arr, op=rop,
        process_set=process_set)
    tail_shape = tuple(tensor.shape[1:])

    def finalize(result):
        out = torch.from_numpy(np.asarray(result))
        if tail_shape:
            out = out.reshape(-1, *tail_shape)
        return out.to(tensor.dtype).to(tensor.device)

    return _register(h, finalize)


def reducescatter(tensor, average=None, name=None, op=None,
                  process_set=None) -> torch.Tensor:
    return synchronize(reducescatter_async(tensor, average, name, op,
                                           process_set))


# ---------------------------------------------------------------------------
# broadcast
# ---------------------------------------------------------------------------


def broadcast_async(tensor, root_rank, name=None,
                    process_set=None) -> int:
    arr = _to_numpy(tensor)
    h = basics._engine().broadcast_async(
        _auto_name("torch.broadcast", name), arr, root_rank=root_rank,
        process_set=process_set)

    def finalize(result):
        return torch.from_numpy(np.asarray(result)).reshape(tensor.shape) \
            .to(tensor.dtype).to(tensor.device)

    return _register(h, finalize)


def broadcast_async_(tensor, root_rank, name=None,
                     process_set=None) -> int:
    arr = _to_numpy(tensor)
    h = basics._engine().broadcast_async(
        _auto_name("torch.broadcast", name), arr, root_rank=root_rank,
        process_set=process_set)

    def finalize(result):
        # copy_ performs the host->device transfer itself; no pre-staging.
        out = torch.from_numpy(np.asarray(result)).reshape(tensor.shape) \
            .to(tensor.dtype)
        with torch.no_grad():
            tensor.copy_(out)
        return tensor

    return _register(h, finalize)


class _HorovodBroadcast(torch.autograd.Function):
    """Parity: mpi_ops.py HorovodBroadcast — backward sums gradients to
    the root (over the same process set); non-root ranks receive zero."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name, process_set=None):
        ctx.root_rank = root_rank
        ctx.process_set = process_set
        native, ps_id, ps_size = _native_dispatch(tensor, process_set)
        if native:
            nm = name if name is not None \
                else _auto_name("torch.broadcast", None)
            return torch.ops.hvd.broadcast(tensor, nm, int(root_rank),
                                           ps_id, ps_size)
        return synchronize(broadcast_async(tensor, root_rank, name,
                                           process_set))

    @staticmethod
    def backward(ctx, grad_output):
        grad_reduced = _HorovodAllreduce.apply(
            grad_output, None, None, ReduceOp.SUM, 1.0, 1.0,
            ctx.process_set)
        if basics.rank() != ctx.root_rank:
            grad_reduced = grad_reduced * 0
        return grad_reduced, None, None, None


def broadcast(tensor, root_rank, name=None,
              process_set=None) -> torch.Tensor:
    return _HorovodBroadcast.apply(tensor, root_rank,
                                   _auto_name("torch.broadcast", name),
                                   process_set)


def broadcast_(tensor, root_rank, name=None,
               process_set=None) -> torch.Tensor:
    return synchronize(broadcast_async_(tensor, root_rank, name,
                                        process_set))


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------


def alltoall_async(tensor, splits=None, name=None,
                   process_set=None) -> int:
    arr = _to_numpy(tensor)
    np_splits = None if splits is None else [int(s) for s in splits]
    h = basics._engine().alltoall_async(
        _auto_name("torch.alltoall", name), arr, splits=np_splits,
        process_set=process_set)
    tail_shape = tuple(tensor.shape[1:]) if tensor.dim() > 0 else ()
    want_splits = splits is not None

    def finalize(result):
        if isinstance(result, tuple):
            data, recv_splits = result
        else:
            # size-1 engine returns the bare array; you receive exactly
            # what you sent, so the recv splits are the send splits.
            data, recv_splits = result, np_splits
        out = torch.from_numpy(np.asarray(data))
        if tail_shape:
            out = out.reshape(-1, *tail_shape)
        out = out.to(tensor.dtype).to(tensor.device)
        if not want_splits:
            return out
        return out, torch.tensor(list(recv_splits), dtype=torch.int64)

    return _register(h, finalize)


def alltoall(tensor, splits=None, name=None, process_set=None):
    """Returns (gathered, received_splits) when splits are given, else
    just the gathered tensor."""
    return synchronize(alltoall_async(tensor, splits, name, process_set))
