"""Process-group runtime facade: init/rank/size + eager collectives.

Parity: ``horovod/common/basics.py`` (HorovodBasics, the ctypes layer over
``horovod_init/rank/size/...`` in operations.cc:650-788).  Here the native
core is ``horovod_tpu._core`` (C++, see ``csrc/``), loaded via ctypes; when
the extension is unavailable (or size == 1) a pure-Python engine with the
same contract is used so the framework degrades gracefully instead of
failing to import.

Rank discovery, in priority order (TPU-first — no MPI):
1. explicit ``init(rank=..., size=...)`` arguments,
2. ``HVD_RANK/HVD_SIZE/HVD_LOCAL_RANK/...`` env injected by the launcher
   (the reference's ``HOROVOD_RANK`` scheme, gloo_context.cc:44-49),
3. JAX distributed / TPU slice metadata (``jax.process_index()``) when the
   process is already part of a JAX multi-host setup,
4. single-process defaults (rank 0 of 1).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from horovod_tpu.common.types import ReduceOp, Status

_lock = threading.Lock()
_runtime = None  # the active engine after init()


class _Env:
    RANK = "HVD_RANK"
    SIZE = "HVD_SIZE"
    LOCAL_RANK = "HVD_LOCAL_RANK"
    LOCAL_SIZE = "HVD_LOCAL_SIZE"
    CROSS_RANK = "HVD_CROSS_RANK"
    CROSS_SIZE = "HVD_CROSS_SIZE"
    RENDEZVOUS_ADDR = "HVD_RENDEZVOUS_ADDR"
    RENDEZVOUS_PORT = "HVD_RENDEZVOUS_PORT"


def _discover(rank, size, local_rank, local_size, cross_rank, cross_size):
    env = os.environ
    if size is None and _Env.SIZE in env:
        rank = int(env.get(_Env.RANK, "0"))
        size = int(env[_Env.SIZE])
        local_rank = int(env.get(_Env.LOCAL_RANK, str(rank)))
        local_size = int(env.get(_Env.LOCAL_SIZE, str(size)))
        cross_rank = int(env.get(_Env.CROSS_RANK, "0"))
        cross_size = int(env.get(_Env.CROSS_SIZE, "1"))
    if size is None:
        # TPU pod / JAX multi-host metadata (runner/discovery.py): slice
        # coordinates become the local/cross split the controller uses.
        from horovod_tpu.runner import discovery

        topo = discovery.discover()
        if topo is not None:
            rank, size = topo.rank, topo.size
            local_rank, local_size = topo.local_rank, topo.local_size
            cross_rank, cross_size = topo.cross_rank, topo.cross_size
    if size is None:
        rank, size = 0, 1
    if local_size is None:
        local_rank, local_size = 0, 1
    if cross_rank is None:
        cross_rank, cross_size = rank // max(local_size, 1), (
            size // max(local_size, 1) or 1)
    return rank, size, local_rank, local_size, cross_rank, cross_size


def init(
    rank: Optional[int] = None,
    size: Optional[int] = None,
    local_rank: Optional[int] = None,
    local_size: Optional[int] = None,
    cross_rank: Optional[int] = None,
    cross_size: Optional[int] = None,
) -> None:
    """Initialize the horovod_tpu runtime for this process.

    Idempotent (second call is a no-op), matching ``InitializeHorovodOnce``
    (operations.cc:593-639).
    """
    global _runtime
    with _lock:
        if _runtime is not None:
            return
        r, s, lr, ls, cr, cs = _discover(
            rank, size, local_rank, local_size, cross_rank, cross_size)
        if s == 1:
            from horovod_tpu.runtime_py import SingleProcessEngine

            _runtime = SingleProcessEngine()
        else:
            _runtime = _make_engine(r, s, lr, ls, cr, cs)
        # Telemetry (docs/metrics.md): the Python engines start it in
        # their own __init__ (direct construction in tests included);
        # this idempotent call covers the native engine too, so the
        # eager-layer collective metrics work under either core.
        from horovod_tpu import telemetry

        telemetry.init_from_env(r, lr or 0, size=s)


def _make_engine(r, s, lr, ls, cr, cs):
    addr = os.environ.get(_Env.RENDEZVOUS_ADDR, "127.0.0.1")
    port = int(os.environ.get(_Env.RENDEZVOUS_PORT, "0"))
    try:
        if os.environ.get("HVD_ELASTIC_EPOCH", "") and \
                os.environ.get("HVD_TPU_CORE", "").lower() not in (
                    "py", "python"):
            # The native engine has no in-process reset path (its epoch
            # is pinned to 0 on the wire), so elastic training requires
            # the Python engine.  `hvdrun --min-np/--max-np` sets
            # HVD_TPU_CORE=py automatically; direct users must too.
            raise NotImplementedError(
                "elastic training (HVD_ELASTIC_EPOCH) is not supported "
                "by the native engine; set HVD_TPU_CORE=py")
        from horovod_tpu.runtime_native import NativeEngine
        from horovod_tpu import native

        native.load()  # raises NativeUnavailable before any rendezvous
    except ImportError as e:
        # Only pre-bootstrap failures (no toolchain / forced via
        # HVD_TPU_CORE=py) fall back.  Failures after the mesh is wired
        # must fail fast — peers have already consumed this rank's
        # rendezvous address, so silently re-bootstrapping under a
        # different engine would hang the whole job.
        from horovod_tpu.runtime_py import PyEngine

        eng = PyEngine(r, s, lr, ls, cr, cs, addr, port)
        eng.native_fallback_reason = str(e)
        return eng
    return NativeEngine(r, s, lr, ls, cr, cs, addr, port)


def _engine():
    if _runtime is None:
        raise ValueError(
            "horovod_tpu has not been initialized; call hvd.init() first.")
    return _runtime


def is_initialized() -> bool:
    return _runtime is not None


def shutdown() -> None:
    global _runtime
    with _lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None
    # Stop the metrics server/flusher (final flush included).  The
    # registry itself keeps counting: an elastic re-form calls
    # shutdown() + init() in the same process and the counters span it.
    from horovod_tpu import telemetry

    telemetry.stop()


def rank() -> int:
    return _engine().rank


def size() -> int:
    return _engine().size


def local_rank() -> int:
    return _engine().local_rank


def local_size() -> int:
    return _engine().local_size


def cross_rank() -> int:
    return _engine().cross_rank


def cross_size() -> int:
    return _engine().cross_size


def is_homogeneous() -> bool:
    """True when every host runs the same number of processes
    (parity: basics.py is_homogeneous / controller state)."""
    return _engine().is_homogeneous


def nccl_built() -> bool:
    """Reference-API compatibility shims: the TPU build has no NCCL/MPI/
    Gloo/CUDA; code gated on these returns False and takes the generic
    path (parity: basics.py *_built probes)."""
    return False


def mpi_built() -> bool:
    return False


def gloo_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def xla_built() -> bool:
    return True


def mpi_enabled() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False


def cache_stats() -> dict:
    """Response-cache counters (hits/misses/evictions/size/capacity).
    Parity: the reference exposes no such API, but its autotuner and
    timeline read equivalent internals; this is the observable surface
    for tests and tuning."""
    return _engine().cache_stats()


def metrics_snapshot() -> dict:
    """JSON-serializable view of this worker's telemetry registry
    (docs/metrics.md): ``{"counters": ..., "gauges": ...,
    "histograms": ...}`` with Prometheus-style series keys, or ``{}``
    when telemetry is off.  Process-global, not engine-bound — counters
    accumulate across elastic engine resets."""
    from horovod_tpu import telemetry

    return telemetry.snapshot()
