"""``@hvd.elastic.run``: in-process gang re-form.

Parity: ``horovod/common/elastic.py`` ``run_fn`` — but where the
reference re-executes the user function after a gloo re-rendezvous
driven by the launcher, here the whole reset happens **in process**: the
wrapper catches the failure, tears the engine down, re-forms the gang
through the launcher's KV rendezvous under a bumped membership epoch,
rolls the state back to the last commit, re-syncs it, and calls the
user function again.  No process is relaunched; survivors keep their
JAX compilation caches and device state.

Failure signals handled:

* :class:`~horovod_tpu.common.types.RanksFailedError` — the coordinator
  evicted dead ranks (heartbeat timeout, PR 1) and broadcast the set, so
  every survivor computes the identical new membership locally.
* A lost-coordinator abort (``RuntimeError`` with the engine's
  ``_abort_reason`` naming the coordinator) — treated as a failure of
  the current rank 0.
* :class:`~horovod_tpu.elastic.driver.HostsUpdatedInterrupt` — no
  failure; the host set changed (a joiner announced itself or the
  discovery script found new hosts), raised collectively by
  ``State.commit()``.

Re-form protocol (KV keys; they span epochs, but are prefixed with the
launch-time ``HVD_RDV_SCOPE`` — captured once as
``HVD_ELASTIC_SCOPE_BASE`` — so a ``--max-restarts`` relaunch never
reads a dead attempt's rosters):

* ``elastic/roster/0/{rank}`` — epoch-0 uid publication (later epochs
  get the roster from the world key below).
* ``elastic/pending/{uid}`` + ``elastic/notify`` — a joiner announces
  itself and bumps the notify counter the commit check polls.
* ``elastic/world/{epoch}`` — the leader (lowest surviving old rank)
  writes the new world as a JSON uid list in rank order; every member
  finds its new rank as its index.  Ordering survivors by old rank makes
  the new rank 0 the lowest surviving committed rank — ``state.sync()``
  can always root at 0.
* ``elastic/assign/{uid}`` — the leader's epoch/rank/size grant a
  polling joiner blocks on before its first ``hvd.init()``.

Each incarnation initializes under ``HVD_ELASTIC_EPOCH=<n>`` (stamped on
every wire frame; stale frames are dropped — ``common/wire.py``) and
``HVD_RDV_SCOPE=elastic-<n>`` (fresh rendezvous namespace, so re-used
ranks never read a previous incarnation's addresses).
"""

from __future__ import annotations

import functools
import json
import os
import socket
import time
from typing import List, Optional, Set

from horovod_tpu.elastic.driver import (
    ElasticDriver,
    HostDiscoveryScript,
    HostsUpdatedInterrupt,
)
from horovod_tpu.telemetry import blackbox as _bb
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.telemetry import trace as _trace
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger

_ASSIGN_TIMEOUT_S = 600.0


def _postmortem_suffix() -> str:
    """Pointer appended to terminal elastic errors: where the flight-
    recorder dumps landed, ready for tools/hvd_postmortem.py."""
    if not env_util.blackbox_enabled():
        return ""
    return f"; postmortem: {env_util.blackbox_dir()}"


def _worker_uid() -> str:
    uid = os.environ.get(env_util.ELASTIC_UID, "")
    return uid or f"{socket.gethostname()}:{os.getpid()}"


class _ElasticContext:
    """Per-process view of the gang across incarnations."""

    def __init__(self):
        from horovod_tpu.runner.http_client import KVClient

        self.uid = _worker_uid()
        addr = os.environ.get("HVD_RENDEZVOUS_ADDR", "127.0.0.1")
        port = int(os.environ.get("HVD_RENDEZVOUS_PORT", "0"))
        self.kv = KVClient(addr, port)
        self.scope = os.environ.get("HVD_ELASTIC_SCOPE_BASE", "")
        self.epoch = env_util.get_int(env_util.ELASTIC_EPOCH, 0)
        self.min_np = env_util.get_int(env_util.ELASTIC_MIN_NP, 1)
        self.max_np = env_util.get_int(env_util.ELASTIC_MAX_NP, 1 << 30)
        self.check_interval_s = env_util.get_float(
            env_util.ELASTIC_CHECK_INTERVAL_S, 0.5)
        self.rank = -1
        self.roster: List[str] = []  # uid per rank, current epoch
        self._seen_notify = 0
        self.log = get_logger(0)
        self._driver: Optional[ElasticDriver] = None
        # Host set last seen by the in-process discovery driver.  Kept
        # here (not in the driver) because the driver is restarted at
        # every re-form: its first poll is a baseline snapshot, and only
        # a change against THIS set is a real membership update —
        # otherwise every restart would re-publish and re-form forever.
        self._known_hosts: Optional[Set[str]] = None

    def key(self, suffix: str) -> str:
        """KV key under the attempt's scope base (isolates relaunches)."""
        return f"{self.scope}/{suffix}" if self.scope else suffix

    # -- update notifications ------------------------------------------

    def has_pending_update(self) -> bool:
        v = self.kv.get(self.key("elastic/notify"))
        return int(v) > self._seen_notify if v else False

    def consume_updates(self) -> None:
        v = self.kv.get(self.key("elastic/notify"))
        self._seen_notify = int(v) if v else 0

    def publish_update(self) -> None:
        v = self.kv.get(self.key("elastic/notify"))
        self.kv.put(self.key("elastic/notify"),
                    str((int(v) if v else 0) + 1))

    # -- roster ---------------------------------------------------------

    def gather_initial_roster(self) -> None:
        """Epoch 0: every rank publishes its uid and reads the others'
        (same O(size) pattern the bootstrap uses for addresses)."""
        from horovod_tpu import basics

        self.rank = basics.rank()
        size = basics.size()
        self.kv.put(self.key(f"elastic/roster/{self.epoch}/{self.rank}"),
                    self.uid)
        timeout = env_util.get_float("HVD_START_TIMEOUT", 120.0)
        self.roster = [
            self.kv.wait_get(self.key(f"elastic/roster/{self.epoch}/{r}"),
                             timeout=timeout)
            for r in range(size)]

    # -- discovery driver (rank 0, in-process notification mode) -------

    def maybe_start_driver(self) -> None:
        script = os.environ.get(env_util.HOST_DISCOVERY_SCRIPT, "")
        if not script or self.rank != 0 or self._driver is not None:
            return

        def on_update(epoch, added, removed):
            # The driver's first poll (epoch 1) reports the full current
            # set as "added"; later polls are incremental.
            current = set(added) if epoch == 1 else \
                (self._known_hosts | set(added)) - set(removed)
            if self._known_hosts is not None and \
                    current != self._known_hosts:
                # Publication only — workers agree to interrupt at a
                # commit (state.check_host_updates), never mid-step.
                self.publish_update()
            self._known_hosts = current

        self._driver = ElasticDriver(
            HostDiscoveryScript(script), self.min_np, self.max_np,
            on_hosts_updated=on_update)
        self._driver.start()

    def stop_driver(self) -> None:
        if self._driver is not None:
            self._driver.stop()
            self._driver = None


def _engine_abort_reason() -> Optional[str]:
    from horovod_tpu import basics

    eng = basics._runtime
    if eng is not None and getattr(eng, "_aborted", False):
        return getattr(eng, "_abort_reason", None) or "aborted"
    return None


def _timeline_event(name: str, **args) -> None:
    from horovod_tpu import basics

    eng = basics._runtime
    tl = getattr(eng, "timeline", None) if eng is not None else None
    if tl is not None and tl.enabled:
        tl.elastic_event(name, **args)


def _set_world_env(rank: int, size: int, epoch: int) -> None:
    # Post-reset topology is flat (each survivor is its own block):
    # hierarchical paths stay off until a full relaunch rebuilds the
    # host-grouped layout.
    os.environ["HVD_RANK"] = str(rank)
    os.environ["HVD_SIZE"] = str(size)
    os.environ["HVD_LOCAL_RANK"] = "0"
    os.environ["HVD_LOCAL_SIZE"] = "1"
    os.environ["HVD_CROSS_RANK"] = str(rank)
    os.environ["HVD_CROSS_SIZE"] = str(size)
    os.environ[env_util.ELASTIC_EPOCH] = str(epoch)
    base = os.environ.get("HVD_ELASTIC_SCOPE_BASE", "")
    os.environ["HVD_RDV_SCOPE"] = (
        f"{base}.elastic-{epoch}" if base else f"elastic-{epoch}")


def quorum_lost(roster_size: int, failed: Set[int]) -> bool:
    """True when the side of the partition this process is on does NOT
    hold a re-form quorum of the last-committed ``roster_size`` members.

    Strict majority wins; an EXACT half is broken by which side still
    holds old rank 0 (two live halves must never both win, and exactly
    one holds it).  The honest limit: when rank 0 is truly dead in an
    even split, both sides lose and the job needs a full relaunch
    (docs/fault_tolerance.md)."""
    n_alive = roster_size - len(failed)
    return (2 * n_alive < roster_size
            or (2 * n_alive == roster_size and 0 in failed))


def _reform(ctx: _ElasticContext, failed: Set[int]) -> None:
    """Tear down, compute the new world, and re-init under a new epoch."""
    from horovod_tpu import basics, process_sets

    t_reform0 = time.monotonic_ns()
    # Quorum gate (HVD_QUORUM, default on): re-form only when a STRICT
    # majority of the last-committed membership survived.  A network
    # partition makes both sides see "the others failed" — without the
    # gate each side would re-form its own sibling gang under the same
    # scope and split-brain the job.  The majority side proceeds; a
    # minority self-terminates with a PARTITION_MINORITY verdict.
    # Recorded BEFORE teardown so the still-live timeline and flight
    # recorder capture the verdict.
    n_alive = len(ctx.roster) - len(failed)
    if env_util.quorum_on() and ctx.roster \
            and quorum_lost(len(ctx.roster), failed):
        _timeline_event("PARTITION_MINORITY", alive=n_alive,
                        roster=len(ctx.roster), failed=sorted(failed))
        _bb.note("partition.minority", t_reform0, alive=n_alive,
                 roster=len(ctx.roster), failed=sorted(failed))
        _bb.dump("partition_minority",
                 f"alive={n_alive}/{len(ctx.roster)}")
        print(f"PARTITION_MINORITY: only {n_alive} of "
              f"{len(ctx.roster)} last-committed members reachable; "
              "refusing to re-form a minority gang", flush=True)
        ctx.stop_driver()
        basics.shutdown()
        raise RuntimeError(
            f"PARTITION_MINORITY: {n_alive}/{len(ctx.roster)} members "
            f"reachable after failure of rank(s) {sorted(failed)} — no "
            "strict majority of the last-committed membership; "
            "self-terminating instead of re-forming a split-brain "
            "sibling gang" + _postmortem_suffix())
    if 0 in failed:
        _tmx.inc_counter("hvd_leader_failovers_total")
        # Leader failover is a terminal event for the old incarnation:
        # dump before teardown so the evidence names the dead hub.
        _bb.note("leader.failover", t_reform0, failed=sorted(failed),
                 epoch=ctx.epoch)
        _bb.dump("leader_failover", f"failed={sorted(failed)}")
    _timeline_event("ELASTIC_RESET", failed=sorted(failed))
    ctx.stop_driver()
    basics.shutdown()
    process_sets.reset()  # ranks are renumbered; old sets are meaningless

    new_epoch = ctx.epoch + 1
    survivors = [uid for r, uid in enumerate(ctx.roster) if r not in failed]
    if ctx.uid not in survivors:
        raise RuntimeError(
            "this rank was evicted from the gang; cannot re-join the "
            "same incarnation (restart the process to re-join)"
            + _postmortem_suffix())

    if survivors and survivors[0] == ctx.uid:
        # Leader: lowest surviving old rank.  Admit pending joiners up
        # to max_np, publish the world, grant the joiners.
        prefix = ctx.key("elastic/pending/")
        pending = [k[len(prefix):] for k in ctx.kv.list(prefix)]
        pending = [u for u in pending if u not in survivors]
        room = max(0, ctx.max_np - len(survivors))
        admitted, deferred = pending[:room], pending[room:]
        world = survivors + admitted
        if len(world) < ctx.min_np:
            raise RuntimeError(
                f"only {len(world)} worker(s) left after failure of "
                f"rank(s) {sorted(failed)}, below --min-np={ctx.min_np}; "
                f"exiting for a full relaunch" + _postmortem_suffix())
        ctx.kv.put(ctx.key(f"elastic/world/{new_epoch}"), json.dumps(world))
        ctx.kv.put(ctx.key("elastic/epoch"), str(new_epoch))
        for i, uid in enumerate(world):
            if uid in admitted:
                ctx.kv.put(ctx.key(f"elastic/assign/{uid}"), json.dumps(
                    {"epoch": new_epoch, "rank": i, "size": len(world)}))
                ctx.kv.delete(ctx.key(f"elastic/pending/{uid}"))
        if deferred:
            ctx.log.info("%d joiner(s) deferred (at --max-np=%d)",
                         len(deferred), ctx.max_np)
    else:
        timeout = env_util.get_float("HVD_START_TIMEOUT", 120.0)
        world = json.loads(ctx.kv.wait_get(
            ctx.key(f"elastic/world/{new_epoch}"), timeout=timeout))
        if len(world) < ctx.min_np:
            raise RuntimeError(
                f"re-formed world of {len(world)} is below "
                f"--min-np={ctx.min_np}; exiting for a full relaunch"
                + _postmortem_suffix())

    new_rank = world.index(ctx.uid)
    _set_world_env(new_rank, len(world), new_epoch)
    basics.init()
    ctx.epoch = new_epoch
    ctx.rank = new_rank
    ctx.roster = world
    ctx.consume_updates()
    ctx.maybe_start_driver()
    _tmx.inc_counter("hvd_elastic_reforms_total")
    # Epoch change on the flight recorder (the re-formed engine's
    # from_env restamped rank/epoch on the surviving ring).
    _bb.note("elastic.reform", t_reform0, epoch=new_epoch,
             size=len(world), failed=sorted(failed))
    if 0 in failed:
        # The gang's hub died and the lowest surviving old rank was
        # elected leader by the world protocol above.  Recorded after
        # re-init: before the rank renumbering the promoted process had
        # no timeline (only rank 0 writes one), so an earlier emit
        # would land nowhere — the dead hub can't record its own death.
        _timeline_event("LEADER_FAILOVER", failed=sorted(failed),
                        epoch=ctx.epoch - 1, new_leader=new_rank == 0)
    _timeline_event("ELASTIC_REFORM", epoch=new_epoch, size=len(world))
    # Emitted AFTER basics.init(): the re-formed engine's tracer (a
    # fresh file under the same HVD_TRACE_DIR, appended by epoch) is
    # the one that exists to record it.
    _trace.emit("elastic.reform", t_reform0, time.monotonic_ns(),
                epoch=new_epoch, size=len(world), failed=sorted(failed))
    ctx.log.info("gang re-formed: epoch %d, rank %d/%d",
                 new_epoch, new_rank, len(world))


# -- evict-and-replay ---------------------------------------------------
# When the failure was a CollectiveTimeoutError, every survivor retained
# copies of the aborted fused reduction's original inputs
# (runtime_py.retain_aborted_batch); after the re-form the wrapper
# replays them so the batch is not lost with the wedged rank.

_last_replay: Optional[dict] = None


def last_replay_results() -> Optional[dict]:
    """Results of the most recent evict-and-replay (original tensor
    name -> reduced array over the re-formed gang), or None if no
    replay has run in this process."""
    return _last_replay


def _replay_aborted_batch(ctx: _ElasticContext,
                          old_roster: List[str]) -> None:
    global _last_replay
    from horovod_tpu import runtime_py
    from horovod_tpu.ops import eager

    batch = runtime_py.take_retained_batch()
    if not batch:
        return
    if not set(ctx.roster) <= set(old_roster):
        # A joiner was admitted in this re-form: it holds no retained
        # inputs, so a survivor-only replay would desync the global
        # negotiation.  Drop the batch — the training loop restarts
        # from its last commit instead.
        ctx.log.warning(
            "dropping the retained aborted batch: new worker(s) "
            "joined during the re-form")
        return
    # Async-submit the whole batch so the coordinator re-fuses it like
    # the original launch; names are epoch-scoped so the replay never
    # collides with the training loop's own tensor names.
    t_replay0 = time.monotonic_ns()
    handles = [
        (item["name"], eager.allreduce_async(
            item["array"], name=f"replay.e{ctx.epoch}.{item['name']}",
            op=item["op"], prescale_factor=item["prescale"],
            postscale_factor=item["postscale"]))
        for item in batch]
    _last_replay = {nm: eager.synchronize(h) for nm, h in handles}
    _timeline_event("ELASTIC_REPLAY", epoch=ctx.epoch,
                    tensors=len(handles))
    _trace.emit("elastic.replay", t_replay0, time.monotonic_ns(),
                epoch=ctx.epoch, tensors=len(handles))
    ctx.log.info("replayed %d aborted tensor(s) on the re-formed gang",
                 len(handles))


def _join_as_new_worker(ctx: _ElasticContext) -> None:
    """Late worker: announce, then block for an epoch assignment instead
    of bootstrapping at epoch 0."""
    from horovod_tpu import basics

    ctx.kv.put(ctx.key(f"elastic/pending/{ctx.uid}"), "1")
    ctx.publish_update()
    deadline = time.monotonic() + env_util.get_float(
        "HVD_ELASTIC_JOIN_TIMEOUT", _ASSIGN_TIMEOUT_S)
    while True:
        v = ctx.kv.get(ctx.key(f"elastic/assign/{ctx.uid}"))
        if v is not None:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                "no gang admitted this joiner (is a job with "
                "--max-np headroom running?)")
        time.sleep(ctx.check_interval_s)
    grant = json.loads(v)
    os.environ.pop(env_util.ELASTIC_JOINER, None)
    _set_world_env(grant["rank"], grant["size"], grant["epoch"])
    basics.init()
    ctx.epoch = grant["epoch"]
    ctx.rank = grant["rank"]
    timeout = env_util.get_float("HVD_START_TIMEOUT", 120.0)
    ctx.roster = json.loads(ctx.kv.wait_get(
        ctx.key(f"elastic/world/{ctx.epoch}"), timeout=timeout))
    ctx.consume_updates()


def run(func):
    """Decorator: ``@hvd.elastic.run`` around a training function whose
    first argument is a :class:`~horovod_tpu.elastic.state.State`.

    The function is (re)invoked after every gang re-form with the state
    rolled back to its last commit and synced from the new rank 0 — it
    must resume from the state (e.g. ``state.batch``/``state.epoch``),
    not from scratch.
    """

    @functools.wraps(func)
    def wrapper(state, *args, **kwargs):
        from horovod_tpu import basics
        from horovod_tpu.common.types import (
            CollectiveTimeoutError,
            RanksFailedError,
        )

        # The native engine has no in-process reset path; elastic always
        # runs the Python engine (hvdrun does the same).
        os.environ.setdefault("HVD_TPU_CORE", "py")
        # Freeze the launch-time rendezvous scope before any re-form
        # rewrites HVD_RDV_SCOPE: every elastic/* key and every later
        # scope derives from this base, so a --max-restarts relaunch
        # (which sets a fresh attemptN scope) never collides with keys
        # from a dead attempt.
        if "HVD_ELASTIC_SCOPE_BASE" not in os.environ:
            os.environ["HVD_ELASTIC_SCOPE_BASE"] = \
                os.environ.get("HVD_RDV_SCOPE", "")
        joined = env_util.get_bool(env_util.ELASTIC_JOINER, False)
        if not joined and not basics.is_initialized():
            os.environ.setdefault(env_util.ELASTIC_EPOCH, "0")
            basics.init()
        ctx = _ElasticContext()
        state._elastic_ctx = ctx
        if joined:
            # A joiner never bootstraps the epoch-0 mesh: it blocks for
            # an epoch assignment and first initializes there.
            _join_as_new_worker(ctx)
        else:
            ctx.gather_initial_roster()
            ctx.consume_updates()
            ctx.maybe_start_driver()
        try:
            while True:
                replay = False
                try:
                    if joined:
                        # First sync delivers the gang's state (and the
                        # matching collective on the incumbents runs in
                        # their post-reset sync below).
                        state.sync()
                        joined = False
                    return func(state, *args, **kwargs)
                except RanksFailedError as e:
                    failed = set(e.ranks)
                    # A gang-agreed collective abort (hung rank, not a
                    # dead one) leaves the fused batch's inputs retained
                    # on every survivor: replay after the re-form.
                    replay = isinstance(e, CollectiveTimeoutError)
                except HostsUpdatedInterrupt:
                    failed = set()
                except RuntimeError:
                    # A dead hub surfaces twice on a worker: the training
                    # /serving thread's collective fails with a raw
                    # socket error FIRST, and the engine's own
                    # lost-coordinator abort (recv-loop EOF -> worker
                    # cycle) lands a beat later.  Poll briefly for the
                    # abort verdict before concluding this RuntimeError
                    # is not a hub failure.
                    reason = _engine_abort_reason()
                    if reason is None:
                        deadline = time.monotonic() + 2.0
                        while reason is None and \
                                time.monotonic() < deadline:
                            time.sleep(0.05)
                            reason = _engine_abort_reason()
                    if reason is None or "coordinator" not in reason:
                        raise
                    # The star's hub died: that is a failure of rank 0.
                    failed = {0}
                old_roster = list(ctx.roster)
                _reform(ctx, failed)
                state.on_reset()
                state.restore()
                state.sync()
                if replay:
                    _replay_aborted_batch(ctx, old_roster)
        finally:
            ctx.stop_driver()
            state._elastic_ctx = None

    return wrapper
