"""Elastic training: commit/rollback state, host discovery, and
in-process gang re-form (docs/elastic.md).

Parity: ``horovod.elastic`` — ``@hvd.elastic.run`` around a training
function taking a :class:`State` first; on rank failure or host-set
change the gang re-forms in process under a new membership epoch, the
state rolls back to its last ``commit()`` and re-syncs from the new
rank 0, and the function is invoked again.
"""

from horovod_tpu.elastic.driver import (  # noqa: F401
    ElasticDriver,
    FixedHostDiscovery,
    HostDiscoveryScript,
    HostsUpdatedInterrupt,
)
from horovod_tpu.elastic.run import (  # noqa: F401
    last_replay_results,
    run,
)
from horovod_tpu.elastic.state import (  # noqa: F401
    KerasState,
    ObjectState,
    State,
    TorchState,
)
