"""Host discovery + membership loop for elastic training.

Parity: ``horovod/runner/elastic/discovery.py`` (HostDiscoveryScript and
the HostManager polling loop inside ElasticDriver).  The driver runs on
the coordinator side — inside ``hvdrun`` for launcher-managed elasticity,
or inside the rank-0 process when a job opts in directly — and re-polls
the host set on an interval:

* ``--host-discovery-script`` / HVD_HOST_DISCOVERY_SCRIPT: an executable
  printing one ``hostname[:slots]`` per line (the reference's contract),
* the launcher's :class:`~horovod_tpu.runner.hosts.HostBlacklist` filters
  hosts that recently killed workers,
* TPU pod metadata (``runner/discovery.py``) seeds the initial host set
  when no script is given.

Each accepted membership change bumps the **membership epoch** — the
integer stamped on every wire list frame (``common/wire.py``) and on the
rendezvous scope, so each gang incarnation is isolated from the last.
The in-process re-form protocol that consumes these epochs lives in
``elastic/run.py``.
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional

from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger


class HostsUpdatedInterrupt(Exception):
    """The discovered host set changed: re-form the gang at a commit
    point instead of waiting for a failure.  Raised by
    ``State.commit()`` (all ranks raise in the same commit — see
    ``state.check_host_updates``), caught by ``@hvd.elastic.run``."""

    def __init__(self, res: Optional[dict] = None):
        self.res = res or {}
        super().__init__("host set updated; gang re-form requested")


class HostDiscoveryScript:
    """Runs a user script that prints ``hostname[:slots]`` per line.

    Parity: ``horovod/runner/elastic/discovery.py`` HostDiscoveryScript.
    A failing or hanging script yields the *previous* host set (the
    driver keeps running on stale-but-sane data rather than evicting
    everyone because discovery hiccupped).
    """

    def __init__(self, script: str, default_slots: int = 1,
                 timeout_s: float = 30.0):
        self.script = script
        self.default_slots = default_slots
        self.timeout_s = timeout_s

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(
            self.script, shell=True, timeout=self.timeout_s)
        hosts: Dict[str, int] = {}
        for line in out.decode("utf-8").splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if ":" in line:
                host, slots = line.rsplit(":", 1)
                hosts[host.strip()] = int(slots)
            else:
                hosts[line] = self.default_slots
        return hosts


class FixedHostDiscovery:
    """Static host set (no script): the parsed ``-H`` list, or TPU pod
    metadata (``TPU_WORKER_HOSTNAMES``) when available."""

    def __init__(self, hosts: Optional[Dict[str, int]] = None):
        if hosts is None:
            hosts = {}
            import os

            names = os.environ.get("TPU_WORKER_HOSTNAMES", "")
            for h in names.split(","):
                if h.strip():
                    hosts[h.strip()] = 1
        self._hosts = dict(hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class ElasticDriver:
    """Membership loop: polls discovery, filters the blacklist, and bumps
    the epoch on every accepted change.

    ``on_hosts_updated(epoch, added, removed)`` fires from the poll
    thread on each change; the launcher uses it to start workers on new
    hosts, the in-process path to publish an update notice to the KV
    store (``run.py``).
    """

    def __init__(self, discovery, min_np: int, max_np: int,
                 blacklist=None, interval_s: Optional[float] = None,
                 on_hosts_updated: Optional[Callable] = None):
        self.discovery = discovery
        self.min_np = min_np
        self.max_np = max_np
        self.blacklist = blacklist
        self.interval_s = interval_s if interval_s is not None else \
            env_util.get_float(env_util.ELASTIC_DISCOVERY_INTERVAL_S, 1.0)
        self.on_hosts_updated = on_hosts_updated
        self.log = get_logger(0)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._hosts: Dict[str, int] = {}
        self._epoch = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- polling --------------------------------------------------------

    def start(self) -> None:
        self._poll_once()  # synchronous first poll: start() returns with
        # a host set, so wait_for_available_slots has data immediately
        self._thread = threading.Thread(
            target=self._poll_loop, name="hvd-elastic-driver", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5)

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._poll_once()

    def _poll_once(self) -> None:
        try:
            found = self.discovery.find_available_hosts_and_slots()
        except Exception as e:
            self.log.warning("host discovery failed (%r); keeping the "
                             "current host set", e)
            return
        if self.blacklist is not None:
            found = {h: s for h, s in found.items()
                     if not self.blacklist.is_blacklisted(h)}
        with self._cv:
            if found == self._hosts:
                return
            added = sorted(set(found) - set(self._hosts))
            removed = sorted(set(self._hosts) - set(found))
            self._hosts = found
            self._epoch += 1
            epoch = self._epoch
            self._cv.notify_all()
        self.log.info("host set changed (epoch %d): +%s -%s",
                      epoch, added, removed)
        if self.on_hosts_updated is not None:
            self.on_hosts_updated(epoch, added, removed)

    # -- queries --------------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def hosts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hosts)

    def slots(self) -> int:
        with self._lock:
            return sum(self._hosts.values())

    def wait_for_available_slots(self, np: int,
                                 timeout: float = 600.0) -> Dict[str, int]:
        """Block until discovery reports at least ``np`` slots (the
        reference blocks the same way before each (re)launch)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while sum(self._hosts.values()) < np:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"discovery found {sum(self._hosts.values())} "
                        f"slot(s), need {np} (after {timeout:.0f}s)")
                self._cv.wait(min(remaining, self.interval_s))
            return dict(self._hosts)
