"""Elastic training state: commit / rollback / sync.

Parity: ``horovod/common/elastic.py`` (State, ObjectState) and the thin
framework adapters in ``horovod/torch/elastic.py`` /
``horovod/tensorflow/keras/elastic.py``.

The contract ``@hvd.elastic.run`` relies on:

* ``commit()`` — in-memory snapshot of the registered values, taken at a
  point the training loop could restart from.  Called **collectively**
  (same count on every rank): it also runs the host-update check, which
  agrees via a 1-element MIN-allreduce so either every rank raises
  :class:`~horovod_tpu.elastic.driver.HostsUpdatedInterrupt` at the same
  commit or none does — a lone rank interrupting would strand the others
  in a collective.
* ``restore()`` — roll back to the last commit.  After a rank failure the
  survivors may have half-applied a step whose allreduce completed with
  zero stand-ins; rolling back to the commit makes the re-formed gang
  bit-consistent again.
* ``sync(root=0)`` — broadcast the state from ``root``.  The re-form
  protocol orders survivors by old rank, so new rank 0 is the lowest
  surviving committed rank — the canonical source.  Joiners receive the
  whole state here, which is what makes growth checkpoint-free.
"""

from __future__ import annotations

import copy
import time
from typing import Callable, List, Optional

import numpy as np


class State:
    """Base elastic state; subclasses define what save/restore/sync move.

    ``register_reset_callbacks``: hooks run after every gang re-form
    (new world size — re-partition data, rescale the learning rate...).
    """

    def __init__(self):
        self._reset_callbacks: List[Callable] = []
        # Attached by @hvd.elastic.run; None outside an elastic wrapper
        # (commit() then degrades to a plain snapshot).
        self._elastic_ctx = None
        self._commit_serial = 0
        self._last_host_poll = 0.0
        self._update_pending = False

    def register_reset_callbacks(self, callbacks: List[Callable]) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        # Commit-check collectives are named by this serial; every member
        # of the re-formed gang must agree on it, and a freshly admitted
        # joiner starts at 0 — so survivors rewind theirs too.
        self._commit_serial = 0
        self._last_host_poll = 0.0
        self._update_pending = False
        self.reset()
        for cb in self._reset_callbacks:
            cb()

    def reset(self) -> None:  # subclass hook
        pass

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self, root: int = 0) -> None:
        raise NotImplementedError

    def commit(self) -> None:
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        """Raise ``HostsUpdatedInterrupt`` once every rank has seen a
        pending membership update (joiner announcement or discovery
        change published to the KV store by the driver)."""
        ctx = self._elastic_ctx
        if ctx is None:
            return
        from horovod_tpu.elastic.driver import HostsUpdatedInterrupt
        from horovod_tpu.ops import eager

        now = time.monotonic()
        if not self._update_pending and \
                now - self._last_host_poll >= ctx.check_interval_s:
            self._last_host_poll = now
            self._update_pending = ctx.has_pending_update()
        # Collective agreement: MIN over "I have seen the update" — 1 on
        # every rank only when all have, so all interrupt together.
        self._commit_serial += 1
        flag = np.array([1 if self._update_pending else 0], np.int32)
        agreed = eager.allreduce(
            flag, op=eager.ReduceOp.MIN,
            name=f"elastic.commit_check.{self._commit_serial}")
        if int(agreed[0]) >= 1:
            self._update_pending = False
            raise HostsUpdatedInterrupt()


def _snapshot(value):
    if isinstance(value, np.ndarray):
        return value.copy()
    return copy.deepcopy(value)


class ObjectState(State):
    """State over arbitrary attributes (pytrees, arrays, scalars).

    ``ObjectState(model=params, optimizer=opt_state, batch=0, epoch=0)``
    exposes each kwarg as an attribute; save/restore/sync move all of
    them.  Parity: ``horovod/common/elastic.py`` ObjectState.
    """

    def __init__(self, **kwargs):
        super().__init__()
        self._known = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._saved = {}
        self.save()

    def save(self) -> None:
        self._saved = {k: _snapshot(getattr(self, k)) for k in self._known}

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, _snapshot(v))

    def sync(self, root: int = 0) -> None:
        from horovod_tpu.ops import eager

        values = {k: getattr(self, k) for k in self._known}
        synced = eager.broadcast_object(values, root_rank=root,
                                        name="elastic.state")
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()


class TorchState(State):
    """Elastic state over a torch ``model``/``optimizer`` pair (thin
    adapter; parity: ``horovod/torch/elastic/state.py``).  Extra kwargs
    ride along as an embedded :class:`ObjectState`."""

    def __init__(self, model=None, optimizer=None, **kwargs):
        try:
            import torch  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "TorchState requires torch; use ObjectState for "
                "framework-agnostic pytrees") from e
        super().__init__()
        self.model = model
        self.optimizer = optimizer
        self._extra = ObjectState(**kwargs) if kwargs else None
        self._saved_model = None
        self._saved_opt = None
        self.save()

    def __getattr__(self, name):
        extra = self.__dict__.get("_extra")
        if extra is not None and name in extra._known:
            return getattr(extra, name)
        raise AttributeError(name)

    def save(self) -> None:
        if self.model is not None:
            self._saved_model = copy.deepcopy(self.model.state_dict())
        if self.optimizer is not None:
            self._saved_opt = copy.deepcopy(self.optimizer.state_dict())
        if self._extra is not None:
            self._extra.save()

    def restore(self) -> None:
        if self.model is not None and self._saved_model is not None:
            self.model.load_state_dict(copy.deepcopy(self._saved_model))
        if self.optimizer is not None and self._saved_opt is not None:
            self.optimizer.load_state_dict(copy.deepcopy(self._saved_opt))
        if self._extra is not None:
            self._extra.restore()

    def sync(self, root: int = 0) -> None:
        from horovod_tpu.ops import eager

        if self.model is not None:
            sd = eager.broadcast_object(self.model.state_dict(),
                                        root_rank=root,
                                        name="elastic.torch.model")
            self.model.load_state_dict(sd)
        if self.optimizer is not None:
            sd = eager.broadcast_object(self.optimizer.state_dict(),
                                        root_rank=root,
                                        name="elastic.torch.opt")
            self.optimizer.load_state_dict(sd)
        if self._extra is not None:
            self._extra.sync(root)
        self.save()


class KerasState(State):
    """Elastic state over a Keras ``model`` (weights move as numpy via
    ``get_weights``/``set_weights``); parity:
    ``horovod/tensorflow/keras/elastic.py``."""

    def __init__(self, model=None, **kwargs):
        if model is not None and not (hasattr(model, "get_weights")
                                      and hasattr(model, "set_weights")):
            raise TypeError(
                "KerasState needs a model with get_weights/set_weights")
        super().__init__()
        self.model = model
        self._extra = ObjectState(**kwargs) if kwargs else None
        self._saved_weights: Optional[list] = None
        self.save()

    def __getattr__(self, name):
        extra = self.__dict__.get("_extra")
        if extra is not None and name in extra._known:
            return getattr(extra, name)
        raise AttributeError(name)

    def save(self) -> None:
        if self.model is not None:
            self._saved_weights = [np.array(w)
                                   for w in self.model.get_weights()]
        if self._extra is not None:
            self._extra.save()

    def restore(self) -> None:
        if self.model is not None and self._saved_weights is not None:
            self.model.set_weights([w.copy()
                                    for w in self._saved_weights])
        if self._extra is not None:
            self._extra.restore()

    def sync(self, root: int = 0) -> None:
        from horovod_tpu.ops import eager

        if self.model is not None:
            weights = eager.broadcast_object(self.model.get_weights(),
                                             root_rank=root,
                                             name="elastic.keras.model")
            self.model.set_weights(weights)
        if self._extra is not None:
            self._extra.sync(root)
        self.save()
