"""MXNet front-end: ``import horovod_tpu.mxnet as hvd``.

Role parity: ``horovod/mxnet/__init__.py`` — ``DistributedOptimizer``
(gradient allreduce with rescale_grad /= size), gluon
``DistributedTrainer``, and ``broadcast_parameters``.  MXNet is not
shipped in this environment (the project reached end-of-life upstream);
the module degrades to a clear ImportError at use time while keeping
the surface importable for introspection.
"""

from __future__ import annotations

from horovod_tpu.basics import (  # noqa: F401
    cross_rank,
    cross_size,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)

try:
    import mxnet  # noqa: F401

    _HAVE_MXNET = True
except ImportError:
    _HAVE_MXNET = False


def _require_mxnet(what: str):
    if not _HAVE_MXNET:
        raise ImportError(
            f"horovod_tpu.mxnet.{what} requires the `mxnet` package, "
            "which is not installed in this environment. The eager "
            "collective engine itself is framework-agnostic — see "
            "horovod_tpu (JAX), horovod_tpu.torch, or "
            "horovod_tpu.tensorflow for supported front-ends.")


def _from_nd(tensor):
    """NDArray → numpy; anything else passes through untouched."""
    if _HAVE_MXNET and hasattr(tensor, "asnumpy"):
        return tensor.asnumpy()
    return tensor


def _to_nd(out, like):
    """Return ``out`` in the caller's type (NDArray in, NDArray out)."""
    if _HAVE_MXNET and hasattr(like, "asnumpy"):
        import mxnet as mx

        nd = getattr(mx, "nd", None)
        if nd is not None and hasattr(nd, "array"):
            return nd.array(out, dtype=out.dtype)
    return out


def allreduce(tensor, average=None, name=None, op=None):
    """Parity: mxnet/mpi_ops.py ``allreduce`` — accepts an NDArray (or
    anything the eager engine takes: numpy, scalars) and returns the
    combined tensor in the caller's type."""
    from horovod_tpu.ops import eager

    return _to_nd(eager.allreduce(_from_nd(tensor), average=average,
                                  name=name, op=op), tensor)


def allgather(tensor, name=None):
    """Parity: mxnet/mpi_ops.py ``allgather`` (ragged first dims)."""
    from horovod_tpu.ops import eager

    return _to_nd(eager.allgather(_from_nd(tensor), name=name), tensor)


def broadcast(tensor, root_rank=0, name=None):
    """Parity: mxnet/mpi_ops.py ``broadcast``."""
    from horovod_tpu.ops import eager

    return _to_nd(eager.broadcast(_from_nd(tensor), root_rank=root_rank,
                                  name=name), tensor)


def reducescatter(tensor, average=None, name=None, op=None):
    """Reduce across ranks, scatter over dim 0 (the reference project
    added ``hvd.reducescatter`` right after the v0.19 line)."""
    from horovod_tpu.ops import eager

    return _to_nd(eager.reducescatter(_from_nd(tensor), average=average,
                                      name=name, op=op), tensor)


def DistributedOptimizer(optimizer, op=None):
    """Parity: mxnet/__init__.py:40-69 — wraps an mxnet optimizer,
    allreducing gradients with rescale_grad divided by world size."""
    _require_mxnet("DistributedOptimizer")
    from horovod_tpu.ops import eager
    import numpy as np

    class _DistributedOptimizer(optimizer.__class__):
        def __init__(self, inner):
            self.__dict__.update(inner.__dict__)
            self.rescale_grad = getattr(inner, "rescale_grad", 1.0) / size()

        def _do_allreduce(self, index, grad):
            if size() == 1:
                return
            if isinstance(index, (tuple, list)):
                for i in range(len(index)):
                    out = eager.allreduce(grad[i].asnumpy(),
                                          name=f"mx.grad.{index[i]}",
                                          average=False)
                    grad[i][:] = out
            else:
                out = eager.allreduce(grad.asnumpy(),
                                      name=f"mx.grad.{index}",
                                      average=False)
                grad[:] = out

        def update(self, index, weight, grad, state):
            self._do_allreduce(index, grad)
            super().update(index, weight, grad, state)

        def update_multi_precision(self, index, weight, grad, state):
            self._do_allreduce(index, grad)
            super().update_multi_precision(index, weight, grad, state)

    return _DistributedOptimizer(optimizer)


def DistributedTrainer(params, optimizer, optimizer_params=None):
    """Parity: mxnet/__init__.py:87-108 gluon Trainer wrapper."""
    _require_mxnet("DistributedTrainer")
    import mxnet as mx
    from horovod_tpu.ops import eager

    class _DistributedTrainer(mx.gluon.Trainer):
        def __init__(self):
            param_list = params
            if isinstance(param_list, dict):
                param_list = [param_list[k] for k in sorted(param_list)]
            super().__init__(param_list, optimizer,
                             optimizer_params, kvstore=None)
            self._scale /= size()

        def _allreduce_grads(self):
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for g in param.list_grad():
                        out = eager.allreduce(g.asnumpy(),
                                              name=f"mx.tr.{i}",
                                              average=False)
                        g[:] = out

    return _DistributedTrainer()


def broadcast_parameters(params, root_rank=0):
    """Parity: mxnet/__init__.py broadcast_parameters — works on gluon
    ParameterDict or a plain dict of NDArrays."""
    _require_mxnet("broadcast_parameters")
    from horovod_tpu.ops import eager

    if hasattr(params, "items"):
        items = sorted(params.items())
    else:
        raise ValueError("invalid params of type: %s" % type(params))
    import mxnet as mx

    deferred = getattr(
        getattr(getattr(mx, "gluon", None), "parameter", None),
        "DeferredInitializationError", None) or ()
    for name, p in items:
        try:
            nd = p.data()
        except AttributeError:
            nd = p
        except deferred:
            # Shape-deferred gluon parameter (no forward pass yet):
            # wrap its init so the value is broadcast right after it
            # materializes, keeping ranks in sync without forcing an
            # early forward (same contract as the reference's
            # post-initialization broadcast injection).
            import types as _types

            orig_init = p._init_impl

            def _bcast_after_init(self, *a, _orig=orig_init, _name=name,
                                  **kw):
                _orig(*a, **kw)
                nd2 = self.data()
                out2 = eager.broadcast(
                    nd2.asnumpy(), root_rank=root_rank,
                    name=f"mx.bp.late.{_name}")
                nd2[:] = out2

            p._init_impl = _types.MethodType(_bcast_after_init, p)
            continue
        out = eager.broadcast(nd.asnumpy(), root_rank=root_rank,
                              name=f"mx.bp.{name}")
        nd[:] = out
