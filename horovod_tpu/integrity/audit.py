"""Replica-divergence audit: catch silent bit-level state corruption.

Data-parallel training assumes the replicated parameters are *identical*
on every rank — one flipped bit (bad HBM, a non-deterministic kernel, a
torn host transfer) silently forks the model, and the fork only shows up
much later as an unexplained loss excursion.  The audit makes the
assumption checked:

1. every ``HVD_AUDIT_INTERVAL`` steps each rank fingerprints its
   replicated tree — a bit-pattern sha256 digest per leaf (dtype + shape
   + raw bytes), folded into one 64-bit digest,
2. the per-leaf digest vectors allgather (as int64 bit patterns — the
   wire has no uint64),
3. every rank compares the identical gathered matrix and computes the
   identical verdict: all folded digests equal → clean; otherwise the
   majority digest is canonical (ties break to the digest held by the
   lowest rank) and every other rank is a deviant.

On divergence the audit records ``DIVERGENCE_DETECTED`` on the timeline
and raises :class:`ReplicaDivergenceError` naming the deviant rank(s)
and the first divergent leaf path.  Because the error subclasses
``RanksFailedError`` with ``.ranks`` = the deviants, ``@hvd.elastic.run``
treats it like a dead rank: survivors roll back to the last commit and
re-form without the deviant, and the deviant — which reached the very
same verdict about itself — exits instead of re-joining.

The ``state.bitflip`` fault-injection site lives in
:func:`fingerprint`: an armed ``corrupt`` fault flips one bit of the
first leaf's bytes before digesting, simulating the silent corruption
end to end (tests/test_integrity.py).
"""

from __future__ import annotations

import hashlib
from collections import Counter
from typing import List, Optional, Tuple

import numpy as np

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.common.types import ReplicaDivergenceError
from horovod_tpu.telemetry import blackbox as _bb
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import timeline as timeline_mod


def _digest8(chunks) -> int:
    h = hashlib.sha256()
    for c in chunks:
        h.update(c)
    return int.from_bytes(h.digest()[:8], "little")


def fingerprint(tree, _detail: str = "") -> Tuple[int, List[Tuple[str, int]]]:
    """``(folded, [(leaf_path, digest), ...])`` over a pytree's leaves.

    Digests cover dtype + shape + raw bytes, so a dtype drift and a value
    drift are equally visible.  The fold is a sha256 over the per-leaf
    digests, so any single-leaf change moves the folded digest.
    """
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    flip = _fi.should_corrupt("state.bitflip", _detail)
    per_leaf: List[Tuple[str, int]] = []
    for path, leaf in flat:
        arr = np.asarray(leaf)
        buf = arr.tobytes()
        if flip and buf:
            # The injected silent corruption: one bit of the first
            # audited leaf, exactly what bad memory produces.
            ba = bytearray(buf)
            ba[0] ^= 0x01
            buf = bytes(ba)
            flip = False
        per_leaf.append((
            jax.tree_util.keystr(path),
            _digest8([str(arr.dtype).encode(),
                      np.asarray(arr.shape, np.int64).tobytes(),
                      buf])))
    folded = _digest8(
        [d.to_bytes(8, "little") for _, d in per_leaf])
    return folded, per_leaf


def _verdict(mat: np.ndarray) -> Tuple[List[int], int]:
    """Deviant ranks + the canonical row index, from the folded column.

    Majority digest wins; ties break to the digest held by the lowest
    rank — deterministic, so every rank (deviants included) agrees.
    """
    col = mat[:, 0].tolist()
    counts = Counter(col)
    maxc = max(counts.values())
    canonical = min((d for d, c in counts.items() if c == maxc),
                    key=col.index)
    deviants = [r for r, d in enumerate(col) if d != canonical]
    return deviants, col.index(canonical)


def audit_replicas(tree, name: str = "integrity.audit") -> int:
    """One collective audit round over ``tree`` (replicated state).

    Collective: every rank must call it with its own copy of the same
    logical tree, in the same order.  Returns the folded digest (all
    ranks equal) on success; raises :class:`ReplicaDivergenceError` on
    mismatch.  Works on a single rank too (trivially clean).
    """
    from horovod_tpu import basics
    from horovod_tpu.ops import eager

    folded, per_leaf = fingerprint(tree, _detail=name)
    # Ride the wire as int64 bit patterns (no uint64 on the wire).
    local = np.array([folded] + [d for _, d in per_leaf],
                     dtype=np.uint64).view(np.int64)
    gathered = eager.allgather(local, name=name)
    size = basics.size()
    mat = np.ascontiguousarray(
        np.asarray(gathered).reshape(size, len(per_leaf) + 1)
    ).view(np.uint64)
    if len(set(mat[:, 0].tolist())) == 1:
        return folded
    deviants, canon = _verdict(mat)
    leaf_path = ""
    for j in range(1, mat.shape[1]):
        if any(mat[r, j] != mat[canon, j] for r in deviants):
            leaf_path = per_leaf[j - 1][0]
            break
    digests = {r: f"{int(mat[r, 0]):016x}" for r in range(size)}
    timeline_mod.engine_event(
        timeline_mod.DIVERGENCE_DETECTED, ranks=deviants,
        leaf=leaf_path, digests=digests)
    # Terminal event: dump the flight recorder before the raise so the
    # postmortem names the deviant rank(s) with the leaf that diverged.
    _bb.note("replica.divergence", 0, ranks=deviants, leaf=leaf_path)
    _bb.dump("replica_divergence",
             f"deviants={deviants} leaf={leaf_path}")
    raise ReplicaDivergenceError(deviants, leaf_path, digests)


class ReplicaAuditor:
    """Paced audit driver for a training loop.

    Call :meth:`maybe_audit` once per step on every rank; every
    ``interval`` steps (``HVD_AUDIT_INTERVAL``; 0 disables) it runs
    :func:`audit_replicas`.

    Pass the gang-synchronized step (the committed ``state.step`` in an
    elastic loop) as ``step`` — the audit fires when
    ``step % interval == 0``, so every rank, *including a joiner whose
    process just started*, paces off the same clock.  Without ``step``
    the pacing falls back to a process-local call counter, which is only
    safe when every rank's process has made the identical sequence of
    calls (NOT true across an elastic re-form that admits a joiner: the
    joiner's counter starts at 0 while incumbents are mid-interval, and
    the collective allgather cross-matches or hangs).
    """

    def __init__(self, interval: Optional[int] = None):
        self.interval = interval if interval is not None else \
            env_util.get_int(env_util.AUDIT_INTERVAL, 0)
        if self.interval < 0:
            raise ValueError("audit interval must be >= 0")
        self.audits = 0     # audit rounds completed clean
        self._step = 0

    def maybe_audit(self, tree, step: Optional[int] = None) -> bool:
        """Returns True when an audit ran (and passed) this step."""
        if self.interval <= 0:
            return False
        if step is None:
            self._step += 1
            step = self._step
        else:
            step = int(step)
            self._step = step
        if step % self.interval:
            return False
        audit_replicas(tree, name=f"integrity.audit.{step}")
        self.audits += 1
        return True
