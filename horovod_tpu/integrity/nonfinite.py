"""Non-finite gradient guard: agreed skip/zero/raise on NaN or Inf.

One rank's NaN gradient poisons every replica through the allreduce —
and worse, ranks that *locally* diverge on whether to apply a step
strand each other in collectives.  The guard makes the decision
collective and deterministic:

1. each rank computes a local 1-element ``any non-finite`` flag over its
   gradient tree,
2. the flags agree via a 1-element **MAX-allreduce** (if any rank saw a
   non-finite value, every rank sees 1),
3. every rank applies the same policy to the same step:

   * ``skip`` — drop the step (parameters and inner optimizer state
     unchanged) and count it,
   * ``zero`` — replace non-finite gradient entries with zeros and
     apply the step,
   * ``raise`` — behave like ``skip``, but raise
     :class:`NonFiniteGradientError` once ``HVD_NONFINITE_LIMIT``
     *consecutive* steps agreed non-finite (the loss-scale-collapsed /
     diverged-model escape hatch),
   * ``off`` — guard disabled, zero extra collectives (the default;
     pinned by tests/test_integrity.py).

The policy comes from ``HVD_NONFINITE_POLICY`` unless passed explicitly
to :func:`~horovod_tpu.parallel.optimizer.DistributedOptimizer`.  Agreed
skips are recorded on the timeline as ``NONFINITE_SKIP`` events and in
process-global counters (:func:`counters`) so survivors of a burst can
be audited after the fact.

Two regimes, matching the optimizer:

* **eager** (``axis=None``): :class:`NonFiniteGuard` runs host-side
  python — the 1-element agreement rides the engine, and ``raise`` is
  fully supported.  The ``grad.nonfinite`` fault-injection site lives
  here (chaos: poison this rank's local gradients with NaN).
* **in-graph**: the same flag/agreement/masking as traced ops; the
  counters live in :class:`GuardState` inside the optimizer state
  (read them with :func:`stats`).  ``raise`` is rejected at wrap time —
  a data-dependent raise cannot cross a jit boundary.
"""

from __future__ import annotations

import threading
from typing import Any, NamedTuple, Optional

import numpy as np

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import timeline as timeline_mod

POLICIES = ("off", "skip", "zero", "raise")

_agg_lock = threading.Lock()
_agg = {"agreed": 0, "skipped": 0}


class NonFiniteGradientError(RuntimeError):
    """``HVD_NONFINITE_LIMIT`` consecutive steps agreed non-finite under
    policy ``raise`` — the model has diverged (or the loss scale
    collapsed); skipping further steps cannot recover it."""

    def __init__(self, consecutive: int, limit: int):
        self.consecutive = consecutive
        self.limit = limit
        super().__init__(
            f"{consecutive} consecutive step(s) had non-finite gradients "
            f"on some rank (limit {limit}); every rank agreed via "
            f"MAX-allreduce and raised together — restore from the last "
            f"good checkpoint (HVD_NONFINITE_POLICY governs this policy)")


def resolve_policy(policy: Optional[str] = None) -> str:
    """Explicit argument beats ``HVD_NONFINITE_POLICY`` beats ``off``."""
    p = (policy if policy is not None
         else env_util.get_str(env_util.NONFINITE_POLICY, "off"))
    p = (p or "off").strip().lower()
    if p not in POLICIES:
        raise ValueError(
            f"unknown non-finite policy {p!r}; expected one of {POLICIES}")
    return p


def consecutive_limit(limit: Optional[int] = None) -> int:
    k = limit if limit is not None else env_util.get_int(
        env_util.NONFINITE_LIMIT, 3)
    if k < 1:
        raise ValueError("non-finite consecutive limit must be >= 1")
    return k


def counters() -> dict:
    """Process-global guard counters: ``agreed`` (steps the gang agreed
    were non-finite) and ``skipped`` (steps actually dropped)."""
    with _agg_lock:
        return dict(_agg)


def reset_counters() -> None:
    with _agg_lock:
        _agg["agreed"] = 0
        _agg["skipped"] = 0


def _bump(key: str) -> None:
    with _agg_lock:
        _agg[key] += 1


def _local_nonfinite(leaves) -> bool:
    for leaf in leaves:
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.all(np.isfinite(arr)):
            return True
    return False


def _poison_first_float_leaf(grads):
    """The ``grad.nonfinite`` chaos site: NaN-fill the first floating
    leaf of this rank's local gradients (what a bad kernel / overflowed
    loss scale produces)."""
    import jax

    leaves, treedef = jax.tree.flatten(grads)
    for i, leaf in enumerate(leaves):
        arr = np.array(np.asarray(leaf), copy=True)
        if arr.dtype.kind == "f":
            arr.fill(np.nan)
            leaves[i] = arr
            break
    return jax.tree.unflatten(treedef, leaves)


class NonFiniteGuard:
    """Eager-regime guard; one instance per optimizer (or shared).

    ``intercept(grads)`` returns ``(grads, skip)``: with ``skip`` True
    the caller must drop the step (zero updates, optimizer state
    untouched).  Collective: every rank must call it once per step, in
    step order — the agreement allreduce is named by an internal serial.
    Host-side: it must see concrete gradients, so the guarded step runs
    outside ``jit`` (traced leaves are rejected with a clear error; the
    in-graph guard covers the jitted path).
    """

    def __init__(self, policy: Optional[str] = None,
                 limit: Optional[int] = None):
        self.policy = resolve_policy(policy)
        if self.policy == "off":
            raise ValueError(
                "NonFiniteGuard with policy 'off' is a contradiction; "
                "simply do not install a guard")
        self.limit = consecutive_limit(limit)
        self.nonfinite_steps = 0   # steps the gang agreed were bad
        self.skipped = 0           # steps actually dropped
        self.consecutive = 0       # current agreed-bad run length
        self._serial = 0

    def intercept(self, grads):
        import jax

        from horovod_tpu.ops import eager

        self._serial += 1
        leaves = jax.tree.leaves(grads)
        if any(eager._is_traced(g) for g in leaves):
            raise RuntimeError(
                "NonFiniteGuard inspects gradients host-side and cannot "
                "see traced values: call the guarded optimizer step "
                "outside jit, or use the in-graph guard "
                "(DistributedOptimizer(axis=..., nonfinite_policy=...))")
        if _fi.should_corrupt("grad.nonfinite", str(self._serial)):
            grads = _poison_first_float_leaf(grads)
            leaves = jax.tree.leaves(grads)
        local = _local_nonfinite(leaves)
        flag = np.array([1 if local else 0], np.int32)
        agreed = eager.allreduce(
            flag, op=ReduceOp.MAX,
            name=f"integrity.nonfinite.{self._serial}")
        if int(np.asarray(agreed)[0]) == 0:
            self.consecutive = 0
            return grads, False
        self.nonfinite_steps += 1
        self.consecutive += 1
        _bump("agreed")
        if self.policy == "zero":
            import jax.numpy as jnp

            # jnp (not np) so jax.Array leaves stay jax.Arrays.
            grads = jax.tree.map(
                lambda g: jnp.where(jnp.isfinite(g), g, jnp.zeros_like(g))
                if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)
                else g, grads)
            return grads, False
        self.skipped += 1
        _bump("skipped")
        _tmx.inc_counter("hvd_nonfinite_skips_total")
        timeline_mod.engine_event(
            timeline_mod.NONFINITE_SKIP, serial=self._serial,
            policy=self.policy, consecutive=self.consecutive)
        if self.policy == "raise" and self.consecutive >= self.limit:
            raise NonFiniteGradientError(self.consecutive, self.limit)
        return grads, True


class GuardState(NamedTuple):
    """In-graph guard counters wrapped around the inner optimizer state
    (the in-graph twin of :class:`NonFiniteGuard`'s host-side counters).
    Read with :func:`stats`."""

    nonfinite_steps: Any
    consecutive: Any
    inner: Any


def stats(opt_state) -> dict:
    """Counters from an in-graph guarded optimizer state."""
    if not isinstance(opt_state, GuardState):
        raise TypeError(
            "stats() wants the state of a DistributedOptimizer built "
            "with an in-graph nonfinite_policy (GuardState); got "
            f"{type(opt_state).__name__}")
    return {"nonfinite_steps": int(opt_state.nonfinite_steps),
            "consecutive": int(opt_state.consecutive)}
