"""Data-plane integrity: trust, but verify, the numbers themselves.

The fault-tolerance stack (heartbeats, elastic re-form) handles ranks
that *die*; this package handles ranks that *lie* — silently corrupted
data that would otherwise train a broken model:

* :mod:`~horovod_tpu.integrity.nonfinite` — NaN/Inf gradient guard with
  a 1-element MAX-allreduce agreement so every rank skips (or zeros, or
  raises on) the same step (``HVD_NONFINITE_POLICY``); wired into
  :func:`~horovod_tpu.parallel.optimizer.DistributedOptimizer`.
* :mod:`~horovod_tpu.integrity.audit` — replica-divergence audit: leafwise
  bit-pattern fingerprints of the replicated state, allgathered and
  compared every ``HVD_AUDIT_INTERVAL`` steps; deviants raise
  :class:`ReplicaDivergenceError` and feed elastic eviction.
* verified checkpoints live in :mod:`horovod_tpu.utils.checkpoint`
  (``save_verified`` / ``restore_verified``): atomic writes, sha256
  manifests, fallback restore.

See docs/fault_tolerance.md ("Data-plane integrity").
"""

from horovod_tpu.common.types import ReplicaDivergenceError
from horovod_tpu.integrity.audit import (ReplicaAuditor, audit_replicas,
                                         fingerprint)
from horovod_tpu.integrity.nonfinite import (GuardState, NonFiniteGradientError,
                                             NonFiniteGuard)
from horovod_tpu.integrity.nonfinite import counters as nonfinite_counters
from horovod_tpu.integrity.nonfinite import reset_counters \
    as reset_nonfinite_counters
from horovod_tpu.integrity.nonfinite import stats as nonfinite_stats

__all__ = [
    "ReplicaAuditor",
    "ReplicaDivergenceError",
    "NonFiniteGradientError",
    "NonFiniteGuard",
    "GuardState",
    "audit_replicas",
    "fingerprint",
    "nonfinite_counters",
    "reset_nonfinite_counters",
    "nonfinite_stats",
]
