"""In-process control-plane scale simulation: star vs. hierarchical tree.

The hierarchical control plane (docs/fault_tolerance.md) claims the
two-level tree cuts the coordinator's per-cycle work from O(ranks) frame
receives to O(hosts): per-host sub-coordinators fold their children's
negotiation frames into one ``TAG_TREE_UP`` aggregate.  This module
*measures* that claim without hardware: it spins up N in-process "ranks"
connected by socketpairs (the ``tests/test_dataplane.py`` fake-mesh
idiom), runs real wire-codec negotiation cycles through both topologies,
and reports the p50 coordination-cycle latency per gang size.

One simulated cycle is the coordinator half of ``_coordinator_cycle``:

* **star**  — root receives one ``TAG_REQUEST_LIST`` frame from every
  other rank, decodes each, folds the requests, encodes one shared
  ``TAG_RESPONSE_LIST`` and sends it to every rank.
* **tree**  — ranks on the root's host still send direct; every other
  host's children send to their sub-coordinator thread, which folds the
  raw frames into a single ``TAG_TREE_UP`` (no decode at the subcoord —
  exactly what ``runtime_py`` does); root receives one aggregate per
  host, decodes the entries, and answers every rank on its direct
  socket (responses never route through the tree, by design).

The root is modeled the way ``runtime_py`` actually runs it: one recv
thread per direct connection (``_ctrl_recv_loop``) decoding frames and
folding them into a shared table, with the coordinator cycle blocking
until every rank's request list has landed.  That is where the star
hurts at scale — 255 recv-thread wakeups, GIL handoffs, and lock
acquisitions per cycle against the tree's 31 — and it is exactly the
cost the sub-coordinator fold removes (children's frames arrive inside
one aggregate on one connection, and the per-child receive syscalls run
in parallel on the sub-coordinator threads instead of serializing on
the root).

The per-cycle latency is measured at the root — start of the wait for
the cycle's uplink frames to the last response byte handed to the
kernel — and each tree sample is observed into
``hvd_ctrl_cycle_seconds{ranks}`` so the metric the real coordinator
emits gets scale coverage too.

Used by ``bench.py`` (``coordination_cycle_p50_us``) and
``tests/test_ctrl_tree.py``; runnable standalone::

    python -m horovod_tpu.ctrl_sim            # 8/64/256-rank curve
"""

from __future__ import annotations

import socket
import statistics
import struct
import threading
import time
from typing import Dict, List, Tuple

from horovod_tpu.common import wire
from horovod_tpu.common.types import Request, Response, ResponseType
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.utils import socketutil as su

# Gang sizes for the latency-vs-ranks curve.  256 is the proof point:
# far past any gang the multiprocess tests can spawn, and big enough
# that O(ranks) vs O(hosts) separates clearly.
CURVE_SIZES = (8, 64, 256)
DEFAULT_LOCAL_SIZE = 8


def _plan_hosts(size: int, local_size: int) -> List[List[int]]:
    """Ranks grouped by host, mirroring ``runtime_py._plan_tree``'s
    contiguous-block layout: host h owns [h*ls, min((h+1)*ls, size))."""
    return [list(range(lo, min(lo + local_size, size)))
            for lo in range(0, size, local_size)]


def _request_payload(rank: int, cycle: int) -> bytes:
    """One realistic negotiation frame: a couple of gradient allreduce
    announcements, exactly what a worker posts per training step."""
    reqs = [Request(request_rank=rank, tensor_name=f"grad_{cycle % 4}_{i}")
            for i in range(2)]
    return wire.encode_request_list(reqs, epoch=0)


def _response_payload(cycle: int) -> bytes:
    resp = Response(response_type=ResponseType.ALLREDUCE)
    resp.add_tensor_name(f"grad_{cycle % 4}_0")
    resp.add_tensor_name(f"grad_{cycle % 4}_1")
    return wire.encode_response_list([resp], epoch=0)


def _pair() -> Tuple[socket.socket, socket.socket]:
    return socket.socketpair()


def _worker(uplink: socket.socket, downlink: socket.socket,
            rank: int, cycles: int) -> None:
    """A simulated non-root rank: post the step's request list on the
    uplink (direct-to-root in star mode, to the sub-coordinator in tree
    mode), then block on the root's response before the next step."""
    try:
        for c in range(cycles):
            su.send_frame(uplink, su.TAG_REQUEST_LIST,
                          _request_payload(rank, c))
            tag, _ = su.recv_frame(downlink)
            if tag != su.TAG_RESPONSE_LIST:
                return
    except (ConnectionError, OSError):
        pass


def _subcoord(children: List[Tuple[int, socket.socket]],
              root_uplink: socket.socket, downlink: socket.socket,
              rank: int, cycles: int) -> None:
    """A simulated sub-coordinator: fold this host's raw frames (its own
    request plus one frame per child) into a single TAG_TREE_UP — no
    per-child decode, same as ``runtime_py._worker_cycle`` — then wait
    for the root's direct response like any other rank."""
    try:
        for c in range(cycles):
            entries = [(rank, su.TAG_REQUEST_LIST,
                        _request_payload(rank, c))]
            for child_rank, csock in children:
                tag, payload = su.recv_frame(csock)
                entries.append((child_rank, tag, payload))
            su.send_frame(root_uplink, su.TAG_TREE_UP,
                          wire.encode_tree_up(entries, epoch=0))
            tag, _ = su.recv_frame(downlink)
            if tag != su.TAG_RESPONSE_LIST:
                return
    except (ConnectionError, OSError):
        pass


def _decode_uplink(tag: int, payload: bytes) -> Tuple[int, List[str]]:
    """Root-side decode of one uplink frame: ``(n_request_lists,
    tensor_names)``.  A TREE_UP aggregate yields its host's whole
    member count from a single frame on a single connection."""
    names: List[str] = []
    if tag == su.TAG_TREE_UP:
        entries, _epoch = wire.decode_tree_up(payload)
        n = 0
        for _rank, etag, epayload in entries:
            if etag == su.TAG_REQUEST_LIST:
                reqs, _s, _h, _e = wire.decode_request_list(epayload)
                names.extend(r.tensor_name for r in reqs)
                n += 1
        return n, names
    if tag == su.TAG_REQUEST_LIST:
        reqs, _s, _h, _e = wire.decode_request_list(payload)
        return 1, [r.tensor_name for r in reqs]
    return 0, names


def simulate(size: int, mode: str = "star", cycles: int = 40,
             warmup: int = 5,
             local_size: int = DEFAULT_LOCAL_SIZE) -> List[float]:
    """Run ``cycles`` coordination cycles over a ``size``-rank fake mesh
    and return the per-cycle root latencies in seconds (warmup cycles
    are run but not reported).

    ``mode`` is ``"star"`` (every rank direct to root) or ``"tree"``
    (per-host sub-coordinators, fanout = host size).  With one host the
    tree degenerates to the star, matching ``_plan_tree``'s gate.
    """
    if mode not in ("star", "tree"):
        raise ValueError(f"unknown ctrl_sim mode {mode!r}")
    if size < 2:
        raise ValueError("simulate needs size >= 2")
    total = cycles + warmup
    hosts = _plan_hosts(size, local_size)

    # Direct root<->rank links exist in both modes: responses always
    # travel root->rank directly (the tree is uplink-only).
    root_side: Dict[int, socket.socket] = {}
    rank_side: Dict[int, socket.socket] = {}
    for r in range(1, size):
        a, b = _pair()
        root_side[r], rank_side[r] = a, b

    threads: List[threading.Thread] = []
    uplinks: List[socket.socket] = []  # what the root drains each cycle
    extra_socks: List[socket.socket] = []

    if mode == "star" or len(hosts) == 1:
        for r in range(1, size):
            uplinks.append(root_side[r])
            threads.append(threading.Thread(
                target=_worker,
                args=(rank_side[r], rank_side[r], r, total),
                name=f"sim-worker-{r}", daemon=True))
    else:
        # Root's own host stays direct.
        for r in hosts[0]:
            if r == 0:
                continue
            uplinks.append(root_side[r])
            threads.append(threading.Thread(
                target=_worker,
                args=(rank_side[r], rank_side[r], r, total),
                name=f"sim-worker-{r}", daemon=True))
        for host in hosts[1:]:
            sub = host[0]
            children: List[Tuple[int, socket.socket]] = []
            for child in host[1:]:
                ca, cb = _pair()
                extra_socks.extend((ca, cb))
                children.append((child, ca))
                threads.append(threading.Thread(
                    target=_worker,
                    args=(cb, rank_side[child], child, total),
                    name=f"sim-worker-{child}", daemon=True))
            uplinks.append(root_side[sub])
            threads.append(threading.Thread(
                target=_subcoord,
                args=(children, rank_side[sub], rank_side[sub], sub, total),
                name=f"sim-subcoord-{sub}", daemon=True))

    # The root mirrors runtime_py: one recv thread per direct
    # connection decoding + folding into a shared table under a lock;
    # the coordinator cycle blocks until every rank has reported.
    cv = threading.Condition()
    pending = {"count": 0}
    folded: Dict[str, int] = {}

    def _root_recv(sock: socket.socket) -> None:
        try:
            while True:
                tag, payload = su.recv_frame(sock)
                n, names = _decode_uplink(tag, payload)
                with cv:
                    for name in names:
                        folded[name] = folded.get(name, 0) + 1
                    pending["count"] += n
                    if pending["count"] >= size - 1:
                        cv.notify()
        except (ConnectionError, OSError, ValueError):
            pass

    for sock in uplinks:
        threads.append(threading.Thread(
            target=_root_recv, args=(sock,),
            name="sim-root-recv", daemon=True))

    for t in threads:
        t.start()

    latencies: List[float] = []
    try:
        for c in range(total):
            t0 = time.perf_counter()
            with cv:
                while pending["count"] < size - 1:
                    if not cv.wait(timeout=30.0):
                        raise RuntimeError(
                            f"cycle {c}: stalled at "
                            f"{pending['count']}/{size - 1} request "
                            f"lists")
                pending["count"] -= size - 1
                folded.clear()
            resp = _response_payload(c)
            for r in range(1, size):
                su.send_frame(root_side[r], su.TAG_RESPONSE_LIST, resp)
            t1 = time.perf_counter()
            if c >= warmup:
                latencies.append(t1 - t0)
    finally:
        for s in list(root_side.values()) + list(rank_side.values()) \
                + extra_socks:
            try:
                s.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=10.0)
    return latencies


def run_curve(sizes: Tuple[int, ...] = CURVE_SIZES, cycles: int = 40,
              local_size: int = DEFAULT_LOCAL_SIZE,
              repeats: int = 3) -> Dict[str, float]:
    """The latency-vs-ranks curve for both topologies.

    Returns a flat dict of microsecond p50s keyed
    ``ctrl_cycle_{mode}_p50_us_{size}``, plus the headline
    ``coordination_cycle_p50_us`` — the hierarchical p50 at the largest
    size (the 256-rank proof point ``bench.py`` regresses on).  Tree
    samples are observed into ``hvd_ctrl_cycle_seconds{ranks}``.

    The two modes are measured in ``repeats`` interleaved passes and
    the p50 is taken over the pooled samples: on a loaded shared host a
    noise burst then lands on both topologies instead of poisoning
    whichever mode it happened to overlap.
    """
    out: Dict[str, float] = {}
    for size in sizes:
        samples: Dict[str, List[float]] = {"star": [], "tree": []}
        for _ in range(max(1, repeats)):
            for sim_mode in ("star", "tree"):
                samples[sim_mode].extend(
                    simulate(size, mode=sim_mode, cycles=cycles,
                             local_size=local_size))
        for sim_mode, lat in samples.items():
            out[f"ctrl_cycle_{sim_mode}_p50_us_{size}"] = round(
                statistics.median(lat) * 1e6, 2)
        for sample in samples["tree"]:
            _tmx.observe("hvd_ctrl_cycle_seconds", sample,
                         labels=(str(size),))
    largest = max(sizes)
    out["coordination_cycle_p50_us"] = out[
        f"ctrl_cycle_tree_p50_us_{largest}"]
    return out


def main() -> None:
    import json

    print(json.dumps(run_curve()))


if __name__ == "__main__":
    main()
