"""Gaussian-process regression for the autotuner.

Role parity: ``horovod/common/optim/gaussian_process.cc/.h`` — GP with an
RBF kernel fit to (parameter vector → score) samples, used only by the
Bayesian-optimization autotuner.  The reference uses Eigen + L-BFGS for
hyperparameter fitting; sample counts here are tiny (tens), so a fixed
length-scale with numpy Cholesky is accurate enough and dependency-free.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class GaussianProcess:
    """GP posterior over f: [0,1]^d -> R with RBF kernel."""

    def __init__(self, length_scale: float = 0.25,
                 signal_variance: float = 1.0,
                 noise_variance: float = 1e-4):
        self.length_scale = length_scale
        self.signal_variance = signal_variance
        self.noise_variance = noise_variance
        self._x: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        # squared exponential: k(x,x') = s² exp(-‖x-x'‖² / (2ℓ²))
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_variance * np.exp(-0.5 * d2 /
                                             (self.length_scale ** 2))

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).ravel()
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        k = self._kernel(x, x) + self.noise_variance * np.eye(len(x))
        self._chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn))
        self._x = x

    @property
    def y_std(self) -> float:
        """Scale of the standardized targets (1.0 before the first fit)."""
        return self._y_std

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at x (de-standardized)."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return (np.full(len(x), self._y_mean),
                    np.full(len(x), np.sqrt(self.signal_variance)))
        ks = self._kernel(x, self._x)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.clip(self.signal_variance - (v ** 2).sum(0), 1e-12, None)
        return (mean * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)
