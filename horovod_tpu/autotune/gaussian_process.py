"""Gaussian-process regression for the autotuner.

Role parity: ``horovod/common/optim/gaussian_process.cc/.h`` — GP with an
RBF kernel fit to (parameter vector → score) samples, used only by the
Bayesian-optimization autotuner.  The reference fits kernel
hyperparameters by L-BFGS maximum marginal likelihood
(``gaussian_process.cc:44+``); at the autotuner's sample counts (tens)
a dense grid over the length-scale maximizes the same objective exactly
as well, with numpy Cholesky and no optimizer dependency — pass
``length_scale=None`` (the default) to select it per ``fit()``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# Log-spaced candidate length-scales for marginal-likelihood selection,
# spanning "every sample is independent" (0.05 on the unit cube) to
# "the surface is one slow trend" (2.0).
_LS_GRID = np.geomspace(0.05, 2.0, 24)


class GaussianProcess:
    """GP posterior over f: [0,1]^d -> R with RBF kernel.

    ``length_scale=None`` selects the length-scale by maximizing the log
    marginal likelihood over ``_LS_GRID`` at each ``fit()``; a float
    pins it (the pre-r5 fixed-hyperparameter behavior).
    """

    def __init__(self, length_scale: Optional[float] = None,
                 signal_variance: float = 1.0,
                 noise_variance: float = 1e-4):
        if length_scale is not None and length_scale <= 0:
            raise ValueError(f"length_scale must be positive or None "
                             f"(auto-fit), got {length_scale}")
        self._fit_length_scale = length_scale is None
        self.length_scale = 0.25 if length_scale is None else length_scale
        self.signal_variance = signal_variance
        self.noise_variance = noise_variance
        self._x: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        # squared exponential: k(x,x') = s² exp(-‖x-x'‖² / (2ℓ²))
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_variance * np.exp(-0.5 * d2 /
                                             (self.length_scale ** 2))

    def _factor(self, x: np.ndarray, yn: np.ndarray):
        """Cholesky + weights for the current hyperparameters."""
        k = self._kernel(x, x) + self.noise_variance * np.eye(len(x))
        chol = np.linalg.cholesky(k)
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, yn))
        return chol, alpha

    def _log_marginal_likelihood(self, chol, alpha, yn) -> float:
        # lml = -1/2 yᵀα − Σ log L_ii − n/2 log 2π   (GPML eq. 2.30)
        return float(-0.5 * yn @ alpha
                     - np.log(np.diag(chol)).sum()
                     - 0.5 * len(yn) * np.log(2 * np.pi))

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        x = np.atleast_2d(np.asarray(x, np.float64))
        y = np.asarray(y, np.float64).ravel()
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        if self._fit_length_scale and len(x) >= 3:
            # Type-II MLE over the grid — the reference's L-BFGS fit
            # (gaussian_process.cc:44+) on a 1-D hyperparameter space,
            # solved by dense evaluation instead of a line search.  A
            # non-PD kernel at an extreme candidate is skipped, not fatal.
            best, best_lml = self.length_scale, -np.inf
            for ls in _LS_GRID:
                self.length_scale = float(ls)
                try:
                    chol, alpha = self._factor(x, yn)
                except np.linalg.LinAlgError:
                    continue
                lml = self._log_marginal_likelihood(chol, alpha, yn)
                if lml > best_lml:
                    best, best_lml = float(ls), lml
            self.length_scale = best
        self._chol, self._alpha = self._factor(x, yn)
        self._x = x

    @property
    def y_std(self) -> float:
        """Scale of the standardized targets (1.0 before the first fit)."""
        return self._y_std

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at x (de-standardized)."""
        x = np.atleast_2d(np.asarray(x, np.float64))
        if self._x is None:
            return (np.full(len(x), self._y_mean),
                    np.full(len(x), np.sqrt(self.signal_variance)))
        ks = self._kernel(x, self._x)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._chol, ks.T)
        var = np.clip(self.signal_variance - (v ** 2).sum(0), 1e-12, None)
        return (mean * self._y_std + self._y_mean,
                np.sqrt(var) * self._y_std)
