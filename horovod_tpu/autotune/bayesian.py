"""Bayesian optimization with expected-improvement acquisition.

Role parity: ``horovod/common/optim/bayesian_optimization.cc/.h`` —
propose the next parameter vector maximizing expected improvement under
the GP posterior.  The reference maximizes EI with L-BFGS restarts; the
search space here is a low-dimensional unit cube, so a deterministic
quasi-random candidate sweep is equally effective and simpler.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from horovod_tpu.autotune.gaussian_process import GaussianProcess


def _normal_cdf(z: np.ndarray) -> np.ndarray:
    from math import sqrt

    try:
        from scipy.special import erf  # pragma: no cover
    except Exception:
        erf = np.vectorize(__import__("math").erf)
    return 0.5 * (1.0 + erf(z / sqrt(2.0)))


def _normal_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / np.sqrt(2.0 * np.pi)


class BayesianOptimization:
    """Maximizes an expensive black-box f over [0,1]^dim."""

    def __init__(self, dim: int, xi: float = 0.01, seed: int = 0,
                 n_candidates: int = 512):
        self.dim = dim
        self.xi = xi  # exploration bonus (parity: bayesian_optimization.h)
        self._rng = np.random.RandomState(seed)
        self._n_candidates = n_candidates
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []
        self.gp = GaussianProcess()

    def add_sample(self, x: np.ndarray, y: float) -> None:
        self._xs.append(np.asarray(x, np.float64).ravel())
        self._ys.append(float(y))
        self.gp.fit(np.stack(self._xs), np.asarray(self._ys))

    def best(self) -> Optional[np.ndarray]:
        if not self._ys:
            return None
        return self._xs[int(np.argmax(self._ys))]

    def expected_improvement(self, x: np.ndarray) -> np.ndarray:
        mean, std = self.gp.predict(x)
        best = max(self._ys) if self._ys else 0.0
        # Work on the GP's standardized scale so the xi exploration bonus
        # is meaningful regardless of the raw score units (bytes/sec is
        # ~1e8; raw xi=0.01 would be vacuous).
        y_std = self.gp.y_std
        imp = (mean - best) / y_std - self.xi
        sd = std / y_std
        z = imp / sd
        return imp * _normal_cdf(z) + sd * _normal_pdf(z)

    def next_sample(self) -> np.ndarray:
        """Candidate with the highest EI (random sweep + past-best jitter)."""
        if not self._ys:
            return self._rng.uniform(size=self.dim)
        cands = self._rng.uniform(size=(self._n_candidates, self.dim))
        # densify around the incumbent — EI is often maximized nearby
        best = self.best()
        local = np.clip(
            best + self._rng.normal(scale=0.1,
                                    size=(self._n_candidates // 4, self.dim)),
            0.0, 1.0)
        cands = np.concatenate([cands, local])
        ei = self.expected_improvement(cands)
        return cands[int(np.argmax(ei))]
