"""Autotuning parameter manager.

Role parity: ``horovod/common/parameter_manager.cc/.h`` — the runtime
knobs of the coordination loop (tensor-fusion threshold, cycle time,
response-cache toggle) tuned online by Bayesian optimization, scored by
throughput (bytes per second of allreduced payload; the reference scores
bytes/µs).  Rank 0 owns the tuner; tuned values are broadcast to workers
in the response stream and applied before they take effect on any
coherence-relevant path (fusion of cached hits must use the same
threshold on every rank).

Knobs: fusion threshold, cycle time, cache on/off, and — on hierarchical
topologies (local_size>1 and cross_size>1) — the hierarchical
allreduce/allgather toggles, matching the reference's tunable set
(``parameter_manager.cc:44-60``).  One difference by design: categorical
dims ride the same GP with rounding instead of separate per-category
optimizers.

Explicitly set env knobs are *fixed* and excluded from tuning (parity:
``parameter_manager.h:60-78`` — fixed=true wins over tuning).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from horovod_tpu.autotune.bayesian import BayesianOptimization
from horovod_tpu.utils import env as env_util

_MAX_FUSION = 64 << 20  # tuning range upper bound, parity with reference
_MIN_CYCLE_S = 0.0005
_MAX_CYCLE_S = 0.025
# Ring-hop segment (docs/performance.md): 0 = unsegmented; tuned over
# 64 KiB steps up to 4 MiB — past that a segment no longer fits typical
# kernel socket buffers and the recv/reduce overlap disappears.
_MAX_SEGMENT = 4 << 20
_SEGMENT_STEP = 64 << 10


def autotune_options_from_env(hierarchical_ok: bool = False
                              ) -> Optional[dict]:
    """The single source of the autotune env policy, shared by the Python
    engine (ParameterManager.from_env) and the native engine (which ships
    these values through hvd_create).  None when tuning is off or every
    knob is env-pinned.  ``hierarchical_ok``: the hierarchy toggles are
    only tunable on a topology where they do anything."""
    if not env_util.get_bool(env_util.AUTOTUNE, False):
        return None
    opts = dict(
        tune_fusion=env_util.FUSION_THRESHOLD not in os.environ,
        tune_cycle=env_util.CYCLE_TIME not in os.environ,
        tune_cache=env_util.CACHE_CAPACITY not in os.environ,
        tune_hier_allreduce=(
            hierarchical_ok
            and env_util.HIERARCHICAL_ALLREDUCE not in os.environ),
        tune_hier_allgather=(
            hierarchical_ok
            and env_util.HIERARCHICAL_ALLGATHER not in os.environ),
        tune_segment=env_util.RING_SEGMENT_BYTES not in os.environ,
        warmup_samples=env_util.get_int(env_util.AUTOTUNE_WARMUP_SAMPLES, 3),
        max_samples=env_util.get_int(env_util.AUTOTUNE_MAX_SAMPLES, 20),
        sample_duration_s=env_util.get_float(
            env_util.AUTOTUNE_SAMPLE_DURATION, 0.5),
        log_path=env_util.get_str(env_util.AUTOTUNE_LOG) or None,
    )
    if not any(opts[k] for k in ("tune_fusion", "tune_cycle", "tune_cache",
                                 "tune_hier_allreduce",
                                 "tune_hier_allgather", "tune_segment")):
        return None
    return opts


@dataclass
class TunedParams:
    """The knob vector shipped coordinator → workers."""

    fusion_threshold: int
    cycle_time_s: float
    cache_enabled: bool
    hierarchical_allreduce: bool = False
    hierarchical_allgather: bool = False
    ring_segment_bytes: int = 0

    def __eq__(self, other) -> bool:
        return (self.fusion_threshold == other.fusion_threshold
                and abs(self.cycle_time_s - other.cycle_time_s) < 1e-9
                and self.cache_enabled == other.cache_enabled
                and self.hierarchical_allreduce
                == other.hierarchical_allreduce
                and self.hierarchical_allgather
                == other.hierarchical_allgather
                and self.ring_segment_bytes == other.ring_segment_bytes)


class ParameterManager:
    """Bayesian-optimization autotuner over the coordination knobs."""

    def __init__(self, initial: TunedParams, *,
                 tune_fusion: bool = True, tune_cycle: bool = True,
                 tune_cache: bool = True,
                 tune_hier_allreduce: bool = False,
                 tune_hier_allgather: bool = False,
                 tune_segment: bool = False,
                 warmup_samples: int = 3, max_samples: int = 20,
                 sample_duration_s: float = 0.5,
                 log_path: Optional[str] = None):
        self.current = initial
        self.initial = initial
        self.done = False
        self._dims = []
        if tune_fusion:
            self._dims.append("fusion")
        if tune_cycle:
            self._dims.append("cycle")
        if tune_cache:
            self._dims.append("cache")
        if tune_hier_allreduce:
            self._dims.append("hier_ar")
        if tune_hier_allgather:
            self._dims.append("hier_ag")
        if tune_segment:
            self._dims.append("segment")
        self._bo = BayesianOptimization(dim=max(1, len(self._dims)))
        self._warmup_left = warmup_samples
        self._max_samples = max_samples
        self._samples = 0
        self._bytes = 0
        self._sample_start: Optional[float] = None
        self._current_x = self._params_to_x(initial)
        self._sample_duration_s = sample_duration_s
        self._log = open(log_path, "w") if log_path else None
        if self._log:
            self._log.write(
                "sample,score_bytes_per_s,fusion_threshold,"
                "cycle_time_ms,cache_enabled,hierarchical_allreduce,"
                "hierarchical_allgather,ring_segment_bytes\n")

    @classmethod
    def from_env(cls, fusion_threshold: int, cycle_time_s: float,
                 hierarchical_allreduce: bool = False,
                 hierarchical_allgather: bool = False,
                 hierarchical_ok: bool = False,
                 ring_segment_bytes: int = 0
                 ) -> Optional["ParameterManager"]:
        """None unless HVD_AUTOTUNE is on.  Env-pinned knobs are fixed;
        if every knob is pinned there is nothing to tune."""
        opts = autotune_options_from_env(hierarchical_ok)
        if opts is None:
            return None
        return cls(TunedParams(fusion_threshold, cycle_time_s, True,
                               hierarchical_allreduce,
                               hierarchical_allgather,
                               ring_segment_bytes), **opts)

    # -- parameter vector mapping ----------------------------------------

    def _params_to_x(self, p: TunedParams) -> np.ndarray:
        x = []
        for d in self._dims:
            if d == "fusion":
                x.append(p.fusion_threshold / _MAX_FUSION)
            elif d == "cycle":
                x.append((p.cycle_time_s - _MIN_CYCLE_S) /
                         (_MAX_CYCLE_S - _MIN_CYCLE_S))
            elif d == "hier_ar":
                x.append(1.0 if p.hierarchical_allreduce else 0.0)
            elif d == "hier_ag":
                x.append(1.0 if p.hierarchical_allgather else 0.0)
            elif d == "segment":
                x.append(p.ring_segment_bytes / _MAX_SEGMENT)
            else:
                x.append(1.0 if p.cache_enabled else 0.0)
        return np.asarray(x or [0.0], np.float64)

    def _x_to_params(self, x: np.ndarray) -> TunedParams:
        p = TunedParams(self.current.fusion_threshold,
                        self.current.cycle_time_s,
                        self.current.cache_enabled,
                        self.current.hierarchical_allreduce,
                        self.current.hierarchical_allgather,
                        self.current.ring_segment_bytes)
        for i, d in enumerate(self._dims):
            v = float(np.clip(x[i], 0.0, 1.0))
            if d == "fusion":
                # snap to 1 MiB steps, like the reference's discretization
                p.fusion_threshold = int(round(v * _MAX_FUSION /
                                               (1 << 20))) << 20
            elif d == "cycle":
                p.cycle_time_s = _MIN_CYCLE_S + v * (_MAX_CYCLE_S -
                                                     _MIN_CYCLE_S)
            elif d == "hier_ar":
                p.hierarchical_allreduce = v >= 0.5
            elif d == "hier_ag":
                p.hierarchical_allgather = v >= 0.5
            elif d == "segment":
                # snap to 64 KiB steps; the bottom step rounds to 0 = off
                p.ring_segment_bytes = int(
                    round(v * _MAX_SEGMENT / _SEGMENT_STEP)) * _SEGMENT_STEP
            else:
                p.cache_enabled = v >= 0.5
        return p

    # -- scoring loop -----------------------------------------------------

    def record_bytes(self, nbytes: int, now: Optional[float] = None
                     ) -> Optional[TunedParams]:
        """Feed allreduced payload bytes; returns new params to apply+
        broadcast when a tuning step fires, else None.
        Parity: ParameterManager::Update (parameter_manager.cc:89-181)."""
        if self.done:
            return None
        now = time.monotonic() if now is None else now
        if self._sample_start is None:
            self._sample_start = now
        self._bytes += nbytes
        elapsed = now - self._sample_start
        if elapsed > 5 * self._sample_duration_s:
            # Idle gap (eval, checkpointing, …): the window no longer
            # measures the knobs, it measures the pause — discard it
            # rather than attribute a near-zero score to the incumbent.
            self._bytes = nbytes
            self._sample_start = now
            return None
        if elapsed < self._sample_duration_s or self._bytes <= 0:
            return None

        score = self._bytes / elapsed
        self._bytes = 0
        self._sample_start = now

        if self._warmup_left > 0:
            self._warmup_left -= 1
            return None

        self._samples += 1
        self._bo.add_sample(self._current_x, score)
        if self._log:
            self._log.write(
                f"{self._samples},{score:.1f},"
                f"{self.current.fusion_threshold},"
                f"{self.current.cycle_time_s * 1e3:.3f},"
                f"{int(self.current.cache_enabled)},"
                f"{int(self.current.hierarchical_allreduce)},"
                f"{int(self.current.hierarchical_allgather)},"
                f"{self.current.ring_segment_bytes}\n")
            self._log.flush()

        if self._samples >= self._max_samples:
            # settle on the best observed configuration
            best = self._bo.best()
            self.current = self._x_to_params(best)
            self.done = True
            if self._log:
                self._log.write(
                    f"final,,{self.current.fusion_threshold},"
                    f"{self.current.cycle_time_s * 1e3:.3f},"
                    f"{int(self.current.cache_enabled)},"
                    f"{int(self.current.hierarchical_allreduce)},"
                    f"{int(self.current.hierarchical_allgather)},"
                    f"{self.current.ring_segment_bytes}\n")
                self._log.close()
                self._log = None
            return self.current

        self._current_x = self._bo.next_sample()
        self.current = self._x_to_params(self._current_x)
        return self.current
