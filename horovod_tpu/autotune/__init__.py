"""Runtime autotuning of coordination-loop knobs.

Role parity: ``horovod/common/parameter_manager.cc/.h`` (tunable knobs,
warmup/sampling schedule, rank-0-tunes-and-broadcasts) +
``horovod/common/optim/bayesian_optimization.cc`` and
``gaussian_process.cc`` (GP regression with expected-improvement
acquisition).  Scored the same way: bytes processed per unit time.
"""

from horovod_tpu.autotune.gaussian_process import GaussianProcess  # noqa
from horovod_tpu.autotune.bayesian import BayesianOptimization  # noqa
from horovod_tpu.autotune.parameter_manager import (  # noqa
    ParameterManager,
    TunedParams,
)
