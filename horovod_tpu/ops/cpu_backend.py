"""CPU data-plane collectives over the TCP mesh: the correctness oracle.

Role parity: ``horovod/common/ops/gloo_operations.cc`` (the reference's CPU
backend, ring algorithms from the gloo library) and ``mpi_operations.cc``.
Algorithms:

* allreduce  — ring reduce-scatter + ring allgather (the NCCL/gloo ring),
  with fp32 per-hop accumulation for 16-bit dtypes matching the reference's
  custom fp16 MPI op (``half.cc:43-77`` promotes to float to add).
* allgather  — ragged ring allgatherv driven by the negotiated first-dim
  sizes in the Response (parity: ``MPIAllgather`` displacement logic,
  mpi_operations.cc:83-166).
* broadcast  — star from the root (control-plane scale data; the TPU
  in-graph path is where broadcast bandwidth matters).
* alltoall   — size-1 rounds of pairwise exchange.
* adasum     — recursive distance-doubling partner exchange (see
  ops/adasum.py for the math; eager variant used when Request.reduce_op is
  ADASUM, parity: adasum_mpi_operations.cc).

Each transfer is a framed TCP message; sends run on a helper thread so the
simultaneous send/recv of ring steps cannot deadlock on kernel buffers.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

from horovod_tpu.common.types import DataType, ReduceOp, Response
from horovod_tpu.utils import socketutil as su


def _np_dtype(dt: DataType):
    from horovod_tpu.runtime_py import _np_dtype as f

    return f(dt)


def _send_async(sock, payload: bytes) -> threading.Thread:
    t = threading.Thread(
        target=su.send_frame, args=(sock, su.TAG_DATA, payload), daemon=True)
    t.start()
    return t


def _recv(sock) -> bytes:
    tag, payload = su.recv_frame(sock)
    if tag != su.TAG_DATA:
        raise ConnectionError(f"expected data frame, got tag {tag}")
    return payload


def _needs_f32_math(dtype: np.dtype) -> bool:
    """Sub-32-bit floats (fp16/bf16/fp8) do their arithmetic in fp32,
    like half.cc."""
    return dtype.name in ("float16", "bfloat16", "float8_e4m3fn",
                          "float8_e5m2")


def _combine(a: np.ndarray, b: np.ndarray, op: ReduceOp) -> np.ndarray:
    """Per-hop reduction; sub-32-bit floats accumulate via fp32 like
    half.cc (fp8 wire formats included)."""
    if _needs_f32_math(a.dtype):
        a32, b32 = a.astype(np.float32), b.astype(np.float32)
        out = _combine(a32, b32, op)
        return out.astype(a.dtype)
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
        return a + b
    if op == ReduceOp.MIN:
        return np.minimum(a, b)
    if op == ReduceOp.MAX:
        return np.maximum(a, b)
    if op == ReduceOp.PRODUCT:
        return a * b
    raise ValueError(f"unsupported reduce op {op}")


def _chunk_bounds(n: int, parts: int) -> List[int]:
    """NCCL-style near-equal split: bounds[i]..bounds[i+1] is chunk i."""
    base, rem = divmod(n, parts)
    bounds = [0]
    for i in range(parts):
        bounds.append(bounds[-1] + base + (1 if i < rem else 0))
    return bounds


def ring_allreduce_flat(engine, flat: np.ndarray,
                        op: ReduceOp) -> np.ndarray:
    """In-place-style ring allreduce of a flat array; returns the result."""
    group = list(range(engine.size))
    return _ring_allreduce_group(engine, flat, op, group, engine.rank)


def _ring_allreduce_group(engine, flat: np.ndarray, op: ReduceOp,
                          group, me: int) -> np.ndarray:
    """Ring allreduce restricted to ``group`` (global ranks, any order);
    ``me`` is this rank's index within it.  Same chunk walk as the C++
    engine (RingAllreduceGroup) so mixed jobs stay bit-identical."""
    size = len(group)
    if size == 1:
        return flat
    right = engine._data[group[(me + 1) % size]]
    left = engine._data[group[(me - 1) % size]]
    dtype = flat.dtype
    bounds = _chunk_bounds(flat.size, size)
    chunks = [flat[bounds[i]:bounds[i + 1]].copy() for i in range(size)]

    # Phase 1: ring reduce-scatter.
    for step in range(size - 1):
        send_idx = (me - step) % size
        recv_idx = (me - step - 1) % size
        t = _send_async(right, chunks[send_idx].tobytes())
        incoming = np.frombuffer(_recv(left), dtype=dtype).copy()
        t.join()
        chunks[recv_idx] = _combine(incoming, chunks[recv_idx], op)

    # Phase 2: ring allgather of the reduced chunks.
    for step in range(size - 1):
        send_idx = (me + 1 - step) % size
        recv_idx = (me - step) % size
        t = _send_async(right, chunks[send_idx].tobytes())
        chunks[recv_idx] = np.frombuffer(_recv(left), dtype=dtype).copy()
        t.join()

    return np.concatenate([np.atleast_1d(c) for c in chunks])


def _local_group(engine):
    L = engine.local_size
    return [engine.cross_rank * L + i for i in range(L)]


def _cross_group(engine):
    L = engine.local_size
    return [k * L + engine.local_rank for k in range(engine.cross_size)]


def hierarchical_allreduce_flat(engine, flat: np.ndarray,
                                op: ReduceOp) -> np.ndarray:
    """Two-level allreduce: local ring reduce-scatter → cross ring
    allreduce of the owned 1/local_size slice → local ring allgather.

    TPU-design parity: ``NCCLHierarchicalAllreduce``
    (nccl_operations.cc:163-363) — the bandwidth-heavy phases ride the
    node-local links; only 1/local_size of the bytes crosses nodes.
    Requires the launcher's homogeneous block rank layout, checked by
    ``engine.hierarchical_topology_ok()`` before dispatching here.
    """
    L = engine.local_size
    li = engine.local_rank
    local = _local_group(engine)
    right = engine._data[local[(li + 1) % L]]
    left = engine._data[local[(li - 1) % L]]
    dtype = flat.dtype
    bounds = _chunk_bounds(flat.size, L)
    chunks = [flat[bounds[i]:bounds[i + 1]].copy() for i in range(L)]

    # Phase 1: local ring reduce-scatter.
    for step in range(L - 1):
        send_idx = (li - step) % L
        recv_idx = (li - step - 1) % L
        t = _send_async(right, chunks[send_idx].tobytes())
        incoming = np.frombuffer(_recv(left), dtype=dtype).copy()
        t.join()
        chunks[recv_idx] = _combine(incoming, chunks[recv_idx], op)

    # Phase 2: cross-node ring allreduce of the fully-reduced owned chunk.
    own = (li + 1) % L
    if chunks[own].size:
        chunks[own] = _ring_allreduce_group(
            engine, chunks[own], op, _cross_group(engine),
            engine.cross_rank)

    # Phase 3: local ring allgather.
    for step in range(L - 1):
        send_idx = (li + 1 - step) % L
        recv_idx = (li - step) % L
        t = _send_async(right, chunks[send_idx].tobytes())
        chunks[recv_idx] = np.frombuffer(_recv(left), dtype=dtype).copy()
        t.join()

    return np.concatenate([np.atleast_1d(c) for c in chunks])


def _adasum_flat(engine, flat: np.ndarray) -> np.ndarray:
    """Eager Adasum via recursive distance-doubling partner exchange.
    Power-of-two sizes only (the reference's VHDD also specializes
    power-of-two and handles the remainder separately — not needed for TPU
    pods, which are power-of-two)."""
    size, rank = engine.size, engine.rank
    if size == 1:
        return flat
    if size & (size - 1):
        raise ValueError("Adasum requires a power-of-two world size")
    from horovod_tpu.ops.adasum import adasum_pair_numpy

    acc = flat.astype(np.float64)
    k = 1
    while k < size:
        partner = rank ^ k
        sock = engine._data[partner]
        t = _send_async(sock, acc.tobytes())
        other = np.frombuffer(_recv(sock), dtype=np.float64).copy()
        t.join()
        if rank < partner:
            acc = adasum_pair_numpy(acc, other)
        else:
            acc = adasum_pair_numpy(other, acc)
        k *= 2
    return acc.astype(flat.dtype)


def resp_group(engine, resp: Response):
    """(member global ranks, my index) for a response — the full world
    for the global set, the registered member list for a process set.

    Ranks the coordinator evicted (heartbeat liveness, PyEngine only)
    drop out of the global group: every survivor filters identically, so
    the ring stays coherent without the dead peer."""
    if resp.process_set_id:
        from horovod_tpu import process_sets

        members = process_sets.ranks_of(resp.process_set_id)
        return members, members.index(engine.rank)
    evicted = getattr(engine, "_evicted_ranks", None)
    if evicted:
        group = [r for r in range(engine.size) if r not in evicted]
        return group, group.index(engine.rank)
    return list(range(engine.size)), engine.rank


class _AllreduceCandidate:
    """One entry of the allreduce dispatch chain (parity: the reference's
    per-category op list in ``ops/operation_manager.cc:37-104`` — ordered
    candidates, the first whose ``enabled()`` returns True executes)."""

    def enabled(self, engine, resp: Response) -> bool:
        raise NotImplementedError

    def execute(self, engine, flat: np.ndarray, op: ReduceOp,
                group, me) -> np.ndarray:
        raise NotImplementedError


class AdasumAllreduce(_AllreduceCandidate):
    def enabled(self, engine, resp):
        # Adasum's distance-doubling assumes the global power-of-two
        # topology; process sets (and a post-eviction shrunken group)
        # fall through to the ring.
        return resp.reduce_op == ReduceOp.ADASUM \
            and not resp.process_set_id \
            and not getattr(engine, "_evicted_ranks", None)

    def execute(self, engine, flat, op, group, me):
        return _adasum_flat(engine, flat)


class HierarchicalAllreduce(_AllreduceCandidate):
    def enabled(self, engine, resp):
        return (resp.reduce_op != ReduceOp.ADASUM
                and not resp.process_set_id
                and not getattr(engine, "_evicted_ranks", None)
                and getattr(engine, "hierarchical_allreduce", False)
                and engine.hierarchical_topology_ok())

    def execute(self, engine, flat, op, group, me):
        return hierarchical_allreduce_flat(engine, flat, op)


class RingAllreduce(_AllreduceCandidate):
    def enabled(self, engine, resp):
        return True

    def execute(self, engine, flat, op, group, me):
        return _ring_allreduce_group(engine, flat, op, group, me)


# Priority order mirrors the reference's CreateOperationManager chain
# (operations.cc:142-228): specialized ops first, flat ring as the
# always-enabled fallback.
ALLREDUCE_CHAIN = (AdasumAllreduce(), HierarchicalAllreduce(),
                   RingAllreduce())


def allreduce(engine, entries, resp: Response):
    """Fused allreduce over all entries of the response.  The op and the
    scale factors come from the negotiated response (identical on every
    rank, including joined ranks whose entries are zero stand-ins)."""
    op = resp.reduce_op
    prescale = resp.prescale_factor
    postscale = resp.postscale_factor
    dtype = _np_dtype(resp.tensor_type)
    flats = [np.ravel(e.array).astype(dtype, copy=False) for e in entries]
    flat = flats[0] if len(flats) == 1 else np.concatenate(flats)
    if prescale != 1.0:
        if _needs_f32_math(dtype):
            flat = (flat.astype(np.float32) * prescale).astype(dtype)
        else:
            flat = flat * dtype.type(prescale)

    group, me = resp_group(engine, resp)
    reduced = next(c for c in ALLREDUCE_CHAIN
                   if c.enabled(engine, resp)).execute(engine, flat, op,
                                                       group, me)

    if op == ReduceOp.AVERAGE:
        n = len(group)
        if _needs_f32_math(dtype):
            reduced = (reduced.astype(np.float32) / n).astype(dtype)
        else:
            reduced = reduced / dtype.type(n)
    if postscale != 1.0:
        reduced = (reduced * postscale).astype(dtype, copy=False)

    results = []
    off = 0
    for e in entries:
        n = e.array.size
        results.append(reduced[off:off + n].reshape(e.array.shape))
        off += n
    return results


def _allgather_hierarchical(engine, entries, resp: Response):
    """Two-level allgatherv (role parity: MPIHierarchicalAllgather,
    mpi_operations.cc:168-309 — there via a node-shared MPI window, here
    via the node ring + a leaders-only cross ring + local fan-out).
    Output ordering matches the flat path because the block rank layout
    makes node blocks contiguous in global rank order."""
    L, li = engine.local_size, engine.local_rank
    C = engine.cross_size
    local = _local_group(engine)
    results = []
    for e in entries:
        dtype = _np_dtype(resp.tensor_type)
        rest_shape = e.array.shape[1:] if e.array.ndim > 0 else ()
        first_dims = resp.tensor_sizes

        # Phase 1: node-local ragged ring allgatherv (raw bytes).
        blocks: List[Optional[bytes]] = [None] * L
        blocks[li] = np.ascontiguousarray(e.array).tobytes()
        right = engine._data[local[(li + 1) % L]]
        left = engine._data[local[(li - 1) % L]]
        for step in range(L - 1):
            send_idx = (li - step) % L
            recv_idx = (li - step - 1) % L
            t = _send_async(right, blocks[send_idx])
            blocks[recv_idx] = _recv(left)
            t.join()
        node_block = b"".join(blocks)

        if li == 0:
            # Phase 2: leaders' ragged ring allgatherv of node blocks.
            me = engine.cross_rank
            nblocks: List[Optional[bytes]] = [None] * C
            nblocks[me] = node_block
            if C > 1:
                nright = engine._data[((me + 1) % C) * L]
                nleft = engine._data[((me - 1) % C) * L]
                for step in range(C - 1):
                    send_idx = (me - step) % C
                    recv_idx = (me - step - 1) % C
                    t = _send_async(nright, nblocks[send_idx])
                    nblocks[recv_idx] = _recv(nleft)
                    t.join()
            full = b"".join(nblocks)
            # Phase 3: fan the full buffer out to the rest of the node.
            threads = [_send_async(engine._data[r], full)
                       for r in local[1:]]
            for t in threads:
                t.join()
        else:
            full = _recv(engine._data[local[0]])

        arr = np.frombuffer(full, dtype=dtype).copy()
        results.append(arr.reshape((sum(first_dims),) + rest_shape))
    return results


class HierarchicalAllgather:
    def enabled(self, engine, resp):
        return (not resp.process_set_id
                and not getattr(engine, "_evicted_ranks", None)
                and getattr(engine, "hierarchical_allgather", False)
                and engine.hierarchical_topology_ok())

    def execute(self, engine, entries, resp):
        return _allgather_hierarchical(engine, entries, resp)


class RingAllgather:
    def enabled(self, engine, resp):
        return True

    def execute(self, engine, entries, resp):
        return _allgather_flat(engine, entries, resp)


ALLGATHER_CHAIN = (HierarchicalAllgather(), RingAllgather())


def allgather(engine, entries, resp: Response):
    """Allgather through the candidate chain (see ALLREDUCE_CHAIN)."""
    return next(c for c in ALLGATHER_CHAIN
                if c.enabled(engine, resp)).execute(engine, entries, resp)


def _allgather_flat(engine, entries, resp: Response):
    """Ragged ring allgatherv; one entry per response.  For a process
    set, the ring walks the member list (``resp.tensor_sizes`` is in
    member order)."""
    group, me = resp_group(engine, resp)
    size = len(group)
    results = []
    for e in entries:
        first_dims = resp.tensor_sizes
        if not resp.process_set_id and len(first_dims) != size:
            # Global-set sizes are negotiated in world-rank order; after
            # an eviction the group is smaller — keep the members' slots.
            first_dims = [first_dims[r] for r in group]
        rest_shape = e.array.shape[1:] if e.array.ndim > 0 else ()
        dtype = _np_dtype(resp.tensor_type)
        blocks: List[Optional[np.ndarray]] = [None] * size
        blocks[me] = np.ascontiguousarray(e.array)
        if size > 1:
            right = engine._data[group[(me + 1) % size]]
            left = engine._data[group[(me - 1) % size]]
            for step in range(size - 1):
                send_idx = (me - step) % size
                recv_idx = (me - step - 1) % size
                t = _send_async(right, blocks[send_idx].tobytes())
                payload = _recv(left)
                t.join()
                blk = np.frombuffer(payload, dtype=dtype)
                blocks[recv_idx] = blk.reshape(
                    (first_dims[recv_idx],) + rest_shape)
        results.append(np.concatenate(blocks, axis=0)
                       if size > 1 else blocks[me].copy())
    return results


def reducescatter(engine, entries, resp: Response):
    """Ring reduce-scatter: reduce across ranks, scatter over dim 0.

    Rank ``r`` receives the reduced rows ``bounds[r]:bounds[r+1]`` of an
    NCCL-style near-equal row split (larger chunks on lower ranks, like
    the reference project's later ``hvd.reducescatter``).  The ring walk
    is the reduce-scatter phase of ``_ring_allreduce_group`` shifted by
    one virtual rank so each rank finishes owning its own chunk; the
    chunk boundaries align to dim-0 rows, not the flat element split.
    """
    group, me = resp_group(engine, resp)
    size = len(group)
    op = resp.reduce_op
    dtype = _np_dtype(resp.tensor_type)
    results = []
    for e in entries:
        arr = np.ascontiguousarray(e.array).astype(dtype, copy=False)
        d0 = arr.shape[0]
        rest = arr.shape[1:]
        bounds = _chunk_bounds(d0, size)
        if size == 1:
            results.append(arr.copy())
            continue
        chunks = [arr[bounds[i]:bounds[i + 1]].copy()
                  for i in range(size)]
        right = engine._data[group[(me + 1) % size]]
        left = engine._data[group[(me - 1) % size]]
        # Virtual rank (me-1): the standard walk leaves member r owning
        # chunk (r+1)%size; shifting by one leaves it owning chunk r.
        for step in range(size - 1):
            send_idx = (me - 1 - step) % size
            recv_idx = (me - 2 - step) % size
            t = _send_async(right, chunks[send_idx].tobytes())
            incoming = np.frombuffer(_recv(left), dtype=dtype).reshape(
                (bounds[recv_idx + 1] - bounds[recv_idx],) + rest).copy()
            t.join()
            chunks[recv_idx] = _combine(incoming, chunks[recv_idx], op)
        out = chunks[me]
        if op == ReduceOp.AVERAGE:
            if _needs_f32_math(dtype):
                out = (out.astype(np.float32) / size).astype(dtype)
            else:
                out = out / dtype.type(size)
        results.append(out)
    return results


def broadcast(engine, entries, resp: Response):
    group, _me = resp_group(engine, resp)
    rank = engine.rank
    results = []
    for e in entries:
        root = int(resp.tensor_sizes[0]) if resp.tensor_sizes \
            else e.root_rank  # root is a GLOBAL rank (set member)
        if len(group) == 1:
            results.append(e.array.copy())
            continue
        if rank == root:
            payload = np.ascontiguousarray(e.array).tobytes()
            threads = [_send_async(engine._data[r], payload)
                       for r in group if r != root]
            for t in threads:
                t.join()
            results.append(e.array.copy())
        else:
            payload = _recv(engine._data[root])
            arr = np.frombuffer(
                payload, dtype=_np_dtype(resp.tensor_type)).copy()
            results.append(arr.reshape(e.array.shape))
    return results


def alltoall(engine, entries, resp: Response):
    # Pairwise exchange rounds; for a process set, partners walk the
    # member list (parity with csrc Engine::DoAlltoall).
    group, rank = resp_group(engine, resp)
    size = len(group)
    results = []
    for e in entries:
        splits = e.splits
        if splits is None:
            if e.array.shape[0] % size:
                raise ValueError(
                    "alltoall without splits requires dim 0 divisible by "
                    "the participant count")
            per = e.array.shape[0] // size
            splits = [per] * size
        offs = np.concatenate([[0], np.cumsum(splits)])
        my_blocks = [np.ascontiguousarray(
            e.array[offs[r]:offs[r + 1]]) for r in range(size)]
        recv_blocks: List[Optional[np.ndarray]] = [None] * size
        recv_blocks[rank] = my_blocks[rank].copy()
        rest_shape = e.array.shape[1:]
        dtype = _np_dtype(resp.tensor_type)
        for step in range(1, size):
            dst = (rank + step) % size
            src = (rank - step) % size
            t = _send_async(engine._data[group[dst]],
                            my_blocks[dst].tobytes())
            payload = _recv(engine._data[group[src]])
            t.join()
            blk = np.frombuffer(payload, dtype=dtype)
            if rest_shape:
                blk = blk.reshape((-1,) + rest_shape)
            recv_blocks[src] = blk.copy()
        recv_splits = [b.shape[0] for b in recv_blocks]
        results.append((np.concatenate(recv_blocks, axis=0)
                        if recv_blocks else e.array.copy(),
                        recv_splits))
    return results


def barrier(engine, resp: Response) -> None:
    # Unconditional group walk, mirroring csrc Engine::DoBarrier —
    # resp_group returns the full world for the global set.
    group, me = resp_group(engine, resp)
    _ring_allreduce_group(engine, np.zeros(1, np.int32), ReduceOp.SUM,
                          group, me)
