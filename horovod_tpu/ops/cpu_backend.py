"""CPU data-plane collectives over the TCP mesh: the correctness oracle.

Role parity: ``horovod/common/ops/gloo_operations.cc`` (the reference's CPU
backend, ring algorithms from the gloo library) and ``mpi_operations.cc``.
Algorithms:

* allreduce  — ring reduce-scatter + ring allgather (the NCCL/gloo ring),
  with fp32 per-hop accumulation for 16-bit dtypes matching the reference's
  custom fp16 MPI op (``half.cc:43-77`` promotes to float to add).
* allgather  — ragged ring allgatherv driven by the negotiated first-dim
  sizes in the Response (parity: ``MPIAllgather`` displacement logic,
  mpi_operations.cc:83-166).
* broadcast  — star from the root (control-plane scale data; the TPU
  in-graph path is where broadcast bandwidth matters).
* alltoall   — size-1 rounds of pairwise exchange.
* adasum     — recursive distance-doubling partner exchange (see
  ops/adasum.py for the math; eager variant used when Request.reduce_op is
  ADASUM, parity: adasum_mpi_operations.cc).

Data-plane hot path (docs/performance.md): sends ride one persistent
:class:`~horovod_tpu.utils.socketutil.PeerSender` thread per peer socket
(so ring steps overlap send and recv without spawning a thread per hop),
entries are packed once into the engine's persistent
:class:`~horovod_tpu.ops.fusion_buffer.FusionBuffer` and the ring
reduce-scatter/allgather walks slices of it in place (``recv_into`` a
preallocated hop buffer, ufuncs with ``out=``, no trailing concatenate),
and each hop's receive is optionally segmented at ``HVD_RING_SEGMENT_BYTES``
so reducing segment k overlaps the kernel receiving segment k+1
(DeAR-style, arXiv:2302.12445).  Segmentation is receiver-local — the wire
still carries one frame per hop, so segmented and unsegmented peers (and
the native C++ engine) interoperate.  Results are bit-identical to the
copy-per-hop implementation this replaced: operand order and the fp32
accumulation path for sub-32-bit floats are preserved exactly.
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from horovod_tpu.common import fault_injection as _fi
# Raised out of a ladder link (HVD_WIRE_CRC=1) when every self-healing
# rung is exhausted; propagates through the collectives untouched (it is
# a ConnectionError, deliberately NOT mapped to HopTimeout — the peer is
# provably misbehaving, not merely slow) and the engine escalates it
# into the same gang-wide abort agreement as a hop deadline.
from horovod_tpu.common.wire import WireCorruptionError  # noqa: F401
from horovod_tpu.common.types import DataType, ReduceOp, Response
from horovod_tpu.ops.fusion_buffer import FusionBuffer
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import socketutil as su
from horovod_tpu.utils import transport as tpt


def _np_dtype(dt: DataType):
    from horovod_tpu.runtime_py import _np_dtype as f

    return f(dt)


class HopTimeout(TimeoutError):
    """A ring hop blocked past the collective deadline.

    Carries the global rank this rank was blocked on (``peer``) so the
    engine can report the suspect to the coordinator for the gang-wide
    abort agreement (docs/fault_tolerance.md, "hung ranks vs dead
    ranks").  ``peer`` is -1 when the blocking side is unknown.
    """

    def __init__(self, peer: int, phase: str):
        super().__init__(
            f"ring hop ({phase}) blocked past the collective deadline "
            f"waiting on rank {peer}")
        self.peer = int(peer)
        self.phase = phase


def _deadline(engine) -> Optional[float]:
    """Absolute ``time.monotonic()`` deadline for a collective starting
    now, or ``None`` when ``HVD_COLLECTIVE_TIMEOUT`` is off (bare test
    engines carry no knob attribute — also ``None``, the block-forever
    behavior the deadline subsystem replaces only on request)."""
    t = float(getattr(engine, "collective_timeout", 0.0) or 0.0)
    return (time.monotonic() + t) if t > 0 else None


def _wait_send(sender, ticket: int,
               deadline: Optional[float], peer: int) -> None:
    """``wait(ticket)`` on a transport or raw ``PeerSender`` with a
    timeout ALWAYS set: the collective deadline when one is active, else
    the generous always-on ``HVD_SEND_WAIT_CAP_S`` backstop — a dead
    sender thread must never hang a hop silently."""
    if deadline is None:
        cap = max(0.001, env_util.send_wait_cap_s())
    else:
        cap = max(0.001, deadline - time.monotonic())
    try:
        sender.wait(ticket, cap)
    except HopTimeout:
        raise
    except TimeoutError:
        raise HopTimeout(peer, "send") from None


def _recv_exact_hop(tr: tpt.Transport, view: memoryview,
                    deadline: Optional[float], peer: int) -> None:
    try:
        tr.recv_exact_into(view, deadline)
    except TimeoutError:
        raise HopTimeout(peer, "recv") from None


def _sender(engine, rank: int) -> su.PeerSender:
    """The persistent sender for peer ``rank`` — created at engine
    bootstrap; lazily instantiated here for bare test engines."""
    senders = getattr(engine, "_senders", None)
    if senders is None:
        senders = engine._senders = {}
    s = senders.get(rank)
    if s is None:
        s = senders[rank] = su.PeerSender(
            engine._data[rank], name=f"hvd-send-{rank}")
    return s


def _transport(engine, rank: int) -> tpt.Transport:
    """The peer link for ``rank``: selected at engine bootstrap (shm ring
    for same-host peers, TCP otherwise); lazily wrapped here for bare
    test engines, reusing the engine's persistent ``PeerSender`` so no
    second sender thread ever appears for a peer."""
    transports = getattr(engine, "_transports", None)
    if transports is None:
        transports = engine._transports = {}
    t = transports.get(rank)
    if t is None:
        t = transports[rank] = tpt.TcpTransport(
            engine._data[rank], peer=rank, sender=_sender(engine, rank))
    return t


def _scratch(engine) -> FusionBuffer:
    fb = getattr(engine, "_fusion_buf", None)
    if fb is None:
        fb = engine._fusion_buf = FusionBuffer()
    return fb


def _segment_elems(engine, itemsize: int) -> int:
    """Ring-hop receive segment in elements (0 = unsegmented), from the
    engine's ``ring_segment_bytes`` knob rounded down to whole elements."""
    seg = int(getattr(engine, "ring_segment_bytes", 0) or 0)
    if seg <= 0:
        return 0
    return max(1, seg // itemsize)


def _recv(tr: tpt.Transport, deadline: Optional[float] = None,
          peer: int = -1) -> bytes:
    # The stall chaos site (sock.stall / shm.stall) fires inside the
    # transport's recv_frame, preserving one fire per received frame.
    try:
        tag, payload = tr.recv_frame(deadline)
    except TimeoutError:
        raise HopTimeout(peer, "recv") from None
    if tag != su.TAG_DATA:
        raise ConnectionError(f"expected data frame, got tag {tag}")
    return payload


def _recv_data_header(tr: tpt.Transport, deadline: Optional[float] = None,
                      peer: int = -1) -> int:
    try:
        tag, nbytes = tr.recv_frame_header(deadline)
    except TimeoutError:
        raise HopTimeout(peer, "recv") from None
    if tag != su.TAG_DATA:
        raise ConnectionError(f"expected data frame, got tag {tag}")
    return nbytes


def _recv_into(tr: tpt.Transport, dst: np.ndarray,
               deadline: Optional[float] = None, peer: int = -1) -> None:
    """Receive one data frame straight into ``dst`` (contiguous view)."""
    nbytes = _recv_data_header(tr, deadline, peer)
    if nbytes != dst.nbytes:
        raise ConnectionError(
            f"ring hop size mismatch: got {nbytes} bytes, expected "
            f"{dst.nbytes}")
    if nbytes:
        _recv_exact_hop(tr, memoryview(dst.view(np.uint8)), deadline,
                        peer)


def _needs_f32_math(dtype: np.dtype) -> bool:
    """Sub-32-bit floats (fp16/bf16/fp8) do their arithmetic in fp32,
    like half.cc."""
    return dtype.name in ("float16", "bfloat16", "float8_e4m3fn",
                          "float8_e5m2")


def _combine(a: np.ndarray, b: np.ndarray, op: ReduceOp) -> np.ndarray:
    """Per-hop reduction; sub-32-bit floats accumulate via fp32 like
    half.cc (fp8 wire formats included)."""
    if _needs_f32_math(a.dtype):
        a32, b32 = a.astype(np.float32), b.astype(np.float32)
        out = _combine(a32, b32, op)
        return out.astype(a.dtype)
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
        return a + b
    if op == ReduceOp.MIN:
        return np.minimum(a, b)
    if op == ReduceOp.MAX:
        return np.maximum(a, b)
    if op == ReduceOp.PRODUCT:
        return a * b
    raise ValueError(f"unsupported reduce op {op}")


def _combine_out(a: np.ndarray, b: np.ndarray, out: np.ndarray,
                 op: ReduceOp) -> None:
    """``out[...] = combine(a, b)`` without allocating.  Operand order
    matches :func:`_combine` so results stay bit-identical (NaN payload
    propagation included)."""
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
        np.add(a, b, out=out)
    elif op == ReduceOp.MIN:
        np.minimum(a, b, out=out)
    elif op == ReduceOp.MAX:
        np.maximum(a, b, out=out)
    elif op == ReduceOp.PRODUCT:
        np.multiply(a, b, out=out)
    else:
        raise ValueError(f"unsupported reduce op {op}")


def _combine_into(incoming: np.ndarray, mine: np.ndarray, op: ReduceOp,
                  fb: FusionBuffer) -> None:
    """In-place hop reduction: ``mine[...] = combine(incoming, mine)``.

    Bit-identical to ``_combine(incoming, mine)``: same operand order,
    and sub-32-bit floats route through persistent fp32 scratch (upcast
    → reduce → downcast, the half.cc path) instead of ``astype``
    temporaries."""
    if _needs_f32_math(mine.dtype):
        n = mine.size
        a32, b32 = fb.f32_views(n)
        a32[...] = incoming
        b32[...] = mine
        _combine_out(a32, b32, b32, op)
        mine[...] = b32
        return
    _combine_out(incoming, mine, mine, op)


def _recv_combine(tr: tpt.Transport, mine: np.ndarray, hop: np.ndarray,
                  hop_mv: memoryview, op: ReduceOp, seg: int,
                  fb: FusionBuffer, deadline: Optional[float] = None,
                  peer: int = -1,
                  reduce_ns: Optional[list] = None) -> None:
    """Receive one hop's chunk and reduce it into ``mine`` in place.

    With ``seg`` > 0, the payload is drained in ``seg``-element slices:
    while numpy reduces slice k, the peer (kernel socket buffer or shm
    ring writer) keeps producing slice k+1 — the DeAR-style
    transfer/reduction overlap, with no extra threads and no
    wire-format change.

    ``reduce_ns`` (tracing only — None on the untraced hot path) is a
    one-element accumulator that separates reduction time from wire
    wait inside this combined receive."""
    nbytes = _recv_data_header(tr, deadline, peer)
    n = mine.size
    isz = mine.itemsize
    if nbytes != n * isz:
        raise ConnectionError(
            f"ring hop size mismatch: got {nbytes} bytes, expected "
            f"{n * isz}")
    if n == 0:
        return
    if seg <= 0 or seg >= n:
        _recv_exact_hop(tr, hop_mv[:nbytes], deadline, peer)
        if reduce_ns is None:
            _combine_into(hop[:n], mine, op, fb)
        else:
            r0 = time.monotonic_ns()
            _combine_into(hop[:n], mine, op, fb)
            reduce_ns[0] += time.monotonic_ns() - r0
        return
    done = 0
    while done < n:
        k = min(seg, n - done)
        _recv_exact_hop(tr, hop_mv[done * isz:(done + k) * isz],
                        deadline, peer)
        if reduce_ns is None:
            _combine_into(hop[done:done + k], mine[done:done + k], op, fb)
        else:
            r0 = time.monotonic_ns()
            _combine_into(hop[done:done + k], mine[done:done + k], op, fb)
            reduce_ns[0] += time.monotonic_ns() - r0
        done += k


def _chunk_bounds(n: int, parts: int) -> List[int]:
    """NCCL-style near-equal split: bounds[i]..bounds[i+1] is chunk i."""
    base, rem = divmod(n, parts)
    bounds = [0]
    for i in range(parts):
        bounds.append(bounds[-1] + base + (1 if i < rem else 0))
    return bounds


def ring_allreduce_flat(engine, flat: np.ndarray,
                        op: ReduceOp) -> np.ndarray:
    """Ring allreduce of a flat array; the input is left unmodified and
    the reduced result is returned as a new array."""
    group = list(range(engine.size))
    return _ring_allreduce_group(engine, flat.copy(), op, group,
                                 engine.rank, _deadline(engine))


def _ring_allreduce_group(engine, flat: np.ndarray, op: ReduceOp,
                          group, me: int,
                          deadline: Optional[float] = None) -> np.ndarray:
    """Ring allreduce restricted to ``group`` (global ranks, any order);
    ``me`` is this rank's index within it.  Same chunk walk as the C++
    engine (RingAllreduceGroup) so mixed jobs stay bit-identical.

    Operates IN PLACE on ``flat`` (and returns it): callers pass scratch
    — a fusion-buffer view or their own copy.  Each step's send region
    and recv/reduce region are adjacent-but-disjoint chunks, so the
    sender thread reads stable memory while this thread reduces."""
    size = len(group)
    if size == 1:
        return flat
    right_rank = group[(me + 1) % size]
    left_rank = group[(me - 1) % size]
    right = _transport(engine, right_rank)
    left = _transport(engine, left_rank)
    dtype = flat.dtype
    bounds = _chunk_bounds(flat.size, size)
    max_chunk = max(bounds[i + 1] - bounds[i] for i in range(size))
    fb = _scratch(engine)
    hop = fb.hop_view(max_chunk, dtype)
    hop_mv = memoryview(hop.view(np.uint8))
    seg = _segment_elems(engine, dtype.itemsize)
    timed = _tmx.enabled()
    tracer = getattr(engine, "_tracer", None)
    # Tracing-only reduce-time accumulator threaded into _recv_combine;
    # None keeps the untraced hot path allocation-identical (pinned by
    # tests/test_dataplane.py steady-state tracemalloc test).
    rns = [0] if tracer is not None else None

    # Phase 1: ring reduce-scatter.
    for step in range(size - 1):
        t0 = time.perf_counter() if timed else 0.0
        tr0 = time.monotonic_ns() if tracer is not None else 0
        send_idx = (me - step) % size
        recv_idx = (me - step - 1) % size
        ticket = right.send(flat[bounds[send_idx]:bounds[send_idx + 1]])
        _recv_combine(left, flat[bounds[recv_idx]:bounds[recv_idx + 1]],
                      hop, hop_mv, op, seg, fb, deadline, left_rank,
                      reduce_ns=rns)
        tr1 = time.monotonic_ns() if tracer is not None else 0
        _wait_send(right, ticket, deadline, right_rank)
        if timed:
            _tmx.observe("hvd_ring_hop_seconds",
                         time.perf_counter() - t0, ("reduce_scatter",))
        if tracer is not None:
            tr2 = time.monotonic_ns()
            tracer.span("hop", tr0, tr2, ring="reduce_scatter", hop=step,
                        peer=left_rank, tp=left.kind,
                        recv_ns=tr1 - tr0 - rns[0], reduce_ns=rns[0],
                        send_wait_ns=tr2 - tr1)
            rns[0] = 0

    # Phase 2: ring allgather of the reduced chunks, straight into place.
    for step in range(size - 1):
        t0 = time.perf_counter() if timed else 0.0
        tr0 = time.monotonic_ns() if tracer is not None else 0
        send_idx = (me + 1 - step) % size
        recv_idx = (me - step) % size
        ticket = right.send(flat[bounds[send_idx]:bounds[send_idx + 1]])
        _recv_into(left, flat[bounds[recv_idx]:bounds[recv_idx + 1]],
                   deadline, left_rank)
        tr1 = time.monotonic_ns() if tracer is not None else 0
        _wait_send(right, ticket, deadline, right_rank)
        if timed:
            _tmx.observe("hvd_ring_hop_seconds",
                         time.perf_counter() - t0, ("allgather",))
        if tracer is not None:
            tr2 = time.monotonic_ns()
            tracer.span("hop", tr0, tr2, ring="allgather", hop=step,
                        peer=left_rank, tp=left.kind,
                        recv_ns=tr1 - tr0, reduce_ns=0,
                        send_wait_ns=tr2 - tr1)

    return flat


def _local_group(engine):
    L = engine.local_size
    return [engine.cross_rank * L + i for i in range(L)]


def _cross_group(engine):
    L = engine.local_size
    return [k * L + engine.local_rank for k in range(engine.cross_size)]


def hierarchical_allreduce_flat(engine, flat: np.ndarray, op: ReduceOp,
                                deadline: Optional[float] = None
                                ) -> np.ndarray:
    """Two-level allreduce: local ring reduce-scatter → cross ring
    allreduce of the owned 1/local_size slice → local ring allgather.

    TPU-design parity: ``NCCLHierarchicalAllreduce``
    (nccl_operations.cc:163-363) — the bandwidth-heavy phases ride the
    node-local links; only 1/local_size of the bytes crosses nodes.
    Requires the launcher's homogeneous block rank layout, checked by
    ``engine.hierarchical_topology_ok()`` before dispatching here.
    In place on ``flat`` like :func:`_ring_allreduce_group`.
    """
    L = engine.local_size
    li = engine.local_rank
    local = _local_group(engine)
    right_rank = local[(li + 1) % L]
    left_rank = local[(li - 1) % L]
    right = _transport(engine, right_rank)
    left = _transport(engine, left_rank)
    dtype = flat.dtype
    bounds = _chunk_bounds(flat.size, L)
    max_chunk = max(bounds[i + 1] - bounds[i] for i in range(L))
    fb = _scratch(engine)
    hop = fb.hop_view(max_chunk, dtype)
    hop_mv = memoryview(hop.view(np.uint8))
    seg = _segment_elems(engine, dtype.itemsize)

    # Phase 1: local ring reduce-scatter.
    for step in range(L - 1):
        send_idx = (li - step) % L
        recv_idx = (li - step - 1) % L
        ticket = right.send(flat[bounds[send_idx]:bounds[send_idx + 1]])
        _recv_combine(left, flat[bounds[recv_idx]:bounds[recv_idx + 1]],
                      hop, hop_mv, op, seg, fb, deadline, left_rank)
        _wait_send(right, ticket, deadline, right_rank)

    # Phase 2: cross-node ring allreduce of the fully-reduced owned
    # chunk, in place on its slice of the fusion buffer.
    own = (li + 1) % L
    own_slice = flat[bounds[own]:bounds[own + 1]]
    if own_slice.size:
        _ring_allreduce_group(engine, own_slice, op, _cross_group(engine),
                              engine.cross_rank, deadline)

    # Phase 3: local ring allgather.
    for step in range(L - 1):
        send_idx = (li + 1 - step) % L
        recv_idx = (li - step) % L
        ticket = right.send(flat[bounds[send_idx]:bounds[send_idx + 1]])
        _recv_into(left, flat[bounds[recv_idx]:bounds[recv_idx + 1]],
                   deadline, left_rank)
        _wait_send(right, ticket, deadline, right_rank)

    return flat


def _adasum_flat(engine, flat: np.ndarray,
                 deadline: Optional[float] = None) -> np.ndarray:
    """Eager Adasum via recursive distance-doubling partner exchange.
    Power-of-two sizes only (the reference's VHDD also specializes
    power-of-two and handles the remainder separately — not needed for TPU
    pods, which are power-of-two)."""
    size, rank = engine.size, engine.rank
    if size == 1:
        return flat
    if size & (size - 1):
        raise ValueError("Adasum requires a power-of-two world size")
    from horovod_tpu.ops.adasum import adasum_pair_numpy

    acc = flat.astype(np.float64)
    k = 1
    while k < size:
        partner = rank ^ k
        tr = _transport(engine, partner)
        ticket = tr.send(acc)
        other = np.frombuffer(_recv(tr, deadline, partner),
                              dtype=np.float64).copy()
        _wait_send(tr, ticket, deadline, partner)
        if rank < partner:
            acc = adasum_pair_numpy(acc, other)
        else:
            acc = adasum_pair_numpy(other, acc)
        k *= 2
    return acc.astype(flat.dtype)


def resp_group(engine, resp: Response):
    """(member global ranks, my index) for a response — the full world
    for the global set, the registered member list for a process set.

    Ranks the coordinator evicted (heartbeat liveness, PyEngine only)
    drop out of the global group: every survivor filters identically, so
    the ring stays coherent without the dead peer."""
    if resp.process_set_id:
        from horovod_tpu import process_sets

        members = process_sets.ranks_of(resp.process_set_id)
        return members, members.index(engine.rank)
    evicted = getattr(engine, "_evicted_ranks", None)
    if evicted:
        group = [r for r in range(engine.size) if r not in evicted]
        return group, group.index(engine.rank)
    return list(range(engine.size)), engine.rank


class _AllreduceCandidate:
    """One entry of the allreduce dispatch chain (parity: the reference's
    per-category op list in ``ops/operation_manager.cc:37-104`` — ordered
    candidates, the first whose ``enabled()`` returns True executes)."""

    def enabled(self, engine, resp: Response) -> bool:
        raise NotImplementedError

    def execute(self, engine, flat: np.ndarray, op: ReduceOp,
                group, me, deadline=None) -> np.ndarray:
        raise NotImplementedError


class AdasumAllreduce(_AllreduceCandidate):
    def enabled(self, engine, resp):
        # Adasum's distance-doubling assumes the global power-of-two
        # topology; process sets (and a post-eviction shrunken group)
        # fall through to the ring.
        return resp.reduce_op == ReduceOp.ADASUM \
            and not resp.process_set_id \
            and not getattr(engine, "_evicted_ranks", None)

    def execute(self, engine, flat, op, group, me, deadline=None):
        return _adasum_flat(engine, flat, deadline)


class HierarchicalAllreduce(_AllreduceCandidate):
    def enabled(self, engine, resp):
        return (resp.reduce_op != ReduceOp.ADASUM
                and not resp.process_set_id
                and not getattr(engine, "_evicted_ranks", None)
                and getattr(engine, "hierarchical_allreduce", False)
                and engine.hierarchical_topology_ok())

    def execute(self, engine, flat, op, group, me, deadline=None):
        return hierarchical_allreduce_flat(engine, flat, op, deadline)


class RingAllreduce(_AllreduceCandidate):
    def enabled(self, engine, resp):
        return True

    def execute(self, engine, flat, op, group, me, deadline=None):
        return _ring_allreduce_group(engine, flat, op, group, me,
                                     deadline)


# Priority order mirrors the reference's CreateOperationManager chain
# (operations.cc:142-228): specialized ops first, flat ring as the
# always-enabled fallback.
ALLREDUCE_CHAIN = (AdasumAllreduce(), HierarchicalAllreduce(),
                   RingAllreduce())


def allreduce(engine, entries, resp: Response):
    """Fused allreduce over all entries of the response.  The op and the
    scale factors come from the negotiated response (identical on every
    rank, including joined ranks whose entries are zero stand-ins).

    Entries are packed once into the engine's persistent fusion buffer;
    the ring then mutates that scratch in place.  ``fused`` tracks
    whether ``reduced`` still aliases the fusion buffer — if it does,
    results are carved from a per-collective copy so the next collective
    cannot clobber them."""
    op = resp.reduce_op
    prescale = resp.prescale_factor
    postscale = resp.postscale_factor
    dtype = _np_dtype(resp.tensor_type)
    fb = _scratch(engine)
    tracer = getattr(engine, "_tracer", None)
    tp0 = time.monotonic_ns() if tracer is not None else 0
    flat = fb.pack(entries, dtype)
    if tracer is not None:
        tracer.span("pack", tp0, time.monotonic_ns(),
                    tensors=len(entries), nbytes=int(flat.nbytes))
    fused = True
    if prescale != 1.0:
        if _needs_f32_math(dtype):
            flat = (flat.astype(np.float32) * prescale).astype(dtype)
        else:
            flat = flat * dtype.type(prescale)
        fused = False

    group, me = resp_group(engine, resp)
    reduced = next(c for c in ALLREDUCE_CHAIN
                   if c.enabled(engine, resp)).execute(
                       engine, flat, op, group, me, _deadline(engine))
    fused = fused and reduced is flat

    if op == ReduceOp.AVERAGE:
        n = len(group)
        if _needs_f32_math(dtype):
            reduced = (reduced.astype(np.float32) / n).astype(dtype)
        else:
            reduced = reduced / dtype.type(n)
        fused = False
    if postscale != 1.0:
        reduced = (reduced * postscale).astype(dtype, copy=False)
        fused = False
    if fused:
        reduced = reduced.copy()
    tu0 = time.monotonic_ns() if tracer is not None else 0
    out = fb.unpack(reduced, entries)
    if tracer is not None:
        tracer.span("unpack", tu0, time.monotonic_ns(),
                    tensors=len(entries))
    return out


def _allgather_hierarchical(engine, entries, resp: Response):
    """Two-level allgatherv (role parity: MPIHierarchicalAllgather,
    mpi_operations.cc:168-309 — there via a node-shared MPI window, here
    via the node ring + a leaders-only cross ring + local fan-out).
    Output ordering matches the flat path because the block rank layout
    makes node blocks contiguous in global rank order."""
    L, li = engine.local_size, engine.local_rank
    C = engine.cross_size
    local = _local_group(engine)
    dl = _deadline(engine)
    results = []
    for e in entries:
        dtype = _np_dtype(resp.tensor_type)
        rest_shape = e.array.shape[1:] if e.array.ndim > 0 else ()
        first_dims = resp.tensor_sizes

        # Phase 1: node-local ragged ring allgatherv (raw bytes).
        blocks: List[Optional[bytes]] = [None] * L
        blocks[li] = np.ascontiguousarray(e.array).tobytes()
        right_rank = local[(li + 1) % L]
        left_rank = local[(li - 1) % L]
        right = _transport(engine, right_rank)
        left = _transport(engine, left_rank)
        for step in range(L - 1):
            send_idx = (li - step) % L
            recv_idx = (li - step - 1) % L
            ticket = right.send(blocks[send_idx])
            blocks[recv_idx] = _recv(left, dl, left_rank)
            _wait_send(right, ticket, dl, right_rank)
        node_block = b"".join(blocks)

        if li == 0:
            # Phase 2: leaders' ragged ring allgatherv of node blocks.
            me = engine.cross_rank
            nblocks: List[Optional[bytes]] = [None] * C
            nblocks[me] = node_block
            if C > 1:
                nright_rank = ((me + 1) % C) * L
                nleft_rank = ((me - 1) % C) * L
                nright = _transport(engine, nright_rank)
                nleft = _transport(engine, nleft_rank)
                for step in range(C - 1):
                    send_idx = (me - step) % C
                    recv_idx = (me - step - 1) % C
                    ticket = nright.send(nblocks[send_idx])
                    nblocks[recv_idx] = _recv(nleft, dl, nleft_rank)
                    _wait_send(nright, ticket, dl, nright_rank)
            full = b"".join(nblocks)
            # Phase 3: fan the full buffer out to the rest of the node
            # on their persistent senders (the seed spawned a thread per
            # peer per tensor here).
            tickets = [(r, _transport(engine, r),
                        _transport(engine, r).send(full))
                       for r in local[1:]]
            for r, s, ticket in tickets:
                _wait_send(s, ticket, dl, r)
        else:
            full = _recv(_transport(engine, local[0]), dl, local[0])

        arr = np.frombuffer(full, dtype=dtype).copy()
        results.append(arr.reshape((sum(first_dims),) + rest_shape))
    return results


class HierarchicalAllgather:
    def enabled(self, engine, resp):
        return (not resp.process_set_id
                and not getattr(engine, "_evicted_ranks", None)
                and getattr(engine, "hierarchical_allgather", False)
                and engine.hierarchical_topology_ok())

    def execute(self, engine, entries, resp):
        return _allgather_hierarchical(engine, entries, resp)


class RingAllgather:
    def enabled(self, engine, resp):
        return True

    def execute(self, engine, entries, resp):
        return _allgather_flat(engine, entries, resp)


ALLGATHER_CHAIN = (HierarchicalAllgather(), RingAllgather())


def allgather(engine, entries, resp: Response):
    """Allgather through the candidate chain (see ALLREDUCE_CHAIN)."""
    return next(c for c in ALLGATHER_CHAIN
                if c.enabled(engine, resp)).execute(engine, entries, resp)


def _allgather_flat(engine, entries, resp: Response):
    """Ragged ring allgatherv; one entry per response.  For a process
    set, the ring walks the member list (``resp.tensor_sizes`` is in
    member order)."""
    group, me = resp_group(engine, resp)
    size = len(group)
    dl = _deadline(engine)
    results = []
    for e in entries:
        first_dims = resp.tensor_sizes
        if not resp.process_set_id and len(first_dims) != size:
            # Global-set sizes are negotiated in world-rank order; after
            # an eviction the group is smaller — keep the members' slots.
            first_dims = [first_dims[r] for r in group]
        rest_shape = e.array.shape[1:] if e.array.ndim > 0 else ()
        dtype = _np_dtype(resp.tensor_type)
        blocks: List[Optional[np.ndarray]] = [None] * size
        blocks[me] = np.ascontiguousarray(e.array)
        if size > 1:
            right_rank = group[(me + 1) % size]
            left_rank = group[(me - 1) % size]
            right = _transport(engine, right_rank)
            left = _transport(engine, left_rank)
            for step in range(size - 1):
                send_idx = (me - step) % size
                recv_idx = (me - step - 1) % size
                ticket = right.send(blocks[send_idx])
                payload = _recv(left, dl, left_rank)
                _wait_send(right, ticket, dl, right_rank)
                blk = np.frombuffer(payload, dtype=dtype)
                blocks[recv_idx] = blk.reshape(
                    (first_dims[recv_idx],) + rest_shape)
        results.append(np.concatenate(blocks, axis=0)
                       if size > 1 else blocks[me].copy())
    return results


def reducescatter(engine, entries, resp: Response):
    """Ring reduce-scatter: reduce across ranks, scatter over dim 0.

    Rank ``r`` receives the reduced rows ``bounds[r]:bounds[r+1]`` of an
    NCCL-style near-equal row split (larger chunks on lower ranks, like
    the reference project's later ``hvd.reducescatter``).  The ring walk
    is the reduce-scatter phase of ``_ring_allreduce_group`` shifted by
    one virtual rank so each rank finishes owning its own chunk; the
    chunk boundaries align to dim-0 rows, not the flat element split.
    """
    group, me = resp_group(engine, resp)
    size = len(group)
    op = resp.reduce_op
    dtype = _np_dtype(resp.tensor_type)
    dl = _deadline(engine)
    results = []
    for e in entries:
        arr = np.ascontiguousarray(e.array).astype(dtype, copy=False)
        d0 = arr.shape[0]
        rest = arr.shape[1:]
        bounds = _chunk_bounds(d0, size)
        if size == 1:
            results.append(arr.copy())
            continue
        chunks = [arr[bounds[i]:bounds[i + 1]].copy()
                  for i in range(size)]
        right_rank = group[(me + 1) % size]
        left_rank = group[(me - 1) % size]
        right = _transport(engine, right_rank)
        left = _transport(engine, left_rank)
        # Virtual rank (me-1): the standard walk leaves member r owning
        # chunk (r+1)%size; shifting by one leaves it owning chunk r.
        for step in range(size - 1):
            send_idx = (me - 1 - step) % size
            recv_idx = (me - 2 - step) % size
            ticket = right.send(chunks[send_idx])
            incoming = np.frombuffer(
                _recv(left, dl, left_rank), dtype=dtype).reshape(
                (bounds[recv_idx + 1] - bounds[recv_idx],) + rest).copy()
            _wait_send(right, ticket, dl, right_rank)
            chunks[recv_idx] = _combine(incoming, chunks[recv_idx], op)
        out = chunks[me]
        if op == ReduceOp.AVERAGE:
            if _needs_f32_math(dtype):
                out = (out.astype(np.float32) / size).astype(dtype)
            else:
                out = out / dtype.type(size)
        results.append(out)
    return results


def broadcast(engine, entries, resp: Response):
    group, _me = resp_group(engine, resp)
    rank = engine.rank
    dl = _deadline(engine)
    results = []
    for e in entries:
        root = int(resp.tensor_sizes[0]) if resp.tensor_sizes \
            else e.root_rank  # root is a GLOBAL rank (set member)
        if len(group) == 1:
            results.append(e.array.copy())
            continue
        if rank == root:
            payload = np.ascontiguousarray(e.array)
            tickets = [(r, _transport(engine, r),
                        _transport(engine, r).send(payload))
                       for r in group if r != root]
            for r, s, ticket in tickets:
                _wait_send(s, ticket, dl, r)
            results.append(e.array.copy())
        else:
            payload = _recv(_transport(engine, root), dl, root)
            arr = np.frombuffer(
                payload, dtype=_np_dtype(resp.tensor_type)).copy()
            results.append(arr.reshape(e.array.shape))
    return results


def alltoall(engine, entries, resp: Response):
    # Pairwise exchange rounds; for a process set, partners walk the
    # member list (parity with csrc Engine::DoAlltoall).
    group, rank = resp_group(engine, resp)
    size = len(group)
    dl = _deadline(engine)
    results = []
    for e in entries:
        splits = e.splits
        if splits is None:
            if e.array.shape[0] % size:
                raise ValueError(
                    "alltoall without splits requires dim 0 divisible by "
                    "the participant count")
            per = e.array.shape[0] // size
            splits = [per] * size
        offs = np.concatenate([[0], np.cumsum(splits)])
        my_blocks = [np.ascontiguousarray(
            e.array[offs[r]:offs[r + 1]]) for r in range(size)]
        recv_blocks: List[Optional[np.ndarray]] = [None] * size
        recv_blocks[rank] = my_blocks[rank].copy()
        rest_shape = e.array.shape[1:]
        dtype = _np_dtype(resp.tensor_type)
        for step in range(1, size):
            dst = (rank + step) % size
            src = (rank - step) % size
            sender = _transport(engine, group[dst])
            ticket = sender.send(my_blocks[dst])
            payload = _recv(_transport(engine, group[src]), dl,
                            group[src])
            _wait_send(sender, ticket, dl, group[dst])
            blk = np.frombuffer(payload, dtype=dtype)
            if rest_shape:
                blk = blk.reshape((-1,) + rest_shape)
            recv_blocks[src] = blk.copy()
        recv_splits = [b.shape[0] for b in recv_blocks]
        results.append((np.concatenate(recv_blocks, axis=0)
                        if recv_blocks else e.array.copy(),
                        recv_splits))
    return results


def barrier(engine, resp: Response) -> None:
    # Unconditional group walk, mirroring csrc Engine::DoBarrier —
    # resp_group returns the full world for the global set.
    group, me = resp_group(engine, resp)
    _ring_allreduce_group(engine, np.zeros(1, np.int32), ReduceOp.SUM,
                          group, me, _deadline(engine))
