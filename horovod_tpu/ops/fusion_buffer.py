"""Persistent scratch memory for the eager data plane.

Role parity: the reference's ``FusionBufferManager`` (fusion_buffer_manager.cc)
— one long-lived buffer per engine that fused tensors are packed into, so the
steady-state collective hot path performs zero payload-sized allocations.
Four regions live here, all grown geometrically and never shrunk:

* ``data``  — the fusion buffer proper: entries are packed into it once and
  the ring reduce-scatter/allgather walks slices of it in place.
* ``hop``   — the ring's receive landing zone (one chunk, filled by
  ``recv_into``).
* ``f32a``/``f32b`` — fp32 scratch for sub-32-bit float arithmetic
  (fp16/bf16/fp8 hops upcast, reduce, downcast — half.cc parity — without
  allocating the temporaries ``astype`` would).

Raw storage is ``uint8``; views are reinterpreted per collective dtype via
``ndarray.view``, which works for ml_dtypes extension types (bfloat16, fp8)
whose PEP-3118 buffers ``memoryview`` rejects.  Growth is reported on the
``hvd_dataplane_alloc_bytes`` counter — in steady state it stays flat, which
is what the tracemalloc pin in tests/test_dataplane.py asserts.
"""

from __future__ import annotations

import numpy as np

from horovod_tpu.telemetry import registry as _tmx

_MIN_BYTES = 1024


class FusionBuffer:
    """Per-engine persistent buffers; not thread-safe (the engine's
    background loop is the only caller, one collective at a time)."""

    def __init__(self):
        self._data = np.empty(0, np.uint8)
        self._hop = np.empty(0, np.uint8)
        self._f32a = np.empty(0, np.float32)
        self._f32b = np.empty(0, np.float32)

    # -- growth ----------------------------------------------------------

    @staticmethod
    def _capacity(need: int, have: int) -> int:
        cap = max(have, _MIN_BYTES)
        while cap < need:
            cap *= 2
        return cap

    def _ensure_u8(self, buf: np.ndarray, nbytes: int) -> np.ndarray:
        if buf.nbytes >= nbytes:
            return buf
        cap = self._capacity(nbytes, buf.nbytes)
        _tmx.inc_counter("hvd_dataplane_alloc_bytes", cap)
        return np.empty(cap, np.uint8)

    # -- views -----------------------------------------------------------

    def data_view(self, n: int, dtype) -> np.ndarray:
        """Flat ``n``-element view of the fusion buffer as ``dtype``."""
        dtype = np.dtype(dtype)
        self._data = self._ensure_u8(self._data, n * dtype.itemsize)
        return self._data[:n * dtype.itemsize].view(dtype)

    def hop_view(self, n: int, dtype) -> np.ndarray:
        """Flat ``n``-element receive-scratch view as ``dtype``."""
        dtype = np.dtype(dtype)
        self._hop = self._ensure_u8(self._hop, n * dtype.itemsize)
        return self._hop[:n * dtype.itemsize].view(dtype)

    def f32_views(self, n: int):
        """Two ``n``-element fp32 scratch arrays (incoming, accumulator)."""
        if self._f32a.size < n:
            cap = self._capacity(n * 4, self._f32a.nbytes) // 4
            _tmx.inc_counter("hvd_dataplane_alloc_bytes", cap * 8)
            self._f32a = np.empty(cap, np.float32)
            self._f32b = np.empty(cap, np.float32)
        return self._f32a[:n], self._f32b[:n]

    # -- pack / unpack ---------------------------------------------------

    def pack(self, entries, dtype) -> np.ndarray:
        """Pack every entry's array, flattened and cast to ``dtype``, into
        the fusion buffer; returns the fused flat view.  One copy total —
        the same copy the seed's ``concatenate`` made, but into memory
        that is reused across collectives."""
        dtype = np.dtype(dtype)
        total = sum(int(e.array.size) for e in entries)
        flat = self.data_view(total, dtype)
        off = 0
        for e in entries:
            n = int(e.array.size)
            flat[off:off + n] = np.ravel(e.array)
            off += n
        return flat

    @staticmethod
    def unpack(flat: np.ndarray, entries):
        """Reshaped per-entry views over ``flat``.  The caller passes a
        per-collective copy (NOT the live fusion buffer) so results stay
        valid when the next collective repacks."""
        results = []
        off = 0
        for e in entries:
            n = int(e.array.size)
            results.append(flat[off:off + n].reshape(e.array.shape))
            off += n
        return results
