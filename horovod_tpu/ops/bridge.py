"""XLA host-callback bridge: compiled programs ride the negotiated engine.

This is the TPU counterpart of the reference's defining mechanism — the
framework-op-to-coordinator enqueue (``tensorflow/mpi_ops.cc:287-320``
``HorovodAllreduceOp::ComputeAsync`` → ``EnqueueTensorAllreduce``): a
collective called *inside* a jitted JAX program that routes through the
shared background engine, so compiled steps get the controller's full
subsystem stack — tensor **naming**, cross-rank **negotiation**, response
**fusion**, the response **cache**, the **timeline**, **join**/allreduce
interaction, and stall detection — none of which exist on the pure
``lax.psum`` in-graph path (``ops/collective.py``).

Mechanism
---------
Each op lowers to ``jax.experimental.io_callback(ordered=True)``.  At run
time XLA transfers the operand to the host, the callback enqueues it into
the engine (``allreduce_async`` et al.), blocks on ``synchronize``, and
returns the reduced buffer, which XLA transfers back.  The engine's
background thread negotiates with the coordinator exactly as for eager
ops — a bridge tensor and an eager tensor with the same name are
indistinguishable on the wire, and the results are bitwise identical
(same ring walk, same chunk math; asserted by
``tests/eager_worker.py::scenario_bridge_jit``).

Ordering / deadlock-freedom
---------------------------
``ordered=True`` makes XLA execute the callbacks in **program order**.
Every rank compiles the *same* traced program, so the sequence of
(blocking) bridge calls is identical on every rank: when rank 0 sits in
the callback for tensor ``k``, every other rank is in — or headed into —
the callback for the same tensor ``k``.  This is the static-schedule
answer to the async-enqueue problem the reference solves with
``ComputeAsync`` + done-callbacks (SURVEY.md §7 "hard parts"): a dynamic
framework scheduler may issue ops in different orders per rank and needs
the coordinator to re-order; XLA's fixed schedule makes the submission
order itself deterministic.  The coordinator still runs full name-based
negotiation underneath, so even the degenerate interleavings that
host-callback threading could produce (e.g. a second program launched
concurrently) resolve by name, and fusion batches are chosen by the
coordinator (rank 0) in negotiated order — identical on every rank.

For gradient reductions use :func:`grouped_allreduce` (one callback
enqueues *all* tensors asynchronously, then synchronizes them all): the
engine sees the whole group outstanding at once and fuses them into
large wire messages (``runtime_py.py::_fuse_responses``), which is the
compiled-path analog of the reference's fusion-buffer cycle.

Differentiation: ``allreduce``/``grouped_allreduce``/``allgather``/
``broadcast`` carry ``custom_vjp`` rules mirroring the reference's
registered gradients (``tensorflow/__init__.py`` ``_allreduce_grad``:
the gradient of an allreduce is an allreduce of the gradient, name
suffixed ``.grad``).

Shapes are static under jit, so the bridge supports the statically-shaped
subset: equal-shape allgather (ragged first dims negotiate only on the
eager path) and equal-split alltoall.  ``reducescatter`` output shapes are
rank-dependent but *trace-time-constant* (each process traces its own
program), so the NCCL-style near-equal row split works unchanged.

This regime targets the reference's deployment shape: one process per
accelerator (chip), jit placed on that process's device.  Inside a
multi-device ``shard_map``/``pjit`` program, use the mesh-axis collectives
in ``ops/collective.py`` — there XLA *is* the coordinator.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np

from horovod_tpu import basics
from horovod_tpu.common.types import ReduceOp


def _auto_name(kind: str, name: Optional[str]) -> str:
    """Trace-time fallback names (shared counter machinery with the eager
    surface — identical call order across ranks required; pass ``name=``
    in anything beyond a single train step, like the reference's TF graph
    mode derives names from op names)."""
    if name is not None:
        return name
    from horovod_tpu.ops import eager

    return eager._auto_name(f"bridge.{kind}", None)


_MISUSE_MSG = (
    "engine-bridge collectives cannot run inside shard_map/pmap "
    "bodies (named mesh axes are in scope — each shard would "
    "enqueue separately under one tensor name); use the in-graph "
    "mesh-axis collectives in horovod_tpu.ops.collective instead")


def _check_single_device_trace(*operands) -> None:
    """The bridge targets the reference's deployment shape: one process
    per chip, jit on that device.  Inside shard_map/pmap bodies (named
    mesh axes in scope) XLA is the coordinator — ordered host callbacks
    there would submit one enqueue per *shard* under the same tensor
    name; refuse with a pointer to the mesh-axis collectives.

    Two detection layers so the failure mode is a ``TypeError`` at trace
    time rather than a hang (tests/test_eager_single.py pins the raise
    on the shipped jax version):

    1. the axis-env probe (``nonempty_axis_env_DO_NOT_USE``, jax<=0.9);
    2. if a jax upgrade removes that API: the *operands* themselves —
       inside shard_map/pmap the arguments are tracers whose trace type
       lives in the shard_map/pmap interpreter module, which survives
       private-API churn far better than any probe function.
    """
    import jax.core

    probe = getattr(jax.core, "nonempty_axis_env_DO_NOT_USE", None)
    if probe is not None:
        if probe():
            raise TypeError(_MISUSE_MSG)
        return
    # Probe API gone: read the axis env directly (what the probe wraps).
    # Modern pmap traces through the ordinary jaxpr machinery, so the
    # operand tracers below cannot tell it apart from plain jit — the
    # axis env is the only reliable signal for it.
    try:
        from jax._src.core import get_axis_env

        if get_axis_env().axis_sizes:
            raise TypeError(_MISUSE_MSG)
        return
    except (ImportError, AttributeError):
        pass
    # Last resort: operand-trace inspection.  A concrete (non-tracer)
    # operand positively proves there is no surrounding trace, and a
    # plain-jit tracer is equally conclusive — only the zero-operand
    # path (barrier) leaves the guard blind.
    for x in operands:
        if isinstance(x, jax.core.Tracer):
            tr = type(getattr(x, "_trace", None))
            label = f"{tr.__module__}.{tr.__name__}".lower()
            # pmap tracers live in jax's pxla/batching machinery
            # (MapTracer / pxla module names) rather than a module
            # spelled "pmap" — match those too, or pmap misuse would
            # hang instead of raising on probe-less jax versions.
            if ("shard_map" in label or "pmap" in label
                    or "pxla" in label or "maptracer" in label):
                raise TypeError(_MISUSE_MSG)
    if not operands:
        # Nothing to inspect: the guard is blind on this jax version —
        # warn once rather than fail silently, because the misuse
        # symptom is a hang.
        import warnings

        warnings.warn(
            "horovod_tpu: cannot detect shard_map/pmap context on this "
            "jax version; engine-bridge collectives called inside "
            "shard_map bodies will misbehave instead of raising. Use "
            "ops.collective there.", RuntimeWarning, stacklevel=3)


def _io_callback(fn, result_spec, *args):
    from jax.experimental import io_callback

    return io_callback(fn, result_spec, *args, ordered=True)


def _spec_like(x):
    import jax

    return jax.ShapeDtypeStruct(np.shape(x), x.dtype)


def _group_size(process_set) -> int:
    if process_set is not None:
        process_set.validate(basics.rank(), basics.size())
        return len(process_set.ranks)
    return basics.size()


def _group_index(process_set) -> int:
    if process_set is not None:
        process_set.validate(basics.rank(), basics.size())
        return list(process_set.ranks).index(basics.rank())
    return basics.rank()


# ---------------------------------------------------------------------------
# native XLA custom-call fast path (CPU platform + native engine)
#
# ffi_bridge.cc registers an FFI handler that enqueues straight into the
# C++ engine — no Python in the compiled program's hot loop (the exact
# mechanism of the reference's registered framework op,
# tensorflow/mpi_ops.cc:287-320).  TPU executions keep io_callback (TPU
# has no user custom-call surface; XLA stages the host transfer).

# Only the REGISTRATION is cached; engine/backend checks re-derive per
# trace so a shutdown()/init() cycle (possibly onto the py engine, whose
# process has no live C++ Engine) can never route to a stale handler.
_ffi_state = {"registered": None}

# dtypes the handler's MapDtype accepts (ffi_bridge.cc)
_FFI_DTYPES = ("float32", "float64", "float16", "bfloat16",
               "float8_e4m3fn", "float8_e5m2", "int8", "uint8", "int16",
               "uint16", "int32", "int64", "bool")


def _ffi_api():
    # jax < 0.4.38 ships the same surface (register_ffi_target,
    # pycapsule, ffi_call) under jax.extend.ffi instead of jax.ffi.
    import jax

    mod = getattr(jax, "ffi", None)
    if mod is None:
        from jax.extend import ffi as mod
    return mod


def _native_ffi_ready() -> bool:
    import os

    if os.environ.get("HVD_NO_FFI_BRIDGE") == "1":
        return False
    try:
        import jax

        from horovod_tpu.runtime_native import NativeEngine

        if not isinstance(basics._engine(), NativeEngine):
            return False
        if jax.default_backend() != "cpu":
            return False
    except Exception:
        return False
    if _ffi_state["registered"] is None:
        _ffi_state["registered"] = False
        try:
            from horovod_tpu import native

            lib = native.load()
            handler = getattr(lib, "HvdGroupedAllreduce", None)
            if handler is not None:
                ffi = _ffi_api()
                ffi.register_ffi_target(
                    "hvd_grouped_allreduce",
                    ffi.pycapsule(handler), platform="cpu")
                _ffi_state["registered"] = True
        except Exception:
            _ffi_state["registered"] = False
    return _ffi_state["registered"]


def _ffi_eligible(leaves, compression) -> bool:
    from horovod_tpu.ops.compression import Compression

    if compression is not None and compression is not Compression.none:
        # wire compression casts host-side — io_callback path
        return False
    if not all(str(l.dtype) in _FFI_DTYPES for l in leaves):
        return False
    return _native_ffi_ready()


def _ffi_grouped_call(leaves, base, op, prescale, postscale, process_set):
    ps_id, ps_size = 0, 0
    if process_set is not None:
        ps_id, ps_size = process_set.validate(basics.rank(), basics.size())
    call = _ffi_api().ffi_call(
        "hvd_grouped_allreduce",
        tuple(_spec_like(l) for l in leaves),
        has_side_effect=True)
    # `single=0`: grouped entries wire-name as `{base}.{i}`, identical
    # to the io_callback/eager grouped surface (mixed gangs align).
    return call(*leaves, name=base, op=np.int32(int(op)),
                prescale=np.float64(prescale),
                postscale=np.float64(postscale),
                ps_id=np.int32(ps_id), ps_size=np.int32(ps_size),
                single=np.int32(0))


# ---------------------------------------------------------------------------
# allreduce


def _host_allreduce(name, op, prescale, postscale, compression,
                    process_set, arr):
    from horovod_tpu.ops.eager import _np_compress, _np_decompress

    arr = np.asarray(arr)
    comp, ctx = _np_compress(compression, arr)
    eng = basics._engine()
    h = eng.allreduce_async(name, comp, op=op, prescale=prescale,
                            postscale=postscale, process_set=process_set)
    out = _np_decompress(compression, eng.synchronize(h), ctx)
    return np.ascontiguousarray(out, dtype=arr.dtype)


def allreduce(x, name: Optional[str] = None,
              op: ReduceOp = ReduceOp.AVERAGE,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              compression=None, process_set=None):
    """Named, negotiated allreduce usable inside ``jit``.

    Parity: ``HorovodAllreduceOp`` (tensorflow/mpi_ops.cc:287-320) — the
    collective enters the compiled program but executes on the shared
    engine, with negotiation/fusion/cache/timeline on the path.
    Differentiable: the cotangent rides its own allreduce (name
    ``{name}.grad``), matching ``_allreduce_grad``.
    """
    from horovod_tpu.ops.compression import Compression

    _check_single_device_trace(x)
    _ensure_vjps()
    name = _auto_name("allreduce", name)
    compression = compression or Compression.none
    return _allreduce_vjp(x, name, op, prescale_factor, postscale_factor,
                          compression, process_set)


def _allreduce_call(x, name, op, prescale, postscale, compression,
                    process_set):
    # Single-tensor calls stay on the ORDERED host callback even when
    # the native custom call is available: a program with several
    # independent blocking collectives relies on identical cross-rank
    # submission order, which only the ordered-effects path guarantees
    # (XLA may schedule plain custom calls in any data-flow-consistent
    # order).  The FFI fast path serves grouped_allreduce, where every
    # tensor is enqueued before any wait inside ONE call.
    return _io_callback(
        partial(_host_allreduce, name, op, prescale, postscale,
                compression, process_set),
        _spec_like(x), x)


def _make_allreduce_vjp():
    import jax

    @partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
    def f(x, name, op, prescale, postscale, compression, process_set):
        return _allreduce_call(x, name, op, prescale, postscale,
                               compression, process_set)

    def fwd(x, name, op, prescale, postscale, compression, process_set):
        return _allreduce_call(x, name, op, prescale, postscale,
                               compression, process_set), None

    def bwd(name, op, prescale, postscale, compression, process_set, _, ct):
        # Reference `_allreduce_grad`: grad of an allreduce is an
        # allreduce of the grad with the same op (pre/post scaling swap
        # by linearity; both are scalar multiplies, so reuse as-is).
        g = _allreduce_call(ct, name + ".grad", op, prescale, postscale,
                            compression, process_set)
        return (g,)

    f.defvjp(fwd, bwd)
    return f


_allreduce_vjp = None


def _ensure_vjps():
    global _allreduce_vjp, _grouped_vjp, _allgather_vjp, _broadcast_vjp
    if _allreduce_vjp is None:
        _allreduce_vjp = _make_allreduce_vjp()
        _grouped_vjp = _make_grouped_vjp()
        _allgather_vjp = _make_allgather_vjp()
        _broadcast_vjp = _make_broadcast_vjp()


# ---------------------------------------------------------------------------
# grouped allreduce (fusion on the compiled path)


def _host_grouped_allreduce(base, op, compression, process_set, *arrs):
    """One host call for the whole gradient group: enqueue every tensor
    async, then synchronize — the engine's controller sees them all
    outstanding and fuses compatible responses into large wire messages
    (the compiled-path analog of the fusion-buffer cycle,
    fusion_buffer_manager.h:28-55)."""
    from horovod_tpu.ops.eager import _np_compress, _np_decompress

    eng = basics._engine()
    handles = []
    for i, a in enumerate(arrs):
        a = np.asarray(a)
        comp, ctx = _np_compress(compression, a)
        h = eng.allreduce_async(f"{base}.{i}", comp, op=op,
                                process_set=process_set)
        handles.append((h, ctx, a.dtype))
    outs = []
    for h, ctx, dt in handles:
        out = _np_decompress(compression, eng.synchronize(h), ctx)
        outs.append(np.ascontiguousarray(out, dtype=dt))
    return tuple(outs)


def grouped_allreduce(tensors, name: Optional[str] = None,
                      op: ReduceOp = ReduceOp.AVERAGE,
                      compression=None, process_set=None):
    """Allreduce a pytree through the engine with controller fusion,
    inside ``jit``.  The gradient-reduction primitive for
    ``DistributedOptimizer`` on the compiled path."""
    import jax

    from horovod_tpu.ops.compression import Compression

    _check_single_device_trace(*jax.tree.leaves(tensors))
    _ensure_vjps()
    base = _auto_name("grouped_allreduce", name)
    compression = compression or Compression.none
    leaves, treedef = jax.tree.flatten(tensors)
    if not leaves:
        return tensors
    outs = _grouped_vjp(tuple(leaves), base, op, compression, process_set)
    return jax.tree.unflatten(treedef, list(outs))


def _grouped_call(leaves, base, op, compression, process_set):
    # Native custom call (ffi_bridge.cc): every tensor enqueues before
    # any wait inside one blocking call, so a step's gradient reduction
    # cannot cross-rank deadlock regardless of XLA's schedule.  Several
    # INDEPENDENT grouped calls in one program must be ordered by data
    # flow (true for optimizer steps; HVD_NO_FFI_BRIDGE=1 opts out and
    # the stall inspector names the tensors if a custom program trips
    # this).
    if _ffi_eligible(leaves, compression):
        return tuple(_ffi_grouped_call(
            list(leaves), base, op, 1.0, 1.0, process_set))
    return _io_callback(
        partial(_host_grouped_allreduce, base, op, compression,
                process_set),
        tuple(_spec_like(l) for l in leaves), *leaves)


def _make_grouped_vjp():
    import jax

    @partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
    def f(leaves, base, op, compression, process_set):
        return _grouped_call(leaves, base, op, compression, process_set)

    def fwd(leaves, base, op, compression, process_set):
        return _grouped_call(leaves, base, op, compression, process_set), \
            None

    def bwd(base, op, compression, process_set, _, cts):
        return (_grouped_call(tuple(cts), base + ".grad", op, compression,
                              process_set),)

    f.defvjp(fwd, bwd)
    return f


# ---------------------------------------------------------------------------
# allgather


def _host_allgather(name, process_set, arr):
    eng = basics._engine()
    h = eng.allgather_async(name, np.asarray(arr), process_set=process_set)
    return np.ascontiguousarray(eng.synchronize(h))


def allgather(x, name: Optional[str] = None, process_set=None):
    """First-dim-concat allgather through the engine, inside ``jit``.
    Static shapes require every rank to contribute the same shape (the
    ragged-first-dim negotiation is eager-only; in-graph XLA has the same
    restriction, ops/collective.py:153)."""
    _check_single_device_trace(x)
    _ensure_vjps()
    name = _auto_name("allgather", name)
    return _allgather_vjp(x, name, process_set)


def _allgather_call(x, name, process_set):
    import jax

    n = _group_size(process_set)
    shape = (n * x.shape[0],) + tuple(x.shape[1:]) if x.ndim else (n,)
    spec = jax.ShapeDtypeStruct(shape, x.dtype)
    return _io_callback(partial(_host_allgather, name, process_set),
                        spec, x)


def _make_allgather_vjp():
    import jax
    import jax.numpy as jnp

    @partial(jax.custom_vjp, nondiff_argnums=(1, 2))
    def f(x, name, process_set):
        return _allgather_call(x, name, process_set)

    def fwd(x, name, process_set):
        return _allgather_call(x, name, process_set), x.shape

    def bwd(name, process_set, in_shape, ct):
        # Reference `_allgather_grad`: sum-allreduce the cotangent and
        # slice out this rank's segment.
        summed = _allreduce_call(
            ct, name + ".grad", ReduceOp.SUM, 1.0, 1.0,
            _none_compression(), process_set)
        d0 = in_shape[0] if in_shape else 1
        me = _group_index(process_set)
        seg = jax.lax.dynamic_slice_in_dim(summed, me * d0, d0, axis=0)
        return (jnp.reshape(seg, in_shape),)

    f.defvjp(fwd, bwd)
    return f


def _none_compression():
    from horovod_tpu.ops.compression import Compression

    return Compression.none


# ---------------------------------------------------------------------------
# broadcast


def _host_broadcast(name, root_rank, process_set, arr):
    eng = basics._engine()
    h = eng.broadcast_async(name, np.asarray(arr), root_rank=root_rank,
                            process_set=process_set)
    return np.ascontiguousarray(eng.synchronize(h))


def broadcast(x, root_rank: int = 0, name: Optional[str] = None,
              process_set=None):
    """Negotiated broadcast inside ``jit``.  Gradient: sum-allreduce on
    the root, zero elsewhere (reference ``_broadcast_grad``)."""
    _check_single_device_trace(x)
    _ensure_vjps()
    name = _auto_name("broadcast", name)
    return _broadcast_vjp(x, name, root_rank, process_set)


def _broadcast_call(x, name, root_rank, process_set):
    return _io_callback(
        partial(_host_broadcast, name, root_rank, process_set),
        _spec_like(x), x)


def _make_broadcast_vjp():
    import jax
    import jax.numpy as jnp

    @partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
    def f(x, name, root_rank, process_set):
        return _broadcast_call(x, name, root_rank, process_set)

    def fwd(x, name, root_rank, process_set):
        return _broadcast_call(x, name, root_rank, process_set), None

    def bwd(name, root_rank, process_set, _, ct):
        g = _allreduce_call(ct, name + ".grad", ReduceOp.SUM, 1.0, 1.0,
                            _none_compression(), process_set)
        if basics.rank() != root_rank:
            g = jnp.zeros_like(g)
        return (g,)

    f.defvjp(fwd, bwd)
    return f


# ---------------------------------------------------------------------------
# reducescatter / alltoall / barrier (non-differentiable bridge ops)


def _host_reducescatter(name, op, process_set, arr):
    eng = basics._engine()
    h = eng.reducescatter_async(name, np.asarray(arr), op=op,
                                process_set=process_set)
    return np.ascontiguousarray(eng.synchronize(h))


def reducescatter(x, name: Optional[str] = None,
                  op: ReduceOp = ReduceOp.AVERAGE, process_set=None):
    """Reduce+scatter over dim 0 inside ``jit``.  The output shape is this
    rank's NCCL-style near-equal row chunk — rank-dependent but constant
    at trace time (each process traces its own program), so it stays
    static under XLA.  Chunk math is the engine's own
    (ops/cpu_backend.py::_chunk_bounds, imported, not copied)."""
    import jax

    from horovod_tpu.ops.cpu_backend import _chunk_bounds

    _check_single_device_trace(x)
    if op not in (ReduceOp.AVERAGE, ReduceOp.SUM, ReduceOp.MIN,
                  ReduceOp.MAX, ReduceOp.PRODUCT):
        raise ValueError(f"reducescatter does not support op {op}")
    if x.ndim == 0:
        raise ValueError(
            "reducescatter needs at least one dimension to scatter over "
            "(got a scalar)")
    name = _auto_name("reducescatter", name)
    n = _group_size(process_set)
    me = _group_index(process_set)
    bounds = _chunk_bounds(x.shape[0], n)
    shape = (bounds[me + 1] - bounds[me],) + tuple(x.shape[1:])
    spec = jax.ShapeDtypeStruct(shape, x.dtype)
    return _io_callback(partial(_host_reducescatter, name, op, process_set),
                        spec, x)


def _host_alltoall(name, splits, process_set, arr):
    eng = basics._engine()
    h = eng.alltoall_async(name, np.asarray(arr), splits=splits,
                           process_set=process_set)
    out = eng.synchronize(h)
    if isinstance(out, tuple):
        out = out[0]
    return np.ascontiguousarray(out)


def alltoall(x, name: Optional[str] = None, process_set=None):
    """Equal-split alltoall inside ``jit`` (dim 0 divisible by group
    size; ragged ``splits`` need runtime shapes — eager path only, same
    restriction as the in-graph op, ops/collective.py:232)."""
    import jax

    _check_single_device_trace(x)
    name = _auto_name("alltoall", name)
    n = _group_size(process_set)
    if x.shape[0] % n:
        raise ValueError(
            f"bridge alltoall needs dim 0 ({x.shape[0]}) divisible by "
            f"group size ({n}); ragged splits are eager-only")
    spec = jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return _io_callback(partial(_host_alltoall, name, None, process_set),
                        spec, x)


def _host_barrier(process_set, _x):
    basics._engine().barrier(process_set=process_set)
    return np.zeros((), np.int32)


def barrier(process_set=None):
    """Engine barrier inside ``jit``; returns an int32 token (use or
    thread it so XLA cannot dead-code it away)."""
    import jax
    import jax.numpy as jnp

    _check_single_device_trace()
    return _io_callback(partial(_host_barrier, process_set),
                        jax.ShapeDtypeStruct((), np.int32),
                        jnp.zeros((), jnp.int32))
