"""Eager (op-by-op) collective API over the process-group engine.

Parity: the public op surface of ``horovod/torch/mpi_ops.py`` /
``horovod/tensorflow/mpi_ops.py``: sync + async variants, ``poll`` /
``synchronize`` handles, auto-generated tensor names, broadcast_object.
Framework-agnostic: accepts numpy arrays, JAX arrays, python scalars, and
torch tensors; results come back in the caller's type.

Inside a ``jit`` trace these functions cannot run (the engine is host-side);
they raise with a pointer to the in-graph ops in
``horovod_tpu.ops.collective``, which is the TPU data plane.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from horovod_tpu import basics
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.telemetry import registry as _tmx

_counter_lock = threading.Lock()
_op_counters: Dict[str, int] = {}

# handle -> postprocess(raw_result) -> user-facing result
_post: Dict[int, Callable] = {}
_post_lock = threading.Lock()


def _auto_name(kind: str, name: Optional[str]) -> str:
    """Deterministic fallback names; identical call order across ranks
    yields identical names (parity: mpi_ops.py noname counters)."""
    if name is not None:
        return name
    with _counter_lock:
        c = _op_counters.get(kind, 0)
        _op_counters[kind] = c + 1
    return f"{kind}.noname.{c}"


def _is_traced(x) -> bool:
    try:
        import jax.core

        return isinstance(x, jax.core.Tracer)
    except ImportError:
        return False


def _check_not_traced(x) -> None:
    if _is_traced(x):
        raise TypeError(
            "async horovod_tpu collectives cannot run inside jit/pjit "
            "traces (handles are host-side); the sync ops dispatch to "
            "horovod_tpu.ops.bridge (engine-negotiated host callback) "
            "inside jit, and horovod_tpu.ops.collective.* are the "
            "axis-name in-graph collectives for shard_map/pjit meshes")


def _to_numpy(x) -> Tuple[np.ndarray, Callable[[np.ndarray], Any]]:
    """Convert input to numpy + a restore function back to the input type."""
    _check_not_traced(x)
    mod = type(x).__module__
    if mod.startswith("torch"):
        import torch

        device = x.device
        arr = x.detach().cpu().numpy()
        return arr, lambda a: torch.from_numpy(
            np.ascontiguousarray(a)).to(device)
    if mod.startswith("jax") or "ArrayImpl" in type(x).__name__:
        import jax
        import jax.numpy as jnp

        devs = getattr(x, "devices", None)
        arr = np.asarray(x)
        return arr, jnp.asarray
    arr = np.asarray(x)
    if arr.dtype == np.float64 and not isinstance(x, np.ndarray):
        # python floats → fp32, matching framework default behavior
        arr = arr.astype(np.float32)
    return arr, lambda a: a


def _register(handle: int, fn: Callable) -> int:
    with _post_lock:
        _post[handle] = fn
    return handle


def _timed_post(kind: str, arr: np.ndarray,
                post: Optional[Callable]) -> Optional[Callable]:
    """Per-collective telemetry (docs/metrics.md): count + input bytes at
    enqueue, enqueue-to-completion latency observed when the handle's
    postprocess runs in ``synchronize``.  With telemetry off this returns
    ``post`` untouched — the labels and closure below are the allocating
    part, and they only exist behind the ``enabled()`` check.  The jit
    bridge funnels through these same async ops (io_callback →
    *_async), so both entry points are covered."""
    if not _tmx.enabled():
        return post
    labels = (kind, str(arr.dtype))
    _tmx.inc_counter("hvd_collectives_total", labels=labels)
    _tmx.observe("hvd_collective_bytes", arr.nbytes, labels=labels)
    t0 = time.monotonic()

    def timed(raw):
        _tmx.observe("hvd_collective_latency_seconds",
                     time.monotonic() - t0, labels=labels)
        return post(raw) if post is not None else raw

    return timed


def poll(handle: int) -> bool:
    return basics._engine().poll(handle)


def synchronize(handle: int):
    """Wait for an async op; returns its result.
    Parity: mpi_ops.py synchronize (busy-wait replaced by a condvar)."""
    raw = basics._engine().synchronize(handle)
    with _post_lock:
        fn = _post.pop(handle, None)
    return fn(raw) if fn else raw


def _resolve_op(op: Optional[ReduceOp], average: Optional[bool]) -> ReduceOp:
    """Reconcile the modern ``op=`` arg with the classic ``average=`` flag
    (horovod 0.19 surface: allreduce(tensor, average=True); op= and
    average= are mutually exclusive, torch/mpi_ops.py:68-90)."""
    if average is not None:
        if op is not None:
            raise ValueError(
                "The op parameter supersedes average; pass only one")
        return ReduceOp.AVERAGE if average else ReduceOp.SUM
    return ReduceOp.AVERAGE if op is None else op


def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None,
                    op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    compression=None, process_set=None) -> int:
    """Positional order matches horovod 0.19 (tensor, average, name) so
    ported calls like ``allreduce_async(grad, False)`` keep their meaning
    (torch/mpi_ops.py:94-129)."""
    from horovod_tpu.ops.compression import Compression

    op = _resolve_op(op, average)
    compression = compression or Compression.none
    arr, restore = _to_numpy(tensor)
    # Eager compression operates on numpy: cast down before the wire.
    comp_arr, ctx = _np_compress(compression, arr)
    h = basics._engine().allreduce_async(
        _auto_name("allreduce", name), comp_arr, op=op,
        prescale=prescale_factor, postscale=postscale_factor,
        process_set=process_set)

    def post(raw):
        raw = _np_decompress(compression, raw, ctx)
        return restore(raw)

    return _register(h, _timed_post("allreduce", comp_arr, post))


def _np_compress(compression, arr):
    import ml_dtypes

    from horovod_tpu.ops import compression as C

    if compression is C.Compression.none or compression is C.NoneCompressor:
        return arr, None
    if compression is C.Float16Compressor:
        wire = np.dtype("float16")
    elif compression is C.Float8Compressor:
        wire = np.dtype(ml_dtypes.float8_e4m3fn)
    elif compression is C.Float8E5M2Compressor:
        wire = np.dtype(ml_dtypes.float8_e5m2)
    else:
        wire = _bf16_dtype()
    if arr.dtype.kind == "f" and arr.dtype != wire:
        return arr.astype(wire), arr.dtype
    return arr, None


def _np_decompress(compression, arr, ctx):
    if ctx is not None:
        return arr.astype(ctx)
    return arr


def _bf16_dtype():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None,
              op: Optional[ReduceOp] = None,
              prescale_factor: float = 1.0,
              postscale_factor: float = 1.0,
              compression=None, process_set=None):
    if _is_traced(tensor):
        # Inside a jit trace the sync surface rides the engine through
        # the host-callback bridge (negotiation/fusion/cache/timeline on
        # the compiled path) — the TPU analog of ComputeAsync-enqueue.
        from horovod_tpu.ops import bridge

        return bridge.allreduce(
            tensor, name=name, op=_resolve_op(op, average),
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            compression=compression, process_set=process_set)
    return synchronize(allreduce_async(
        tensor, average, name, op, prescale_factor, postscale_factor,
        compression, process_set))


def grouped_allreduce(tensors: List, average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Optional[ReduceOp] = None,
                      process_set=None) -> List:
    """Eager grouped allreduce; entries negotiate individually but fuse in
    the controller exactly like individually-submitted tensors do."""
    op = _resolve_op(op, average)
    if any(_is_traced(t) for t in tensors):
        from horovod_tpu.ops import bridge

        return list(bridge.grouped_allreduce(
            list(tensors), name=name, op=op, process_set=process_set))
    base = _auto_name("grouped_allreduce", name)
    handles = [allreduce_async(t, name=f"{base}.{i}", op=op,
                               process_set=process_set)
               for i, t in enumerate(tensors)]
    return [synchronize(h) for h in handles]


def allgather_async(tensor, name: Optional[str] = None,
                    process_set=None) -> int:
    arr, restore = _to_numpy(tensor)
    h = basics._engine().allgather_async(
        _auto_name("allgather", name), arr, process_set=process_set)
    return _register(h, _timed_post("allgather", arr, restore))


def allgather(tensor, name: Optional[str] = None, process_set=None):
    if _is_traced(tensor):
        from horovod_tpu.ops import bridge

        return bridge.allgather(tensor, name=name, process_set=process_set)
    return synchronize(allgather_async(tensor, name, process_set))


def sparse_allreduce(values, indices, average: Optional[bool] = None,
                     name: Optional[str] = None,
                     op: Optional[ReduceOp] = None):
    """Sparse (IndexedSlices-style) allreduce of embedding-row gradients.

    Parity: the reference never densifies sparse gradients — it
    allgathers each slice's values and indices and lets the optimizer
    apply them, duplicates accumulating (tensorflow/__init__.py:74-89,
    SURVEY.md §2.8.4).  Returns ``(values, indices)`` of the combined
    slices, where ``values`` has been pre-divided by ``size()`` when the
    resolved op is Average.  Apply with a scatter-add, e.g.
    ``param = param.at[indices].add(-lr * values)`` in JAX.
    """
    rop = _resolve_op(op, average)
    if rop not in (ReduceOp.AVERAGE, ReduceOp.SUM):
        raise ValueError(
            f"sparse_allreduce supports Average/Sum, got {rop}")
    base = _auto_name("sparse_allreduce", name)
    hv = allgather_async(values, name=f"{base}.values")
    hi = allgather_async(indices, name=f"{base}.indices")
    out_values = synchronize(hv)
    out_indices = synchronize(hi)
    if rop == ReduceOp.AVERAGE:
        out_values = out_values / basics.size()
    return out_values, out_indices


def reducescatter_async(tensor, average: Optional[bool] = None,
                        name: Optional[str] = None,
                        op: Optional[ReduceOp] = None,
                        process_set=None) -> int:
    """Reduce across ranks, scatter over dim 0 (rank r gets the r-th
    near-equal row chunk).  The reference project added
    ``hvd.reducescatter`` right after the v0.19 line; the in-graph twin
    is ``ops.collective.reduce_scatter`` (``lax.psum_scatter``)."""
    rop = _resolve_op(op, average)
    if rop not in (ReduceOp.AVERAGE, ReduceOp.SUM, ReduceOp.MIN,
                   ReduceOp.MAX, ReduceOp.PRODUCT):
        raise ValueError(f"reducescatter does not support op {rop}")
    if np.ndim(tensor) == 0:
        # Checked here, not just in the engines: _to_numpy lifts 0-d
        # scalars to shape (1,) for the wire.
        raise ValueError(
            "reducescatter needs at least one dimension to scatter over "
            "(got a scalar)")
    arr, restore = _to_numpy(tensor)
    h = basics._engine().reducescatter_async(
        _auto_name("reducescatter", name), arr, op=rop,
        process_set=process_set)
    return _register(h, _timed_post("reducescatter", arr, restore))


def reducescatter(tensor, average: Optional[bool] = None,
                  name: Optional[str] = None,
                  op: Optional[ReduceOp] = None, process_set=None):
    if _is_traced(tensor):
        from horovod_tpu.ops import bridge

        return bridge.reducescatter(tensor, name=name,
                                    op=_resolve_op(op, average),
                                    process_set=process_set)
    return synchronize(reducescatter_async(tensor, average, name, op,
                                           process_set))


def broadcast_async(tensor, root_rank: int = 0,
                    name: Optional[str] = None, process_set=None) -> int:
    arr, restore = _to_numpy(tensor)
    h = basics._engine().broadcast_async(
        _auto_name("broadcast", name), arr, root_rank=root_rank,
        process_set=process_set)
    return _register(h, _timed_post("broadcast", arr, restore))


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None,
              process_set=None):
    if _is_traced(tensor):
        from horovod_tpu.ops import bridge

        return bridge.broadcast(tensor, root_rank=root_rank, name=name,
                                process_set=process_set)
    return synchronize(broadcast_async(tensor, root_rank, name,
                                       process_set))


def alltoall_async(tensor, splits=None, name: Optional[str] = None,
                   process_set=None) -> int:
    arr, restore = _to_numpy(tensor)
    if splits is not None:
        splits = list(np.asarray(splits).astype(int))
    h = basics._engine().alltoall_async(
        _auto_name("alltoall", name), arr, splits=splits,
        process_set=process_set)

    def post(raw):
        if isinstance(raw, tuple):
            data, recv_splits = raw
            return restore(data), recv_splits
        return restore(raw)

    return _register(h, _timed_post("alltoall", arr, post))


def alltoall(tensor, splits=None, name: Optional[str] = None,
             process_set=None):
    if _is_traced(tensor):
        if splits is not None:
            n = (len(process_set.ranks) if process_set is not None
                 else basics.size())
            sp = [int(s) for s in np.asarray(splits)]
            if len(sp) != n:
                raise ValueError(
                    f"alltoall needs one split per participant ({n}), "
                    f"got {len(sp)}")
            if len(set(sp)) != 1 or sum(sp) != tensor.shape[0]:
                raise NotImplementedError(
                    "ragged alltoall needs runtime shapes, which jit "
                    "cannot express; only uniform splits covering dim 0 "
                    "work in-trace — move ragged calls out of the trace")
        from horovod_tpu.ops import bridge

        return bridge.alltoall(tensor, name=name, process_set=process_set)
    return synchronize(alltoall_async(tensor, splits, name, process_set))


def barrier(process_set=None) -> None:
    basics._engine().barrier(process_set=process_set)


def join() -> int:
    """Fault-tolerant data exhaustion; parity: torch/mpi_ops.py:494-510
    and SURVEY.md §3.5.  Returns the last rank that joined."""
    return basics._engine().join()


def broadcast_object(obj, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Pickle-based arbitrary-object broadcast;
    parity: torch/__init__.py:607 (cloudpickle there, stdlib pickle here —
    user fns cross process boundaries via the launcher, not this call)."""
    name = _auto_name("broadcast_object", name)
    if basics.rank() == root_rank:
        payload = np.frombuffer(
            pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
            dtype=np.uint8).copy()
        n = np.array([payload.size], np.int64)
    else:
        payload = None
        n = np.zeros(1, np.int64)
    n = broadcast(n, root_rank, name=f"{name}.len")
    if payload is None:
        payload = np.zeros(int(n[0]), np.uint8)
    payload = broadcast(payload, root_rank, name=f"{name}.data")
    return pickle.loads(payload.tobytes())


def broadcast_parameters(params, root_rank: int = 0,
                         prefix: str = "bcast_param") -> Any:
    """Broadcast every array leaf of a pytree / dict of parameters from
    ``root_rank``; returns the synchronized structure.
    Parity: torch/__init__.py:451 broadcast_parameters."""
    import jax

    leaves, treedef = jax.tree.flatten(params)
    handles = [broadcast_async(leaf, root_rank, name=f"{prefix}.{i}")
               for i, leaf in enumerate(leaves)]
    return jax.tree.unflatten(treedef, [synchronize(h) for h in handles])
