"""Collective ops: in-graph XLA data plane + eager process-group ops.

``horovod_tpu.ops.collective`` — axis-name collectives for use inside
``shard_map``/``pjit`` (the TPU/ICI data plane).
``horovod_tpu.ops.eager``      — host-side eager ops over the engine.
``horovod_tpu.ops.cpu_backend``— ring algorithms (the correctness oracle).
``horovod_tpu.ops.adasum``     — scale-invariant reduction (in-graph+eager).
``horovod_tpu.ops.compression``— fp16/bf16 gradient compression.
"""

from horovod_tpu.ops import adasum, collective, compression, eager  # noqa
from horovod_tpu.ops.compression import Compression  # noqa: F401
