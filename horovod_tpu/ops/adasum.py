"""Adasum: scale-invariant gradient combination.

Parity (math only): ``horovod/common/ops/adasum/adasum.h`` — the pairwise
combination of gradients a, b is

    a' = (1 - dot(a,b) / (2·‖a‖²)) · a  +  (1 - dot(a,b) / (2·‖b‖²)) · b

applied recursively over pairs of ranks (vector-halving distance-doubling,
adasum.h:167-338).  The result is invariant to per-rank gradient scale and
behaves like an average for orthogonal gradients and like a sum for
identical ones.

TPU-native design: the reference implements VHDD with MPI point-to-point
send/recv because NCCL has no pairwise primitive.  On a TPU mesh we express
each VHDD round as an in-graph ``ppermute`` partner exchange, so the whole
recursion compiles into one XLA program over the ICI ring — no host round
trips.  Dot products and norms accumulate in fp32 regardless of input dtype,
matching the reference's fp16 path (adasum.h:404-520 promotes to float).
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.ops.collective import _one_axis_size


def adasum_pair(a, b, dot, anorm_sq, bnorm_sq):
    """Combine two gradients given precomputed <a,b>, ‖a‖², ‖b‖².

    Scalar guard behavior matches adasum.h:367-391: if either norm is zero
    the corresponding coefficient contribution degenerates to a plain sum.
    """
    acoef = jnp.where(anorm_sq > 0, 1.0 - dot / (2.0 * anorm_sq), 1.0)
    bcoef = jnp.where(bnorm_sq > 0, 1.0 - dot / (2.0 * bnorm_sq), 1.0)
    return acoef.astype(a.dtype) * a + bcoef.astype(b.dtype) * b


def adasum_pair_numpy(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Eager pairwise combine used by the CPU data plane."""
    dot = float(np.dot(a.ravel(), b.ravel()))
    an = float(np.dot(a.ravel(), a.ravel()))
    bn = float(np.dot(b.ravel(), b.ravel()))
    acoef = 1.0 - dot / (2.0 * an) if an > 0 else 1.0
    bcoef = 1.0 - dot / (2.0 * bn) if bn > 0 else 1.0
    return acoef * a + bcoef * b


def adasum_reduce_numpy(grads: Sequence[np.ndarray]) -> np.ndarray:
    """Reference (oracle) implementation over a list of per-rank gradients,
    recursing over rank pairs exactly like VHDD's distance-doubling order.
    Used by tests as the golden model (the reference validates against a
    NumPy model the same way, test_adasum_tensorflow.py).
    """
    grads = [np.asarray(g, np.float64) for g in grads]
    n = len(grads)
    assert n & (n - 1) == 0, "adasum oracle requires power-of-two ranks"
    if n == 1:
        return grads[0]
    half = n // 2
    a = adasum_reduce_numpy(grads[:half])
    b = adasum_reduce_numpy(grads[half:])
    dot = float(np.dot(a.ravel(), b.ravel()))
    an = float(np.dot(a.ravel(), a.ravel()))
    bn = float(np.dot(b.ravel(), b.ravel()))
    acoef = 1.0 - dot / (2.0 * an) if an > 0 else 1.0
    bcoef = 1.0 - dot / (2.0 * bn) if bn > 0 else 1.0
    return acoef * a + bcoef * b


def adasum_allreduce(x, axis: Union[str, Sequence[str]] = "dp"):
    """In-graph Adasum allreduce over one mesh axis (or axis tuple treated
    as its linearization).

    Implementation: recursive halving by partner exchange.  At round k the
    partner is ``index XOR 2^k``; both sides compute the pairwise statistics
    with an fp32 psum over the *pair* — but since XLA collectives span the
    whole axis, we instead exchange the partner's full vector with
    ``ppermute`` and compute the statistics locally in fp32.  log2(n)
    rounds, each one ppermute of the full vector: same bytes on the wire as
    the reference's VHDD recursive halving+doubling combined.
    """
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    # Linearize multi-axis reductions by reshaping into one logical axis:
    # do Adasum over the first axis, then again over the next, which equals
    # the VHDD recursion order (local pairs first).
    out = x
    for ax in reversed(axes):
        out = _adasum_one_axis(out, ax)
    return out


def _adasum_one_axis(x, axis: str):
    n = _one_axis_size(axis)
    if n == 1:
        return x
    assert n & (n - 1) == 0, "adasum requires power-of-two axis size"
    acc = x
    k = 1
    while k < n:
        # Partner exchange: everyone sends acc to (index XOR k).
        perm = [(i, i ^ k) for i in range(n)]
        partner = lax.ppermute(acc, axis, perm)
        a32 = acc.astype(jnp.float32)
        b32 = partner.astype(jnp.float32)
        dot = jnp.vdot(a32, b32)
        an = jnp.vdot(a32, a32)
        bn = jnp.vdot(b32, b32)
        # The pairwise combine is symmetric in (a, b), so both partners
        # compute the identical value and no second exchange is needed.
        acc = adasum_pair(a32, b32, dot, an, bn).astype(x.dtype)
        k *= 2
    return acc
