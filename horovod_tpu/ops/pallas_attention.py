"""Flash attention as a Pallas TPU kernel (forward + backward).

The hot op of the flagship transformer, written for the hardware: the
blockwise online-softmax algorithm keeps every [block_q, block_k] score
tile in VMEM and never materializes the [S, S] attention matrix in HBM —
O(S) memory instead of O(S^2), with the two matmuls per tile landing on
the MXU.  The backward pass recomputes score tiles from the saved
logsumexp (the standard flash recipe): one kernel accumulates dQ over key
blocks, a second accumulates dK/dV over query blocks.

This is a TPU-native extension, not a reference port (the reference has
no attention code at all — SURVEY.md §2.8); the algorithm is the public
FlashAttention-2 blockwise recipe re-derived for Pallas.  Composition:

* ``attn_impl="flash"`` on :class:`TransformerConfig` routes the
  non-sequence-parallel attention path here.
* Under sequence parallelism the ring attention layer
  (``parallel/ring_attention.py``) rotates K/V blocks over the ``sp``
  ring with the same online-softmax update — this kernel is the
  single-chip analog of one ring hop.

Runs under ``interpret=True`` off-TPU (tests run on the CPU backend);
on a TPU backend it compiles to Mosaic.

Tuning (measured on one TPU v5e chip, B=8 S=1024 H=16 D=64 bf16):
dot inputs keep their storage dtype (f32 upcasts before the dots ran
the MXU at its multi-pass fp32 rate) and the default blocks are
512x512 — together fwd+bwd went 15.0 ms → 7.8 ms vs 45.4 ms for the
XLA dense-softmax path on the same shapes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30



def _block_needed(qi, ki, block_q, block_k, causal):
    """False only for key blocks strictly above the causal diagonal."""
    if not causal:
        return True
    return ki * block_k < (qi + 1) * block_q


def _causal_mask(s, qi, ki, block_q, block_k):
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows >= cols, s, _NEG_INF)


def _pick_block(seq_len: int, want: int) -> int:
    b = min(want, seq_len)
    while seq_len % b:
        b //= 2
    return max(b, 1)


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: key blocks strictly above the diagonal contribute nothing.
    needed = _block_needed(qi, ki, block_q, block_k, causal)

    @pl.when(needed)
    def _tile():
        # Dot inputs keep their storage dtype (bf16 in the flagship
        # model) so the MXU runs at its native rate; accumulation is
        # always f32 via preferred_element_type.  Softmax math is f32.
        q = q_ref[0]                               # [bq, d]
        k = k_ref[0]                               # [bk, d]
        v = v_ref[0]                               # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        m_prev = m_scr[:]                          # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                     # [bq, bk]
        corr = jnp.exp(m_prev - m_new)             # [bq, 1]
        l_scr[:] = corr * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = corr * acc_scr[:] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_scr[:]
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(l)         # [bq, 1]


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               out_f32=False):
    BH, S, D = q.shape
    bq = _pick_block(S, block_q)
    bk = _pick_block(S, block_k)
    grid = (BH, S // bq, S // bk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            # lse rides as [BH, S, 1]: a 2-D (1, bq) block over [BH, S]
            # is not Mosaic-tileable (second-minor must be 8-divisible
            # or the full dim); a trailing singleton lane dim is.
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            # out_f32: emit fp32 partials (ring composition carries them
            # through the logsumexp combine without per-hop rounding).
            jax.ShapeDtypeStruct((BH, S, D),
                                 jnp.float32 if out_f32 else q.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((bq, 1)),
            _vmem((bq, 1)),
            _vmem((bq, D)),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dlse_ref,
               dq_ref, acc_scr, *, scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    needed = _block_needed(qi, ki, block_q, block_k, causal)

    @pl.when(needed)
    def _tile():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                           # [bq, 1]
        delta = delta_ref[0]                       # [bq, 1]
        dlse = dlse_ref[0]                         # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse)                       # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bq, bk]
        # d lse_i / d s_ij = p_ij, so an lse cotangent adds p * dlse.
        ds = p * (dp - delta + dlse)
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dlse_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = _block_needed(qi, ki, block_q, block_k, causal)

    @pl.when(needed)
    def _tile():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                           # [bq, 1]
        delta = delta_ref[0]                       # [bq, 1]
        dlse = dlse_ref[0]                         # [bq, 1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse)                       # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta + dlse)
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(res, g, scale, causal, block_q, block_k, interpret):
    q, k, v, o, lse = res
    do, dlse = g
    BH, S, D = q.shape
    bq = _pick_block(S, block_q)
    bk = _pick_block(S, block_k)
    # delta_i = rowsum(dO_i * O_i) — cheap, fused by XLA outside pallas;
    # keepdims so the [BH, S, 1] layout matches lse's Mosaic-tileable
    # trailing-singleton blocks.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)         # [BH, S, 1]
    dlse = dlse.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(BH, S // bq, S // bk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[_vmem((bq, D))],
        interpret=interpret,
    )(q, k, v, do, lse, delta, dlse)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(BH, S // bk, S // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
        scratch_shapes=[_vmem((bk, D)), _vmem((bk, D))],
        interpret=interpret,
    )(q, k, v, do, lse, delta, dlse)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret,
           out_f32):
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                      out_f32)


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                   out_f32):
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                        interpret, out_f32)
    return (o, lse), (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, out_f32,
                   res, g):
    return _flash_bwd(res, g, scale, causal, block_q, block_k, interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _run_flash(q, k, v, causal, scale, block_q, block_k, interpret,
               out_f32=False):
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = _interpret_default()

    def fold(x):
        return jnp.moveaxis(x, 2, 1).reshape(B * H, S, D)

    o, lse = _flash(fold(q), fold(k), fold(v), float(scale),
                    bool(causal), int(block_q), int(block_k),
                    bool(interpret), bool(out_f32))
    o = jnp.moveaxis(o.reshape(B, H, S, D), 1, 2)
    lse = jnp.moveaxis(lse.reshape(B, H, S), 1, 2)   # [B, S, H]
    return o, lse


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: Optional[bool] = None):
    """Blockwise flash attention.  ``q/k/v``: [B, S, H, D].

    Returns [B, S, H, D] context.  Differentiable (custom VJP running the
    flash backward kernels).  ``interpret`` defaults to True off-TPU so
    the same code tests on the CPU backend.
    """
    o, _ = _run_flash(q, k, v, causal, scale, block_q, block_k, interpret)
    return o


def flash_attention_lse(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None,
                        block_q: int = 512, block_k: int = 512,
                        interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but also returns the per-query
    logsumexp ``[B, S, H]`` (fp32).  The pair ``(o, lse)`` is what
    blockwise composition needs: partial attentions over disjoint key
    sets combine exactly via logsumexp weights, which is how
    ``parallel.ring_attention`` chains this kernel across ``sp`` hops.
    Both outputs carry gradients (the lse cotangent adds the ``p·dlse``
    term in the backward kernels).  The partial output is emitted in
    fp32 (no per-hop rounding when partials are combined)."""
    return _run_flash(q, k, v, causal, scale, block_q, block_k,
                      interpret, out_f32=True)
