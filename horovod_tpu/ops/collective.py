"""In-graph collective ops: the TPU data plane.

These are the XLA-native equivalents of the reference's backend ops
(``horovod/common/ops/nccl_operations.cc``, ``mpi_operations.cc``,
``gloo_operations.cc``).  Instead of launching NCCL/MPI from a background
thread, each op lowers to an XLA HLO collective (all-reduce, all-gather,
all-to-all, collective-permute) over named mesh axes inside ``shard_map`` /
``pjit`` — XLA schedules them onto the ICI rings and overlaps them with
compute, which subsumes the reference's hand-rolled stream management
(``gpu_operations.h:49-63``).

Every function takes ``axis``: one mesh axis name or a tuple of names.  Use
them inside ``shard_map``/``pjit`` bodies; outside a trace use
``horovod_tpu.allreduce`` etc., which dispatch to the process-level runtime.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.types import ReduceOp

AxisSpec = Union[str, Sequence[str]]


def _axes(axis: AxisSpec) -> Tuple[str, ...]:
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def _one_axis_size(ax: str) -> int:
    # lax.axis_size is jax >= 0.5; older jax exposes the same static
    # sizes through the trace's axis env.
    if hasattr(lax, "axis_size"):
        return lax.axis_size(ax)
    from jax._src.core import get_axis_env

    return get_axis_env().axis_sizes[ax]


def axis_size(axis: AxisSpec) -> int:
    n = 1
    for ax in _axes(axis):
        n *= _one_axis_size(ax)
    return n


def axis_index(axis: AxisSpec):
    """Linearized index of this shard along ``axis`` (row-major over the
    given axis tuple)."""
    axes = _axes(axis)
    idx = lax.axis_index(axes[0])
    for ax in axes[1:]:
        idx = idx * _one_axis_size(ax) + lax.axis_index(ax)
    return idx


def allreduce(
    x,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis: AxisSpec = "dp",
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
):
    """All-reduce over mesh axes.  Parity: ``NCCLAllreduce::Execute``
    (nccl_operations.cc:109-159) — one fused device collective; pre/post
    scaling mirrors the v2 torch binding's prescale/postscale arguments.

    Average divides by the *total* size of the reduction axes, matching the
    reference's ``tensor / horovod_size`` semantics.  Adasum at the pure
    in-graph level needs pairwise recursion — see ``horovod_tpu.ops.adasum``;
    requesting it here raises.
    """
    axes = _axes(axis)
    if op == ReduceOp.ADASUM:
        from horovod_tpu.ops import adasum as _adasum

        return _adasum.adasum_allreduce(x, axis=axes)
    if prescale_factor != 1.0:
        x = x * prescale_factor
    if op in (ReduceOp.AVERAGE, ReduceOp.SUM):
        y = lax.psum(x, axes)
        if op == ReduceOp.AVERAGE:
            y = y / axis_size(axes)
    elif op == ReduceOp.MIN:
        y = lax.pmin(x, axes)
    elif op == ReduceOp.MAX:
        y = lax.pmax(x, axes)
    elif op == ReduceOp.PRODUCT:
        # No hardware product collective: exp/sum-of-logs is lossy, so do an
        # all-gather and reduce locally; product allreduce is rare and small.
        g = lax.all_gather(x, axes[0], axis=0, tiled=False)
        for ax in axes[1:]:
            g = lax.all_gather(g, ax, axis=0, tiled=True)
        y = jnp.prod(g, axis=0)
    else:
        raise ValueError(f"unsupported reduce op {op}")
    if postscale_factor != 1.0:
        y = y * postscale_factor
    return y


def grouped_allreduce(
    tensors,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis: AxisSpec = "dp",
    hierarchical: bool = False,
    outer_axis: str = "dcn",
):
    """Fused allreduce of a pytree: the in-graph analog of the reference's
    tensor fusion (``fusion_buffer_manager.h:28-55`` + ``FuseResponses``,
    controller.cc:638-759).

    Leaves are flattened and concatenated into one contiguous buffer per
    dtype, reduced with a single collective each, then split back.  Fewer,
    larger collectives keep the ICI links saturated exactly like the
    reference's fusion buffer keeps NCCL busy.

    ``hierarchical=True`` reduces each fused buffer with
    :func:`hierarchical_allreduce` — the in-graph twin of
    ``HVD_HIERARCHICAL_ALLREDUCE``.  ``axis`` must then name exactly the
    inner (ICI) and outer (``outer_axis``, DCN) axes, so the reduction
    set is identical to the flat path's.
    """
    inner = None
    if hierarchical:
        names = _axes(axis)
        if len(names) != 2 or outer_axis not in names:
            raise ValueError(
                "hierarchical grouped_allreduce needs axis to name "
                f"exactly the inner and outer axes (got {names}, "
                f"outer_axis={outer_axis!r})")
        inner = names[0] if names[1] == outer_axis else names[1]
    leaves, treedef = jax.tree.flatten(tensors)
    if not leaves:
        return tensors
    by_dtype = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.asarray(leaf).dtype, []).append(i)
    out = [None] * len(leaves)
    for dtype, idxs in by_dtype.items():
        flat = jnp.concatenate(
            [jnp.ravel(leaves[i]) for i in idxs], axis=0)
        if hierarchical:
            red = hierarchical_allreduce(
                flat, op=op, inner_axis=inner, outer_axis=outer_axis)
        else:
            red = allreduce(flat, op=op, axis=axis)
        offset = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = jnp.reshape(red[offset:offset + n], leaves[i].shape)
            offset += n
    return jax.tree.unflatten(treedef, out)


def allgather(x, axis: AxisSpec = "dp", tiled: bool = True):
    """Concatenate each shard's tensor along dim 0 across ``axis``.

    Parity: ``MPIAllgather`` / ``NCCLAllgather`` semantics (first-dim
    concatenation, mpi_operations.cc:83-166).  In-graph XLA all-gather
    requires equal shapes on every shard; ragged first dims are only
    supported on the eager path where the controller negotiates sizes.
    """
    axes = _axes(axis)
    g = x
    for ax in reversed(axes):
        g = lax.all_gather(g, ax, axis=0, tiled=tiled)
        tiled = True
    return g


def broadcast(x, root_rank: int = 0, axis: AxisSpec = "dp"):
    """Broadcast the value from linearized index ``root_rank`` of ``axis``.

    Parity: ``NCCLBroadcast`` (nccl_operations.cc:366-396).  Lowered as a
    masked psum, which XLA pattern-matches to a broadcast-like collective;
    correct for every dtype including bool/int.
    """
    idx = axis_index(axis)
    mask = (idx == root_rank)
    if x.dtype == jnp.bool_:
        y = jnp.where(mask, x, False)
        return lax.psum(y.astype(jnp.int32), _axes(axis)).astype(jnp.bool_)
    y = jnp.where(mask, x, jnp.zeros_like(x))
    return lax.psum(y, _axes(axis))


def reduce_scatter(x, op: ReduceOp = ReduceOp.AVERAGE, axis: str = "dp"):
    """Reduce across ``axis`` and scatter equal slices of dim 0.

    The building block of hierarchical allreduce (the reference's
    ``ncclReduceScatter`` leg, nccl_operations.cc:224-342).
    """
    n = _one_axis_size(axis)
    y = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
    if op == ReduceOp.AVERAGE:
        y = y / n
    elif op != ReduceOp.SUM:
        raise ValueError("reduce_scatter supports SUM/AVERAGE")
    return y


def hierarchical_allreduce(
    x,
    op: ReduceOp = ReduceOp.AVERAGE,
    inner_axis: str = "dp",
    outer_axis: str = "dcn",
):
    """reduce-scatter(ICI) → all-reduce(DCN) → all-gather(ICI).

    Direct TPU mapping of ``NCCLHierarchicalAllreduce``
    (nccl_operations.cc:163-363): the bandwidth-heavy phases ride the fast
    inner fabric; only 1/inner_size of the bytes crosses the slow outer
    links.  Requires dim 0 divisible by the inner axis size (the reference
    pads the fused buffer for the same reason).
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("hierarchical_allreduce supports SUM/AVERAGE")
    n_in = _one_axis_size(inner_axis)
    pad = (-x.shape[0]) % n_in
    orig = x.shape[0]
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
    piece = lax.psum_scatter(x, inner_axis, scatter_dimension=0, tiled=True)
    piece = lax.psum(piece, outer_axis)
    full = lax.all_gather(piece, inner_axis, axis=0, tiled=True)
    if pad:
        full = full[:orig]
    if op == ReduceOp.AVERAGE:
        full = full / (n_in * _one_axis_size(outer_axis))
    return full


def alltoall(x, splits=None, axis: str = "dp"):
    """Exchange equal (or ``splits``-described) chunks of dim 0 between all
    shards of ``axis``.  Equal-split maps to one XLA all-to-all; ragged
    splits (the torch ``alltoall(splits=...)`` API) are emulated with
    all-gather + gather because XLA all-to-all is static-shape.
    """
    if splits is None:
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    # Ragged output sizes are data-dependent, which XLA's static-shape
    # model cannot express without padding every segment to a max size —
    # the eager engine (which negotiates sizes) is the supported path.
    raise NotImplementedError(
        "ragged in-graph alltoall is not supported; use equal splits "
        "in-graph or horovod_tpu.alltoall (eager) for ragged splits")


def barrier(axis: AxisSpec = "dp"):
    """Synchronization barrier: a zero-byte psum every shard must reach."""
    return lax.psum(jnp.zeros((), jnp.int32), _axes(axis))


def ppermute_ring(x, axis: str, shift: int = 1):
    """Send to the neighbor ``shift`` steps around the ``axis`` ring —
    the primitive under ring attention and custom pipeline schedules."""
    n = _one_axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)
