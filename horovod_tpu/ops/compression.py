"""Gradient compression.

Parity: ``horovod/torch/compression.py:20-75`` and
``horovod/tensorflow/compression.py`` — a Compressor interface with ``none``
and ``fp16`` implementations.  TPU-first difference: the wire-efficient
16-bit format on TPU is **bfloat16** (same exponent range as fp32 — no
overflow on large gradients, and it is the MXU's native input type), so
``Compression.fp16`` here means "16-bit compression" and defaults to
bfloat16, with IEEE fp16 available explicitly for bit-parity testing
against the reference.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class Compressor:
    """Interface: compress before the collective, decompress after."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


def _is_float(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype) if not hasattr(dtype, "name")
                          else dtype, jnp.floating)


class _HalfCompressor(Compressor):
    """Cast floating tensors to a 16-bit dtype; restore original dtype
    after the reduction.  Non-float tensors pass through untouched, matching
    the reference (compression.py:47-52)."""

    wire_dtype = jnp.bfloat16

    @classmethod
    def compress(cls, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != cls.wire_dtype:
            return tensor.astype(cls.wire_dtype), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class BFloat16Compressor(_HalfCompressor):
    wire_dtype = jnp.bfloat16


class Float16Compressor(_HalfCompressor):
    wire_dtype = jnp.float16


class Float8Compressor(_HalfCompressor):
    """8-bit wire format (beyond the reference): OCP FP8 e4m3fn — 4x
    smaller than fp32 on the wire, ring hops still accumulate in fp32.
    e4m3 keeps 3 mantissa bits and saturates near ±448; gradients are
    typically pre-normalized, but prefer ``fp8_e5m2`` (fp16's range,
    2 mantissa bits) when overflow is a concern."""

    wire_dtype = jnp.float8_e4m3fn


class Float8E5M2Compressor(_HalfCompressor):
    wire_dtype = jnp.float8_e5m2


class Compression:
    """Optional gradient compression algorithms, Horovod-API-compatible."""

    none = NoneCompressor
    fp16 = BFloat16Compressor      # 16-bit wire format, TPU-native bf16
    float16 = Float16Compressor    # strict IEEE fp16 (reference parity)
    bfloat16 = BFloat16Compressor
    fp8 = Float8Compressor         # 8-bit wire format (e4m3fn)
    fp8_e5m2 = Float8E5M2Compressor
