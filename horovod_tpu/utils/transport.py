"""Pluggable data-plane transports: TCP sockets and same-host shm rings.

The eager data plane historically spoke one language — framed TCP
(``socketutil.py``) — even between two ranks on the same host, where
every ring hop paid kernel copies and syscalls for bytes that never
leave the machine.  This module extracts the transport contract the
collectives actually use (:class:`Transport`: ticketed async send,
frame receive, segmented ``recv_exact_into``, teardown) and provides
two implementations:

* :class:`TcpTransport` — the existing socket path, byte-identical to
  before: sends ride the peer's persistent
  :class:`~horovod_tpu.utils.socketutil.PeerSender`, receives go through
  the same ``recv_frame`` / ``recv_frame_header`` / ``recv_exact_into``
  helpers, and the ``sock.stall`` chaos site fires exactly where the
  backend used to fire it.
* :class:`ShmRingTransport` — a per peer-pair
  ``multiprocessing.shared_memory`` segment holding two directed rings
  of seqlock'd slots (one per direction).  The writer thread packs
  frame bytes straight from fusion-buffer views into the mapped slots;
  the reader ``recv_into``s straight out of them.  Handoff is a
  sequence counter per slot — payload bytes and length are stored
  first, the slot's sequence word last, so a reader that observes
  ``seq == expected`` observes a complete slot (single writer + single
  reader per ring; CPython's byte-store ordering under the GIL provides
  the publication barrier).  Waits are adaptive: a short hot spin, then
  GIL-yielding ``sleep(0)``, then escalating micro-sleeps — and they
  honor the PR-6 collective deadline, raising the same
  ``TimeoutError("receive deadline exceeded")`` the socket path raises
  so ``HopTimeout(peer, phase)`` mapping is transport-agnostic.

Framing over shm is the same byte stream as the wire: each frame is the
5-byte ``socketutil.HEADER`` followed by the payload, chunked across
slots.  Receiver-local segmentation (``HVD_RING_SEGMENT_BYTES``) and
the dtype/op reduction order therefore work identically over both
transports, which is what keeps shm results bit-identical to TCP
(pinned by tests/test_dataplane.py).

Pairing protocol (:func:`build_transports`), leak-proof by construction:

1. every rank publishes a host record (hostname + boot id) to the KV
   rendezvous; ranks that cannot attach shm (native engine,
   ``HVD_SHM_DISABLE``) publish a rank-unique token so no peer ever
   selects shm against them;
2. for each same-host pair, the LOWER rank creates the segment and
   publishes its name; the higher rank attaches (the ``shm.attach``
   chaos site fires here) and acks;
3. on ack the creator **immediately unlinks** the ``/dev/shm`` entry —
   both mappings persist, but the name is gone, so a SIGKILL of either
   peer (or both) can never leak a segment;
4. any create/attach failure is acked as such and both sides
   deterministically fall back to TCP over the already-connected mesh
   socket.
"""

from __future__ import annotations

import collections
import os
import socket
import struct
import threading
import time
import uuid
from typing import Dict, Optional, Tuple

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.telemetry import trace as _trace
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import socketutil as su


def _payload_nbytes(payload) -> int:
    n = getattr(payload, "nbytes", None)
    return n if n is not None else len(payload)


class Transport:
    """What a data-plane peer link must provide (see module docstring).

    ``send`` returns a ticket; ``wait(ticket)`` fences it (raising
    ``TimeoutError`` / ``ConnectionError`` with the same semantics as
    ``PeerSender.wait``).  ``deadline`` arguments are absolute
    ``time.monotonic()`` timestamps or ``None`` for block-forever."""

    kind = "none"
    peer = -1

    def send(self, payload, tag: int = su.TAG_DATA) -> int:
        raise NotImplementedError

    def wait(self, seq: int, timeout: Optional[float] = None) -> None:
        raise NotImplementedError

    def recv_frame(self,
                   deadline: Optional[float] = None) -> Tuple[int, bytes]:
        raise NotImplementedError

    def recv_frame_header(self,
                          deadline: Optional[float] = None
                          ) -> Tuple[int, int]:
        raise NotImplementedError

    def recv_exact_into(self, view: memoryview,
                        deadline: Optional[float] = None) -> None:
        raise NotImplementedError

    def close(self, timeout: float = 5.0) -> None:
        raise NotImplementedError

    def join(self, timeout: float = 2.0) -> None:
        """Join the sender thread after sockets/segments are torn down."""
        raise NotImplementedError


class TcpTransport(Transport):
    """The socket path behind the :class:`Transport` interface.

    Byte-identical to the pre-transport-layer code: same framing, same
    ``PeerSender`` ticket semantics, and the ``sock.stall`` chaos site
    fires once per received frame exactly where ``cpu_backend._recv`` /
    ``_recv_data_header`` used to fire it.  The socket stays owned by
    the engine (closed in engine shutdown, which is also what unblocks
    a sender thread wedged in the kernel)."""

    kind = "tcp"

    def __init__(self, sock: socket.socket, peer: int = -1,
                 sender: Optional[su.PeerSender] = None):
        self.sock = sock
        self.peer = peer
        self.sender = sender if sender is not None else su.PeerSender(
            sock, name=f"hvd-send-{peer}")

    def send(self, payload, tag: int = su.TAG_DATA) -> int:
        if _tmx.enabled():
            _tmx.inc_counter("hvd_transport_bytes_total",
                             float(_payload_nbytes(payload)), ("tcp",))
        return self.sender.send(payload, tag)

    def wait(self, seq: int, timeout: Optional[float] = None) -> None:
        self.sender.wait(seq, timeout)

    def recv_frame(self,
                   deadline: Optional[float] = None) -> Tuple[int, bytes]:
        _fi.fire("sock.stall")
        return su.recv_frame(self.sock, deadline)

    def recv_frame_header(self,
                          deadline: Optional[float] = None
                          ) -> Tuple[int, int]:
        _fi.fire("sock.stall")
        return su.recv_frame_header(self.sock, deadline)

    def recv_exact_into(self, view: memoryview,
                        deadline: Optional[float] = None) -> None:
        su.recv_exact_into(self.sock, view, deadline)

    def close(self, timeout: float = 5.0) -> None:
        self.sender.close(timeout)

    def join(self, timeout: float = 2.0) -> None:
        self.sender.thread.join(timeout)


# ---------------------------------------------------------------------------
# shared-memory ring segment
# ---------------------------------------------------------------------------

# Segment layout (all little-endian):
#   0    u32 magic, u32 version, u32 nslots, u32 slot_bytes
#   64   ring 0 write_seq (u64)   -- lower rank -> higher rank
#   128  ring 0 read_seq  (u64)
#   192  ring 1 write_seq (u64)   -- higher rank -> lower rank
#   256  ring 1 read_seq  (u64)
#   320  ring 0 slots, then ring 1 slots
# Slot: u64 seq, u32 nbytes, 4 pad bytes, payload; stride 64-aligned.
# The read_seq word is the writer's backpressure signal; the write_seq
# word is informational (attach validation / debugging) — readers use
# the per-slot seq, which is what makes the handoff a seqlock.
_MAGIC = 0x524D5348  # "HSMR"
_VERSION = 1
_HDR = struct.Struct("<IIII")
_CTRL = 64
_SLOTS_OFF = 320
_SLOT_HDR = 16

_SHM_PREFIX = "hvd-shm-"

# Wait-loop shape, env-tunable (HVD_SHM_SPIN / HVD_SHM_SLEEP_US;
# docs/performance.md "Transport selection").  Spinning is only
# profitable when the peer can make progress WHILE we spin — i.e. there
# is a spare core for it — so the spin default drops to 0 on a single
# core.  The escalating microsleep is capped at HVD_SHM_SLEEP_US on
# every host: the old single-core 1 ms ceiling meant ~0.5 ms average
# wake-up latency per slot while the TCP path got kernel-event wakeups,
# which is how shm lost its own shoot-out in BENCH_r08.  On one core the
# yield phase is what hands the quantum to the producer; the sleep only
# exists so a yield storm cannot starve it.
_CPUS = os.cpu_count() or 1
_SPIN_HOT = env_util.shm_spin()
_SPIN_YIELD = _SPIN_HOT + (512 if _CPUS > 1 else 256)
_READ_SLEEP_CAP = env_util.shm_sleep_us() * 1e-6


def _slot_stride(slot_bytes: int) -> int:
    return (_SLOT_HDR + slot_bytes + 63) & ~63


_untracked: set = set()


def _untrack(shm) -> None:
    # Python 3.10's SharedMemory has no ``track=`` parameter: every
    # attach registers the segment with the resource tracker, which
    # unlinks it when ANY attaching process exits and prints "leaked
    # shared_memory" warnings besides.  Ownership here is explicit
    # (create -> attach ack -> immediate unlink), so opt out.  The
    # tracker's cache is per-process and dedups registrations, so
    # unregister at most once per name (an in-process create + attach
    # pair, as in tests, registers once but would unregister twice).
    if shm._name in _untracked:
        return
    _untracked.add(shm._name)
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


class ShmSegment:
    """One mapped peer-pair segment: two directed seqlock'd rings."""

    def __init__(self, shm, nslots: int, slot_bytes: int, created: bool):
        self._shm = shm
        self.name = shm.name
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        self.created = created
        self._unlinked = False

    @classmethod
    def create(cls, slot_bytes: Optional[int] = None,
               nslots: Optional[int] = None,
               name: Optional[str] = None) -> "ShmSegment":
        from multiprocessing import shared_memory

        slot_bytes = slot_bytes if slot_bytes is not None \
            else env_util.shm_slot_bytes()
        nslots = nslots if nslots is not None else env_util.shm_slots()
        stride = _slot_stride(slot_bytes)
        total = _SLOTS_OFF + 2 * nslots * stride
        name = name or f"{_SHM_PREFIX}{os.getpid()}-{uuid.uuid4().hex[:12]}"
        shm = shared_memory.SharedMemory(name=name, create=True,
                                         size=total)
        _untrack(shm)
        # Fresh tmpfs pages are zero-filled, so every seq word already
        # reads 0; only the header needs writing.
        _HDR.pack_into(shm.buf, 0, _MAGIC, _VERSION, nslots, slot_bytes)
        return cls(shm, nslots, slot_bytes, created=True)

    @classmethod
    def attach(cls, name: str) -> "ShmSegment":
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=name)
        _untrack(shm)
        magic, version, nslots, slot_bytes = _HDR.unpack_from(shm.buf, 0)
        if magic != _MAGIC or version != _VERSION or nslots < 1 \
                or slot_bytes < 1:
            shm.close()
            raise ValueError(
                f"shm segment {name!r} has an incompatible header "
                f"(magic={magic:#x} version={version})")
        return cls(shm, nslots, slot_bytes, created=False)

    @property
    def buf(self):
        return self._shm.buf

    def ring_offsets(self, ring: int) -> Tuple[int, int, int]:
        """(write_seq offset, read_seq offset, first slot offset)."""
        stride = _slot_stride(self.slot_bytes)
        return (_CTRL + ring * 128, _CTRL + ring * 128 + 64,
                _SLOTS_OFF + ring * self.nslots * stride)

    def unlink(self) -> None:
        """Remove the /dev/shm name; existing mappings stay valid.

        Raw ``shm_unlink`` rather than ``SharedMemory.unlink`` — the
        stdlib version also unregisters with the resource tracker, but
        :func:`_untrack` already did that at create/attach time, and a
        second unregister makes the tracker process print a KeyError
        traceback at exit.
        """
        if self._unlinked:
            return
        self._unlinked = True
        try:
            import _posixshmem

            _posixshmem.shm_unlink(self._shm._name)
        except (ImportError, FileNotFoundError, OSError):
            pass

    def close(self) -> None:
        try:
            self._shm.close()
        except (BufferError, OSError):
            pass


class _RingWriter:
    """Single-threaded frame writer for one directed ring."""

    def __init__(self, seg: ShmSegment, ring: int):
        self._buf = seg.buf
        self._nslots = seg.nslots
        self._slot_bytes = seg.slot_bytes
        self._stride = _slot_stride(seg.slot_bytes)
        self._w_off, self._r_off, self._slot0 = seg.ring_offsets(ring)
        self._wseq = struct.unpack_from("<Q", self._buf, self._w_off)[0]

    def _slot_base(self, seq: int) -> int:
        return self._slot0 + (seq % self._nslots) * self._stride

    def _acquire(self, stopped) -> int:
        """Next writable slot index; adaptive wait while the ring is
        full (reader behind).  ``stopped()`` breaks the wait so close()
        never hangs on a dead peer."""
        w = self._wseq
        n = 0
        while True:
            r = struct.unpack_from("<Q", self._buf, self._r_off)[0]
            if w - r < self._nslots:
                return w
            n += 1
            if n < _SPIN_HOT:
                continue
            if stopped():
                raise ConnectionError("shm transport closed")
            time.sleep(0 if n < _SPIN_YIELD else
                       min(_READ_SLEEP_CAP, 1e-6 * n))

    def _publish(self, w: int, nbytes: int) -> None:
        base = self._slot_base(w)
        struct.pack_into("<I", self._buf, base + 8, nbytes)
        # The seq store is the publication: everything above must be in
        # the slot before the reader can observe seq == w + 1.
        struct.pack_into("<Q", self._buf, base, w + 1)
        self._wseq = w + 1
        struct.pack_into("<Q", self._buf, self._w_off, self._wseq)

    def write_frame(self, tag: int, payload, stopped) -> None:
        view = su._as_byte_view(payload)
        total = len(view)
        header = su.HEADER.pack(tag, total)
        hb = len(header)
        w = self._acquire(stopped)
        base = self._slot_base(w)
        k = min(self._slot_bytes - hb, total)
        self._buf[base + _SLOT_HDR:base + _SLOT_HDR + hb] = header
        if k:
            self._buf[base + _SLOT_HDR + hb:
                      base + _SLOT_HDR + hb + k] = view[:k]
        self._publish(w, hb + k)
        off = k
        while off < total:
            w = self._acquire(stopped)
            base = self._slot_base(w)
            k = min(self._slot_bytes, total - off)
            self._buf[base + _SLOT_HDR:
                      base + _SLOT_HDR + k] = view[off:off + k]
            self._publish(w, k)
            off += k


class _RingReader:
    """Single-threaded byte-stream reader for one directed ring."""

    def __init__(self, seg: ShmSegment, ring: int):
        self._buf = seg.buf
        self._nslots = seg.nslots
        self._stride = _slot_stride(seg.slot_bytes)
        self._w_off, self._r_off, self._slot0 = seg.ring_offsets(ring)
        self._rseq = struct.unpack_from("<Q", self._buf, self._r_off)[0]
        self._avail = 0  # unread payload bytes left in the current slot
        self._pos = 0    # read cursor within the current slot

    def _slot_base(self, seq: int) -> int:
        return self._slot0 + (seq % self._nslots) * self._stride

    def _wait_slot(self, deadline: Optional[float], stopped) -> int:
        """Spin-then-sleep until slot ``_rseq`` is published; returns
        its base offset.  Raises the socket path's exact
        ``TimeoutError("receive deadline exceeded")`` past ``deadline``
        so HopTimeout mapping is shared."""
        base = self._slot_base(self._rseq)
        want = self._rseq + 1
        n = 0
        while True:
            if struct.unpack_from("<Q", self._buf, base)[0] == want:
                return base
            n += 1
            if n < _SPIN_HOT:
                continue
            if stopped():
                raise ConnectionError("shm transport closed")
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError("receive deadline exceeded")
            time.sleep(0 if n < _SPIN_YIELD else
                       min(_READ_SLEEP_CAP, 1e-6 * n))

    def recv_into(self, view: memoryview, deadline: Optional[float],
                  stopped) -> None:
        if view.format != "B":
            view = view.cast("B")
        need = len(view)
        got = 0
        while got < need:
            if self._avail == 0:
                base = self._wait_slot(deadline, stopped)
                self._avail = struct.unpack_from(
                    "<I", self._buf, base + 8)[0]
                self._pos = 0
            base = self._slot_base(self._rseq)
            k = min(self._avail, need - got)
            src = base + _SLOT_HDR + self._pos
            view[got:got + k] = self._buf[src:src + k]
            got += k
            self._pos += k
            self._avail -= k
            if self._avail == 0:
                # Slot fully drained: hand it back to the writer.
                self._rseq += 1
                struct.pack_into("<Q", self._buf, self._r_off,
                                 self._rseq)


class ShmRingTransport(Transport):
    """Same-host peer link over one mapped :class:`ShmSegment`.

    The send side mirrors ``PeerSender`` exactly — a named daemon
    thread (``hvd-send-shm-<peer>``) fed through a deque, tickets that
    ``wait`` fences, failures surfaced at ``wait`` — so the collectives
    and the sender-leak assertions treat both transports identically.
    The ``lower`` flag picks which directed ring this side writes
    (ring 0 belongs to the pair's lower rank)."""

    kind = "shm"

    def __init__(self, segment: ShmSegment, lower: bool, peer: int = -1,
                 name: Optional[str] = None):
        self._seg = segment
        self.peer = peer
        self._writer = _RingWriter(segment, 0 if lower else 1)
        self._reader = _RingReader(segment, 1 if lower else 0)
        self._hdr_buf = bytearray(su.HEADER.size)
        self._deque: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._enq_seq = 0
        self._done_seq = 0
        self._fail_seq: Optional[int] = None
        self._exc: Optional[BaseException] = None
        self._closing = False
        self._stop = False
        self.thread = threading.Thread(
            target=self._loop, name=name or f"hvd-send-shm-{peer}",
            daemon=True)
        self.thread.start()

    def _stopped(self) -> bool:
        return self._stop

    # -- send side (PeerSender-mirror) ----------------------------------

    def send(self, payload, tag: int = su.TAG_DATA) -> int:
        if _tmx.enabled():
            _tmx.inc_counter("hvd_transport_bytes_total",
                             float(_payload_nbytes(payload)), ("shm",))
        with self._cv:
            if self._closing:
                raise ConnectionError("sender is closed")
            if self._exc is not None:
                raise ConnectionError(
                    f"peer send failed: {self._exc!r}") from self._exc
            self._enq_seq += 1
            seq = self._enq_seq
            self._deque.append((seq, tag, payload))
            self._cv.notify_all()
        return seq

    def wait(self, seq: int, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._done_seq < seq and self._exc is None:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            "send did not complete in time")
                if not self._cv.wait(remaining):
                    raise TimeoutError("send did not complete in time")
            if self._exc is not None and self._fail_seq is not None \
                    and seq >= self._fail_seq:
                raise ConnectionError(
                    f"peer send failed: {self._exc!r}") from self._exc

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._deque and not self._closing:
                    self._cv.wait()
                if not self._deque and self._closing:
                    return
                seq, tag, payload = self._deque.popleft()
            try:
                if self._exc is None:
                    self._writer.write_frame(tag, payload, self._stopped)
            except BaseException as e:  # surface at wait()
                with self._cv:
                    self._exc = e
                    if self._fail_seq is None:
                        self._fail_seq = seq
                    self._cv.notify_all()
            with self._cv:
                self._done_seq = seq
                self._cv.notify_all()

    # -- receive side ----------------------------------------------------

    def recv_frame(self,
                   deadline: Optional[float] = None) -> Tuple[int, bytes]:
        tag, n = self.recv_frame_header(deadline)
        payload = bytearray(n)
        if n:
            self._reader.recv_into(memoryview(payload), deadline,
                                   self._stopped)
        return tag, bytes(payload)

    def recv_frame_header(self,
                          deadline: Optional[float] = None
                          ) -> Tuple[int, int]:
        # Same chaos role as the TCP path's sock.stall: wedge this
        # rank's next data-plane receive while the process stays alive.
        _fi.fire("shm.stall")
        self._reader.recv_into(memoryview(self._hdr_buf), deadline,
                               self._stopped)
        return su.HEADER.unpack(bytes(self._hdr_buf))

    def recv_exact_into(self, view: memoryview,
                        deadline: Optional[float] = None) -> None:
        self._reader.recv_into(view, deadline, self._stopped)

    # -- teardown --------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        """Drain-then-force: let already-enqueued frames finish, then
        break any writer blocked on a full ring (dead peer) via the
        stop flag, join the thread, and unmap the segment."""
        with self._cv:
            closing = self._closing
            self._closing = True
            self._cv.notify_all()
        if not closing:
            self.thread.join(timeout)
            if self.thread.is_alive():
                self._stop = True
                self.thread.join(timeout)
            self._stop = True  # unblock any reader still spinning
            self._seg.close()
        else:
            self.thread.join(timeout)

    def join(self, timeout: float = 2.0) -> None:
        self._stop = True
        self.thread.join(timeout)


# ---------------------------------------------------------------------------
# transport selection: KV host records + per-pair create/attach/ack
# ---------------------------------------------------------------------------


def shm_enabled() -> bool:
    return not env_util.shm_disabled()


def host_fingerprint() -> str:
    """Same-host equality token: hostname + kernel boot id (containers
    sharing a hostname but not an IPC namespace still differ by boot id
    only when the kernel differs — the mesh socket pairing below is the
    functional check: attach failure falls back to TCP)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = ""
    return f"{socket.gethostname()}|{boot}"


def host_record_value(rank: int, shm_capable: bool) -> str:
    """What a rank publishes under ``{prefix}hostid/{rank}``.  A
    non-capable rank (native engine, ``HVD_SHM_DISABLE``) publishes a
    rank-unique token, so both sides of every pair agree on TCP without
    any extra negotiation."""
    if shm_capable and shm_enabled():
        return host_fingerprint()
    return f"tcp-only-{rank}"


# KV value marking a failed create (wait_get cannot distinguish an empty
# value from an absent key, so the marker is a real string).
_CREATE_FAILED = "none"


def build_transports(rank: int, size: int, data: Dict[int, socket.socket],
                     kv, prefix: str,
                     timeout: Optional[float] = None,
                     tcp_factory=None, shm_factory=None
                     ) -> Dict[int, Transport]:
    """One :class:`Transport` per mesh peer, selected at mesh-build time.

    Same-host peers (matching KV host records) pair a shm segment via
    create/attach/ack with the lower rank owning creation; the name is
    unlinked the moment the ack lands, so no segment can outlive the
    gang.  Cross-host peers — and any pair whose shm pairing fails —
    get a :class:`TcpTransport` over the existing mesh socket.

    Peers are processed in ascending rank order on every rank; the
    globally smallest unfinished pair can always complete, so the
    ack waits cannot deadlock.

    ``tcp_factory(sock, peer)`` / ``shm_factory(sock, seg, lower, peer)``
    override what gets built on the selected medium without duplicating
    the pairing protocol — utils/ladder.py uses them to wrap every pair
    in a self-healing :class:`LadderLink` when ``HVD_WIRE_CRC=1``.
    """
    if timeout is None:
        timeout = env_util.get_float("HVD_START_TIMEOUT", 120.0)
    if tcp_factory is None:
        def tcp_factory(sock, peer):
            return TcpTransport(sock, peer=peer)
    if shm_factory is None:
        def shm_factory(sock, seg, lower, peer):
            return ShmRingTransport(seg, lower=lower, peer=peer)
    transports: Dict[int, Transport] = {}
    mine = host_record_value(rank, shm_capable=True)
    want_shm = shm_enabled() and "|" in mine
    for r in sorted(data):
        sock = data[r]
        peer_fp = kv.wait_get(f"{prefix}hostid/{r}",
                              timeout=timeout) if want_shm else None
        if isinstance(peer_fp, bytes):
            peer_fp = peer_fp.decode()
        if not want_shm or peer_fp != mine:
            transports[r] = tcp_factory(sock, r)
            continue
        a, b = (rank, r) if rank < r else (r, rank)
        name_key = f"{prefix}shm/{a}_{b}"
        ack_key = f"{prefix}shmack/{a}_{b}"
        if rank == a:
            seg = None
            try:
                seg = ShmSegment.create()
                kv.put(name_key, seg.name)
            except Exception:
                kv.put(name_key, _CREATE_FAILED)
            if seg is None:
                transports[r] = tcp_factory(sock, r)
                continue
            try:
                ack = kv.wait_get(ack_key, timeout=timeout)
            finally:
                # Unlink NOW, ack or not (even when the attacher died
                # mid-pairing and the wait raised): our mapping — and
                # the peer's, when it acked ok — persists; the /dev/shm
                # name must not survive a SIGKILL of either side.
                seg.unlink()
            if isinstance(ack, bytes):
                ack = ack.decode()
            if ack == "ok":
                transports[r] = shm_factory(sock, seg, True, r)
            else:
                seg.close()
                transports[r] = tcp_factory(sock, r)
        else:
            name = kv.wait_get(name_key, timeout=timeout)
            if isinstance(name, bytes):
                name = name.decode()
            seg = None
            if name and name != _CREATE_FAILED:
                try:
                    _fi.fire("shm.attach", name)
                    seg = ShmSegment.attach(name)
                except Exception:
                    seg = None
            if seg is None:
                kv.put(ack_key, "fail")
                transports[r] = tcp_factory(sock, r)
            else:
                kv.put(ack_key, "ok")
                transports[r] = shm_factory(sock, seg, False, r)
    if _trace.active():
        # Record the selected medium per peer so merged traces can
        # attribute hop latencies to the transport that carried them.
        for r, t in sorted(transports.items()):
            _trace.emit_instant("transport.map", peer=r, tp=t.kind)
    return transports


def make_transport_pair(slot_bytes: int = 4096, nslots: int = 4
                        ) -> Tuple[ShmRingTransport, ShmRingTransport]:
    """In-process shm transport pair for tests: create + attach + unlink
    immediately, exactly like the KV protocol, no rendezvous needed."""
    seg_a = ShmSegment.create(slot_bytes=slot_bytes, nslots=nslots)
    seg_b = ShmSegment.attach(seg_a.name)
    seg_a.unlink()
    return (ShmRingTransport(seg_a, lower=True, peer=1),
            ShmRingTransport(seg_b, lower=False, peer=0))
