"""Framed TCP helpers for the controller and CPU data plane.

The control plane is host-side traffic exactly like the reference's
(gloo-over-TCP / MPI): tiny framed messages.  Frame = u8 tag, u32 LE length,
payload.
"""

from __future__ import annotations

import socket
import struct
from typing import Optional, Tuple

from horovod_tpu.common import fault_injection as _fi

HEADER = struct.Struct("<BI")

# Channel tags.
TAG_REQUEST_LIST = 1
TAG_RESPONSE_LIST = 2
TAG_DATA = 3
TAG_KV = 4
TAG_HEARTBEAT = 5


def send_frame(sock: socket.socket, tag: int, payload: bytes) -> None:
    _fi.fire("sock.send", str(tag))
    sock.sendall(HEADER.pack(tag, len(payload)) + payload)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    _fi.fire("sock.recv")
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            raise ConnectionError("peer closed connection")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    hdr = recv_exact(sock, HEADER.size)
    tag, n = HEADER.unpack(hdr)
    return tag, recv_exact(sock, n)


def listen_on(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, port))
    s.listen(128)
    return s


def connect_retry(host: str, port: int, timeout: float = 30.0,
                  interval: float = 0.05) -> socket.socket:
    """Dial ``host:port`` until ``timeout``, with capped exponential
    backoff + jitter between attempts (``interval`` seeds the backoff
    base) so a gang of workers dialing one listener does not retry in
    lockstep."""
    import time

    from horovod_tpu.common.retry import backoff_delays

    deadline = time.monotonic() + timeout
    delays = iter(backoff_delays(
        attempts=64, base_delay=interval, max_delay=1.0, jitter=0.5,
        seed=port))
    last: Optional[OSError] = None
    while time.monotonic() < deadline:
        try:
            _fi.fire("sock.connect", f"{host}:{port}")
            s = socket.create_connection((host, port), timeout=5.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(None)
            return s
        except OSError as e:
            last = e
            d = next(delays, 1.0)
            time.sleep(min(d, max(0.0, deadline - time.monotonic())))
    raise ConnectionError(f"cannot connect to {host}:{port}: {last}")
