"""Framed TCP helpers for the controller and CPU data plane.

The control plane is host-side traffic exactly like the reference's
(gloo-over-TCP / MPI): tiny framed messages.  Frame = u8 tag, u32 LE length,
payload.

The data plane additionally gets a zero-copy hot path (docs/performance.md):

* :func:`send_frame_zc` writes header + payload with scatter-gather
  (``sendmsg``), so neither the header concat nor a ``tobytes()`` copy of
  the payload happens — the payload memoryview goes straight to the kernel.
* :func:`recv_exact_into` / :func:`recv_frame_into` receive straight into a
  caller-owned buffer with ``recv_into`` — no per-chunk ``bytes`` objects,
  no ``b"".join``.
* :class:`PeerSender` is a persistent per-socket sender thread fed by a
  queue: ring hops enqueue a send and overlap it with their receive without
  spawning a thread per hop (the seed spawned one ``threading.Thread`` per
  ring step, which dominated small-message latency).
"""

from __future__ import annotations

import collections
import socket
import struct
import threading
import time
from typing import Optional, Tuple

from horovod_tpu.common import fault_injection as _fi

HEADER = struct.Struct("<BI")

# Channel tags.
TAG_REQUEST_LIST = 1
TAG_RESPONSE_LIST = 2
TAG_DATA = 3
TAG_KV = 4
TAG_HEARTBEAT = 5
# Collective-abort agreement (Python engine only, like TAG_HEARTBEAT:
# csrc/sockets.h stops at kTagData, and the native engine never
# negotiates HVD_COLLECTIVE_TIMEOUT).  Payload codecs: common/wire.py;
# protocol: docs/fault_tolerance.md "hung ranks vs dead ranks".
TAG_ABORT_REPORT = 6    # worker -> coordinator: local hop timeout
TAG_PROBE = 7           # coordinator -> workers: are you wedged?
TAG_PROBE_ACK = 8       # worker -> coordinator: busy flag + duration
TAG_ABORT_VERDICT = 9   # coordinator -> workers: agreed wedged ranks
# Serving admission broadcast (Python engine only, like the abort tags):
# rank 0's continuous-batching scheduler pushes each decode step's batch
# delta to every rank so the whole gang steps the same jit-ed decode
# function.  Payload codec: common/wire.py; protocol: docs/serving.md.
TAG_SERVE = 10          # coordinator -> workers: serve-step batch delta
# Recovery-ladder control frames (Python engine only, HVD_WIRE_CRC=1;
# utils/ladder.py, docs/fault_tolerance.md "recovery ladder").  These
# ride the data links themselves, never the ctrl star: csrc/wire.h
# reserves the values so the native engine can refuse them cleanly.
TAG_NACK = 11           # receiver -> sender: retransmit from seq
TAG_RESUME = 12         # both ways on a reconnected socket: resume point
TAG_FAILOVER = 13       # both ways on the mesh socket: shm->TCP demotion
# Gang-wide tracing clock sync (Python engine only, HVD_TRACE=1;
# telemetry/trace.py, docs/timeline.md "Gang-wide tracing").  Workers
# ping the coordinator over the ctrl star; the answer aligns per-rank
# monotonic clocks for the merged trace.  Payload codecs: common/wire.py;
# values reserved in csrc/wire.h.
TAG_CLOCK_PING = 14     # worker -> coordinator: my clock, now
TAG_CLOCK_PONG = 15     # coordinator -> worker: echo + coord clock
# Flight-recorder dump pull (Python engine only, always-on unless
# HVD_BLACKBOX=0; telemetry/blackbox.py, docs/fault_tolerance.md "the
# black box").  After broadcasting an abort verdict the coordinator
# pulls each still-live worker's in-memory ring over the ctrl star, so
# one archive survives even when a rank's disk doesn't.  Payload
# codecs: common/wire.py; values reserved in csrc/wire.h.
TAG_BLACKBOX = 16       # coordinator -> worker: send me your ring
TAG_BLACKBOX_DUMP = 17  # worker -> coordinator: serialized ring dump
# Hierarchical control tree (Python engine only, multi-host gangs;
# runtime_py.py "two-level control plane", docs/fault_tolerance.md
# "Hierarchical control plane, fencing, and quorum").  One
# sub-coordinator per host folds its children's request/heartbeat
# frames into a single TAG_TREE_UP aggregate; the root routes probes
# down through TAG_TREE_DOWN; an orphaned child of a dead
# sub-coordinator adopts itself back to the root with TAG_REPARENT
# over its still-live bootstrap-time control link.  TAG_FENCE is the
# coordinator's typed rejection of a stale-epoch sender (the zombie
# exits with FencedError instead of corrupting the re-formed gang).
# Payload codecs: common/wire.py; values reserved in csrc/wire.h.
TAG_TREE_UP = 18        # sub-coordinator -> root: aggregated child frames
TAG_TREE_DOWN = 19      # root -> sub-coordinator: routed/broadcast frame
TAG_REPARENT = 20       # orphaned child -> root: adopt me directly
TAG_FENCE = 21          # coordinator -> stale-epoch sender: epoch fenced


def send_frame(sock: socket.socket, tag: int, payload: bytes) -> None:
    _fi.fire("sock.send", str(tag))
    sock.sendall(HEADER.pack(tag, len(payload)) + payload)


def _as_byte_view(payload) -> memoryview:
    """A flat ``memoryview`` of bytes over ``payload`` without copying.

    Accepts bytes/bytearray/memoryview and C-contiguous numpy arrays —
    including dtypes whose PEP-3118 format memoryview rejects (bfloat16,
    fp8): those go through a uint8 reinterpret view of the same memory.
    """
    if isinstance(payload, memoryview):
        return payload.cast("B") if payload.format != "B" else payload
    if isinstance(payload, (bytes, bytearray)):
        return memoryview(payload)
    # numpy array (possibly an extension dtype): reinterpret as raw bytes.
    import numpy as np

    arr = payload
    if not arr.flags["C_CONTIGUOUS"]:
        arr = np.ascontiguousarray(arr)
    return memoryview(arr.reshape(-1).view(np.uint8))


def send_frame_zc(sock: socket.socket, tag: int, payload) -> None:
    """Scatter-gather frame send: header and payload go to the kernel as
    one ``sendmsg`` (falling back to two ``sendall``s), with the payload
    read directly from the caller's buffer — zero copies in user space.

    Fires the same ``sock.send`` fault site as :func:`send_frame`, so the
    chaos harness covers both framings identically.
    """
    _fi.fire("sock.send", str(tag))
    view = _as_byte_view(payload)
    header = HEADER.pack(tag, len(view))
    if not len(view):
        sock.sendall(header)
        return
    try:
        sent = sock.sendmsg([header, view])
    except (AttributeError, OSError):
        # No sendmsg (exotic platforms / wrapped sockets): two sendalls —
        # still no payload copy, just one extra syscall.
        sock.sendall(header)
        sock.sendall(view)
        return
    total = len(header) + len(view)
    while sent < total:
        # Short write: finish the remainder with sendall over views.
        if sent < len(header):
            sock.sendall(header[sent:])
            sock.sendall(view)
        else:
            sock.sendall(view[sent - len(header):])
        return


def recv_exact(sock: socket.socket, n: int,
               deadline: Optional[float] = None) -> bytes:
    """Receive exactly ``n`` bytes as a new ``bytes`` object.

    Implemented over one preallocated ``bytearray`` + ``recv_into`` — no
    per-chunk ``bytes`` objects and no trailing ``b"".join`` (the seed's
    version allocated both).  The ``sock.recv`` fault site fires exactly
    once per call, as before, so tests/test_chaos.py semantics hold.
    """
    buf = bytearray(n)
    recv_exact_into(sock, memoryview(buf), deadline)
    return bytes(buf)


def recv_exact_into(sock: socket.socket, view: memoryview,
                    deadline: Optional[float] = None) -> None:
    """Fill ``view`` completely from the socket via ``recv_into``.

    The caller owns the buffer; nothing is allocated here.  Fires the
    ``sock.recv`` fault-injection site once (same contract as
    :func:`recv_exact`).

    ``deadline`` is an absolute ``time.monotonic()`` timestamp; when
    set, every ``recv_into`` runs under ``settimeout(remaining)`` and a
    :class:`TimeoutError` is raised once the deadline passes.  When
    ``None`` (the default) the code path is byte-identical to before:
    no clock reads, no ``settimeout`` calls, block forever.
    """
    _fi.fire("sock.recv")
    got = 0
    n = len(view)
    if deadline is None:
        while got < n:
            r = sock.recv_into(view[got:], min(n - got, 1 << 20))
            if not r:
                raise ConnectionError("peer closed connection")
            got += r
        return
    try:
        while got < n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError("receive deadline exceeded")
            sock.settimeout(remaining)
            try:
                r = sock.recv_into(view[got:], min(n - got, 1 << 20))
            except socket.timeout:  # alias of TimeoutError on >=3.10
                raise TimeoutError("receive deadline exceeded") from None
            if not r:
                raise ConnectionError("peer closed connection")
            got += r
    finally:
        # Restore blocking mode; on the timeout path the socket is
        # poisoned (mid-frame) and the caller tears it down anyway.
        try:
            sock.settimeout(None)
        except OSError:
            pass


def recv_frame(sock: socket.socket,
               deadline: Optional[float] = None) -> Tuple[int, bytes]:
    hdr = recv_exact(sock, HEADER.size, deadline)
    tag, n = HEADER.unpack(hdr)
    return tag, recv_exact(sock, n, deadline)


def recv_frame_into(sock: socket.socket, view: memoryview,
                    deadline: Optional[float] = None) -> Tuple[int, int]:
    """Receive one frame's payload straight into ``view`` (which must be
    at least the frame's length); returns ``(tag, nbytes)``."""
    hdr = recv_exact(sock, HEADER.size, deadline)
    tag, n = HEADER.unpack(hdr)
    if n > len(view):
        raise ValueError(
            f"frame payload of {n} bytes exceeds the receive buffer "
            f"({len(view)} bytes)")
    recv_exact_into(sock, view[:n], deadline)
    return tag, n


def recv_frame_header(sock: socket.socket,
                      deadline: Optional[float] = None) -> Tuple[int, int]:
    """Read just the frame header: ``(tag, payload_len)``.  The caller
    then drains exactly ``payload_len`` bytes with
    :func:`recv_exact_into` — in one gulp or in segments (the segmented
    ring reads a hop in ``HVD_RING_SEGMENT_BYTES`` slices so each
    slice's reduction overlaps the next slice's receive)."""
    hdr = recv_exact(sock, HEADER.size, deadline)
    return HEADER.unpack(hdr)


def configure_data_socket(sock: socket.socket) -> None:
    """Socket options for data-plane (and ctrl) mesh connections, applied
    on BOTH the dialing and the accepting side: ``TCP_NODELAY`` (ring
    frames are latency-bound; Nagle on the accept side delayed half of
    every ring link in the seed) and, when ``HVD_SOCK_BUF_BYTES`` is set,
    matching ``SO_SNDBUF``/``SO_RCVBUF`` so segment pipelining has kernel
    buffer to overlap into."""
    from horovod_tpu.utils import env as env_util

    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass  # not a TCP socket (tests use socketpairs)
    buf = env_util.get_int(env_util.SOCK_BUF_BYTES, 0)
    if buf > 0:
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, buf)
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, buf)
        except OSError:
            pass


class PeerSender:
    """Persistent sender thread for one peer socket.

    Replaces the seed's thread-per-hop ``_send_async``: the thread is
    created once (at engine bootstrap) and fed through a deque; a ring
    hop enqueues its chunk view and gets back a ticket (sequence number)
    to wait on after its receive completes.  Waiting is a counter
    comparison under a condition variable — no per-send Event object, so
    the steady-state hop loop allocates nothing.

    Send failures (peer gone) are captured and re-raised at ``wait``, so
    the hop loop sees a ``ConnectionError`` where the seed's daemon
    thread silently swallowed it.
    """

    def __init__(self, sock: socket.socket, name: str = "hvd-send"):
        self._sock = sock
        self._deque: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._enq_seq = 0
        self._done_seq = 0
        self._fail_seq: Optional[int] = None
        self._exc: Optional[BaseException] = None
        self._closing = False
        self.thread = threading.Thread(
            target=self._loop, name=name, daemon=True)
        self.thread.start()

    def send(self, payload, tag: int = TAG_DATA) -> int:
        """Enqueue one frame; returns the ticket to pass to :meth:`wait`.
        ``payload`` may be bytes or a (contiguous) numpy array / view —
        the sender reads it in place, so the region must stay unmodified
        until ``wait`` returns."""
        with self._cv:
            if self._closing:
                raise ConnectionError("sender is closed")
            if self._exc is not None:
                raise ConnectionError(
                    f"peer send failed: {self._exc!r}") from self._exc
            self._enq_seq += 1
            seq = self._enq_seq
            self._deque.append((seq, tag, payload))
            self._cv.notify_all()
        return seq

    def wait(self, seq: int, timeout: Optional[float] = None) -> None:
        """Block until ticket ``seq`` has hit the kernel (or raise the
        send error that stopped the thread).

        ``timeout`` bounds the *total* wait: remaining time is
        recomputed across spurious/partial wakeups, so the call returns
        (or raises :class:`TimeoutError`) within ``timeout`` seconds of
        entry, not per condition-variable wait."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._done_seq < seq and self._exc is None:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            "send did not complete in time")
                if not self._cv.wait(remaining):
                    raise TimeoutError("send did not complete in time")
            if self._exc is not None and self._fail_seq is not None \
                    and seq >= self._fail_seq:
                # This ticket (or an earlier one it was queued behind)
                # never reached the kernel.
                raise ConnectionError(
                    f"peer send failed: {self._exc!r}") from self._exc

    def close(self, timeout: float = 5.0) -> None:
        """Stop the thread (after draining already-enqueued sends)."""
        with self._cv:
            if self._closing:
                self.thread.join(timeout)
                return
            self._closing = True
            self._cv.notify_all()
        self.thread.join(timeout)

    # -- internal ---------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._deque and not self._closing:
                    self._cv.wait()
                if not self._deque and self._closing:
                    return
                seq, tag, payload = self._deque.popleft()
            try:
                if self._exc is None:
                    # Half-open fault site: a peer whose outbound path
                    # silently blackholes (kind "halfopen" blocks here,
                    # then surfaces as a ConnectionError at wait()).
                    _fi.fire("sock.halfopen", str(tag))
                    send_frame_zc(self._sock, tag, payload)
            except BaseException as e:  # surface at wait()
                with self._cv:
                    self._exc = e
                    if self._fail_seq is None:
                        self._fail_seq = seq
                    self._cv.notify_all()
            # _done_seq advances even past a failure so close() and
            # wait() never hang; wait() raises via _fail_seq instead.
            with self._cv:
                self._done_seq = seq
                self._cv.notify_all()


def listen_on(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, port))
    s.listen(128)
    return s


def connect_retry(host: str, port: int, timeout: float = 30.0,
                  interval: float = 0.05) -> socket.socket:
    """Dial ``host:port`` until ``timeout``, with capped exponential
    backoff + jitter between attempts (``interval`` seeds the backoff
    base) so a gang of workers dialing one listener does not retry in
    lockstep."""
    from horovod_tpu.common.retry import backoff_delays

    deadline = time.monotonic() + timeout
    delays = iter(backoff_delays(
        attempts=64, base_delay=interval, max_delay=1.0, jitter=0.5,
        seed=port))
    last: Optional[OSError] = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            _fi.fire("sock.connect", f"{host}:{port}")
            # Per-attempt dial timeout: the 5 s cap, shrunk to whatever
            # is left on the overall deadline near expiry — a negative
            # or zero timeout must never reach create_connection.
            s = socket.create_connection(
                (host, port), timeout=min(5.0, remaining))
            configure_data_socket(s)
            s.settimeout(None)
            return s
        except OSError as e:
            last = e
            d = next(delays, 1.0)
            time.sleep(min(d, max(0.0, deadline - time.monotonic())))
    raise ConnectionError(f"cannot connect to {host}:{port}: {last}")
