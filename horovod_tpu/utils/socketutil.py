"""Framed TCP helpers for the controller and CPU data plane.

The control plane is host-side traffic exactly like the reference's
(gloo-over-TCP / MPI): tiny framed messages.  Frame = u8 tag, u32 LE length,
payload.
"""

from __future__ import annotations

import socket
import struct
from typing import Optional, Tuple

HEADER = struct.Struct("<BI")

# Channel tags.
TAG_REQUEST_LIST = 1
TAG_RESPONSE_LIST = 2
TAG_DATA = 3
TAG_KV = 4


def send_frame(sock: socket.socket, tag: int, payload: bytes) -> None:
    sock.sendall(HEADER.pack(tag, len(payload)) + payload)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        b = sock.recv(min(n - got, 1 << 20))
        if not b:
            raise ConnectionError("peer closed connection")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Tuple[int, bytes]:
    hdr = recv_exact(sock, HEADER.size)
    tag, n = HEADER.unpack(hdr)
    return tag, recv_exact(sock, n)


def listen_on(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, port))
    s.listen(128)
    return s


def connect_retry(host: str, port: int, timeout: float = 30.0,
                  interval: float = 0.05) -> socket.socket:
    import time

    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            s = socket.create_connection((host, port), timeout=5.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(None)
            return s
        except OSError as e:
            last = e
            time.sleep(interval)
    raise ConnectionError(f"cannot connect to {host}:{port}: {last}")
