"""Checkpoint / resume helpers.

Role parity: the reference ships checkpointing as *idioms*, not a
subsystem (SURVEY.md §5 — broadcast state from rank 0 at start,
rank-0-only checkpoint writing in the examples, Spark estimators saving
to the Store).  The TPU-native equivalent is a thin layer over orbax,
which already understands sharded ``jax.Array`` trees (multi-host GSPMD
checkpoints work out of the box):

* :func:`save` — write a pytree checkpoint.  In the eager multi-process
  regime state is replicated, so only rank 0 writes (the reference's
  idiom); in the GSPMD regime every process holds distinct shards and
  all of them must participate, so rank gating is disabled
  automatically when the tree contains sharded arrays.
* :func:`restore` — read it back (optionally into the sharding/dtype
  layout of a template tree).
* :func:`resume_or_init` — the standard training-loop entry: restore the
  latest step if a checkpoint exists, else initialize fresh and
  broadcast from rank 0 so every rank starts identical.

On top of that sits the *verified* layer (docs/fault_tolerance.md,
"Data-plane integrity"): a checkpoint that restores without error is not
necessarily the checkpoint that was written — torn writes and bit rot
restore fine and train a corrupted model.

* :func:`save_verified` — atomic write (temp dir + rename) under
  ``<root>/step_<n>``, plus a ``step_<n>.manifest.json`` sidecar with a
  sha256 per file, the step, and the elastic membership epoch; prunes to
  the newest ``HVD_CKPT_KEEP`` checkpoints.
* :func:`restore_verified` — newest-first: re-hash every file against
  the manifest, fall back to the next-newest checkpoint on any mismatch
  (recording ``CKPT_VERIFY_FAIL`` on the timeline), raise
  :class:`CheckpointVerifyError` only when nothing verifies.

The ``ckpt.corrupt`` fault-injection site fires right after a verified
save, poisoning one file the way a disk would — tests/test_integrity.py
proves the fallback end to end.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import shutil
from typing import Any, Callable, List, Optional, Tuple

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import timeline as timeline_mod

logger = logging.getLogger("horovod_tpu.checkpoint")

MANIFEST_FORMAT = 1
_STEP_DIR = re.compile(r"^step_(\d+)$")


def _is_sharded(tree) -> bool:
    import jax

    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and \
                getattr(sharding, "num_devices", 1) > 1:
            return True
    return False


def save(path: str, tree: Any, *, force: bool = True) -> bool:
    """Write ``tree`` to ``path``.  Returns True if this process wrote.

    Replicated (eager-regime) state is written by rank 0 only; sharded
    state is written collectively by every process (orbax requirement).
    """
    import orbax.checkpoint as ocp

    from horovod_tpu import basics

    sharded = _is_sharded(tree)
    if not sharded and basics.is_initialized() and basics.rank() != 0:
        # Replicated state, non-root rank: the reference's rank-0-only
        # idiom.  A barrier would be wrong here (root may take a while);
        # callers needing sync call hvd.barrier() themselves.
        return False
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=force)
    ckptr.wait_until_finished()
    return True


def restore(path: str, template: Optional[Any] = None) -> Any:
    """Read a checkpoint; with ``template``, restore into its exact
    sharding/structure (required for GSPMD states)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        return ckptr.restore(path, template)
    return ckptr.restore(path)


def exists(path: str) -> bool:
    return os.path.isdir(path) and bool(os.listdir(path))


def resume_or_init(path: str, init_fn: Callable[[], Any],
                   *, broadcast: bool = True) -> Any:
    """Restore ``path`` if present, else ``init_fn()`` (+ broadcast the
    fresh state from rank 0 in the eager regime so ranks agree —
    parity: the reference's broadcast-at-start idiom)."""
    if exists(path):
        import jax

        # Prefer an abstract template (shapes/dtypes/shardings without
        # materializing a full state that is immediately discarded);
        # fall back to a concrete one when eval_shape can't trace
        # init_fn or orbax needs real arrays.
        try:
            template = jax.eval_shape(init_fn)
            return restore(path, template)
        except Exception:
            return restore(path, init_fn())
    state = init_fn()
    from horovod_tpu import basics

    if broadcast and basics.is_initialized() and basics.size() > 1 \
            and not _is_sharded(state):
        from horovod_tpu.ops import eager

        state = eager.broadcast_parameters(state, 0, prefix="ckpt.init")
    return state


# -- verified checkpoints -------------------------------------------------


class CheckpointVerifyError(RuntimeError):
    """Checkpoints exist under the root but none passed verification."""

    def __init__(self, root: str, failures):
        self.root = root
        self.failures = list(failures)
        detail = "; ".join(f"{os.path.basename(p)}: {r}"
                           for p, r in self.failures)
        super().__init__(
            f"no verifiable checkpoint under {root!r} — every candidate "
            f"failed its manifest check ({detail}); restore from a backup "
            f"or re-initialize")


def manifest_path(ckpt_dir: str) -> str:
    return ckpt_dir.rstrip("/") + ".manifest.json"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _walk_files(root: str) -> List[str]:
    out = []
    for dirpath, _, names in os.walk(root):
        for n in names:
            out.append(os.path.relpath(os.path.join(dirpath, n), root))
    return sorted(out)


def _write_manifest(ckpt_dir: str, step: int, epoch: int) -> None:
    files = {}
    for rel in _walk_files(ckpt_dir):
        full = os.path.join(ckpt_dir, rel)
        files[rel] = {"sha256": _sha256_file(full),
                      "bytes": os.path.getsize(full)}
    manifest = {"format": MANIFEST_FORMAT, "step": step, "epoch": epoch,
                "files": files}
    target = manifest_path(ckpt_dir)
    tmp = f"{target}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, target)


def verify_checkpoint(ckpt_dir: str) -> Tuple[bool, str]:
    """``(ok, reason)`` — re-hash every manifest-listed file.

    Extra files are tolerated (orbax layouts vary by version); missing
    or mismatching ones are not.
    """
    mpath = manifest_path(ckpt_dir)
    if not os.path.isfile(mpath):
        return False, "no manifest sidecar"
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
        files = manifest["files"]
    except (ValueError, KeyError, TypeError) as e:
        return False, f"unreadable manifest ({e})"
    for rel, meta in sorted(files.items()):
        full = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(full):
            return False, f"missing file {rel!r}"
        if _sha256_file(full) != meta.get("sha256"):
            return False, f"sha256 mismatch on {rel!r}"
    return True, ""


def list_steps(root: str) -> List[Tuple[int, str]]:
    """``(step, dir)`` pairs under ``root``, newest step first."""
    out = []
    if os.path.isdir(root):
        for name in os.listdir(root):
            m = _STEP_DIR.match(name)
            if m and os.path.isdir(os.path.join(root, name)):
                out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out, reverse=True)


def _corrupt_one_file(ckpt_dir: str) -> None:
    """The ``ckpt.corrupt`` chaos payload: flip one byte in the middle of
    the largest file — bit rot / a torn write, after the manifest was
    sealed, exactly what verification exists to catch."""
    rels = _walk_files(ckpt_dir)
    if not rels:
        return
    target = max(rels, key=lambda r: os.path.getsize(
        os.path.join(ckpt_dir, r)))
    full = os.path.join(ckpt_dir, target)
    size = os.path.getsize(full)
    if size == 0:
        return
    with open(full, "r+b") as fh:
        fh.seek(size // 2)
        b = fh.read(1)
        fh.seek(size // 2)
        fh.write(bytes([b[0] ^ 0xFF]))


def _prune(root: str, keep: int) -> None:
    for step, d in list_steps(root)[keep:]:
        shutil.rmtree(d, ignore_errors=True)
        try:
            os.remove(manifest_path(d))
        except OSError:
            pass


def _gang_barrier() -> None:
    from horovod_tpu import basics

    if basics.is_initialized() and basics.size() > 1:
        from horovod_tpu.ops import eager

        eager.barrier()


def save_verified(root: str, tree: Any, *, step: int,
                  keep: Optional[int] = None,
                  force: bool = True) -> Optional[str]:
    """Atomically write ``<root>/step_<step>`` + manifest; prune to the
    newest ``keep`` (``HVD_CKPT_KEEP``, default 3).  Returns the final
    directory, or None on a non-writing (non-root, replicated) rank.

    Replicated trees keep :func:`save`'s rank-0-only gating (and its
    no-barrier caveat).  Sharded trees are a *collective*: orbax requires
    every process to pass the SAME directory, so the temp path is
    deterministic (no pid) and the write is bracketed by gang barriers —
    rank 0 seals (rename + manifest) only after every rank's shards are
    on disk, and no rank returns before the seal is visible.
    """
    import orbax.checkpoint as ocp

    from horovod_tpu import basics

    keep = keep if keep is not None else env_util.get_int(
        env_util.CKPT_KEEP, 3)
    if keep < 1:
        raise ValueError("checkpoint retention (keep) must be >= 1")
    final = os.path.join(root, f"step_{step}")
    sharded = _is_sharded(tree)
    if not sharded and basics.is_initialized() and basics.rank() != 0:
        return None
    collective = sharded and basics.is_initialized() and basics.size() > 1
    if sharded and not collective:
        # Multi-process GSPMD without the engine: there is no barrier to
        # order the collective shard write against the rank-0 seal, and
        # a half-sealed checkpoint that *passes* verification is exactly
        # what this layer exists to prevent.
        import jax

        if jax.process_count() > 1:
            raise RuntimeError(
                "save_verified on a multi-process sharded tree needs the "
                "gang barrier that hvd.init() provides; initialize "
                "horovod_tpu first or use the unverified save()")
    if not force and os.path.isdir(final):
        raise FileExistsError(final)
    os.makedirs(root, exist_ok=True)
    if collective:
        tmp = os.path.join(root, f".tmp.step_{step}")
        if basics.rank() == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        _gang_barrier()  # leftover tmp cleared before anyone writes
    else:
        tmp = os.path.join(root, f".tmp.step_{step}.{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(tmp, tree, force=True)
    ckptr.wait_until_finished()
    if collective:
        _gang_barrier()  # every rank's shards durable before the seal
    finalize = not (sharded and basics.is_initialized()
                    and basics.rank() != 0)
    if finalize:
        if os.path.isdir(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        epoch = env_util.get_int(env_util.ELASTIC_EPOCH, 0)
        _write_manifest(final, step, epoch)
        if _fi.should_corrupt("ckpt.corrupt", final):
            _corrupt_one_file(final)
        _prune(root, keep)
    if collective:
        _gang_barrier()  # the sealed dir is visible on every rank's return
    return final


def restore_verified(root: str, template: Optional[Any] = None
                     ) -> Tuple[Any, int]:
    """Newest-first verified restore: ``(tree, step)`` from the newest
    checkpoint whose manifest checks out, falling back past any that
    don't (each fallback logs a warning and records ``CKPT_VERIFY_FAIL``
    on the timeline).  Raises FileNotFoundError with no candidates at
    all, :class:`CheckpointVerifyError` when none verify.
    """
    candidates = list_steps(root)
    if not candidates:
        raise FileNotFoundError(
            f"no step_<n> checkpoints under {root!r}")
    failures = []
    for step, d in candidates:
        ok, reason = verify_checkpoint(d)
        if not ok:
            logger.warning(
                "checkpoint %s failed verification (%s); "
                "falling back to the next newest", d, reason)
            timeline_mod.engine_event(
                timeline_mod.CKPT_VERIFY_FAIL, path=d, reason=reason)
            failures.append((d, reason))
            continue
        return restore(d, template), step
    raise CheckpointVerifyError(root, failures)
