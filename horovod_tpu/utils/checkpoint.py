"""Checkpoint / resume helpers.

Role parity: the reference ships checkpointing as *idioms*, not a
subsystem (SURVEY.md §5 — broadcast state from rank 0 at start,
rank-0-only checkpoint writing in the examples, Spark estimators saving
to the Store).  The TPU-native equivalent is a thin layer over orbax,
which already understands sharded ``jax.Array`` trees (multi-host GSPMD
checkpoints work out of the box):

* :func:`save` — write a pytree checkpoint.  In the eager multi-process
  regime state is replicated, so only rank 0 writes (the reference's
  idiom); in the GSPMD regime every process holds distinct shards and
  all of them must participate, so rank gating is disabled
  automatically when the tree contains sharded arrays.
* :func:`restore` — read it back (optionally into the sharding/dtype
  layout of a template tree).
* :func:`resume_or_init` — the standard training-loop entry: restore the
  latest step if a checkpoint exists, else initialize fresh and
  broadcast from rank 0 so every rank starts identical.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional


def _is_sharded(tree) -> bool:
    import jax

    for leaf in jax.tree.leaves(tree):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and \
                getattr(sharding, "num_devices", 1) > 1:
            return True
    return False


def save(path: str, tree: Any, *, force: bool = True) -> bool:
    """Write ``tree`` to ``path``.  Returns True if this process wrote.

    Replicated (eager-regime) state is written by rank 0 only; sharded
    state is written collectively by every process (orbax requirement).
    """
    import orbax.checkpoint as ocp

    from horovod_tpu import basics

    sharded = _is_sharded(tree)
    if not sharded and basics.is_initialized() and basics.rank() != 0:
        # Replicated state, non-root rank: the reference's rank-0-only
        # idiom.  A barrier would be wrong here (root may take a while);
        # callers needing sync call hvd.barrier() themselves.
        return False
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=force)
    ckptr.wait_until_finished()
    return True


def restore(path: str, template: Optional[Any] = None) -> Any:
    """Read a checkpoint; with ``template``, restore into its exact
    sharding/structure (required for GSPMD states)."""
    import orbax.checkpoint as ocp

    ckptr = ocp.StandardCheckpointer()
    if template is not None:
        return ckptr.restore(path, template)
    return ckptr.restore(path)


def exists(path: str) -> bool:
    return os.path.isdir(path) and bool(os.listdir(path))


def resume_or_init(path: str, init_fn: Callable[[], Any],
                   *, broadcast: bool = True) -> Any:
    """Restore ``path`` if present, else ``init_fn()`` (+ broadcast the
    fresh state from rank 0 in the eager regime so ranks agree —
    parity: the reference's broadcast-at-start idiom)."""
    if exists(path):
        import jax

        # Prefer an abstract template (shapes/dtypes/shardings without
        # materializing a full state that is immediately discarded);
        # fall back to a concrete one when eval_shape can't trace
        # init_fn or orbax needs real arrays.
        try:
            template = jax.eval_shape(init_fn)
            return restore(path, template)
        except Exception:
            return restore(path, init_fn())
    state = init_fn()
    from horovod_tpu import basics

    if broadcast and basics.is_initialized() and basics.size() > 1 \
            and not _is_sharded(state):
        from horovod_tpu.ops import eager

        state = eager.broadcast_parameters(state, 0, prefix="ckpt.init")
    return state
