"""Leveled, rank-prefixed logging.

Parity: ``horovod/common/logging.cc:39-67`` (``LOG(LEVEL, rank)`` macros,
``HOROVOD_LOG_LEVEL`` / ``HOROVOD_LOG_HIDE_TIME``).  Env knobs here:
``HVD_LOG_LEVEL`` ∈ {trace, debug, info, warning, error, fatal} and
``HVD_LOG_HIDE_TIME``.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(5, "TRACE")


def get_logger(rank: int = -1) -> logging.Logger:
    name = "horovod_tpu" if rank < 0 else f"horovod_tpu[{rank}]"
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        hide_time = os.environ.get("HVD_LOG_HIDE_TIME", "0") in ("1", "true")
        fmt = "[%(name)s %(levelname)s] %(message)s" if hide_time else \
            "%(asctime)s [%(name)s %(levelname)s] %(message)s"
        handler.setFormatter(logging.Formatter(fmt))
        logger.addHandler(handler)
        level = os.environ.get("HVD_LOG_LEVEL", "warning").lower()
        logger.setLevel(_LEVELS.get(level, logging.WARNING))
        logger.propagate = False
    return logger
