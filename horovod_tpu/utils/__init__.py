"""Utility subsystems: logging, env parsing, sockets, timeline."""
