"""Chrome-tracing timeline profiler.

Parity: ``horovod/common/timeline.cc/.h`` — rank 0 writes a Chrome
``chrome://tracing`` JSON stream of per-tensor phases: NEGOTIATE_<OP> (with
per-rank ready ticks), the top-level op, and nested activities (QUEUE,
MEMCPY_IN_FUSION_BUFFER, <BACKEND>_ALLREDUCE, ...).  Enabled by
``HVD_TIMELINE=<path>`` (reference: HOROVOD_TIMELINE, operations.cc:392).

Design difference: the reference drains a boost lock-free SPSC queue on a
dedicated writer thread; here a plain ``queue.SimpleQueue`` + writer thread
gives the same non-blocking hot path in far less machinery.  The native C++
core has its own writer (csrc/timeline.cc) with the same file format.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Optional

# Canonical activity names (parity: common.h:30-59).
NEGOTIATE_ALLREDUCE = "NEGOTIATE_ALLREDUCE"
NEGOTIATE_ALLGATHER = "NEGOTIATE_ALLGATHER"
NEGOTIATE_BROADCAST = "NEGOTIATE_BROADCAST"
NEGOTIATE_ALLTOALL = "NEGOTIATE_ALLTOALL"
ALLREDUCE = "ALLREDUCE"
ALLGATHER = "ALLGATHER"
BROADCAST = "BROADCAST"
ALLTOALL = "ALLTOALL"
QUEUE = "QUEUE"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
CPU_RING_ALLREDUCE = "CPU_RING_ALLREDUCE"
XLA_ALLREDUCE = "XLA_ALLREDUCE"
CYCLE_START = "CYCLE_START"

# Data-plane integrity records (horovod_tpu.integrity).
NONFINITE_SKIP = "NONFINITE_SKIP"
DIVERGENCE_DETECTED = "DIVERGENCE_DETECTED"
CKPT_VERIFY_FAIL = "CKPT_VERIFY_FAIL"

# Collective-deadline record (runtime_py._apply_abort_verdict): the gang
# agreed a collective blew HVD_COLLECTIVE_TIMEOUT and named the wedged
# rank(s).
COLLECTIVE_ABORT = "COLLECTIVE_ABORT"

# Recovery-ladder records (utils/ladder.py; docs/fault_tolerance.md
# "recovery ladder").  HOP_RETRY = a data frame was retransmitted on one
# link (args name the peer and cause: corrupt/reset/failover);
# TRANSPORT_FAILOVER = a peer pair was demoted from shm to TCP in place.
# Both are instants on the rank that healed — a soak run with zero
# ELASTIC_REFORM records but HOP_RETRY records present is the ladder
# working as designed.
HOP_RETRY = "HOP_RETRY"
TRANSPORT_FAILOVER = "TRANSPORT_FAILOVER"

# Hierarchical-control-plane records (runtime_py.py, elastic/run.py;
# docs/fault_tolerance.md "Hierarchical control plane, fencing, and
# quorum").  SUBCOORD_REPARENT = a child of a dead per-host
# sub-coordinator re-attached directly to the root (args name the child
# and the dead parent) — failure isolation working: only the dead rank
# is evicted, no gang-wide abort.  PARTITION_MINORITY = this side of a
# membership split holds no strict majority of the last-committed
# roster, so it self-terminates instead of re-forming a split-brain
# sibling gang.
SUBCOORD_REPARENT = "SUBCOORD_REPARENT"
PARTITION_MINORITY = "PARTITION_MINORITY"

# Telemetry records (horovod_tpu.telemetry; docs/metrics.md).  ALERT =
# the gang aggregator's streaming anomaly engine tripped a rule
# (telemetry/aggregate.py; args name the rule, the implicated rank, the
# observed value and its EWMA baseline).
STRAGGLER = "STRAGGLER"
ALERT = "ALERT"

# Writer-thread flush cadence: events are buffered and flushed when the
# queue runs dry or every _FLUSH_EVERY events, whichever comes first —
# one syscall per burst instead of one per event.
_FLUSH_EVERY = 64

# Process-wide clock anchors, captured once at import: every timeline
# (and every trace.py span stream) in this process shares ONE monotonic
# base, so streams started at different moments — e.g. the engines of
# successive elastic incarnations — land on one comparable time axis,
# immune to NTP steps.  The paired wall-clock read is recorded as a
# CLOCK_ANCHOR event so external tools can align per-host files to
# NTP-grade accuracy (the trace clock-sync protocol does better).
MONO_ANCHOR_NS = time.monotonic_ns()
WALL_ANCHOR_NS = time.time_ns()

# Live timelines by path: an elastic reset tears the engine down and
# re-initializes it in the SAME process, and the new engine must append
# to the trace instead of truncating it — the reset/re-form cycle being
# visible in one file is the point of recording it.
_live: dict = {}
_live_lock = threading.Lock()


class Timeline:
    """Per-process timeline; no-op unless ``initialize`` is called with a
    filename (only rank 0 does, like the reference)."""

    def __init__(self):
        self._q: Optional[queue.SimpleQueue] = None
        self._writer: Optional[threading.Thread] = None
        self._f = None
        self._start_ns = 0
        self._tensor_tids = {}
        self._mark_cycles = False
        self._persistent = False  # survive engine shutdown (elastic)

    @property
    def enabled(self) -> bool:
        return self._q is not None

    def initialize(self, filename: str, mark_cycles: bool = False,
                   persistent: bool = False) -> None:
        if self.enabled or not filename:
            return
        self._persistent = persistent
        # Persistent (elastic) traces append: after a gang re-form, the
        # new lowest-rank process may be one that never wrote the file —
        # "w" would erase the pre-reset history.
        self._f = open(filename, "a" if persistent else "w")
        self._f.write("[\n")
        # One shared monotonic base per process (not per initialize):
        # an elastic re-init appends to the same file, and its events
        # must stay on the first incarnation's time axis.  The format
        # (relative-µs ``ts``) is byte-compatible with existing parsers.
        self._start_ns = MONO_ANCHOR_NS
        self._mark_cycles = mark_cycles
        self._q = queue.SimpleQueue()
        self._writer = threading.Thread(
            target=self._drain, name="hvd-timeline", daemon=True)
        self._writer.start()
        self.instant("CLOCK_ANCHOR", mono_ns=MONO_ANCHOR_NS,
                     wall_ns=WALL_ANCHOR_NS)

    def shutdown(self) -> None:
        if not self.enabled:
            return
        if self._persistent:
            # An elastic engine shutdown is not the end of the story —
            # the re-formed engine re-attaches via from_env().  Events
            # are flushed as they drain, so there is nothing to lose if
            # the process exits instead.
            return
        self._q.put(None)
        self._writer.join(timeout=5)
        try:
            # Close the JSON array: every event line ends with ",\n", so
            # a bare "{}]" sentinel object makes the whole trace valid
            # JSON (chrome://tracing tolerates the unclosed form; plain
            # json.load does not).
            self._f.write("{}]\n")
            self._f.close()
        except Exception:
            pass
        self._q = None

    # -- event emission (hot path: enqueue only) --------------------------

    def _ts_us(self) -> float:
        return (time.monotonic_ns() - self._start_ns) / 1e3

    def _tid(self, tensor_name: str) -> int:
        if tensor_name not in self._tensor_tids:
            tid = len(self._tensor_tids) + 1
            self._tensor_tids[tensor_name] = tid
            # Label the lane after the tensor (chrome-tracing metadata),
            # matching the native writer's per-tensor rows.
            self._q.put({"ph": "M", "pid": 0, "tid": tid,
                         "name": "thread_name",
                         "args": {"name": tensor_name}})
        return self._tensor_tids[tensor_name]

    def _emit(self, ph, name, tensor_name, args=None):
        if not self.enabled:
            return
        ev = {
            "ph": ph,
            "ts": self._ts_us(),
            "pid": 0,
            "tid": self._tid(tensor_name) if tensor_name else 0,
        }
        if name is not None:
            ev["name"] = name
        if args:
            ev["args"] = args
        self._q.put(ev)

    def negotiate_start(self, tensor_name: str, op_name: str) -> None:
        self._emit("B", f"NEGOTIATE_{op_name}", tensor_name)

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        self._emit("i", f"RANK_{rank}_READY", tensor_name)

    def negotiate_end(self, tensor_name: str) -> None:
        self._emit("E", None, tensor_name)

    def start(self, tensor_name: str, op_name: str) -> None:
        self._emit("B", op_name, tensor_name)

    def activity_start(self, tensor_name: str, activity: str) -> None:
        self._emit("B", activity, tensor_name)

    def activity_end(self, tensor_name: str) -> None:
        self._emit("E", None, tensor_name)

    def end(self, tensor_name: str) -> None:
        self._emit("E", None, tensor_name)

    def mark_cycle_start(self) -> None:
        if self._mark_cycles:
            self._emit("i", CYCLE_START, "")

    def instant(self, name: str, **args) -> None:
        """Named instant marker on the process lane (tid 0) — events not
        tied to a tensor: the elastic reset/re-form cycle and the
        data-plane integrity records (``NONFINITE_SKIP``,
        ``DIVERGENCE_DETECTED``, ``CKPT_VERIFY_FAIL``)."""
        self._emit("i", name, "", args=args or None)

    # Historical name for the elastic records; same event shape.
    elastic_event = instant

    # -- writer thread ----------------------------------------------------

    def _drain(self) -> None:
        # Batch flushes: a training step can emit hundreds of events in a
        # burst, and flushing per event turns the writer thread into a
        # syscall loop.  Write eagerly, flush when the queue runs dry (so
        # a reader of the file never lags a quiet trace) or every
        # _FLUSH_EVERY events during a burst.
        unflushed = 0
        while True:
            if unflushed:
                try:
                    ev = self._q.get_nowait()
                except queue.Empty:
                    self._f.flush()
                    unflushed = 0
                    ev = self._q.get()
            else:
                ev = self._q.get()
            if ev is None:
                if unflushed:
                    self._f.flush()
                break
            self._f.write(json.dumps(ev) + ",\n")
            unflushed += 1
            if unflushed >= _FLUSH_EVERY:
                self._f.flush()
                unflushed = 0


def engine_event(name: str, **args) -> None:
    """Emit an instant record on the active engine's timeline, if any —
    the shared helper for subsystems (integrity, checkpoint) that record
    events but do not own a Timeline.  Silently a no-op outside an
    initialized runtime or with the timeline disabled."""
    from horovod_tpu import basics

    eng = basics._runtime
    tl = getattr(eng, "timeline", None) if eng is not None else None
    if tl is not None and tl.enabled:
        tl.instant(name, **args)


def from_env(rank: int) -> Timeline:
    path = os.environ.get("HVD_TIMELINE", "")
    elastic = bool(os.environ.get("HVD_ELASTIC_EPOCH", ""))
    if path and rank == 0 and elastic:
        # Elastic: re-attach to the live timeline across engine
        # resets in this process; the trace file spans epochs.
        with _live_lock:
            t = _live.get(path)
            if t is None or not t.enabled:
                t = Timeline()
                t.initialize(path, mark_cycles=os.environ.get(
                    "HVD_TIMELINE_MARK_CYCLES", "0") in ("1", "true"),
                    persistent=True)
                _live[path] = t
        return t
    t = Timeline()
    if path and rank == 0:
        t.initialize(path, mark_cycles=os.environ.get(
            "HVD_TIMELINE_MARK_CYCLES", "0") in ("1", "true"))
    return t
