"""JAX platform guards for environments with an out-of-tree TPU tunnel.

Some environments (this one included) register a remote-TPU PJRT plugin via
``sitecustomize`` and force-select it through ``jax.config`` — overriding
the ``JAX_PLATFORMS`` env var.  When the tunnel's compile relay is down,
*any* full backend initialization (``jax.devices()``,
``jax.process_count()``) hangs forever instead of erroring.  These helpers
are the one shared copy of the two defenses (used by ``bench.py``,
``__graft_entry__.py``, and tests):

* :func:`force_cpu_platform` — pin the CPU platform in-process, before any
  backend init (the only override that survives the sitecustomize hook).
* :func:`default_backend_alive` — probe the default platform in a
  subprocess with bounded retry/backoff, so a dead relay is detected
  without wedging the caller.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import List, Optional, Tuple


def force_cpu_platform(n_devices: Optional[int] = None) -> None:
    """Force the JAX CPU platform in-process, before any backend init.

    ``n_devices``: also request that many virtual host devices via
    ``--xla_force_host_platform_device_count`` (no-op if the flag is
    already present in ``XLA_FLAGS``).
    """
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def default_backend_alive(timeout: float = 60.0, attempts: int = 2,
                          backoff_s: float = 3.0
                          ) -> Tuple[bool, List[str]]:
    """Probe (in a subprocess, with retry/backoff) whether the default JAX
    platform can actually initialize.  Returns ``(alive, errors)``."""
    errors: List[str] = []
    for i in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout, capture_output=True, text=True)
            if proc.returncode == 0:
                return True, errors
            errors.append(f"rc={proc.returncode}: {proc.stderr[-200:]}")
        except subprocess.TimeoutExpired:
            errors.append(f"timeout after {timeout}s")
        if i + 1 < attempts:
            time.sleep(backoff_s * (i + 1))
    return False, errors
