"""Env knob parsing.

Parity: ``horovod/common/utils/env_parser.cc`` + the knob list in
``common.h:61-87``.  All knobs use the ``HVD_`` prefix; the launcher's CLI
flags and YAML config map onto these (runner/config_parser.py), mirroring
the reference's three-layer config system (SURVEY.md §5 config row).
"""

from __future__ import annotations

import os

# Knob names (reference equivalents in comments).
FUSION_THRESHOLD = "HVD_FUSION_THRESHOLD"          # HOROVOD_FUSION_THRESHOLD
CYCLE_TIME = "HVD_CYCLE_TIME"                      # HOROVOD_CYCLE_TIME (ms)
CACHE_CAPACITY = "HVD_CACHE_CAPACITY"              # HOROVOD_CACHE_CAPACITY
HIERARCHICAL_ALLREDUCE = "HVD_HIERARCHICAL_ALLREDUCE"
HIERARCHICAL_ALLGATHER = "HVD_HIERARCHICAL_ALLGATHER"
TIMELINE = "HVD_TIMELINE"                          # HOROVOD_TIMELINE
TIMELINE_MARK_CYCLES = "HVD_TIMELINE_MARK_CYCLES"
STALL_CHECK_DISABLE = "HVD_STALL_CHECK_DISABLE"
STALL_CHECK_TIME = "HVD_STALL_CHECK_TIME_SECONDS"
STALL_SHUTDOWN_TIME = "HVD_STALL_SHUTDOWN_TIME_SECONDS"
AUTOTUNE = "HVD_AUTOTUNE"
AUTOTUNE_LOG = "HVD_AUTOTUNE_LOG"
AUTOTUNE_WARMUP_SAMPLES = "HVD_AUTOTUNE_WARMUP_SAMPLES"
AUTOTUNE_MAX_SAMPLES = "HVD_AUTOTUNE_MAX_SAMPLES"      # BAYES_OPT_MAX_SAMPLES
AUTOTUNE_SAMPLE_DURATION = "HVD_AUTOTUNE_SAMPLE_DURATION_SECONDS"
ADASUM_MODE = "HVD_ADASUM_MODE"
# Eager data plane (horovod_tpu.ops.cpu_backend; docs/performance.md).
# RING_SEGMENT_BYTES slices each ring hop's receive so reducing segment k
# overlaps receiving segment k+1 (0 = whole-chunk hops, no segmentation);
# SOCK_BUF_BYTES, when > 0, sets SO_SNDBUF/SO_RCVBUF on every data-plane
# socket (both the dialing and the accepting side).
RING_SEGMENT_BYTES = "HVD_RING_SEGMENT_BYTES"
SOCK_BUF_BYTES = "HVD_SOCK_BUF_BYTES"
# Same-host shm transport (horovod_tpu.utils.transport;
# docs/performance.md "Transport selection").  SHM_DISABLE forces every
# peer link onto TCP (the escape hatch for a bad shm path); SLOT_BYTES /
# SLOTS size each directed ring (per peer pair: 2 rings of SLOTS slots
# of SLOT_BYTES payload each, floors 4096 bytes / 2 slots).
SHM_DISABLE = "HVD_SHM_DISABLE"
SHM_SLOT_BYTES = "HVD_SHM_SLOT_BYTES"
SHM_SLOTS = "HVD_SHM_SLOTS"
# Shm seqlock wait policy (docs/performance.md "Transport selection").
# SHM_SPIN is the hot-spin iteration count before a wait starts
# yielding; SHM_SLEEP_US is the escalating-microsleep ceiling in
# microseconds.  Defaults adapt to the host's core count (spinning is
# only profitable when the peer can run WHILE we spin).
SHM_SPIN = "HVD_SHM_SPIN"
SHM_SLEEP_US = "HVD_SHM_SLEEP_US"
# Data-plane recovery ladder (docs/fault_tolerance.md "recovery
# ladder").  WIRE_CRC=1 arms the whole ladder: every data frame gains a
# CRC-32 + sequence trailer (mirrored in csrc/wire.h), a corrupt frame
# is NACKed and retransmitted from the sender's retained copy (at most
# HOP_RETRIES times per link before the link is declared corrupt), a
# dropped data socket is re-dialed for up to RECONNECT_TIMEOUT_S with
# the PR-1 backoff+jitter, and a faulted shm ring demotes its peer pair
# to TCP in place.  Off (default) = byte-identical seed framing and
# zero new threads.  LADDER_RETAIN bounds the per-link replay buffer
# (frames).
WIRE_CRC = "HVD_WIRE_CRC"
HOP_RETRIES = "HVD_HOP_RETRIES"
RECONNECT_TIMEOUT_S = "HVD_RECONNECT_TIMEOUT_S"
LADDER_RETAIN = "HVD_LADDER_RETAIN"
# Liveness / fault tolerance (PyEngine; 0 = heartbeats disabled).
# HOROVOD_HEARTBEAT_TIMEOUT is accepted as an alias of the HVD_ name.
HEARTBEAT_TIMEOUT = "HVD_HEARTBEAT_TIMEOUT"
HEARTBEAT_INTERVAL = "HVD_HEARTBEAT_INTERVAL"
# Collective deadlines (PyEngine data plane; docs/fault_tolerance.md).
# COLLECTIVE_TIMEOUT (seconds, 0 = off = block forever like the seed)
# bounds every eager collective: ring hops get per-phase socket
# deadlines, a local timeout is reported to the coordinator, and the
# gang agrees on a CollectiveTimeoutError naming the wedged rank(s).
# COLLECTIVE_PROBE_TIMEOUT is how long the coordinator's probe round
# waits for acks before ruling (default: half the collective timeout).
# SEND_WAIT_CAP_S is an always-on generous hard cap on PeerSender.wait
# so a dead sender thread can never hang a hop silently, even with the
# collective timeout off.
COLLECTIVE_TIMEOUT = "HVD_COLLECTIVE_TIMEOUT"
COLLECTIVE_PROBE_TIMEOUT = "HVD_COLLECTIVE_PROBE_TIMEOUT"
SEND_WAIT_CAP_S = "HVD_SEND_WAIT_CAP_S"
# Rendezvous KV client retry policy.
KV_RETRIES = "HVD_KV_RETRIES"
KV_TIMEOUT = "HVD_KV_TIMEOUT"
KV_RETRY_BASE_S = "HVD_KV_RETRY_BASE_S"
KV_RETRY_MAX_S = "HVD_KV_RETRY_MAX_S"
# Ordered rendezvous endpoint list "host:port,host:port" (primary
# first, warm standbys after); unset = single HVD_RENDEZVOUS_ADDR/PORT.
KV_ADDRS = "HVD_KV_ADDRS"
# Launcher host blacklist (relaunch path).
BLACKLIST_THRESHOLD = "HVD_BLACKLIST_THRESHOLD"
BLACKLIST_COOLDOWN_S = "HVD_BLACKLIST_COOLDOWN_S"
# Elastic training (horovod_tpu.elastic; docs/elastic.md).  EPOCH is the
# gang's membership incarnation (stamped on every wire list frame);
# MIN_NP/MAX_NP bound the re-formed world; JOINER marks a late worker
# that waits for an epoch assignment instead of bootstrapping at rank 0;
# UID is a stable worker identity across incarnations; the two intervals
# pace the commit-time membership check and the driver's discovery poll.
ELASTIC_EPOCH = "HVD_ELASTIC_EPOCH"
ELASTIC_MIN_NP = "HVD_ELASTIC_MIN_NP"
ELASTIC_MAX_NP = "HVD_ELASTIC_MAX_NP"
ELASTIC_JOINER = "HVD_ELASTIC_JOINER"
ELASTIC_UID = "HVD_ELASTIC_UID"
ELASTIC_CHECK_INTERVAL_S = "HVD_ELASTIC_CHECK_INTERVAL_S"
ELASTIC_DISCOVERY_INTERVAL_S = "HVD_ELASTIC_DISCOVERY_INTERVAL_S"
HOST_DISCOVERY_SCRIPT = "HVD_HOST_DISCOVERY_SCRIPT"
# Hierarchical control plane (runtime_py.py; docs/fault_tolerance.md
# "Hierarchical control plane, fencing, and quorum").  CTRL_FANOUT caps
# how many children each per-host sub-coordinator folds (0 = the whole
# host; overflow children attach directly to the root).  QUORUM gates
# the elastic re-form majority check: with it on (default) a partition
# minority self-terminates (PARTITION_MINORITY) instead of re-forming a
# split-brain sibling gang.  CTRL_TREE is the tree kill-switch: the
# control tree needs every rank speaking the Python engine's tree tags,
# so a deliberately mixed-engine gang must set HVD_CTRL_TREE=0 to stay
# on the flat star (single-host gangs already do, automatically).
CTRL_FANOUT = "HVD_CTRL_FANOUT"
CTRL_TREE = "HVD_CTRL_TREE"
QUORUM = "HVD_QUORUM"
# Data-plane integrity (horovod_tpu.integrity; docs/fault_tolerance.md).
# POLICY gates the non-finite gradient guard in DistributedOptimizer
# (off | skip | zero | raise); LIMIT is the consecutive agreed-non-finite
# step count after which policy "raise" raises; AUDIT_INTERVAL paces the
# replica-divergence audit (steps; 0 = off); CKPT_KEEP is the verified
# checkpoint keep-last-K retention.
NONFINITE_POLICY = "HVD_NONFINITE_POLICY"
NONFINITE_LIMIT = "HVD_NONFINITE_LIMIT"
AUDIT_INTERVAL = "HVD_AUDIT_INTERVAL"
CKPT_KEEP = "HVD_CKPT_KEEP"
# Telemetry (horovod_tpu.telemetry; docs/metrics.md).  METRICS turns the
# registry on by itself; setting a PORT or FILE also enables it.  PORT is
# the per-worker debug server base port (bound at PORT + local_rank);
# FILE is the JSONL flush destination, written every INTERVAL seconds;
# STRAGGLER_WARN_MS is the consistent-last-rank skew threshold that
# triggers the STRAGGLER timeline record + warning.
METRICS = "HVD_METRICS"
METRICS_PORT = "HVD_METRICS_PORT"
METRICS_FILE = "HVD_METRICS_FILE"
METRICS_INTERVAL = "HVD_METRICS_INTERVAL"
STRAGGLER_WARN_MS = "HVD_STRAGGLER_WARN_MS"
# Gang-wide aggregation & streaming anomaly alerts (telemetry/aggregate.py;
# docs/metrics.md "Gang-wide aggregation & alerts").  AGG_INTERVAL paces
# rank 0's fold of every rank's snapshot into the single gang view
# served at /gang/metrics*.  The HVD_ALERT_* knobs tune the EWMA rules
# the anomaly engine evaluates each fold: EWMA_ALPHA is the trailing-
# baseline smoothing factor, WARMUP the folds observed before any rule
# may fire, COLLAPSE_FRAC the gang-throughput fraction of baseline below
# which throughput_collapse fires, SKEW_FACTOR/SKEW_FLOOR_MS the
# straggler-skew growth multiple and absolute floor, QUEUE_FACTOR /
# RETRY_FACTOR the growth multiples for admission-queue depth and
# ladder/KV retry rate, and SERVE_P99_MS the serve-SLO p99 ceiling in
# milliseconds (0 = rule off).
AGG_INTERVAL = "HVD_AGG_INTERVAL"
ALERT_EWMA_ALPHA = "HVD_ALERT_EWMA_ALPHA"
ALERT_WARMUP = "HVD_ALERT_WARMUP"
ALERT_COLLAPSE_FRAC = "HVD_ALERT_COLLAPSE_FRAC"
ALERT_SKEW_FACTOR = "HVD_ALERT_SKEW_FACTOR"
ALERT_SKEW_FLOOR_MS = "HVD_ALERT_SKEW_FLOOR_MS"
ALERT_QUEUE_FACTOR = "HVD_ALERT_QUEUE_FACTOR"
ALERT_RETRY_FACTOR = "HVD_ALERT_RETRY_FACTOR"
ALERT_SERVE_P99_MS = "HVD_ALERT_SERVE_P99_MS"
# Gang-wide distributed tracing (telemetry/trace.py; docs/timeline.md
# "Gang-wide tracing").  TRACE=1 makes EVERY rank stream structured
# spans (negotiate/pack/hop/unpack/callback, serving and elastic steps)
# to a per-rank JSONL file under TRACE_DIR (default: the working
# directory), merged/analyzed by tools/hvd_trace.py.  Workers piggyback
# a clock-offset ping on the control channel at bootstrap and then every
# TRACE_CLOCK_SYNC_CYCLES background cycles so the merged trace aligns
# per-rank monotonic clocks.  Unset (default) = provably zero-cost: no
# spans, no clock frames, allocation/syscall-identical hot path.
TRACE = "HVD_TRACE"
TRACE_DIR = "HVD_TRACE_DIR"
TRACE_CLOCK_SYNC_CYCLES = "HVD_TRACE_CLOCK_SYNC_CYCLES"
# Always-on flight recorder (telemetry/blackbox.py; docs/fault_tolerance.md
# "the black box").  Unlike HVD_TRACE this is ON by default: every rank
# keeps the last BLACKBOX_EVENTS events (default 512) in a fixed-capacity
# in-memory ring and dumps ``blackbox_rank<r>.json`` into BLACKBOX_DIR on
# any terminal failure, so the 3 a.m. crash ships its own evidence.
# BLACKBOX=0 turns the recorder off entirely.
BLACKBOX = "HVD_BLACKBOX"
BLACKBOX_EVENTS = "HVD_BLACKBOX_EVENTS"
BLACKBOX_DIR = "HVD_BLACKBOX_DIR"
# Inference serving (horovod_tpu.serving; docs/serving.md).  PORT is the
# rank-0 HTTP front door (0 = ephemeral); MAX_BATCH is the number of
# continuous-batching decode slots; MAX_QUEUE bounds the admission queue
# (a full queue sheds with HTTP 503).
SERVE_PORT = "HVD_SERVE_PORT"
SERVE_MAX_BATCH = "HVD_SERVE_MAX_BATCH"
SERVE_MAX_QUEUE = "HVD_SERVE_MAX_QUEUE"


def get_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def get_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def get_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v not in (None, "") else default


def get_str(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def fusion_threshold_bytes() -> int:
    """Default 64 MB, like the reference (operations.cc fusion threshold)."""
    return get_int(FUSION_THRESHOLD, 64 * 1024 * 1024)


def cycle_time_ms() -> float:
    """Background-loop cadence; reference default 5 ms (operations.cc:416)."""
    return get_float(CYCLE_TIME, 5.0)


def ring_segment_bytes() -> int:
    """Ring-hop segment size; 0 (default) disables segmentation."""
    return max(0, get_int(RING_SEGMENT_BYTES, 0))


def shm_disabled() -> bool:
    """True when the same-host shm transport is forced off (escape
    hatch: every peer link falls back to TCP)."""
    return get_bool(SHM_DISABLE, False)


def shm_slot_bytes() -> int:
    """Payload bytes per shm ring slot; floor 4096."""
    return max(4096, get_int(SHM_SLOT_BYTES, 256 * 1024))


def shm_slots() -> int:
    """Slots per directed shm ring; floor 2 (writer needs one slot in
    flight while the reader drains another)."""
    return max(2, get_int(SHM_SLOTS, 16))


def shm_spin() -> int:
    """Hot-spin iterations before a shm wait starts yielding.  Spinning
    only pays when a spare core can run the peer meanwhile, so the
    default is 64 on multi-core hosts and 0 on a single core."""
    cpus = os.cpu_count() or 1
    return max(0, get_int(SHM_SPIN, 64 if cpus > 1 else 0))


def shm_sleep_us() -> int:
    """Escalating-microsleep ceiling for shm waits, in microseconds
    (floor 10).  Default 200 us: long enough to stop a yield storm from
    starving the producer, short enough that a ring hop's wake-up
    latency stays well under the kernel's socket wake path (the old
    single-core 1 ms ceiling is what lost BENCH_r08's shm-vs-TCP
    shoot-out)."""
    return max(10, get_int(SHM_SLEEP_US, 200))


def wire_crc() -> bool:
    """True when the recovery ladder (CRC trailers, NACK retransmit,
    reconnect, shm->TCP failover) is armed.  Default off = the seed's
    exact framing and thread census."""
    return get_bool(WIRE_CRC, False)


def hop_retries() -> int:
    """Per-link NACK-retransmit budget before the ladder declares the
    link corrupt and escalates; floor 0 (= first corruption escalates)."""
    return max(0, get_int(HOP_RETRIES, 8))


def reconnect_timeout_s() -> float:
    """Re-dial/re-accept budget for one dropped data socket; past it
    the ladder escalates to the gang abort."""
    return max(0.1, get_float(RECONNECT_TIMEOUT_S, 20.0))


def ladder_retain() -> int:
    """Retained sent frames per link (the replay buffer); floor 2."""
    return max(2, get_int(LADDER_RETAIN, 32))


def collective_timeout_s() -> float:
    """Per-collective deadline in seconds; 0 (default) = no deadline,
    the seed's block-forever behavior."""
    return max(0.0, get_float(COLLECTIVE_TIMEOUT, 0.0))


def ctrl_fanout() -> int:
    """Children per sub-coordinator in the hierarchical control tree;
    0 (default) = every same-host rank.  Overflow children attach
    directly to the root."""
    return max(0, get_int(CTRL_FANOUT, 0))


def ctrl_tree_on() -> bool:
    """Hierarchical control tree kill-switch (HVD_CTRL_TREE, default
    on).  Mixed-engine gangs must turn it off: the tree tags are
    Python-engine-only, and a native parent cannot fold its host."""
    return get_bool(CTRL_TREE, True)


def quorum_on() -> bool:
    """Elastic re-form majority gate (HVD_QUORUM, default on): re-form
    only when a strict majority of the last-committed membership is
    reachable; a minority self-terminates instead of split-braining."""
    return get_bool(QUORUM, True)


def serve_port() -> int:
    """Rank-0 serving front-door port; 0 (default) binds ephemeral."""
    return max(0, get_int(SERVE_PORT, 0))


def serve_max_batch() -> int:
    """Continuous-batching decode slots; floor 1."""
    return max(1, get_int(SERVE_MAX_BATCH, 8))


def serve_max_queue() -> int:
    """Admission queue bound (beyond it, /generate sheds with a 503);
    floor 1."""
    return max(1, get_int(SERVE_MAX_QUEUE, 64))


def trace_enabled() -> bool:
    """True when gang-wide tracing is on: every rank streams spans."""
    return get_bool(TRACE, False)


def trace_dir() -> str:
    """Directory for the per-rank ``trace_rank{R}.jsonl`` span files."""
    return get_str(TRACE_DIR, ".") or "."


def trace_clock_sync_cycles() -> int:
    """Worker clock-ping cadence in background cycles (floor 1); the
    first ping goes out on the first cycle regardless."""
    return max(1, get_int(TRACE_CLOCK_SYNC_CYCLES, 200))


def blackbox_enabled() -> bool:
    """True unless HVD_BLACKBOX=0: the flight recorder is always-on."""
    return get_bool(BLACKBOX, True)


def blackbox_events() -> int:
    """Ring capacity in events (floor 16 — a dump with fewer events than
    one collective's worth of context is not evidence)."""
    return max(16, get_int(BLACKBOX_EVENTS, 512))


def blackbox_dir() -> str:
    """Directory the per-rank ``blackbox_rank<r>.json`` dumps land in."""
    return get_str(BLACKBOX_DIR, "hvd_blackbox") or "hvd_blackbox"


def agg_interval_s() -> float:
    """Gang-aggregation fold cadence on rank 0; floor 0.05 s."""
    return max(0.05, get_float(AGG_INTERVAL, 2.0))


def alert_ewma_alpha() -> float:
    """EWMA smoothing factor for the trailing baselines, clamped to
    (0, 1].  Higher = baseline chases recent folds faster."""
    return min(1.0, max(0.01, get_float(ALERT_EWMA_ALPHA, 0.3)))


def alert_warmup() -> int:
    """Folds a rule's baseline must observe before it may fire; floor 1
    (a rule with no baseline at all has nothing to compare against)."""
    return max(1, get_int(ALERT_WARMUP, 3))


def alert_collapse_frac() -> float:
    """throughput_collapse threshold: fire when the gang collective rate
    drops below this fraction of its EWMA baseline; clamped to (0, 1)."""
    return min(0.99, max(0.01, get_float(ALERT_COLLAPSE_FRAC, 0.5)))


def alert_skew_factor() -> float:
    """straggler_skew growth multiple vs a rank's EWMA baseline;
    floor 1.0."""
    return max(1.0, get_float(ALERT_SKEW_FACTOR, 3.0))


def alert_skew_floor_ms() -> float:
    """Absolute straggler-skew floor in milliseconds — growth below it
    never fires (small-number noise)."""
    return max(0.0, get_float(ALERT_SKEW_FLOOR_MS, 50.0))


def alert_queue_factor() -> float:
    """queue_growth multiple vs the EWMA queue-depth baseline;
    floor 1.0."""
    return max(1.0, get_float(ALERT_QUEUE_FACTOR, 3.0))


def alert_retry_factor() -> float:
    """retry_spike multiple vs the EWMA per-fold retry-count baseline;
    floor 1.0."""
    return max(1.0, get_float(ALERT_RETRY_FACTOR, 3.0))


def alert_serve_p99_ms() -> float:
    """serve_p99_breach ceiling for the interval's gang-wide decode-step
    p99, in milliseconds; 0 (default) disables the rule."""
    return max(0.0, get_float(ALERT_SERVE_P99_MS, 0.0))


def send_wait_cap_s() -> float:
    """Hard cap on any single PeerSender.wait, always on (a dead sender
    thread must never hang a hop silently).  Generous by design: it is
    a backstop, not a tunable deadline — use HVD_COLLECTIVE_TIMEOUT for
    bounded-time collectives."""
    return get_float(SEND_WAIT_CAP_S, 300.0)
