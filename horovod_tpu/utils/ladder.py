"""Self-healing data-plane links: the recovery ladder (``HVD_WIRE_CRC=1``).

The PR-6 collective deadline treats every data-plane fault the same way:
declare the collective dead, run the gang-wide abort agreement, evict the
suspect, replay the epoch.  That is the right answer for a wedged or dead
*process*, but it is a sledgehammer for the transient faults real fabrics
actually produce — a flipped wire byte, a TCP reset from a conntrack
flush, a shm segment whose peer mapping went away.  This module adds a
ladder of cheaper rungs the link climbs **in place**, escalating to the
PR-6 abort only when every rung fails (docs/fault_tolerance.md,
"recovery ladder"):

1. **hop retransmit** — every data frame carries an 8-byte seq+CRC-32
   trailer (common/wire.py).  The receiver validates before any byte
   reaches the reduction; a mismatch NACKs the expected seq and the
   sender replays from its retained copies, bounded by
   ``HVD_HOP_RETRIES`` consecutive failures.
2. **peer reconnect** — a dropped TCP data socket is re-dialed (lower
   rank dials the higher rank's kept-open bootstrap listener, with the
   PR-1 backoff+jitter of ``connect_retry``); a RESUME handshake carries
   each side's next-expected seq so the fused step resumes from the last
   completed hop instead of restarting the epoch.
3. **transport failover** — a shm ring faulting mid-gang demotes that
   one peer pair to TCP in place over the retained mesh socket (a
   FAILOVER handshake doubles as the resume-point exchange); the rest of
   the gang keeps its transports.
4. **abort/evict/replay** — only when a rung is exhausted does the link
   poison itself with :class:`~horovod_tpu.common.wire.WireCorruptionError`,
   which the engine feeds into the exact PR-6 gang-wide abort agreement.

Design notes:

* A :class:`LadderLink` is only ever constructed when ``HVD_WIRE_CRC=1``;
  with the knob off the engine builds the seed transports and none of
  this code runs — the hot path stays byte-identical (pinned by
  tests/test_ladder.py).
* The **sender thread** assigns the link-local data seq, *copies* the
  payload into a retention deque (``HVD_LADDER_RETAIN`` frames) before
  the first write, and acks the caller's ticket at copy time — the
  fusion buffer is free for the next hop immediately, and every
  retransmit replays the retained copy, never a live buffer the
  allgather phase may since have overwritten.
* On TCP the **recv thread** owns the socket's read side: it validates
  CRCs, sends NACKs, answers RESUME handshakes, and queues validated
  frames for the main thread.  A pull-based receiver could never see a
  NACK while its own collective has it receiving from a *different*
  peer — a dedicated reader per link is what makes rung 1 deadlock-free
  in rings larger than two.
* On shm the main thread pulls from the ring exactly like
  ``ShmRingTransport`` (full-frame buffering, so a failed CRC never
  leaks bytes into the reduction), and a **watcher thread** blocks on
  the idle mesh TCP socket, which in shm mode carries exactly one
  possible frame: the peer's FAILOVER.  After demotion the watcher
  *becomes* the TCP recv thread.
* Corruption on a shm ring has no NACK rung: shared memory is not a
  lossy medium, so a bad CRC there means the segment itself is sick —
  it demotes straight to TCP (rung 3), whose handshake replays the gap.

Telemetry: ``hvd_hop_retries_total{cause}`` (corrupt | reset | failover),
``hvd_peer_reconnects_total``, ``hvd_transport_failovers_total``;
timeline instants ``HOP_RETRY`` / ``TRANSPORT_FAILOVER``.  Chaos sites:
``sock.corrupt`` / ``sock.reset`` (TCP data writes), ``shm.lost`` (ring
read/write).
"""

from __future__ import annotations

import collections
import queue as queue_mod
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.common import wire
from horovod_tpu.telemetry import blackbox as _bb
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.telemetry import trace as _trace
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import socketutil as su
from horovod_tpu.utils import timeline as _tl
from horovod_tpu.utils import transport as tpt

# Bootstrap ident channel for reconnect re-dials (bootstrap.py uses
# 0 = data, 1 = ctrl at mesh build time).
CHAN_RECONNECT = 2

_IDENT = struct.Struct("<ii")


class ReconnectListener:
    """The bootstrap listener, kept open for the life of the gang.

    Routes ``chan == CHAN_RECONNECT`` re-dials to the
    :class:`LadderLink` registered for the dialing rank.  Only the
    higher rank of a pair ever accepts (the lower rank dials), so each
    rank registers exactly its lower-ranked peers' links."""

    def __init__(self, listener: socket.socket):
        self._listener = listener
        self._links: Dict[int, "LadderLink"] = {}
        self._closing = False
        self._thread = threading.Thread(
            target=self._loop, name="hvd-ladder-accept", daemon=True)

    def register(self, peer_rank: int, link: "LadderLink") -> None:
        self._links[peer_rank] = link

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        # Polling accept: closing a listening fd does NOT wake a thread
        # already blocked in accept() on Linux, so a blocking loop would
        # pin close() to its join timeout every shutdown.
        self._listener.settimeout(0.25)
        while not self._closing:
            try:
                s, _addr = self._listener.accept()
            except TimeoutError:
                continue
            except OSError:
                return  # listener closed: shutdown
            try:
                su.configure_data_socket(s)
                peer_rank, chan = _IDENT.unpack(
                    su.recv_exact(s, _IDENT.size))
            except (ConnectionError, OSError):
                s.close()
                continue
            link = self._links.get(peer_rank) \
                if chan == CHAN_RECONNECT else None
            if link is None:
                s.close()  # stale bootstrap dial or unknown peer
                continue
            link._accept_q.put(s)

    def close(self, timeout: float = 2.0) -> None:
        self._closing = True
        try:
            self._listener.close()
        except OSError:
            pass
        self._thread.join(timeout)


class LadderLink(tpt.Transport):
    """One self-healing peer link (see module docstring).

    Transport-contract compatible with :class:`TcpTransport` /
    :class:`ShmRingTransport`: ticketed async send, frame receive with
    absolute deadlines, drain-then-force close.  ``kind`` is ``"ladder"``
    so the engine's shutdown path closes it like a shm transport (the
    link owns its threads and, in shm mode, the segment mapping)."""

    kind = "ladder"

    def __init__(self, rank: int, peer: int, sock: socket.socket, *,
                 seg: Optional[tpt.ShmSegment] = None, lower: bool = False,
                 epoch: int = 0,
                 peer_addr: Optional[Tuple[str, int]] = None):
        self.rank = int(rank)
        self.peer = int(peer)
        self.epoch = int(epoch)
        self._sock = sock
        self._sock_gen = 0
        self._peer_addr = peer_addr
        self._seg = seg
        self._mode = "shm" if seg is not None else "tcp"
        if seg is not None:
            self._writer = tpt._RingWriter(seg, 0 if lower else 1)
            self._reader = tpt._RingReader(seg, 1 if lower else 0)
        self._hdr_buf = bytearray(su.HEADER.size)
        self._shm_dead = False

        self._hop_retries = env_util.hop_retries()
        self._retain_max = env_util.ladder_retain()
        self._retain: collections.deque = collections.deque()
        self._next_seq = 0     # sender: seq of the next data frame
        self._expected = 0     # receiver: next data seq we will accept
        self._nack_streak = 0  # consecutive failed validations

        # sender state (PeerSender-mirror tickets)
        self._snd_cv = threading.Condition()
        self._snd_q: collections.deque = collections.deque()
        self._enq_seq = 0
        self._done_seq = 0
        self._closing = False
        self._poison: Optional[BaseException] = None

        # validated-frame queue (recv thread -> main thread, TCP mode)
        self._rcv_cv = threading.Condition()
        self._rcv_q: collections.deque = collections.deque()
        self._cur: Optional[memoryview] = None  # current frame body
        self._cur_off = 0

        # failover handshake state (shm mode)
        self._fo_lock = threading.Lock()
        self._fo_sent = False
        self._fo_done = threading.Event()

        # reconnect accept hand-off (higher rank side)
        self._accept_q: "queue_mod.Queue[socket.socket]" = queue_mod.Queue()

        self._snd_thread = threading.Thread(
            target=self._send_loop, name=f"hvd-ladder-send-{peer}",
            daemon=True)
        self._rcv_thread = threading.Thread(
            target=self._watch_loop if self._mode == "shm"
            else self._recv_loop,
            name=f"hvd-ladder-recv-{peer}", daemon=True)
        self._snd_thread.start()
        self._rcv_thread.start()

    # -- shared helpers ---------------------------------------------------

    def _ring_stopped(self) -> bool:
        return self._shm_dead or self._closing

    def _poison_exc(self) -> BaseException:
        return self._poison if self._poison is not None \
            else ConnectionError("ladder link closed")

    def _set_poison(self, exc: BaseException) -> None:
        """Exhausted ladder: poison every blocked thread.  The exception
        (normally a WireCorruptionError) surfaces from the main thread's
        next recv/send, where the engine escalates it into the PR-6
        gang-wide abort agreement."""
        with self._snd_cv:
            if self._poison is None:
                self._poison = exc
            self._snd_cv.notify_all()
        with self._rcv_cv:
            self._rcv_cv.notify_all()
        self._fo_done.set()

    # -- send side --------------------------------------------------------

    def send(self, payload, tag: int = su.TAG_DATA) -> int:
        if tag != su.TAG_DATA:
            raise ValueError("ladder links carry only data frames")
        if _tmx.enabled():
            _tmx.inc_counter("hvd_transport_bytes_total",
                             float(tpt._payload_nbytes(payload)),
                             (self._mode,))
        with self._snd_cv:
            if self._poison is not None:
                raise self._poison_exc()
            if self._closing:
                raise ConnectionError("sender is closed")
            self._enq_seq += 1
            ticket = self._enq_seq
            self._snd_q.append(("data", ticket, payload))
            self._snd_cv.notify_all()
        return ticket

    def wait(self, seq: int, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._snd_cv:
            while self._done_seq < seq:
                if self._poison is not None:
                    raise self._poison_exc()
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError("send did not complete in time")
                if not self._snd_cv.wait(remaining):
                    raise TimeoutError("send did not complete in time")

    def _send_loop(self) -> None:
        while True:
            with self._snd_cv:
                while not self._snd_q and not self._closing:
                    self._snd_cv.wait()
                if not self._snd_q:
                    return  # closing, queue drained
                entry = self._snd_q.popleft()
            try:
                kind = entry[0]
                if kind == "replay":
                    self._do_replay(entry[1], entry[2])
                elif kind == "ctrl":
                    self._write_ctrl(entry[1], entry[2])
                else:
                    self._process_data(entry[1], entry[2])
            except BaseException as e:
                if self._closing:
                    return
                self._set_poison(
                    e if isinstance(e, ConnectionError)
                    else ConnectionError(f"ladder sender failed: {e!r}"))

    def _process_data(self, ticket: int, payload) -> None:
        # Retention copy FIRST: the caller's buffer (a fusion-buffer
        # slice the allgather phase will overwrite) is free the moment
        # the ticket acks, and every replay reads this copy.
        body = bytes(su._as_byte_view(payload))
        seq = self._next_seq
        self._next_seq = seq + 1
        frame = body + wire.pack_trailer(body, seq)
        self._retain.append((seq, frame))
        while len(self._retain) > self._retain_max:
            self._retain.popleft()
        with self._snd_cv:
            self._done_seq = ticket
            self._snd_cv.notify_all()
        self._write_wire(frame)

    def _do_replay(self, expected: int, cause: str) -> None:
        """Rung 1 in action: re-send every retained frame the peer has
        not validated yet (``seq >= expected``)."""
        if self._retain:
            oldest = self._retain[0][0]
        else:
            oldest = self._next_seq
        if expected < oldest:
            # The needed frames aged out of the retention window: this
            # rung cannot heal the link any more.
            self._set_poison(wire.WireCorruptionError(self.peer, cause))
            return
        frames = [f for s, f in self._retain if s >= expected]
        _tmx.inc_counter("hvd_hop_retries_total", 1.0, (cause,))
        _tl.engine_event(_tl.HOP_RETRY, peer=self.peer, cause=cause,
                         expected=int(expected), frames=len(frames))
        # Rung climb on the flight recorder (untimed: a recovery rung is
        # rare enough to sequence by ring order, and the recorder must
        # not add clock reads the ladder doesn't already take).
        _bb.note("ladder.retry", 0, peer=self.peer, cause=cause,
                 frames=len(frames))
        t0 = time.monotonic_ns() if _trace.active() else 0
        for f in frames:
            if self._closing or self._poison is not None:
                return
            self._write_wire(f)
        if t0:
            _trace.emit("hop.retry", t0, time.monotonic_ns(),
                        peer=self.peer, cause=cause, frames=len(frames))

    def _write_ctrl(self, tag: int, payload: bytes) -> None:
        """NACKs (TCP rung only).  A write failure here means the socket
        died; the RESUME handshake that heals it re-synchronizes both
        seq cursors, so a lost NACK needs no retry of its own."""
        gen = self._sock_gen
        try:
            su.send_frame_zc(self._sock, tag, payload)
        except (ConnectionError, OSError):
            self._await_new_sock(gen)

    def _write_wire(self, frame: bytes) -> None:
        if self._poison is not None or self._closing:
            return
        if self._mode == "shm":
            try:
                _fi.fire("shm.lost", "write")
                self._writer.write_frame(su.TAG_DATA, frame,
                                         self._ring_stopped)
            except (ConnectionError, OSError) as e:
                # Ring is sick: demote.  The frame is retained; the
                # failover replay covers it, so no rewrite here.
                self._shm_fault(e)
            return
        sock = self._sock
        gen = self._sock_gen
        try:
            _fi.fire("sock.reset", str(self.peer))
        except _fi.InjectedFault:
            self._inject_reset(sock)
        out = frame
        if _fi.should_corrupt("sock.corrupt", str(self.peer)):
            # Flip one byte of a scratch copy: the wire sees garbage,
            # the retention deque keeps the good bytes for the replay.
            out = bytearray(frame)
            out[len(out) // 2] ^= 0x01
        try:
            su.send_frame_zc(sock, su.TAG_DATA, out)
        except (ConnectionError, OSError):
            # Socket died mid-send: the recv thread notices the same
            # death and runs the reconnect dance; its RESUME replay
            # covers this retained frame.
            self._await_new_sock(gen)

    def _await_new_sock(self, gen: int) -> bool:
        """Park the sender until the recv thread heals the socket (or
        the link poisons)."""
        deadline = time.monotonic() + env_util.reconnect_timeout_s() + 5.0
        with self._snd_cv:
            while self._sock_gen == gen and self._poison is None \
                    and not self._closing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._snd_cv.wait(remaining)
            healed = self._sock_gen != gen
        if not healed and self._poison is None and not self._closing:
            self._set_poison(wire.WireCorruptionError(self.peer, "reset"))
        return healed

    @staticmethod
    def _inject_reset(sock: socket.socket) -> None:
        """sock.reset chaos: kill the socket so BOTH sides observe it.
        shutdown() (not just close) matters — a real network reset
        delivers an RST that wakes our recv thread out of its blocked
        read, but closing our own fd would not, and that recv thread is
        the one that runs the reconnect dance."""
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
        except OSError:
            pass
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    # -- receive side: main-thread API ------------------------------------

    def recv_frame(self,
                   deadline: Optional[float] = None) -> Tuple[int, bytes]:
        tag, n = self.recv_frame_header(deadline)
        buf = bytearray(n)
        if n:
            self.recv_exact_into(memoryview(buf), deadline)
        return tag, bytes(buf)

    def recv_frame_header(self,
                          deadline: Optional[float] = None
                          ) -> Tuple[int, int]:
        if self._mode == "shm":
            return self._shm_recv_header(deadline)
        return self._tcp_recv_header(deadline)

    def recv_exact_into(self, view: memoryview,
                        deadline: Optional[float] = None) -> None:
        if view.format != "B":
            view = view.cast("B")
        need = len(view)
        got = 0
        while got < need:
            cur = self._cur
            if cur is None or self._cur_off >= len(cur):
                # Segmented readers drain exactly one frame per header,
                # so crossing here means byte-stream continuation into
                # the next validated frame.
                self.recv_frame_header(deadline)
                cur = self._cur
            k = min(len(cur) - self._cur_off, need - got)
            view[got:got + k] = cur[self._cur_off:self._cur_off + k]
            self._cur_off += k
            got += k

    def _tcp_recv_header(self, deadline: Optional[float]) -> Tuple[int, int]:
        _fi.fire("sock.stall")
        with self._rcv_cv:
            while not self._rcv_q:
                if self._poison is not None:
                    raise self._poison_exc()
                if self._closing:
                    raise ConnectionError("ladder link closed")
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError("receive deadline exceeded")
                self._rcv_cv.wait(remaining)
            body = self._rcv_q.popleft()
        self._cur = body
        self._cur_off = 0
        return su.TAG_DATA, len(body)

    def _shm_recv_header(self, deadline: Optional[float]) -> Tuple[int, int]:
        _fi.fire("shm.stall")
        while True:
            if self._mode != "shm":
                # Demoted under us (peer-initiated failover): the recv
                # thread is filling the validated queue now.
                return self._tcp_recv_header(deadline)
            try:
                _fi.fire("shm.lost", "read")
                self._reader.recv_into(memoryview(self._hdr_buf),
                                       deadline, self._ring_stopped)
                tag, n = su.HEADER.unpack(bytes(self._hdr_buf))
                payload = bytearray(n)
                if n:
                    self._reader.recv_into(memoryview(payload), deadline,
                                           self._ring_stopped)
            except TimeoutError:
                raise  # collective deadline, not a link fault
            except (ConnectionError, OSError) as e:
                self._shm_fault(e)
                continue
            if tag != su.TAG_DATA:
                continue  # shm carries only data frames
            try:
                body, seq, crc = wire.split_trailer(memoryview(payload))
                ok = crc == wire.data_crc(body, seq)
            except ValueError:
                ok, seq = False, -1
            if not ok:
                # Memory is not a lossy medium: a bad CRC here means the
                # segment is sick.  No NACK rung — demote to TCP, whose
                # handshake replays everything we have not validated.
                self._shm_fault(ConnectionError(
                    f"shm frame from rank {self.peer} failed CRC"))
                continue
            if seq != self._expected:
                continue  # stale duplicate from a replay
            self._expected += 1
            self._cur = body
            self._cur_off = 0
            return su.TAG_DATA, len(body)

    # -- TCP recv thread --------------------------------------------------

    def _recv_loop(self) -> None:
        while not self._closing and self._poison is None:
            sock = self._sock
            try:
                tag, n = su.recv_frame_header(sock)
                payload = bytearray(n)
                if n:
                    su.recv_exact_into(sock, memoryview(payload))
            except (ConnectionError, OSError, ValueError):
                if self._closing or self._poison is not None:
                    return
                if not self._heal_reconnect():
                    return
                continue
            if tag == su.TAG_DATA:
                self._on_data(payload)
            elif tag == su.TAG_NACK:
                self._push_replay(wire.decode_nack(bytes(payload)),
                                  "corrupt")
            # TAG_RESUME / TAG_FAILOVER here are stale handshake echoes
            # from an already-healed incident: ignore.

    def _on_data(self, payload: bytearray) -> None:
        try:
            body, seq, crc = wire.split_trailer(memoryview(payload))
            ok = crc == wire.data_crc(body, seq)
        except ValueError:
            ok = False
        if not ok:
            self._nack_streak += 1
            if self._nack_streak > self._hop_retries:
                self._set_poison(
                    wire.WireCorruptionError(self.peer, "corrupt"))
                return
            with self._snd_cv:
                self._snd_q.appendleft(
                    ("ctrl", su.TAG_NACK, wire.encode_nack(self._expected)))
                self._snd_cv.notify_all()
            return
        if seq != self._expected:
            # Replay duplicate (seq < expected) or an in-flight frame
            # past a corruption (seq > expected — its replay is coming):
            # drop either way, order stays monotonic.
            return
        self._expected += 1
        self._nack_streak = 0
        with self._rcv_cv:
            self._rcv_q.append(body)
            self._rcv_cv.notify_all()

    def _push_replay(self, expected: int, cause: str) -> None:
        with self._snd_cv:
            self._snd_q.appendleft(("replay", int(expected), cause))
            self._snd_cv.notify_all()

    def _heal_reconnect(self) -> bool:
        """Rung 2: re-dial (lower rank) or re-accept (higher rank) the
        data socket, exchange RESUME, and hand the sender a replay of
        everything the peer has not validated."""
        try:
            self._sock.close()
        except OSError:
            pass
        timeout = env_util.reconnect_timeout_s()
        deadline = time.monotonic() + timeout
        try:
            # Re-dial / re-accept in short slices so an overlapping
            # close() (our side OR the peer racing us down during gang
            # shutdown — its FIN looks exactly like a dropped socket)
            # stops the heal within a poll interval instead of pinning
            # this thread for the whole reconnect budget.
            s = None
            while s is None:
                if self._closing or self._poison is not None:
                    return False
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(
                        f"reconnect to rank {self.peer} timed out")
                if self.rank < self.peer:
                    if self._peer_addr is None:
                        raise ConnectionError(
                            f"no reconnect address for rank {self.peer}")
                    try:
                        s = su.connect_retry(
                            self._peer_addr[0], self._peer_addr[1],
                            timeout=min(0.5, remaining))
                    except ConnectionError:
                        continue
                    s.sendall(_IDENT.pack(self.rank, CHAN_RECONNECT))
                else:
                    try:
                        s = self._accept_q.get(timeout=min(0.25, remaining))
                    except queue_mod.Empty:
                        continue
            # Both sides send first, then read: no ordering deadlock.
            su.send_frame(s, su.TAG_RESUME, wire.encode_resume(
                self.rank, self._expected, self.epoch))
            tag, pl = su.recv_frame(s, deadline)
            if tag != su.TAG_RESUME:
                raise ConnectionError(f"bad resume tag {tag}")
            prank, pexp, pepoch = wire.decode_resume(pl)
            if prank != self.peer or pepoch != self.epoch:
                raise ConnectionError(
                    f"resume from rank {prank} epoch {pepoch}, expected "
                    f"rank {self.peer} epoch {self.epoch}")
        except (ConnectionError, OSError, TimeoutError, queue_mod.Empty):
            self._set_poison(wire.WireCorruptionError(self.peer, "reset"))
            return False
        with self._snd_cv:
            self._sock = s
            self._sock_gen += 1
            self._snd_q.appendleft(("replay", int(pexp), "reset"))
            self._snd_cv.notify_all()
        _tmx.inc_counter("hvd_peer_reconnects_total")
        _bb.note("ladder.reconnect", 0, peer=self.peer)
        return True

    # -- shm watcher / failover -------------------------------------------

    def _watch_loop(self) -> None:
        """shm mode: the mesh TCP socket is idle except for exactly one
        frame — the peer's FAILOVER.  Receiving it (or having sent ours
        and receiving the answer) completes the demotion, after which
        this thread becomes the TCP recv thread."""
        try:
            tag, pl = su.recv_frame(self._sock)
        except (ConnectionError, OSError):
            if not self._closing and self._poison is None:
                # The mesh socket under a healthy shm link died: peer
                # process is gone, which no rung can heal.
                self._set_poison(ConnectionError(
                    f"mesh socket to rank {self.peer} lost"))
            return
        if tag != su.TAG_FAILOVER:
            self._set_poison(ConnectionError(
                f"unexpected tag {tag} on idle mesh socket"))
            return
        try:
            prank, pexp, pepoch = wire.decode_resume(pl)
        except struct.error:
            self._set_poison(wire.WireCorruptionError(self.peer,
                                                      "failover"))
            return
        if prank != self.peer or pepoch != self.epoch:
            self._set_poison(ConnectionError(
                f"failover from rank {prank} epoch {pepoch}"))
            return
        self._begin_failover()  # our half of the handshake, if not out yet
        self._complete_failover(pexp)
        self._recv_loop()

    def _begin_failover(self) -> None:
        """Send our FAILOVER (rank, next-expected seq, epoch) exactly
        once, whichever thread detects first."""
        with self._fo_lock:
            if self._fo_sent:
                return
            self._fo_sent = True
            try:
                su.send_frame(self._sock, su.TAG_FAILOVER,
                              wire.encode_resume(self.rank, self._expected,
                                                 self.epoch))
            except (ConnectionError, OSError):
                self._set_poison(
                    wire.WireCorruptionError(self.peer, "failover"))

    def _complete_failover(self, peer_expected: int) -> None:
        """Swap the link to TCP in place (watcher thread only)."""
        self._shm_dead = True  # break ring readers/writers
        with self._snd_cv:
            self._mode = "tcp"
            self._snd_q.appendleft(
                ("replay", int(peer_expected), "failover"))
            self._snd_cv.notify_all()
        with self._rcv_cv:
            self._rcv_cv.notify_all()
        _tmx.inc_counter("hvd_transport_failovers_total")
        _bb.note("ladder.failover", 0, peer=self.peer)
        _tl.engine_event(_tl.TRANSPORT_FAILOVER, peer=self.peer,
                         rank=self.rank)
        _trace.emit_instant("transport.failover", peer=self.peer, tp="tcp")
        self._fo_done.set()

    def _shm_fault(self, exc: BaseException) -> None:
        """A ring read/write faulted: initiate (or join) the demotion
        and wait for the watcher to complete it."""
        if self._closing:
            raise ConnectionError("ladder link closed")
        if self._poison is not None:
            raise self._poison_exc()
        self._begin_failover()
        if not self._fo_done.wait(env_util.reconnect_timeout_s() + 5.0):
            self._set_poison(
                wire.WireCorruptionError(self.peer, "failover"))
        if self._poison is not None:
            raise self._poison_exc()

    # -- teardown ---------------------------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        with self._snd_cv:
            already = self._closing
            self._closing = True
            self._snd_cv.notify_all()
        self._shm_dead = True
        self._fo_done.set()
        with self._rcv_cv:
            self._rcv_cv.notify_all()
        self._snd_thread.join(timeout)
        # shutdown(), not just close(): closing an fd does not wake a
        # thread already blocked in recv()/send() on it, and the recv
        # thread lives in a blocking read whenever the link is idle.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._snd_thread.join(1.0)  # a sender wedged mid-write is free now
        self._rcv_thread.join(timeout)
        if self._seg is not None and not already:
            self._seg.close()

    def join(self, timeout: float = 2.0) -> None:
        self._snd_thread.join(timeout)
        self._rcv_thread.join(timeout)


# ---------------------------------------------------------------------------
# engine wiring
# ---------------------------------------------------------------------------


def build_ladder_links(rank: int, size: int,
                       data: Dict[int, socket.socket], kv, prefix: str,
                       peers: Dict[int, Tuple[str, int]],
                       listener: socket.socket, epoch: int = 0
                       ) -> Tuple[Dict[int, tpt.Transport],
                                  ReconnectListener]:
    """Ladder-mode replacement for ``tpt.build_transports``: the same
    KV shm pairing (create/attach/ack, leak-proof unlink), but every
    pair gets a :class:`LadderLink` — shm-backed for same-host peers,
    TCP otherwise — and the bootstrap listener stays open behind a
    :class:`ReconnectListener` for rung-2 re-dials."""
    rl = ReconnectListener(listener)

    def tcp_factory(sock, peer):
        link = LadderLink(rank, peer, sock, epoch=epoch,
                          peer_addr=peers.get(peer))
        rl.register(peer, link)
        return link

    def shm_factory(sock, seg, lower, peer):
        link = LadderLink(rank, peer, sock, seg=seg, lower=lower,
                          epoch=epoch, peer_addr=peers.get(peer))
        rl.register(peer, link)
        return link

    links = tpt.build_transports(rank, size, data, kv, prefix,
                                 tcp_factory=tcp_factory,
                                 shm_factory=shm_factory)
    rl.start()
    return links, rl


def make_ladder_pair(shm: bool = False, slot_bytes: int = 4096,
                     nslots: int = 4
                     ) -> Tuple[LadderLink, LadderLink, ReconnectListener]:
    """In-process pair over loopback for tests: real TCP sockets (so
    resets and reconnects behave like the wire) and a live
    :class:`ReconnectListener` on the higher-rank side.  The caller
    closes both links and the listener."""
    lst = su.listen_on("127.0.0.1")
    host, port = lst.getsockname()
    a = socket.create_connection((host, port))
    su.configure_data_socket(a)
    b, _ = lst.accept()
    su.configure_data_socket(b)
    seg_a = seg_b = None
    if shm:
        seg_a = tpt.ShmSegment.create(slot_bytes=slot_bytes, nslots=nslots)
        seg_b = tpt.ShmSegment.attach(seg_a.name)
        seg_a.unlink()
    link0 = LadderLink(0, 1, a, seg=seg_a, lower=True,
                       peer_addr=(host, port))
    link1 = LadderLink(1, 0, b, seg=seg_b, lower=False)
    rl = ReconnectListener(lst)
    rl.register(0, link1)
    rl.start()
    return link0, link1, rl
