"""Spark integration: ``horovod_tpu.spark.run(fn, ...)``.

Role parity: ``horovod/spark/__init__.py`` — run a training function in
``num_proc`` Spark tasks, with rank/local-rank assignment, a rendezvous
back to the driver, and per-rank results returned to the caller.  The
reference tunnels mpirun through Spark task services
(``spark/__init__.py:39-72`` + ``driver/mpirun_rsh.py``); Spark 2.4+
barrier execution mode makes that machinery unnecessary — the tasks
rendezvous against the driver's HTTP server exactly like `hvdrun`
workers do, so the whole coordination stack is shared with the plain
launcher.

``run()`` is gated on the ``pyspark`` package (not installable in this
environment — see ``docs/spark_descope.md``), but it is *executed*
end-to-end by ``tests/test_spark.py::test_run_executes_under_barrier_shim``
against a pyspark-API conformance shim whose barrier tasks are real
separate processes.  The Estimator API
(``horovod/spark/common/estimator.py``) is not gated at all: it
materializes to parquet with pyarrow and can execute through either the
Spark barrier backend or the plain launcher (``spark/estimator.py``),
so ``TorchEstimator``/``KerasEstimator`` run — and are tested — without
a Spark cluster.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Callable, List, Optional

try:
    import pyspark  # noqa: F401

    _HAVE_PYSPARK = True
except ImportError:
    _HAVE_PYSPARK = False


def _require_pyspark(what: str):
    if not _HAVE_PYSPARK:
        raise ImportError(
            f"horovod_tpu.spark.{what} requires the `pyspark` package, "
            "which is not installed in this environment. For multi-"
            "process launching without Spark, use the `hvdrun` launcher "
            "or the programmatic horovod_tpu.runner.run.run() API.")


def run(fn: Callable, args=(), kwargs=None, num_proc: Optional[int] = None,
        start_timeout: float = 600.0, env=None,
        verbose: int = 1) -> List[Any]:
    """Runs ``fn(*args, **kwargs)`` on ``num_proc`` Spark tasks as one
    Horovod job; returns the per-rank results ordered by rank (parity:
    horovod/spark/__init__.py:104 run()).

    Requires the cluster to support barrier execution mode (Spark 2.4+),
    which guarantees gang scheduling — all ranks run concurrently, the
    property the reference builds its own task-service machinery for.
    Task stdout/stderr go to Spark's executor logs.
    """
    _require_pyspark("run")
    kwargs = kwargs or {}
    extra_env = dict(env or {})
    extra_env.setdefault("HVD_START_TIMEOUT", str(start_timeout))

    from pyspark import BarrierTaskContext
    from pyspark.sql import SparkSession

    from horovod_tpu.runner import secret as secret_mod
    from horovod_tpu.runner.http_server import RendezvousServer
    from horovod_tpu.runner.run import _routable_address

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = max(1, sc.defaultParallelism)

    # Prefer the address Spark already knows executors can reach the
    # driver at; fall back to default-route discovery (hostname
    # resolution often yields loopback on Debian-style /etc/hosts).
    addr = sc.getConf().get("spark.driver.host", None) or \
        _routable_address()
    job_secret = secret_mod.make_secret()
    server = RendezvousServer(addr, secret=job_secret)
    port = server.start()
    nproc = num_proc
    if verbose:
        print(f"horovod_tpu.spark: launching {nproc} barrier tasks, "
              f"rendezvous at {addr}:{port}")

    def _task(_iterator):
        ctx = BarrierTaskContext.get()
        rank = ctx.partitionId()
        # Slot assignment from the gang's host placement, mirroring the
        # launcher (runner/hosts.py): hosts ordered by first appearance;
        # the cross "axis" at local index L spans the hosts that have a
        # local rank L.
        hosts = [info.address.split(":")[0]
                 for info in ctx.getTaskInfos()]
        by_host = OrderedDict()
        for r, h in enumerate(hosts):
            by_host.setdefault(h, []).append(r)
        my_host = hosts[rank]
        local_rank = by_host[my_host].index(rank)
        cross_hosts = [h for h, rs in by_host.items()
                       if len(rs) > local_rank]

        task_env = {
            "HVD_RANK": str(rank),
            "HVD_SIZE": str(nproc),
            "HVD_LOCAL_RANK": str(local_rank),
            "HVD_LOCAL_SIZE": str(len(by_host[my_host])),
            "HVD_CROSS_RANK": str(cross_hosts.index(my_host)),
            "HVD_CROSS_SIZE": str(len(cross_hosts)),
            "HVD_RENDEZVOUS_ADDR": addr,
            "HVD_RENDEZVOUS_PORT": str(port),
            secret_mod.ENV_VAR: job_secret,
            # Stage retries must not rendezvous against a previous
            # attempt's stale addresses on the still-running server.
            "HVD_RDV_SCOPE": f"attempt{ctx.stageAttemptNumber()}",
        }
        task_env.update(extra_env)
        # Snapshot + restore: PySpark reuses worker processes, and stale
        # HVD_* would hijack a later unrelated hvd.init() in this app.
        saved = {k: os.environ.get(k) for k in task_env}
        os.environ.update(task_env)

        import horovod_tpu as hvd

        try:
            hvd.init()
            try:
                result = fn(*args, **kwargs)
            finally:
                hvd.shutdown()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        yield rank, result

    try:
        pairs = (sc.parallelize(range(nproc), nproc)
                 .barrier()
                 .mapPartitions(_task)
                 .collect())
    finally:
        server.stop()
    return [result for _, result in sorted(pairs)]


from horovod_tpu.spark.estimator import (  # noqa: E402,F401
    HorovodEstimator,
    KerasEstimator,
    KerasModel,
    LocalBackend,
    SparkBackend,
    TorchEstimator,
    TorchModel,
)
from horovod_tpu.spark.store import LocalStore, Store  # noqa: E402,F401
