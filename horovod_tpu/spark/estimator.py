"""Estimator API: fit a Keras or Torch model on a DataFrame.

Role parity: ``horovod/spark/common/estimator.py`` (HorovodEstimator),
``spark/keras/estimator.py`` and ``spark/torch/estimator.py`` +
``torch/remote.py`` — there: materialize the DataFrame to Parquet in a
``Store`` with Petastorm, run a remote training fn under mpirun-on-Spark,
return a Spark Model.  Redesigned for this stack:

* Materialization is plain pyarrow parquet, one shard per rank, written
  through :class:`horovod_tpu.spark.store.Store` — works with a pyspark
  DataFrame, a pandas DataFrame, or a dict of numpy arrays, so the whole
  estimator path executes (and is tested) without a Spark cluster.
* The distributed run uses ``horovod_tpu.spark.run`` (barrier mode) when
  a Spark session is available, else the launcher's programmatic
  ``horovod_tpu.runner.run.run`` — the estimator is backend-agnostic the
  way the reference's ``Backend`` abstraction intended.
* The fitted wrapper exposes ``getModel()`` / ``predict`` / ``transform``
  (pandas in, pandas out) instead of a Spark Transformer.
"""

from __future__ import annotations

import copy
import os
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from horovod_tpu.spark.store import Store


# ---------------------------------------------------------------------------
# data plumbing
# ---------------------------------------------------------------------------


def _to_pandas(df):
    if hasattr(df, "toPandas"):          # pyspark DataFrame
        return df.toPandas()
    if hasattr(df, "iloc"):              # already pandas
        return df
    import pandas as pd

    return pd.DataFrame({k: list(np.asarray(v)) for k, v in df.items()})


def _df_digest(pdf, num_shards: int, validation, seed: int) -> str:
    """Content digest of a materialization request (parity:
    spark/common/cache.py TrainingDataCache — repeated fits over the
    same data skip the Petastorm re-write there; the parquet re-write
    here).  Hashes the raw column bytes: equal bytes = equal shards."""
    import hashlib

    m = hashlib.sha1()
    m.update(repr((sorted(map(str, pdf.columns)), num_shards,
                   validation, seed)).encode())
    for c in sorted(map(str, pdf.columns)):
        col = pdf[c].to_numpy()
        # dtype + length prefix: byte-identical buffers of different
        # dtypes (int32 [1,2] vs int64 [big]) must not collide.
        m.update(f"{c}:{col.dtype.str}:{len(col)}:".encode())
        if col.dtype == object:
            # NEVER col.tobytes() on object arrays — that serializes
            # heap POINTERS (no error raised), so equal values hash
            # differently and, worse, recycled addresses can collide.
            hashed = False
            if len(col) and isinstance(col[0], (list, np.ndarray)):
                try:
                    arr = np.stack([np.asarray(v) for v in col])
                    m.update(f"{arr.dtype.str}:{arr.shape}:".encode())
                    m.update(np.ascontiguousarray(arr).tobytes())
                    hashed = True
                except (TypeError, ValueError):
                    pass
            if not hashed:
                m.update(repr(col.tolist()).encode())
        else:
            m.update(np.ascontiguousarray(col).tobytes())
    return m.hexdigest()


def _write_shards(pdf, store: Store, path: str, num_shards: int) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    store.delete(path)
    store.makedirs(path)
    bounds = np.linspace(0, len(pdf), num_shards + 1).astype(int)
    for i in range(num_shards):
        shard = pdf.iloc[bounds[i]:bounds[i + 1]]
        # Through the store's own open() so remote (fsspec) stores get
        # the shards too, not just local paths.
        with store.open(store.join(path, f"part-{i:05d}.parquet"),
                        "wb") as f:
            pq.write_table(pa.Table.from_pandas(shard), f)


def materialize(df, store: Store, run_id: str, num_shards: int,
                validation=None, seed: int = 0) -> int:
    """Write ``df`` as ``num_shards`` parquet shards (shard i is rank i's
    training data).  Returns the training row count.  Parity:
    ``util.prepare_data`` + Petastorm materialization in
    ``spark/common/util.py``.

    ``validation`` (parity: common/params.py:52 + util.py:426-449):
    a float in (0, 1) holds out that fraction of rows (seeded shuffle);
    a string names an indicator column — truthy rows become the
    validation set and the column is dropped from both splits.
    Validation shards land in ``store.val_data_path(run_id)``.

    Repeated fits over byte-identical data under the same ``run_id``
    skip the re-write entirely (a content digest is stored alongside
    the shards; parity: spark/common/cache.py TrainingDataCache).
    """
    pdf = _to_pandas(df)
    digest = _df_digest(pdf, num_shards, validation, seed)
    digest_path = store.join(store.train_data_path(run_id), "_digest")
    try:
        prev = store.read_bytes(digest_path).decode().splitlines()
        if prev and prev[0] == digest:
            return int(prev[1])
    except Exception:
        pass  # absent/corrupt digest -> materialize fresh
    val_pdf = None
    if validation is not None:
        if isinstance(validation, str):
            mask = pdf[validation].astype(bool).to_numpy()
            val_pdf = pdf[mask].drop(columns=[validation])
            pdf = pdf[~mask].drop(columns=[validation])
        elif isinstance(validation, float) and 0 < validation < 1:
            rs = np.random.RandomState(seed)
            idx = rs.permutation(len(pdf))
            n_val = int(round(len(pdf) * validation))
            val_pdf = pdf.iloc[idx[:n_val]]
            pdf = pdf.iloc[idx[n_val:]]
        else:
            raise ValueError(
                f"validation must be a float in (0, 1) or a column "
                f"name, got {validation!r}")
    if val_pdf is not None and len(val_pdf) < num_shards:
        # Fail here, not as a per-rank shape error mid-collective: an
        # empty shard on some ranks only would desync the epoch-end
        # val-loss allreduce.
        raise ValueError(
            f"validation selected {len(val_pdf)} row(s) but the job "
            f"has {num_shards} ranks; every rank needs at least one "
            "validation row — increase the fraction or provide more "
            "rows")
    if len(pdf) < num_shards:
        # Same desync hazard on the training side: some ranks would get
        # an empty shard and fail (or skip steps) mid-gang.
        raise ValueError(
            f"training split has {len(pdf)} row(s) but the job has "
            f"{num_shards} ranks; every rank needs at least one "
            "training row — provide more rows or reduce num_proc")
    _write_shards(pdf, store, store.train_data_path(run_id), num_shards)
    if val_pdf is not None:
        _write_shards(val_pdf, store, store.val_data_path(run_id),
                      num_shards)
    # Digest written LAST: a partial materialization can never pass as
    # cached on the next fit.
    store.write_bytes(digest_path,
                      f"{digest}\n{len(pdf)}\n".encode())
    return len(pdf)


def _keras_ckpt_encode(weights, opt_vars, history) -> bytes:
    """Pickle-free epoch-checkpoint codec: weight/slot arrays in an npz
    archive, history and counts as a JSON blob riding a uint8 array.
    The store is attacker-writable territory (the trust model
    ``TorchModel.load`` already assumes) — loading one of these must
    never be able to execute embedded code."""
    import io
    import json

    arrays = {f"w{i}": np.asarray(a) for i, a in enumerate(weights)}
    n_opt = -1
    if opt_vars is not None:
        opt_vars = list(opt_vars)
        n_opt = len(opt_vars)
        arrays.update({f"o{i}": np.asarray(a)
                       for i, a in enumerate(opt_vars)})
    meta = {"n_weights": len(weights), "n_opt": n_opt,
            "history": {str(k): [float(x) for x in v]
                        for k, v in (history or {}).items()}}
    arrays["meta"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _keras_ckpt_decode(payload: bytes) -> Dict[str, Any]:
    """Inverse of :func:`_keras_ckpt_encode`; ``allow_pickle=False`` is
    the point — a poisoned checkpoint fails to parse instead of running."""
    import io
    import json

    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        meta = json.loads(z["meta"].tobytes().decode("utf-8"))
        weights = [z[f"w{i}"] for i in range(meta["n_weights"])]
        opt_vars = None if meta["n_opt"] < 0 else \
            [z[f"o{i}"] for i in range(meta["n_opt"])]
    return {"weights": weights, "opt_vars": opt_vars,
            "history": meta["history"]}


def _restore_optimizer_slots(variables, saved) -> bool:
    """Positionally restore optimizer slot values after validating count
    and shapes.  A checkpoint from a different model/optimizer config
    must not be zipped in silently (short zip = partial restore); warn
    and keep fresh optimizer state instead.  Returns True on restore."""
    import warnings

    saved = list(saved)
    if len(variables) != len(saved):
        warnings.warn(
            f"optimizer checkpoint has {len(saved)} slot variables but "
            f"the model expects {len(variables)}; ignoring saved "
            "optimizer state (fresh slots)")
        return False
    for var, val in zip(variables, saved):
        if tuple(var.shape) != tuple(np.shape(val)):
            warnings.warn(
                f"optimizer slot {var.name if hasattr(var, 'name') else var} "
                f"shape {tuple(var.shape)} does not match checkpoint "
                f"value shape {tuple(np.shape(val))}; ignoring saved "
                "optimizer state (fresh slots)")
            return False
    for var, val in zip(variables, saved):
        var.assign(val)
    return True


def columns_to_matrix(pdf, cols: Sequence[str]) -> np.ndarray:
    """Dense float32 matrix from DataFrame columns.  Columns holding
    vectors (lists/arrays) are stacked; scalars become width-1 features,
    matching the reference's flattening of Spark vector columns."""
    if len(pdf) == 0:
        # An empty frame cannot reveal vector-column widths; the caller
        # would get a wrong-shaped matrix and fail later, possibly on
        # only some ranks of a collective.
        raise ValueError("cannot build a feature matrix from an empty "
                         "shard")
    parts = []
    for c in cols:
        col = pdf[c].to_numpy()
        if len(col) and isinstance(col[0], (list, np.ndarray)):
            parts.append(np.stack([np.asarray(v) for v in col]))
        else:
            parts.append(col.reshape(-1, 1))
    return np.concatenate(parts, axis=1).astype(np.float32)


def read_shard(store: Store, run_id: str, rank: int, size: int,
               feature_cols: Sequence[str], label_cols: Sequence[str],
               val: bool = False):
    """Load this rank's shard(s) back as dense float32 arrays."""
    import pyarrow.parquet as pq

    paths = store.shard_paths(run_id, val=val)
    mine = paths[rank::size] if len(paths) != size else [paths[rank]]
    if not mine:
        raise ValueError(
            f"rank {rank}: no {'validation' if val else 'training'} "
            f"shard — {len(paths)} shard(s) were "
            f"materialized but the job has {size} ranks; set the "
            f"estimator's num_proc to the actual world size")

    frames = []
    for p in mine:
        with store.open(p, "rb") as f:
            frames.append(pq.read_table(f).to_pandas())
    import pandas as pd

    pdf = pd.concat(frames) if len(frames) > 1 else frames[0]
    return columns_to_matrix(pdf, feature_cols), \
        columns_to_matrix(pdf, label_cols)


# ---------------------------------------------------------------------------
# backends
# ---------------------------------------------------------------------------


class LocalBackend:
    """Launcher-backed execution (programmatic run-func) — the default in
    environments without a Spark session."""

    def __init__(self, num_proc: int):
        self.num_proc = num_proc

    def run(self, fn: Callable) -> List[Any]:
        from horovod_tpu.runner.run import run as run_func

        return run_func(fn, np=self.num_proc)


class SparkBackend:
    """Barrier-mode execution via ``horovod_tpu.spark.run`` (parity:
    spark/common/backend.py SparkBackend)."""

    def __init__(self, num_proc: Optional[int] = None):
        self.num_proc = num_proc

    def run(self, fn: Callable) -> List[Any]:
        from horovod_tpu.spark import run as spark_run

        return spark_run(fn, num_proc=self.num_proc)


def default_backend(num_proc: int):
    try:
        from pyspark.sql import SparkSession

        if SparkSession.getActiveSession() is not None:
            return SparkBackend(num_proc)
    except Exception:
        pass
    return LocalBackend(num_proc)


# ---------------------------------------------------------------------------
# base estimator
# ---------------------------------------------------------------------------


class HorovodEstimator:
    """Shared fit() skeleton (parity: spark/common/estimator.py:27):
    materialize → distributed train fn → collect rank-0 artifacts →
    return a fitted model wrapper."""

    def __init__(self, *, feature_cols=("features",), label_cols=("label",),
                 batch_size=32, epochs=1, num_proc=2, store=None,
                 backend=None, run_id=None, verbose=1, seed=1234,
                 resume=True, validation=None):
        """``resume=True`` (default, matching the reference's
        torch/remote.py contract): a fit whose ``run_id`` already has
        epoch checkpoints in the store continues from the newest one.
        ``resume=False`` deletes the run's directory first so the fit
        is clean even under a reused ``run_id``.

        ``validation`` (parity: common/params.py:52): float fraction in
        (0, 1) or an indicator column name; held-out rows are scored
        each epoch with the cross-rank-averaged validation loss.  Keras
        reports it as ``fitted.history["val_loss"]``; torch as the
        ``fitted.val_history`` list (``fitted.history`` stays the flat
        train-loss list), aligned by epoch (``None`` for epochs that
        ran before validation was enabled)."""
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.store = store or Store.create(
            os.path.join(os.getcwd(), ".horovod_tpu_store"))
        self.backend = backend
        self.run_id = run_id
        self.verbose = verbose
        self.seed = seed
        self.resume = resume
        self.validation = validation

    def _fit(self, df, train_fn_builder) -> Dict[str, Any]:
        run_id = self.run_id or f"run-{uuid.uuid4().hex[:8]}"
        self._last_run_id = run_id
        if not self.resume:
            self.store.delete(self.store.run_path(run_id))
        materialize(df, self.store, run_id, self.num_proc,
                    validation=self.validation, seed=self.seed)
        backend = self.backend or default_backend(self.num_proc)
        results = backend.run(train_fn_builder(run_id))
        arts = next(r for r in results if r is not None)
        return arts


# ---------------------------------------------------------------------------
# torch
# ---------------------------------------------------------------------------


class TorchEstimator(HorovodEstimator):
    """Parity: ``horovod/spark/torch/estimator.py`` + ``torch/remote.py``.

    ``model``: a ``torch.nn.Module``; ``optimizer``: an instance (rebuilt
    per worker from its class + defaults, like the reference's optimizer
    serialization) or a factory ``params -> Optimizer``; ``loss``: a
    callable ``(pred, target) -> scalar tensor``.
    """

    def __init__(self, model, optimizer=None, loss=None,
                 classification=None, metrics=(), **kw):
        """``classification``: force (True/False) the index-target
        coercion for single-column labels; default None auto-detects
        CrossEntropyLoss/NLLLoss instances — pass True for functional
        or custom index-target losses.

        ``metrics`` (parity: common/params.py:32 + torch/remote.py
        metric aggregation): callables ``(pred, target) -> scalar
        tensor``, evaluated per epoch on train and validation data and
        cross-rank averaged; results land in the fitted model's
        ``metrics_history[name]`` / ``val_metrics_history[name]``
        (``name`` = the callable's ``__name__``)."""
        super().__init__(**kw)
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.classification = classification
        self.metrics = list(metrics)

    def fit(self, df) -> "TorchModel":
        import torch

        model = self.model
        loss_fn = self.loss or torch.nn.MSELoss()
        opt = self.optimizer
        if opt is None:
            opt_builder = lambda ps: torch.optim.SGD(ps, lr=0.01)  # noqa: E731
        elif callable(opt) and not isinstance(opt, torch.optim.Optimizer):
            opt_builder = opt
        else:
            opt_cls, opt_defaults = opt.__class__, dict(opt.defaults)
            opt_builder = lambda ps: opt_cls(ps, **opt_defaults)  # noqa: E731
        store, feature_cols, label_cols = (
            self.store, self.feature_cols, self.label_cols)
        batch_size, epochs, seed = self.batch_size, self.epochs, self.seed
        classification = self.classification
        has_validation = self.validation is not None
        metric_fns = list(self.metrics)
        metric_names = []
        for i, m in enumerate(metric_fns):
            base = getattr(m, "__name__", "") or f"metric{i}"
            name = base
            k = 2
            while name in metric_names:  # two lambdas must not merge
                name = f"{base}_{k}"
                k += 1
            metric_names.append(name)

        def build(run_id):
            def _train():
                import torch

                import horovod_tpu.torch as hvd

                rank, size = hvd.rank(), hvd.size()
                X, y = read_shard(store, run_id, rank, size,
                                  feature_cols, label_cols)
                Xv = yv = None
                if has_validation:
                    Xv, yv = read_shard(store, run_id, rank, size,
                                        feature_cols, label_cols,
                                        val=True)
                # Classification losses take 1-D class indices; the
                # parquet shards carry labels as float32 matrices
                # (parity: the reference feeds NLLLoss int targets in
                # examples/pytorch_spark_mnist.py).  Only single-column
                # labels coerce — multi-column targets (one-hot / soft
                # labels) stay (B, C) float for CE's soft-target mode.
                classify = classification if classification is not None \
                    else isinstance(loss_fn, (torch.nn.CrossEntropyLoss,
                                              torch.nn.NLLLoss))
                classify = classify and y.shape[1] == 1
                local = copy.deepcopy(model)
                dist_opt = hvd.DistributedOptimizer(
                    opt_builder(local.parameters()),
                    named_parameters=local.named_parameters())
                # Resume: the newest epoch checkpoint in the run's store
                # directory restores model + optimizer + history, and
                # training continues from the following epoch (parity:
                # torch/remote.py loads the store checkpoint before the
                # epoch loop).  Rank 0 reads; broadcast aligns everyone.
                import io as _io

                def _pad(seq, upto):
                    # History lists always index by epoch; epochs that
                    # ran before a knob was enabled read as None.
                    return [None] * (upto - len(seq)) + list(seq)

                start_epoch = 0
                history = []
                val_history = []
                metrics_history = {n: [] for n in metric_names}
                val_metrics_history = {n: [] for n in metric_names}
                ck = store.latest_checkpoint(run_id) if rank == 0 else None
                flag = hvd.broadcast_object(
                    ck[0] if ck else None, root_rank=0,
                    name="est.resume.epoch")
                if flag is not None:
                    if rank == 0:
                        # weights_only: the store is writable by anyone
                        # with filesystem access (same trust model as
                        # TorchModel.load) — never unpickle arbitrary
                        # objects from it.  The checkpoint holds only
                        # tensors and plain containers, all on the
                        # weights_only allowlist.
                        st = torch.load(_io.BytesIO(ck[1]),
                                        map_location="cpu",
                                        weights_only=True)
                        local.load_state_dict(st["model"])
                        dist_opt.load_state_dict(st["optimizer"])
                        history = list(st.get("history", []))
                        val_history = list(st.get("val_history", []))
                        # Only metrics still configured survive resume;
                        # stale keys would stop indexing by epoch.
                        for n in metric_names:
                            if n in st.get("metrics_history", {}):
                                metrics_history[n] = list(
                                    st["metrics_history"][n])
                            if n in st.get("val_metrics_history", {}):
                                val_metrics_history[n] = list(
                                    st["val_metrics_history"][n])
                    start_epoch = int(flag) + 1
                    (history, val_history, metrics_history,
                     val_metrics_history) = hvd.broadcast_object(
                        (history, val_history, metrics_history,
                         val_metrics_history), root_rank=0,
                        name="est.resume.hist")
                    if Xv is not None:
                        val_history = _pad(val_history, start_epoch)
                    for n in metric_names:
                        metrics_history[n] = _pad(
                            metrics_history.get(n, []), start_epoch)
                        if Xv is not None:
                            val_metrics_history[n] = _pad(
                                val_metrics_history.get(n, []),
                                start_epoch)

                def _eval_split(Xa, ya, tag, epoch, named_fns):
                    """Cross-rank-averaged values of ``(name, fn)``
                    pairs over a split: one forward per batch shared by
                    every fn, eval mode, a single sum+count allreduce."""
                    names = [n for n, _ in named_fns]
                    sums = {n: 0.0 for n in names}
                    count = 0
                    local.eval()
                    with torch.no_grad():
                        for i in range(0, len(Xa), batch_size):
                            xb = torch.from_numpy(Xa[i:i + batch_size])
                            yb = torch.from_numpy(ya[i:i + batch_size])
                            if classify:
                                yb = yb.reshape(-1).long()
                            pred = local(xb)
                            for n, fn in named_fns:
                                sums[n] += float(fn(pred, yb)) * len(xb)
                            count += len(xb)
                    local.train()
                    flat = [sums[n] for n in names] + [float(count)]
                    agg = hvd.allreduce(
                        torch.tensor(flat, dtype=torch.float64),
                        op=hvd.Sum, name=f"est.metrics.{tag}.{epoch}")
                    total = max(float(agg[-1]), 1.0)
                    return {n: float(agg[i]) / total
                            for i, n in enumerate(names)}
                # Optimizer state FIRST: on a fresh optimizer its
                # broadcast initializes state via a root-only zero-grad
                # step, which can move root's params (e.g. AdamW's
                # decoupled decay) — the parameter broadcast after it
                # re-syncs every replica.
                hvd.broadcast_optimizer_state(dist_opt, root_rank=0)
                hvd.broadcast_parameters(local.state_dict(), root_rank=0)
                for _epoch in range(start_epoch, epochs):
                    # Permutation keyed by (seed, rank, epoch) so a
                    # resumed epoch E shuffles exactly like epoch E of
                    # an uninterrupted run.
                    perm = np.random.RandomState(
                        [seed, rank, _epoch]).permutation(len(X))
                    total, nb = 0.0, 0
                    for i in range(0, len(X), batch_size):
                        idx = perm[i:i + batch_size]
                        xb = torch.from_numpy(X[idx])
                        yb = torch.from_numpy(y[idx])
                        if classify:
                            yb = yb.reshape(-1).long()
                        dist_opt.zero_grad()
                        out = local(xb)
                        l = loss_fn(out, yb)
                        l.backward()
                        dist_opt.step()
                        total += float(l.detach())
                        nb += 1
                    avg = float(hvd.allreduce(
                        torch.tensor([total / max(nb, 1)]),
                        op=hvd.Average, name=f"est.loss.{_epoch}")[0])
                    history.append(avg)
                    metric_pairs = list(zip(metric_names, metric_fns))
                    if Xv is not None:
                        # One eval pass over the validation shard covers
                        # the loss AND every metric (shared forwards;
                        # eval mode = frozen BN stats, no dropout;
                        # batched so peak memory matches training;
                        # sum+count allreduce = exact mean under uneven
                        # per-rank rows).
                        v = _eval_split(Xv, yv, "v", _epoch,
                                        [("__loss__", loss_fn)]
                                        + metric_pairs)
                        val_history.append(v["__loss__"])
                        for n in metric_names:
                            val_metrics_history[n].append(v[n])
                    if metric_pairs:
                        tr_m = _eval_split(X, y, "t", _epoch,
                                           metric_pairs)
                        for n in metric_names:
                            metrics_history[n].append(tr_m[n])
                    if rank == 0:
                        buf = _io.BytesIO()
                        torch.save(
                            {"model": local.state_dict(),
                             "optimizer": dist_opt.state_dict(),
                             "history": history,
                             "val_history": val_history,
                             "metrics_history": metrics_history,
                             "val_metrics_history": val_metrics_history},
                            buf)
                        store.save_checkpoint(run_id, _epoch,
                                              buf.getvalue())
                if rank == 0:
                    buf = _io.BytesIO()
                    torch.save(local.state_dict(), buf)
                    store.write_bytes(store.checkpoint_path(run_id)
                                      + ".pt", buf.getvalue())
                    return {"state_dict": {
                        k: v.detach().cpu().numpy()
                        for k, v in local.state_dict().items()},
                        "history": history,
                        "val_history": val_history,
                        "metrics_history": metrics_history,
                        "val_metrics_history": val_metrics_history}
                return None

            return _train

        arts = self._fit(df, build)
        fitted = copy.deepcopy(model)
        fitted.load_state_dict(
            {k: __import__("torch").from_numpy(np.asarray(v))
             for k, v in arts["state_dict"].items()})
        return TorchModel(
            fitted, self.feature_cols, self.label_cols,
            history=arts["history"], run_id=self._last_run_id,
            val_history=arts.get("val_history"),
            metrics_history=arts.get("metrics_history"),
            val_metrics_history=arts.get("val_metrics_history"))


class _FittedModel:
    """Shared fitted-model surface (parity role: the Spark Transformer
    returned by estimator.fit — pandas in/out instead of Spark
    DataFrames)."""

    def __init__(self, model, feature_cols, label_cols, history=None,
                 run_id=None, val_history=None, metrics_history=None,
                 val_metrics_history=None):
        self._model = model
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.history = history
        self.run_id = run_id
        self.val_history = list(val_history or [])
        self.metrics_history = dict(metrics_history or {})
        self.val_metrics_history = dict(val_metrics_history or {})

    def getModel(self):
        return self._model

    def transform(self, df):
        pdf = _to_pandas(df).copy()
        pred = self.predict(columns_to_matrix(pdf, self.feature_cols))
        for j, c in enumerate(self.label_cols):
            pdf[f"{c}__output"] = list(pred[:, j] if pred.ndim > 1
                                       else pred)
        return pdf


class TorchModel(_FittedModel):
    def predict(self, X: np.ndarray) -> np.ndarray:
        import torch

        with torch.no_grad():
            return self._model(
                torch.from_numpy(np.asarray(X, np.float32))).numpy()

    @classmethod
    def load(cls, store, run_id: str, model,
             feature_cols=("features",), label_cols=("label",)
             ) -> "TorchModel":
        """Rebuild a fitted model from the store's final artifact
        (parity: the reference estimator's model-back-from-store
        serialization, spark/torch/estimator.py).  ``model``: a module
        with the fitted architecture; its state is loaded from
        ``<run_path>/checkpoint.pt``."""
        import copy as _copy
        import io as _io

        import torch

        payload = store.read_bytes(store.checkpoint_path(run_id) + ".pt")
        fitted = _copy.deepcopy(model)
        # weights_only: the artifact is a plain tensor state_dict, and
        # a remote store is attacker-writable territory — full pickle
        # would mean arbitrary code execution on load.
        fitted.load_state_dict(
            torch.load(_io.BytesIO(payload), map_location="cpu",
                       weights_only=True))
        return cls(fitted, feature_cols, label_cols, run_id=run_id)


# ---------------------------------------------------------------------------
# keras
# ---------------------------------------------------------------------------


def _alias_registered_names(model_json: str, custom_objects):
    """Extend a plain-name custom_objects mapping with the
    ``package>Name`` registered-name keys keras 3 actually looks up.

    Workers receive custom classes by cloudpickle, which does not
    re-run ``@register_keras_serializable`` — their registry is empty
    and ``deserialize_keras_object`` resolves classes by
    ``registered_name``.  The architecture JSON carries both names, so
    the alias map is derivable without asking the user for keras-3
    registry syntax."""
    if not custom_objects:
        return {}
    import json as _json

    out = dict(custom_objects)

    def walk(node):
        if isinstance(node, dict):
            rn, cn = node.get("registered_name"), node.get("class_name")
            if rn and cn and cn in custom_objects:
                out[rn] = custom_objects[cn]
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(_json.loads(model_json))
    return out


class KerasEstimator(HorovodEstimator):
    """Parity: ``horovod/spark/keras/estimator.py`` — the model travels as
    architecture JSON + weights (the reference serializes the compiled
    model the same way, keras/util.py), the optimizer as its keras config.
    """

    def __init__(self, model, optimizer=None, loss="mse", metrics=(),
                 custom_objects=None, **kw):
        """``custom_objects``: name → class/function mapping consulted
        when the architecture JSON is rebuilt on the workers and the
        driver (parity: keras/estimator.py custom_objects param)."""
        super().__init__(**kw)
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = list(metrics)
        self.custom_objects = custom_objects

    def fit(self, df) -> "KerasModel":
        import keras

        model_json = self.model.to_json()
        weights = self.model.get_weights()
        opt_cfg = keras.optimizers.serialize(
            self.optimizer or keras.optimizers.SGD(learning_rate=0.01))
        loss, metrics = self.loss, self.metrics
        custom_objects = self.custom_objects
        has_validation = self.validation is not None
        store, feature_cols, label_cols = (
            self.store, self.feature_cols, self.label_cols)
        batch_size, epochs = self.batch_size, self.epochs

        def build(run_id):
            def _train():
                import keras

                import horovod_tpu.keras as hvd_keras
                import horovod_tpu.tensorflow as hvd

                rank, size = hvd.rank(), hvd.size()
                X, y = read_shard(store, run_id, rank, size,
                                  feature_cols, label_cols)
                val_data = None
                if has_validation:
                    Xv, yv = read_shard(store, run_id, rank, size,
                                        feature_cols, label_cols,
                                        val=True)
                    val_data = (Xv, yv)
                # custom_object_scope with registered-name aliases, not
                # the model_from_json kwarg: keras 3 resolves classes
                # by 'package>Name' and drops the kwarg's mapping in
                # nested from_config calls.
                with keras.saving.custom_object_scope(
                        _alias_registered_names(model_json,
                                                custom_objects)):
                    local = keras.models.model_from_json(model_json)
                local.set_weights(weights)
                opt = hvd_keras.DistributedOptimizer(
                    keras.optimizers.deserialize(copy.deepcopy(opt_cfg)))
                local.compile(optimizer=opt, loss=loss, metrics=metrics,
                              run_eagerly=True)
                # Resume from the newest epoch checkpoint in the store
                # (weights + history; parity: keras/estimator.py resumes
                # from store checkpoints between fit() invocations).
                start_epoch = 0
                prev_hist: Dict[str, List[float]] = {}
                ck = store.latest_checkpoint(run_id) if rank == 0 else None
                resume = hvd.broadcast_object(
                    None if ck is None else
                    {"epoch": ck[0],
                     **_keras_ckpt_decode(ck[1])}, root_rank=0,
                    name="est.keras.resume")
                if resume is not None:
                    local.set_weights(resume["weights"])
                    prev_hist = resume.get("history", {})
                    start_epoch = resume["epoch"] + 1
                    # Restore optimizer slots + iteration counter so the
                    # resumed dynamics (Adam moments, LR schedules)
                    # continue instead of restarting (the torch path
                    # restores dist_opt.state_dict() the same way).
                    # Count/shape-validated: a checkpoint from another
                    # model config falls back to fresh slots with a
                    # warning instead of a silent partial restore.
                    if resume.get("opt_vars") is not None:
                        local.optimizer.build(local.trainable_variables)
                        _restore_optimizer_slots(
                            local.optimizer.variables, resume["opt_vars"])

                class _EpochCheckpoint(keras.callbacks.Callback):
                    """Rank 0 writes weights+history to the store after
                    every epoch (reference: ckpt_callback in
                    keras/estimator.py writing to get_checkpoint_path)."""

                    def __init__(self, running_hist):
                        super().__init__()
                        self._hist = running_hist

                    def on_epoch_end(self, epoch, logs=None):
                        for k, v in (logs or {}).items():
                            self._hist.setdefault(k, []).append(float(v))
                        if rank == 0:
                            store.save_checkpoint(
                                run_id, start_epoch + epoch,
                                _keras_ckpt_encode(
                                    self.model.get_weights(),
                                    [np.asarray(v) for v in
                                     self.model.optimizer.variables],
                                    self._hist))

                running_hist = {k: list(v) for k, v in prev_hist.items()}
                if start_epoch < epochs:
                    local.fit(
                        X, y, batch_size=batch_size,
                        epochs=epochs - start_epoch, verbose=0,
                        validation_data=val_data,
                        callbacks=[
                            hvd_keras.callbacks
                            .BroadcastGlobalVariablesCallback(0),
                            hvd_keras.callbacks.MetricAverageCallback(),
                            _EpochCheckpoint(running_hist),
                        ])
                if rank == 0:
                    store.makedirs(store.run_path(run_id))
                    # .keras archives need a real file; serialize via a
                    # temp file, then place the bytes through the store
                    # so remote backends get the artifact too.
                    import tempfile

                    with tempfile.NamedTemporaryFile(
                            suffix=".keras", delete=False) as tf:
                        tmp_name = tf.name
                    try:
                        local.save(tmp_name)
                        with open(tmp_name, "rb") as f:
                            store.write_bytes(
                                store.checkpoint_path(run_id) + ".keras",
                                f.read())
                    finally:
                        os.unlink(tmp_name)
                    return {"weights": local.get_weights(),
                            "history": running_hist}
                return None

            return _train

        arts = self._fit(df, build)
        with keras.saving.custom_object_scope(
                _alias_registered_names(model_json, self.custom_objects)):
            fitted = keras.models.model_from_json(model_json)
        fitted.set_weights(arts["weights"])
        return KerasModel(fitted, self.feature_cols, self.label_cols,
                          history=arts["history"],
                          run_id=self._last_run_id)


class KerasModel(_FittedModel):
    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(self._model.predict(
            np.asarray(X, np.float32), verbose=0))

    @classmethod
    def load(cls, store, run_id: str, custom_objects=None,
             feature_cols=("features",), label_cols=("label",)
             ) -> "KerasModel":
        """Rebuild a fitted model from the store's ``checkpoint.keras``
        archive (parity: keras/estimator.py:521 model-back-from-store +
        keras/__init__.py load_model).  Works against remote (fsspec)
        stores — the archive bytes are staged through a temp file
        because keras archives need a real filesystem path."""
        import tempfile

        import keras

        payload = store.read_bytes(
            store.checkpoint_path(run_id) + ".keras")
        tmp_name = None
        try:
            with tempfile.NamedTemporaryFile(suffix=".keras",
                                             delete=False) as tf:
                tmp_name = tf.name
                tf.write(payload)
            # compile=False: the archive's optimizer is the runtime
            # DistributedOptimizer wrapper, which only exists inside an
            # hvd worker; this loader serves inference/transform (for
            # retraining with the wrapped optimizer, use
            # horovod_tpu.keras.load_model).
            with keras.saving.custom_object_scope(custom_objects or {}):
                fitted = keras.models.load_model(tmp_name,
                                                 compile=False)
        finally:
            if tmp_name is not None:
                os.unlink(tmp_name)
        return cls(fitted, feature_cols, label_cols, run_id=run_id)
