"""Training artifact stores for the estimator API.

Role parity: ``horovod/spark/common/store.py`` (LocalStore/HDFSStore —
there a filesystem abstraction over train-data, runs, and checkpoints
materialized with Petastorm).  Redesigned: shards are plain parquet files
written with pyarrow — no Petastorm dependency — and the same store serves
a pyspark DataFrame, a pandas DataFrame, or a dict of numpy arrays, so the
estimators are fully executable without a Spark cluster.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional


class Store:
    """Filesystem layout for one estimator workspace:

    ``<prefix>/intermediate_train_data/<run_id>/part-NNNNN.parquet``
    ``<prefix>/runs/<run_id>/checkpoint.*``
    """

    def __init__(self, prefix_path: str):
        self.prefix_path = os.path.abspath(prefix_path)

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Parity: ``Store.create`` picks the backend by URL scheme; only
        local paths exist here (HDFS has no TPU-pod analog — pods mount
        GCS/NFS as local paths)."""
        return LocalStore(prefix_path)

    # -- layout ----------------------------------------------------------

    def train_data_path(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, "intermediate_train_data",
                            run_id)

    def run_path(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, "runs", run_id)

    def checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.run_path(run_id), "checkpoint")

    def logs_path(self, run_id: str) -> str:
        return os.path.join(self.run_path(run_id), "logs")

    # -- fs ops ----------------------------------------------------------

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def shard_paths(self, run_id: str):
        d = self.train_data_path(run_id)
        if not os.path.isdir(d):
            return []
        return sorted(os.path.join(d, f) for f in os.listdir(d)
                      if f.endswith(".parquet"))


class LocalStore(Store):
    """Local-filesystem store (parity: spark/common/store.py LocalStore)."""
