"""Training artifact stores for the estimator API.

Role parity: ``horovod/spark/common/store.py:149-426`` (FilesystemStore →
LocalStore/HDFSStore — a filesystem abstraction over train-data, runs,
checkpoints and logs, with Petastorm materialization).  Redesigned:

* shards are plain parquet files written with pyarrow through the
  store's own ``open()`` — no Petastorm dependency;
* the remote backend is **fsspec** (:class:`FsspecStore`) rather than a
  bespoke HDFS client: the TPU-pod analog of HDFSStore is an object
  store (``gs://`` / ``s3://``), and fsspec serves those, ``hdfs://``,
  and ``memory://`` (which the tests use as a real non-local backend)
  through one interface;
* checkpoint/resume helpers (:meth:`Store.save_checkpoint`,
  :meth:`Store.latest_checkpoint`) give the estimators the reference's
  per-run checkpoint directory contract (``get_checkpoint_path`` +
  torch/remote.py epoch checkpointing) in byte-oriented form that works
  identically on local disk and object stores.

``Store.create`` picks the backend by URL scheme, like the reference's
``Store.create`` → ``FilesystemStore.matches`` dispatch.
"""

from __future__ import annotations

import os
import posixpath
import re
import shutil
from typing import List, Optional, Tuple


class Store:
    """Filesystem layout for one estimator workspace:

    ``<prefix>/intermediate_train_data/<run_id>/part-NNNNN.parquet``
    ``<prefix>/runs/<run_id>/checkpoint.*``
    ``<prefix>/runs/<run_id>/logs/``
    """

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Backend by URL scheme (parity: store.py:142 Store.create):
        plain paths → :class:`LocalStore`; ``scheme://`` URLs →
        :class:`FsspecStore` (gs/s3/hdfs/memory/...)."""
        if re.match(r"^[a-zA-Z0-9]+://", prefix_path) and \
                not prefix_path.startswith("file://"):
            return FsspecStore(prefix_path)
        if prefix_path.startswith("file://"):
            prefix_path = prefix_path[len("file://"):]
        return LocalStore(prefix_path)

    # -- layout ----------------------------------------------------------

    def join(self, *parts: str) -> str:
        return os.path.join(*parts)

    def train_data_path(self, run_id: str) -> str:
        return self.join(self.prefix_path, "intermediate_train_data",
                          run_id)

    def val_data_path(self, run_id: str) -> str:
        """Validation shards (parity: store.py get_val_data_path)."""
        return self.join(self.prefix_path, "intermediate_val_data",
                          run_id)

    def run_path(self, run_id: str) -> str:
        return self.join(self.prefix_path, "runs", run_id)

    def checkpoint_path(self, run_id: str) -> str:
        return self.join(self.run_path(run_id), "checkpoint")

    def logs_path(self, run_id: str) -> str:
        return self.join(self.run_path(run_id), "logs")

    # -- fs ops (backend-specific) --------------------------------------

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def open(self, path: str, mode: str = "rb"):
        """Open a file in the store (binary modes only — parquet and
        checkpoint payloads are bytes)."""
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        """Full paths of directory entries ([] if absent)."""
        raise NotImplementedError

    # -- shared helpers built on the ops --------------------------------

    def shard_paths(self, run_id: str, val: bool = False) -> List[str]:
        d = (self.val_data_path(run_id) if val
             else self.train_data_path(run_id))
        return sorted(p for p in self.listdir(d)
                      if p.endswith(".parquet"))

    def read_bytes(self, path: str) -> bytes:
        with self.open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes) -> None:
        parent = posixpath.dirname(path) if "://" in path \
            else os.path.dirname(path)
        self.makedirs(parent)
        with self.open(path, "wb") as f:
            f.write(data)

    # -- checkpoint/resume (parity: torch/remote.py epoch checkpoints
    #    under get_checkpoint_path; byte-oriented so object stores work)

    def save_checkpoint(self, run_id: str, epoch: int,
                        payload: bytes, keep: int = 2) -> str:
        """Write this epoch's checkpoint and prune all but the newest
        ``keep`` (only the newest is ever read back; without pruning a
        long fit accumulates one full model+optimizer snapshot per
        epoch in the store)."""
        path = self.join(self.run_path(run_id),
                          f"checkpoint-epoch{epoch:05d}.bin")
        self.write_bytes(path, payload)
        pat = re.compile(r"checkpoint-epoch(\d+)\.bin$")
        found = sorted((int(m.group(1)), p)
                       for p in self.listdir(self.run_path(run_id))
                       for m in [pat.search(p)] if m)
        for _, old in found[:-keep] if keep > 0 else []:
            self.delete(old)
        return path

    def latest_checkpoint(
            self, run_id: str) -> Optional[Tuple[int, bytes]]:
        """(epoch, payload) of the newest epoch checkpoint, or None."""
        pat = re.compile(r"checkpoint-epoch(\d+)\.bin$")
        best = None
        for p in self.listdir(self.run_path(run_id)):
            m = pat.search(p)
            if m and (best is None or int(m.group(1)) > best[0]):
                best = (int(m.group(1)), p)
        if best is None:
            return None
        return best[0], self.read_bytes(best[1])


class LocalStore(Store):
    """Local-filesystem store (parity: spark/common/store.py:250
    LocalStore)."""

    def __init__(self, prefix_path: str):
        super().__init__(os.path.abspath(prefix_path))

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def open(self, path: str, mode: str = "rb"):
        if "w" in mode:
            os.makedirs(os.path.dirname(path), exist_ok=True)
        return open(path, mode)

    def listdir(self, path: str) -> List[str]:
        if not os.path.isdir(path):
            return []
        return [os.path.join(path, f) for f in os.listdir(path)]


class FsspecStore(Store):
    """Remote store over any fsspec filesystem (parity role:
    spark/common/store.py:294 HDFSStore — the reference's non-local
    backend; on TPU pods the natural remote is an object store, so the
    backend is chosen by the URL: ``gs://bucket/prefix``,
    ``s3://bucket/prefix``, ``hdfs://nn/prefix``, ``memory://prefix``).

    All paths this store hands out keep the scheme, so a path is usable
    by whichever worker process receives it regardless of host.
    """

    def __init__(self, prefix_path: str):
        try:
            import fsspec
        except ImportError as e:  # pragma: no cover - fsspec is baked in
            raise ImportError(
                "FsspecStore needs the 'fsspec' package for remote "
                "stores; install it or use a local path") from e
        self._fs, _stripped = fsspec.core.url_to_fs(prefix_path)
        self._scheme = prefix_path.split("://", 1)[0]
        super().__init__(prefix_path.rstrip("/"))

    # fsspec filesystems return scheme-less paths; keep our surface
    # uniform by re-attaching the scheme.
    def _with_scheme(self, path: str) -> str:
        if "://" in path:
            return path
        # fs-native paths keep their leading slash (file/memory) or
        # bucket prefix (s3/gs) — prepend the scheme verbatim;
        # "file://tmp/x" would silently become a cwd-relative path.
        return f"{self._scheme}://{path}"

    def join(self, *parts: str) -> str:
        return posixpath.join(*parts)

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def makedirs(self, path: str) -> None:
        # Object stores have no real directories; mkdirs is best-effort
        # and some fsspec backends raise on existing paths.
        try:
            self._fs.makedirs(path, exist_ok=True)
        except (FileExistsError, NotImplementedError):
            pass

    def delete(self, path: str) -> None:
        if self._fs.exists(path):
            self._fs.rm(path, recursive=True)

    def open(self, path: str, mode: str = "rb"):
        return self._fs.open(path, mode)

    def listdir(self, path: str) -> List[str]:
        try:
            if not self._fs.exists(path):
                return []
            return [self._with_scheme(p)
                    for p in self._fs.ls(path, detail=False)]
        except FileNotFoundError:
            return []
