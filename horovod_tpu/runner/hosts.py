"""Host parsing and slot allocation.

Parity: ``horovod/run/run.py`` host/hostfile parsing and
``horovod/run/gloo_run.py:53-111`` ``_allocate`` — ranks are assigned
host-by-host; ``local_rank`` is the index within the host; ``cross_rank``
is the index of the host among all hosts that have a process at the same
local rank (the reference's cross-communicator layout, which on TPU maps to
the DCN axis across slices).
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass
from typing import Dict, List, Tuple

from horovod_tpu.utils import env as env_util


@dataclass(frozen=True)
class HostSlots:
    hostname: str
    slots: int


@dataclass(frozen=True)
class SlotInfo:
    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


# hostname/IPv4, or bracketed IPv6; optional ":slots" suffix
_HOST_RE = re.compile(
    r"^(?P<host>[\w.\-]+|\[[0-9a-fA-F:]+\])(:(?P<slots>\d+))?$")


def parse_hosts(hosts_str: str) -> List[HostSlots]:
    """``"hostA:2,hostB:4"`` → [HostSlots("hostA", 2), ...].  A host with
    no ``:slots`` suffix gets 1 slot (run.py host parsing semantics)."""
    out = []
    for part in hosts_str.split(","):
        part = part.strip()
        if not part:
            continue
        m = _HOST_RE.match(part)
        if not m:
            raise ValueError(f"invalid host string: {part!r}")
        out.append(HostSlots(m.group("host"),
                             int(m.group("slots") or 1)))
    if not out:
        raise ValueError(f"no hosts found in {hosts_str!r}")
    return out


def parse_hostfile(path: str) -> List[HostSlots]:
    """Hostfile lines: ``hostname slots=N`` (mpirun style) or
    ``hostname:N`` or bare ``hostname``."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            m = re.match(r"^(\S+)\s+slots\s*=\s*(\d+)\s*$", line)
            if m:
                out.append(HostSlots(m.group(1), int(m.group(2))))
            else:
                out.extend(parse_hosts(line))
    if not out:
        raise ValueError(f"no hosts found in hostfile {path}")
    return out


class HostBlacklist:
    """Failure tracking for the relaunch loop (``hvdrun --max-restarts``).

    A host whose workers died ``threshold`` times inside the cool-down
    window is *blacklisted*: skipped on the next allocation while the
    remaining hosts still cover ``np`` slots.  Failures age out after
    ``cooldown_s`` — a flaky host is re-probed instead of banned forever
    (parity concept: Elastic Horovod's host blacklist + whitelist decay,
    ``run/elastic/discovery.py``).
    """

    def __init__(self, threshold: int = None, cooldown_s: float = None):
        self.threshold = threshold if threshold is not None else \
            env_util.get_int(env_util.BLACKLIST_THRESHOLD, 2)
        self.cooldown_s = cooldown_s if cooldown_s is not None else \
            env_util.get_float(env_util.BLACKLIST_COOLDOWN_S, 300.0)
        self._failures: Dict[str, List[float]] = {}

    def record_failure(self, hostname: str, now: float = None) -> None:
        if not hostname:
            return
        self._failures.setdefault(hostname, []).append(
            time.monotonic() if now is None else now)

    def failure_count(self, hostname: str, now: float = None) -> int:
        now = time.monotonic() if now is None else now
        recent = [t for t in self._failures.get(hostname, ())
                  if now - t <= self.cooldown_s]
        self._failures[hostname] = recent
        return len(recent)

    def is_blacklisted(self, hostname: str, now: float = None) -> bool:
        return self.failure_count(hostname, now) >= self.threshold

    def filter_hosts(self, hosts: List[HostSlots],
                     np: int) -> List[HostSlots]:
        """``hosts`` minus blacklisted entries — unless that leaves fewer
        than ``np`` slots, in which case the full list comes back (a
        degraded host is better than no relaunch at all)."""
        keep = [h for h in hosts if not self.is_blacklisted(h.hostname)]
        if keep and sum(h.slots for h in keep) >= np:
            return keep
        return hosts


def allocate(hosts: List[HostSlots], np: int) -> List[SlotInfo]:
    """Assign ``np`` ranks to hosts in order (parity: gloo_run._allocate).

    Raises if the hosts provide fewer than ``np`` slots.  Extra slots are
    left unused (matches ``horovodrun -np`` semantics).
    """
    # Merge duplicate hostname entries (mpirun hostfiles add slots by
    # repeating the host), preserving first-seen order.
    merged: Dict[str, int] = {}
    for h in hosts:
        merged[h.hostname] = merged.get(h.hostname, 0) + h.slots
    hosts = [HostSlots(name, slots) for name, slots in merged.items()]

    total = sum(h.slots for h in hosts)
    if total < np:
        raise ValueError(
            f"requested {np} processes but hosts only provide {total} "
            f"slots")
    # Host-by-host assignment.
    assignment: List[Tuple[str, int]] = []  # (hostname, local_rank)
    per_host: List[Tuple[str, int]] = []    # (hostname, local_size)
    remaining = np
    for h in hosts:
        if remaining == 0:
            break
        use = min(h.slots, remaining)
        if use == 0:
            continue  # zero-slot entry excludes a host; keep scanning
        per_host.append((h.hostname, use))
        for lr in range(use):
            assignment.append((h.hostname, lr))
        remaining -= use

    local_sizes = dict(per_host)
    # cross_rank: index of this host among hosts having a slot at the same
    # local_rank; cross_size: number of such hosts.
    hosts_order = [h for h, _ in per_host]
    out = []
    for rank, (hostname, lr) in enumerate(assignment):
        hosts_with_lr = [h for h in hosts_order if local_sizes[h] > lr]
        out.append(SlotInfo(
            hostname=hostname,
            rank=rank,
            size=np,
            local_rank=lr,
            local_size=local_sizes[hostname],
            cross_rank=hosts_with_lr.index(hostname),
            cross_size=len(hosts_with_lr),
        ))
    return out
