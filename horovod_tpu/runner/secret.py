"""Shared-secret request signing for the rendezvous KV store.

Parity: ``horovod/run/common/util/secret.py`` + the HMAC framing in
``run/common/util/network.py`` — the launcher generates a per-job secret,
ships it to workers through their environment (``HVD_SECRET_KEY``), and
every KV request carries an HMAC so a stray or malicious client on the
network cannot read or poison the rendezvous state.
"""

from __future__ import annotations

import hmac
import secrets as _secrets

ENV_VAR = "HVD_SECRET_KEY"
HEADER = "X-HVD-Auth"


def make_secret() -> str:
    return _secrets.token_hex(32)


def sign(secret: str, method: str, path: str, body: bytes = b"") -> str:
    """HMAC-SHA256 over the request essence (method, path, body)."""
    msg = method.encode() + b"\0" + path.encode() + b"\0" + (body or b"")
    return hmac.new(secret.encode(), msg, "sha256").hexdigest()


def verify(secret: str, method: str, path: str, body: bytes,
           signature: str) -> bool:
    if not signature:
        return False
    return hmac.compare_digest(sign(secret, method, path, body), signature)
