"""CLI-flag / YAML-config → ``HVD_*`` env mapping.

Parity: ``horovod/run/common/util/config_parser.py`` (set_env_from_args)
and the ``--config-file`` YAML layer (run.py:275,446-451).  Three
equivalent config layers, later ones winning: raw env < YAML file < CLI
flags — matching the reference's precedence.
"""

from __future__ import annotations

from typing import Dict, Optional

from horovod_tpu.utils import env as E

# argparse dest → env var
_ARG_ENV = {
    "fusion_threshold_mb": E.FUSION_THRESHOLD,
    "cycle_time_ms": E.CYCLE_TIME,
    "cache_capacity": E.CACHE_CAPACITY,
    "hierarchical_allreduce": E.HIERARCHICAL_ALLREDUCE,
    "hierarchical_allgather": E.HIERARCHICAL_ALLGATHER,
    "ring_segment_bytes": E.RING_SEGMENT_BYTES,
    "sock_buf_bytes": E.SOCK_BUF_BYTES,
    "ctrl_fanout": E.CTRL_FANOUT,
    "collective_timeout": E.COLLECTIVE_TIMEOUT,
    "no_shm": E.SHM_DISABLE,
    "shm_slot_bytes": E.SHM_SLOT_BYTES,
    "shm_slots": E.SHM_SLOTS,
    "timeline_filename": E.TIMELINE,
    "timeline_mark_cycles": E.TIMELINE_MARK_CYCLES,
    "no_stall_check": E.STALL_CHECK_DISABLE,
    "stall_warning_time_seconds": E.STALL_CHECK_TIME,
    "stall_shutdown_time_seconds": E.STALL_SHUTDOWN_TIME,
    "autotune": E.AUTOTUNE,
    "autotune_log_file": E.AUTOTUNE_LOG,
    "adasum_mode": E.ADASUM_MODE,
    "log_level": "HVD_LOG_LEVEL",
    "min_np": E.ELASTIC_MIN_NP,
    "max_np": E.ELASTIC_MAX_NP,
    "host_discovery_script": E.HOST_DISCOVERY_SCRIPT,
    "metrics_port": E.METRICS_PORT,
    "serve_port": E.SERVE_PORT,
    "serve_max_batch": E.SERVE_MAX_BATCH,
    "serve_max_queue": E.SERVE_MAX_QUEUE,
    "kv_addrs": E.KV_ADDRS,
}

_MB = {"fusion_threshold_mb"}
_BOOL = {"hierarchical_allreduce", "hierarchical_allgather",
         "timeline_mark_cycles", "no_stall_check", "autotune", "no_shm"}


def _convert(dest: str, v) -> Optional[str]:
    """One flag value → env string; None when the flag was not set.
    ``is``-checks so a legitimate 0 (e.g. --cache-capacity 0) survives."""
    if v is None or v is False:
        return None
    if dest in _BOOL:
        # YAML may spell booleans as 0/1/"false"/"true"; only truthy
        # values enable the feature (argparse store_true always passes
        # the literal True here).
        if isinstance(v, str):
            v = v.strip().lower() not in ("", "0", "false", "no", "off")
        if not v:
            return None
        return "1"
    if dest in _MB:
        return str(int(float(v) * 1024 * 1024))
    return str(v)


def env_from_args(args) -> Dict[str, str]:
    """Collect env assignments from parsed argparse flags (only flags the
    user actually set — unset flags are skipped so they don't override the
    YAML/env layers)."""
    out: Dict[str, str] = {}
    for dest, env_name in _ARG_ENV.items():
        s = _convert(dest, getattr(args, dest, None))
        if s is not None:
            out[env_name] = s
    # --disable-cache is the CLI spelling of cache capacity 0 (parity:
    # config_parser.py maps it the same way in the reference).
    if getattr(args, "disable_cache", False):
        out[E.CACHE_CAPACITY] = "0"
    return out


def env_from_config_file(path: str) -> Dict[str, str]:
    """YAML config: top-level keys are the argparse dests (dashes or
    underscores), e.g. ``fusion-threshold-mb: 32``."""
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    out: Dict[str, str] = {}
    for key, v in cfg.items():
        dest = key.replace("-", "_")
        env_name = _ARG_ENV.get(dest)
        if env_name is None:
            raise ValueError(f"unknown config key {key!r}")
        s = _convert(dest, v)
        if s is not None:
            out[env_name] = s
    return out
