"""Pre-launch NIC discovery: per-host agents + ring routability probe.

Role parity: ``run/driver/driver_service.py:128-198`` +
``run/common/service/task_service.py`` in the reference — before spawning
the job, a small agent runs on every host, registers all of its IPv4
interfaces, probes the interfaces of the *next* host in a ring, and the
launcher intersects the per-host routable sets to find NICs that work
everywhere.  Interfaces that exist but route nowhere (virtual bridges,
wrong-subnet NICs) are filtered out, so a multi-NIC cluster rendezvouses
on a mutually reachable network instead of the default-route guess.

Redesign: the reference builds a bespoke driver/task RPC service with its
own wire format; here the agents coordinate through the launcher's
already-running HMAC-signed HTTP KV store (the same rendezvous every
worker uses), and the probe is one ephemeral TCP connect per candidate
interface.

Flow (n hosts, host index h):
  1. agent h: listen on an ephemeral TCP port, enumerate interfaces,
     ``PUT nicprobe/addrs/h = {ifname: [addr, port], ...}``
  2. agent h: wait for ``nicprobe/addrs/(h+1) % n``; try a TCP connect to
     every advertised (addr, port); ``PUT nicprobe/routable/(h+1)%n`` =
     names of the next host's interfaces reachable from here.
  3. launcher: intersect all ``nicprobe/routable/*`` sets, ``PUT
     nicprobe/done`` so agents release their listeners and exit.
"""

from __future__ import annotations

import array
import fcntl
import json
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional

_SIOCGIFCONF = 0x8912
_DONE_KEY = "nicprobe/done"


def enumerate_interfaces() -> Dict[str, str]:
    """All IPv4-configured interface names → addresses (SIOCGIFCONF)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        # struct ifreq is 40 bytes on LP64 (16 name + 24 ifr_ifru), 32 on
        # 32-bit; ifc_len comes back truncated to whole records, and the
        # ioctl reports success even when truncating — grow until the
        # kernel leaves slack (many-veth container hosts exceed any
        # fixed guess).
        step = 40 if struct.calcsize("P") == 8 else 32
        n_records = 64
        while True:
            bufsize = step * n_records
            buf = array.array("B", b"\0" * bufsize)
            ifconf = struct.pack("iL", bufsize, buf.buffer_info()[0])
            outbytes = struct.unpack(
                "iL", fcntl.ioctl(s.fileno(), _SIOCGIFCONF, ifconf))[0]
            if outbytes < bufsize:
                break
            n_records *= 2
        data = buf.tobytes()[:outbytes]
        out: Dict[str, str] = {}
        for i in range(0, outbytes, step):
            name = data[i:i + 16].split(b"\0", 1)[0].decode()
            addr = socket.inet_ntoa(data[i + 20:i + 24])
            out[name] = addr
        return out
    finally:
        s.close()


def _can_connect(addr: str, port: int, timeout: float) -> bool:
    try:
        with socket.create_connection((addr, port), timeout=timeout):
            return True
    except OSError:
        return False


def run_agent(host_index: int, n_hosts: int, kv,
              probe_timeout: float = 3.0,
              wait_timeout: float = 60.0) -> List[str]:
    """One host's side of the ring probe (steps 1-2 above).

    Returns the list of next-host interface names this host could reach
    (also PUT to the KV store for the launcher).
    """
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind(("0.0.0.0", 0))
    listener.listen(n_hosts * 8)
    port = listener.getsockname()[1]
    stop = threading.Event()

    def _accept_loop():
        listener.settimeout(0.25)
        while not stop.is_set():
            try:
                conn, _ = listener.accept()
                conn.close()
            except socket.timeout:
                continue
            except OSError:
                break

    acceptor = threading.Thread(target=_accept_loop, daemon=True)
    acceptor.start()
    try:
        ifaces = enumerate_interfaces()
        kv.put(f"nicprobe/addrs/{host_index}",
               json.dumps({n: [a, port] for n, a in ifaces.items()}))
        nxt = (host_index + 1) % n_hosts
        theirs = json.loads(
            kv.wait_get(f"nicprobe/addrs/{nxt}", timeout=wait_timeout))
        routable = [name for name, (addr, p) in theirs.items()
                    if _can_connect(addr, p, probe_timeout)]
        kv.put(f"nicprobe/routable/{nxt}", json.dumps(routable))
        # Keep answering probes until every host has reported and the
        # launcher signals completion (step 3).
        deadline = time.monotonic() + wait_timeout
        while time.monotonic() < deadline:
            if kv.get(_DONE_KEY) is not None:
                break
            time.sleep(0.1)
        return routable
    finally:
        stop.set()
        acceptor.join(timeout=2.0)
        listener.close()


def common_interfaces(kv, n_hosts: int,
                      wait_timeout: float = 60.0) -> List[str]:
    """Launcher side (step 3): intersect the per-host routable sets.

    Returns interface names routable on every host, non-loopback first
    (parity: the intersection in driver_service.py:185-193).  Signals
    the agents to exit before returning.
    """
    try:
        sets = []
        for i in range(n_hosts):
            routable = json.loads(
                kv.wait_get(f"nicprobe/routable/{i}",
                            timeout=wait_timeout))
            sets.append(set(routable))
        common = set.intersection(*sets) if sets else set()
    finally:
        kv.put(_DONE_KEY, "1")
    if not common:
        raise RuntimeError(
            "NIC ring probe found no interface reachable from every "
            f"host (per-host routable sets: {sets}); pass "
            "--network-interface explicitly")
    return sorted(common, key=lambda n: (n.startswith("lo"), n))


def main(argv: Optional[List[str]] = None) -> int:
    """Agent entry point: ``python -m horovod_tpu.runner.nic_probe``.

    Host coordinates and the rendezvous location arrive in the same env
    block every worker gets (HVD_RANK here is the *host* index — the
    launcher runs one agent per host, not per slot).
    """
    from horovod_tpu.runner import secret as secret_mod
    from horovod_tpu.runner.http_client import KVClient

    host_index = int(os.environ["HVD_RANK"])
    n_hosts = int(os.environ["HVD_SIZE"])
    kv = KVClient(os.environ["HVD_RENDEZVOUS_ADDR"],
                  int(os.environ["HVD_RENDEZVOUS_PORT"]),
                  secret=os.environ.get(secret_mod.ENV_VAR))
    routable = run_agent(host_index, n_hosts, kv)
    print(f"nic_probe[{host_index}]: routable -> {routable}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
