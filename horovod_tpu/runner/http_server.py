"""Rendezvous / KV HTTP server.

Parity: ``horovod/run/http/http_server.py`` (RendezvousServer +
KVStoreServer: a scoped PUT/GET/DELETE key-value store that workers use to
exchange addresses at startup and to return run-function results).

Protocol: ``PUT /kv/<key>`` stores the body; ``GET /kv/<key>`` returns it or
404; ``DELETE /kv/<key>`` removes it; ``GET /health`` returns ``ok``.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _store(self) -> Dict[str, bytes]:
        return self.server.kv_store  # type: ignore[attr-defined]

    def do_GET(self):
        if self.path == "/health":
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        with self.server.kv_lock:  # type: ignore[attr-defined]
            val = self._store().get(key) if key else None
        if val is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n)
        if key:
            with self.server.kv_lock:  # type: ignore[attr-defined]
                self._store()[key] = body
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        with self.server.kv_lock:  # type: ignore[attr-defined]
            self._store().pop(key, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class RendezvousServer:
    """Threaded KV server; start() returns the bound port."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.kv_store = {}  # type: ignore[attr-defined]
        self._httpd.kv_lock = threading.Lock()  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-rendezvous",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()

    # Direct access for the launcher process (collecting results).
    def get(self, key: str) -> Optional[bytes]:
        with self._httpd.kv_lock:  # type: ignore[attr-defined]
            return self._httpd.kv_store.get(key)  # type: ignore
