"""Rendezvous / KV HTTP server.

Parity: ``horovod/run/http/http_server.py`` (RendezvousServer +
KVStoreServer: a scoped PUT/GET/DELETE key-value store that workers use to
exchange addresses at startup and to return run-function results).

Protocol: ``PUT /kv/<key>`` stores the body; ``GET /kv/<key>`` returns it or
404; ``DELETE /kv/<key>`` removes it; ``GET /kvlist/<prefix>`` returns the
matching keys, newline-separated (the elastic driver enumerates pending
joiners this way); ``GET /health`` returns ``ok``.

When the server holds a job secret (parity: ``run/common/util/secret.py``
HMAC framing), every ``/kv/`` request must carry a valid
``X-HVD-Auth: HMAC-SHA256(method, path, body)`` header or it is rejected
with 403 — an unauthenticated client on the network can neither read nor
poison rendezvous state.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.runner import secret as secret_mod


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _chaos_unavailable(self) -> bool:
        """Chaos hook: an injected fault turns this request into a 503 —
        the retryable shed a loaded/restarting rendezvous server would
        produce."""
        try:
            _fi.fire("kv.server.request", f"{self.command} {self.path}")
        except _fi.InjectedFault:
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return True
        return False

    def _store(self) -> Dict[str, bytes]:
        return self.server.kv_store  # type: ignore[attr-defined]

    def _authorized(self, body: bytes = b"") -> bool:
        secret = self.server.kv_secret  # type: ignore[attr-defined]
        if secret is None:
            return True
        return secret_mod.verify(
            secret, self.command, self.path, body,
            self.headers.get(secret_mod.HEADER, ""))

    def _reject(self):
        self.send_response(403)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        if self._chaos_unavailable():
            return
        if self.path == "/health":
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if not self._authorized():
            self._reject()
            return
        if self.path.startswith("/kvlist/"):
            prefix = self.path[len("/kvlist/"):]
            with self.server.kv_lock:  # type: ignore[attr-defined]
                keys = sorted(k for k in self._store()
                              if k.startswith(prefix))
            body = "\n".join(keys).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        with self.server.kv_lock:  # type: ignore[attr-defined]
            val = self._store().get(key) if key else None
        if val is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n)
        # Chaos check sits after the body read so a 503 leaves the
        # keep-alive stream framed correctly.
        if self._chaos_unavailable():
            return
        if not self._authorized(body):
            self._reject()
            return
        if key:
            with self.server.kv_lock:  # type: ignore[attr-defined]
                self._store()[key] = body
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        if self._chaos_unavailable():
            return
        if not self._authorized():
            self._reject()
            return
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        with self.server.kv_lock:  # type: ignore[attr-defined]
            self._store().pop(key, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class RendezvousServer:
    """Threaded KV server; start() returns the bound port.

    ``secret``: when given, requests must be HMAC-signed (see module
    docstring); ``None`` (default) keeps the open behavior for loopback
    test fixtures."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 secret: Optional[str] = None):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.kv_store = {}  # type: ignore[attr-defined]
        self._httpd.kv_lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd.kv_secret = secret  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-rendezvous",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()

    # Direct access for the launcher process (collecting results).
    def get(self, key: str) -> Optional[bytes]:
        with self._httpd.kv_lock:  # type: ignore[attr-defined]
            return self._httpd.kv_store.get(key)  # type: ignore
