"""Rendezvous / KV HTTP server.

Parity: ``horovod/run/http/http_server.py`` (RendezvousServer +
KVStoreServer: a scoped PUT/GET/DELETE key-value store that workers use to
exchange addresses at startup and to return run-function results).

Protocol: ``PUT /kv/<key>`` stores the body; ``GET /kv/<key>`` returns it or
404; ``DELETE /kv/<key>`` removes it; ``GET /kvlist/<prefix>`` returns the
matching keys, newline-separated (the elastic driver enumerates pending
joiners this way); ``GET /health`` returns ``ok``; ``GET /kvsync`` returns
the full store as JSON (base64 values) so a warm standby can catch up.

When the server holds a job secret (parity: ``run/common/util/secret.py``
HMAC framing), every ``/kv/`` request must carry a valid
``X-HVD-Auth: HMAC-SHA256(method, path, body)`` header or it is rejected
with 403 — an unauthenticated client on the network can neither read nor
poison rendezvous state.

Replication: a server constructed with ``mirrors=[(host, port), ...]``
write-through-forwards every accepted ``PUT``/``DELETE`` to each mirror
over the same HMAC'd protocol (chaos site ``kv.mirror``; a failed mirror
write is logged and dropped — the standby's ``/kvsync`` catch-up on
restart is the repair path).  Clients fail over between primary and
standbys via ``HVD_KV_ADDRS`` (see runner/http_client.py).

Epoch fencing (docs/fault_tolerance.md "Hierarchical control plane,
fencing, and quorum"): a mutation on an ``elastic/*`` key may carry an
``X-HVD-Epoch: <n>`` header — the writer's membership epoch.  The server
remembers the newest epoch seen per elastic namespace and answers any
OLDER write with 409, so a zombie (evicted rank resuming after the gang
re-formed) cannot corrupt the new incarnation's rosters or assignments.
The header forwards to mirrors so standbys fence identically; writes
without the header (bootstrap, non-elastic keys) are untouched.
"""

from __future__ import annotations

import base64
import json
import sys
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.runner import secret as secret_mod
from horovod_tpu.telemetry import registry as _tmx

# Writer's membership epoch on elastic/* mutations (http_client.py
# stamps it from HVD_ELASTIC_EPOCH; wire-protocol cousin: TAG_FENCE).
EPOCH_HEADER = "X-HVD-Epoch"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _chaos_unavailable(self) -> bool:
        """Chaos hook: an injected fault turns this request into a 503 —
        the retryable shed a loaded/restarting rendezvous server would
        produce."""
        try:
            _fi.fire("kv.server.request", f"{self.command} {self.path}")
        except _fi.InjectedFault:
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return True
        return False

    def _store(self) -> Dict[str, bytes]:
        return self.server.kv_store  # type: ignore[attr-defined]

    def _authorized(self, body: bytes = b"") -> bool:
        secret = self.server.kv_secret  # type: ignore[attr-defined]
        if secret is None:
            return True
        return secret_mod.verify(
            secret, self.command, self.path, body,
            self.headers.get(secret_mod.HEADER, ""))

    def _reject(self):
        self.send_response(403)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _fenced(self, key: Optional[str]) -> bool:
        """Epoch fence: True (and 409 already sent) when this mutation
        carries a stale membership epoch for its elastic namespace.
        Writes without the header — bootstrap addresses, results,
        non-elastic keys — never fence."""
        hdr = self.headers.get(EPOCH_HEADER)
        if not key or hdr is None:
            return False
        idx = key.find("elastic/")
        if idx < 0:
            return False
        try:
            epoch = int(hdr)
        except ValueError:
            return False
        scope = key[:idx]
        srv = self.server
        with srv.kv_lock:  # type: ignore[attr-defined]
            newest = srv.kv_epochs.get(scope, -1)  # type: ignore
            if epoch < newest:
                stale = True
            else:
                stale = False
                srv.kv_epochs[scope] = epoch  # type: ignore
        if stale:
            _tmx.inc_counter("hvd_fenced_writes_total")
            body = (f"fenced: epoch {epoch} is stale, the gang "
                    f"re-formed at epoch {newest}").encode("utf-8")
            self.send_response(409)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        return stale

    def do_GET(self):
        if self._chaos_unavailable():
            return
        if self.path == "/health":
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if not self._authorized():
            self._reject()
            return
        if self.path == "/kvsync":
            # Full-state dump for standby catch-up: {key: b64(value)}.
            with self.server.kv_lock:  # type: ignore[attr-defined]
                snap = {k: base64.b64encode(v).decode("ascii")
                        for k, v in self._store().items()}
            body = json.dumps(snap).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.startswith("/kvlist/"):
            prefix = self.path[len("/kvlist/"):]
            with self.server.kv_lock:  # type: ignore[attr-defined]
                keys = sorted(k for k in self._store()
                              if k.startswith(prefix))
            body = "\n".join(keys).encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        with self.server.kv_lock:  # type: ignore[attr-defined]
            val = self._store().get(key) if key else None
        if val is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_PUT(self):
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n)
        # Chaos check sits after the body read so a 503 leaves the
        # keep-alive stream framed correctly.
        if self._chaos_unavailable():
            return
        if not self._authorized(body):
            self._reject()
            return
        if self._fenced(key):
            return
        if key:
            with self.server.kv_lock:  # type: ignore[attr-defined]
                self._store()[key] = body
            self.server.mirror_write(  # type: ignore[attr-defined]
                "PUT", key, body,
                epoch=self.headers.get(EPOCH_HEADER))
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        if self._chaos_unavailable():
            return
        if not self._authorized():
            self._reject()
            return
        key = self.path[len("/kv/"):] if self.path.startswith("/kv/") else None
        if self._fenced(key):
            return
        with self.server.kv_lock:  # type: ignore[attr-defined]
            self._store().pop(key, None)
        if key:
            self.server.mirror_write(  # type: ignore[attr-defined]
                "DELETE", key, None,
                epoch=self.headers.get(EPOCH_HEADER))
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class _KVServer(ThreadingHTTPServer):
    """ThreadingHTTPServer + write-through mirroring to warm standbys."""

    daemon_threads = True

    kv_store: Dict[str, bytes]
    kv_lock: threading.Lock
    kv_secret: Optional[str]
    kv_mirrors: List[Tuple[str, int]]
    kv_mirror_timeout: float
    # Epoch fence state: elastic namespace prefix -> newest epoch seen.
    kv_epochs: Dict[str, int]

    def mirror_write(self, method: str, key: str,
                     body: Optional[bytes],
                     epoch: Optional[str] = None) -> None:
        """Forward an accepted mutation to every standby.  Best-effort:
        a dead/slow mirror costs one short timeout, never the request —
        the standby repairs itself on restart via ``/kvsync``.  The
        ``kv.mirror`` chaos site drops individual forwards so tests can
        prove a torn mirror stream is absorbed."""
        for host, port in self.kv_mirrors:
            try:
                _fi.fire("kv.mirror", f"{method} {key} -> {host}:{port}")
                path = f"/kv/{key}"
                req = urllib.request.Request(
                    f"http://{host}:{port}{path}", data=body,
                    method=method)
                if epoch is not None:
                    # Standbys fence identically: a zombie that fails
                    # over to a mirror after a primary 409 gets the
                    # same answer there.
                    req.add_header(EPOCH_HEADER, epoch)
                if self.kv_secret is not None:
                    req.add_header(secret_mod.HEADER, secret_mod.sign(
                        self.kv_secret, method, path, body or b""))
                with urllib.request.urlopen(
                        req, timeout=self.kv_mirror_timeout):
                    pass
            except Exception:
                # Mirror unreachable / chaos-dropped: the write is
                # already durable on this server; skip the standby.
                pass


class RendezvousServer:
    """Threaded KV server; start() returns the bound port.

    ``secret``: when given, requests must be HMAC-signed (see module
    docstring); ``None`` (default) keeps the open behavior for loopback
    test fixtures.  ``mirrors``: optional ``(host, port)`` standbys that
    receive a write-through copy of every PUT/DELETE."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 secret: Optional[str] = None,
                 mirrors: Optional[Sequence[Tuple[str, int]]] = None,
                 mirror_timeout: float = 2.0):
        self._httpd = _KVServer((host, port), _Handler)
        self._httpd.kv_store = {}
        self._httpd.kv_epochs = {}
        self._httpd.kv_lock = threading.Lock()
        self._httpd.kv_secret = secret
        self._httpd.kv_mirrors = [(h, int(p)) for h, p in (mirrors or [])]
        self._httpd.kv_mirror_timeout = mirror_timeout
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def mirrors(self) -> List[Tuple[str, int]]:
        return list(self._httpd.kv_mirrors)

    def set_mirrors(self, mirrors: Sequence[Tuple[str, int]]) -> None:
        self._httpd.kv_mirrors = [(h, int(p)) for h, p in mirrors]

    def start(self, name: str = "hvd-rendezvous") -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=name,
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()

    # Direct access for the launcher process (collecting results).
    def get(self, key: str) -> Optional[bytes]:
        with self._httpd.kv_lock:  # type: ignore[attr-defined]
            return self._httpd.kv_store.get(key)  # type: ignore

    def sync_from(self, host: str, port: int,
                  timeout: float = 5.0) -> bool:
        """Standby catch-up: replace this store with the source server's
        ``/kvsync`` snapshot.  Returns False (leaving the store alone)
        when the source is unreachable — a standby that starts before
        its primary simply begins empty and fills via mirroring."""
        path = "/kvsync"
        req = urllib.request.Request(f"http://{host}:{port}{path}",
                                     method="GET")
        secret = self._httpd.kv_secret
        if secret is not None:
            req.add_header(secret_mod.HEADER, secret_mod.sign(
                secret, "GET", path, b""))
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                snap = json.loads(r.read().decode("utf-8"))
        except Exception:
            return False
        store = {k: base64.b64decode(v) for k, v in snap.items()}
        with self._httpd.kv_lock:
            self._httpd.kv_store.clear()
            self._httpd.kv_store.update(store)
        return True


def _main(argv: Optional[List[str]] = None) -> int:
    """Standalone KV server process (``python -m
    horovod_tpu.runner.http_server``) — lets tests and operators run the
    primary and its standbys as separate killable processes.  The secret
    comes from ``HVD_SECRET_KEY`` when set."""
    import argparse
    import os
    import signal

    parser = argparse.ArgumentParser(
        prog="horovod_tpu.runner.http_server",
        description="Standalone rendezvous KV server (primary or standby).")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--port-file", default="",
                        help="write the bound port here (atomic rename)")
    parser.add_argument("--mirror", action="append", default=[],
                        metavar="HOST:PORT",
                        help="standby to write-through mirror to "
                             "(repeatable)")
    parser.add_argument("--sync-from", default="", metavar="HOST:PORT",
                        help="catch up from this server's /kvsync at start")
    args = parser.parse_args(argv)

    from horovod_tpu.runner.http_client import parse_kv_addrs

    mirrors = [parse_kv_addrs(m)[0] for m in args.mirror]
    secret = os.environ.get(secret_mod.ENV_VAR) or None
    server = RendezvousServer(host=args.host, port=args.port,
                              secret=secret, mirrors=mirrors)
    if args.sync_from:
        src = parse_kv_addrs(args.sync_from)[0]
        server.sync_from(src[0], src[1])
    port = server.start(name="hvd-kv-main")
    if args.port_file:
        with open(args.port_file + ".tmp", "w") as f:
            f.write(str(port))
        os.replace(args.port_file + ".tmp", args.port_file)
    print(f"KV {args.host}:{port}", flush=True)

    done = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: done.set())
    signal.signal(signal.SIGINT, lambda *_: done.set())
    done.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(_main())
