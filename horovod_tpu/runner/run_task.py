"""Worker-side stub for run-func mode: fetch the cloudpickled fn from the
rendezvous KV store, init the runtime, run, post the result.

Parity: ``horovod/run/run_task.py`` + ``task_fn.py`` (the reference ships
the fn through its KVStoreServer the same way).
"""

from __future__ import annotations

import os
import sys
import traceback


def main() -> int:
    import cloudpickle

    from horovod_tpu.runner.http_client import KVClient

    addr = os.environ["HVD_RENDEZVOUS_ADDR"]
    port = int(os.environ["HVD_RENDEZVOUS_PORT"])
    rank = int(os.environ.get("HVD_RANK", "0"))
    kv = KVClient(addr, port)
    blob = kv.get_bytes("runfunc/fn")
    if blob is None:
        print("run_task: no function in KV store", file=sys.stderr)
        return 1
    fn, args, kwargs = cloudpickle.loads(blob)

    import horovod_tpu as hvd

    hvd.init()
    try:
        result = fn(*args, **kwargs)
        payload = cloudpickle.dumps((True, result))
        ret = 0
    except Exception:
        payload = cloudpickle.dumps((False, traceback.format_exc()))
        ret = 1
    finally:
        hvd.shutdown()
    kv.put(f"runfunc/result/{rank}", payload)
    return ret


if __name__ == "__main__":
    sys.exit(main())
