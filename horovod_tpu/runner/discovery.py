"""TPU pod topology discovery.

The reference learns world topology from MPI or launcher-injected env
(``HOROVOD_RANK``, gloo_context.cc:44-49).  On TPU pods the runtime itself
knows the topology: each host process belongs to a slice with a bounded
set of chips.  This module turns that metadata into the same
rank/local/cross coordinates the controller uses, with no ssh or env
injection needed.

Sources, in priority order:
1. ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` (GCE TPU VM metadata, set
   on every TPU VM worker),
2. ``MEGASCALE_SLICE_ID`` / ``MEGASCALE_NUM_SLICES`` for multislice (the
   DCN/cross axis),
3. an initialized ``jax.distributed`` runtime (process_index/count).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class PodTopology:
    rank: int            # host process index in the whole job
    size: int            # total host processes
    local_rank: int      # index within the slice
    local_size: int      # hosts per slice
    cross_rank: int      # slice index (DCN coordinate)
    cross_size: int      # number of slices


def block_topology_ok(rank: int, size: int, local_rank: int,
                      local_size: int, cross_rank: int,
                      cross_size: int) -> bool:
    """True for a genuine two-level topology in the launcher's homogeneous
    block rank layout (rank = cross_rank*local_size + local_rank) — the
    precondition for the hierarchical data plane (the single shared copy
    of this invariant; the C++ engine mirrors it in
    ``Engine::HierarchicalTopologyOk``)."""
    return (local_size > 1 and cross_size > 1
            and local_size * cross_size == size
            and rank == cross_rank * local_size + local_rank)


def from_tpu_metadata() -> Optional[PodTopology]:
    """Build topology from TPU VM env metadata; None when not on a pod."""
    env = os.environ
    worker_id = env.get("TPU_WORKER_ID")
    hostnames = env.get("TPU_WORKER_HOSTNAMES")
    if worker_id is None or hostnames is None:
        return None
    try:
        local_rank = int(worker_id)
        cross_rank = int(env.get("MEGASCALE_SLICE_ID", "0"))
        cross_size = int(env.get("MEGASCALE_NUM_SLICES", "1"))
    except ValueError:
        # Malformed pod metadata (e.g. a k8s setup exporting a worker
        # *name*): treat as "not on a pod" rather than crashing init().
        return None
    local_size = len([h for h in hostnames.split(",") if h.strip()])
    return PodTopology(
        rank=cross_rank * local_size + local_rank,
        size=cross_size * local_size,
        local_rank=local_rank,
        local_size=local_size,
        cross_rank=cross_rank,
        cross_size=cross_size,
    )


def from_jax_distributed() -> Optional[PodTopology]:
    try:
        import jax

        n = jax.process_count()
    except Exception:
        return None
    if n <= 1:
        return None
    r = jax.process_index()
    return PodTopology(rank=r, size=n, local_rank=0, local_size=1,
                       cross_rank=r, cross_size=n)


def discover() -> Optional[PodTopology]:
    return from_tpu_metadata() or from_jax_distributed()
