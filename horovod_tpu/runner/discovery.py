"""TPU pod topology discovery.

The reference learns world topology from MPI or launcher-injected env
(``HOROVOD_RANK``, gloo_context.cc:44-49).  On TPU pods the runtime itself
knows the topology: each host process belongs to a slice with a bounded
set of chips.  This module turns that metadata into the same
rank/local/cross coordinates the controller uses, with no ssh or env
injection needed.

Sources, in priority order:
1. ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` (GCE TPU VM metadata, set
   on every TPU VM worker),
2. ``MEGASCALE_SLICE_ID`` / ``MEGASCALE_NUM_SLICES`` for multislice (the
   DCN/cross axis),
3. an initialized ``jax.distributed`` runtime (process_index/count).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class PodTopology:
    rank: int            # host process index in the whole job
    size: int            # total host processes
    local_rank: int      # index within the slice
    local_size: int      # hosts per slice
    cross_rank: int      # slice index (DCN coordinate)
    cross_size: int      # number of slices


def block_topology_ok(rank: int, size: int, local_rank: int,
                      local_size: int, cross_rank: int,
                      cross_size: int) -> bool:
    """True for a genuine two-level topology in the launcher's homogeneous
    block rank layout (rank = cross_rank*local_size + local_rank) — the
    precondition for the hierarchical data plane (the single shared copy
    of this invariant; the C++ engine mirrors it in
    ``Engine::HierarchicalTopologyOk``)."""
    return (local_size > 1 and cross_size > 1
            and local_size * cross_size == size
            and rank == cross_rank * local_size + local_rank)


def from_tpu_metadata() -> Optional[PodTopology]:
    """Build topology from TPU VM env metadata; None when not on a pod."""
    env = os.environ
    worker_id = env.get("TPU_WORKER_ID")
    hostnames = env.get("TPU_WORKER_HOSTNAMES")
    if worker_id is None or hostnames is None:
        return None
    try:
        local_rank = int(worker_id)
        cross_rank = int(env.get("MEGASCALE_SLICE_ID", "0"))
        cross_size = int(env.get("MEGASCALE_NUM_SLICES", "1"))
    except ValueError:
        # Malformed pod metadata (e.g. a k8s setup exporting a worker
        # *name*): treat as "not on a pod" rather than crashing init().
        return None
    local_size = len([h for h in hostnames.split(",") if h.strip()])
    return PodTopology(
        rank=cross_rank * local_size + local_rank,
        size=cross_size * local_size,
        local_rank=local_rank,
        local_size=local_size,
        cross_rank=cross_rank,
        cross_size=cross_size,
    )


def from_jax_distributed() -> Optional[PodTopology]:
    try:
        import jax

        n = jax.process_count()
    except Exception:
        return None
    if n <= 1:
        return None
    r = jax.process_index()
    return PodTopology(rank=r, size=n, local_rank=0, local_size=1,
                       cross_rank=r, cross_size=n)


# MPI-launcher env schemas: (rank, size, local_rank, local_size) names.
# Lets `hvd.init()` work under mpirun / srun / jsrun with no HVD_* env —
# the reference gets this from MPI_Init; we read the launcher's env.
_MPI_SCHEMAS = (
    ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE",
     "OMPI_COMM_WORLD_LOCAL_RANK", "OMPI_COMM_WORLD_LOCAL_SIZE"),
    # IBM JSM (jsrun on LSF/Summit) namespace exports.
    ("JSM_NAMESPACE_RANK", "JSM_NAMESPACE_SIZE",
     "JSM_NAMESPACE_LOCAL_RANK", "JSM_NAMESPACE_LOCAL_SIZE"),
    ("PMIX_RANK", "PMIX_SIZE", "PMIX_LOCAL_RANK", "PMIX_LOCAL_SIZE"),
    ("PMI_RANK", "PMI_SIZE", None, None),
    ("SLURM_PROCID", "SLURM_NTASKS", "SLURM_LOCALID",
     "SLURM_NTASKS_PER_NODE"),
)


def from_mpi_env() -> Optional[PodTopology]:
    """Topology from an MPI-style launcher's environment (Open MPI /
    PMIx / PMI / Slurm).  None when not launched that way."""
    env = os.environ
    for rank_k, size_k, lrank_k, lsize_k in _MPI_SCHEMAS:
        if rank_k not in env or size_k not in env:
            continue
        try:
            rank = int(env[rank_k])
            size = int(env[size_k])
            local_rank = int(env[lrank_k]) if lrank_k and lrank_k in env \
                else 0
            local_size = int(env[lsize_k]) if lsize_k and lsize_k in env \
                else 1
        except ValueError:
            continue
        if size <= 0:
            continue
        cross_rank = rank // local_size if local_size > 0 else 0
        # The hierarchical data plane assumes the block rank layout;
        # launchers mapping by node (mpirun --map-by node) violate it, and
        # ranks must not *disagree* about hierarchy — degrade to a flat
        # local topology unless the layout verifiably holds.
        if (local_size <= 0 or size % local_size
                or rank != cross_rank * local_size + local_rank):
            local_rank, local_size = 0, 1
            cross_rank = rank
        return PodTopology(
            rank=rank, size=size,
            local_rank=local_rank, local_size=local_size,
            cross_rank=cross_rank,
            cross_size=size // local_size,
        )
    return None


def discover() -> Optional[PodTopology]:
    return from_tpu_metadata() or from_mpi_env() or from_jax_distributed()
