"""mpirun launch mode: drive an MPI cluster from ``hvdrun``.

Role parity: ``run/mpi_run.py:81-158`` — the reference builds one
``mpirun`` command line (implementation detection, per-variable ``-x``
env forwarding, NIC selection, large-cluster workarounds) and lets MPI
fan the job out.  Redesigned for this stack: the launched tasks need no
MPI linkage at all — they read rank/size from the env mpirun sets
(``discovery.from_mpi_env``: OMPI_*/PMI_*/PMIX_*) and rendezvous against
the launcher's HTTP KV server exactly like spawned workers, so ``mpirun``
is purely a remote-process fan-out.

Secrets: env values (job secret, rendezvous coordinates) are exported by
NAME (OpenMPI ``-x VAR``, Hydra ``-genvlist VAR``), with values read
from the launcher's process environment — never on the ps-visible
command line (same policy as the jsrun and ssh paths).
"""

from __future__ import annotations

import re
import subprocess
from typing import Dict, List, Optional, Sequence

from horovod_tpu.runner.hosts import SlotInfo


class MpiImpl:
    OPENMPI = "openmpi"
    MPICH = "mpich"  # Hydra family: MPICH, Intel MPI, MVAPICH


def detect_mpi_impl(mpirun: str = "mpirun") -> Optional[str]:
    """Which MPI flavor ``mpirun`` belongs to, or None when unusable.

    Parity: ``run/mpi_run.py`` probes ``mpirun --version`` and matches
    "Open MPI"/"OpenRTE"; everything Hydra-shaped (MPICH, Intel MPI,
    MVAPICH) takes the mpich command form.
    """
    try:
        out = subprocess.run(
            [mpirun, "--version"], capture_output=True, text=True,
            timeout=15)
    except (OSError, subprocess.TimeoutExpired):
        return None
    text = (out.stdout or "") + (out.stderr or "")
    return classify_mpi_version(text)


def classify_mpi_version(text: str) -> Optional[str]:
    if re.search(r"Open(?:\s+MPI|RTE|\s+RTE)", text, re.IGNORECASE):
        return MpiImpl.OPENMPI
    if re.search(r"HYDRA|MPICH|Intel\(R\) MPI|MVAPICH", text,
                 re.IGNORECASE):
        return MpiImpl.MPICH
    return None


def _host_list(slots: Sequence[SlotInfo]) -> List[str]:
    """Ordered unique hostnames with slot counts, e.g. ['a:2', 'b:2']."""
    counts: Dict[str, int] = {}
    order: List[str] = []
    for s in slots:
        if s.hostname not in counts:
            order.append(s.hostname)
        counts[s.hostname] = counts.get(s.hostname, 0) + 1
    return [f"{h}:{counts[h]}" for h in order]


# Above this task count OpenMPI's rsh tree-spawn needs throttling and a
# wider routing radix (parity: run/mpi_run.py's large-cluster flags).
_LARGE_CLUSTER_NP = 64


def mpirun_command(np: int, slots: Sequence[SlotInfo],
                   command: Sequence[str],
                   env_var_names: Sequence[str],
                   impl: str = MpiImpl.OPENMPI,
                   mpirun: str = "mpirun",
                   nics: Optional[Sequence[str]] = None,
                   ssh_port: Optional[int] = None,
                   ssh_identity_file: Optional[str] = None,
                   extra_args: Optional[Sequence[str]] = None) -> List[str]:
    """Build the single ``mpirun`` invocation for the job.

    ``env_var_names`` are forwarded by name (values stay in the
    launcher's environment).  OpenMPI gets the reference's TCP-only
    binding (``-mca pml ob1 -mca btl tcp,self``) because the tasks use
    MPI for process placement only — the data plane is this stack's own.
    """
    hostlist = _host_list(slots)
    if impl == MpiImpl.OPENMPI:
        cmd = [mpirun, "--allow-run-as-root", "--tag-output",
               "-np", str(np),
               "-H", ",".join(hostlist),
               "--map-by", "slot",
               "-mca", "pml", "ob1",
               "-mca", "btl", "tcp,self"]
        if np >= _LARGE_CLUSTER_NP:
            cmd += ["-mca", "plm_rsh_num_concurrent",
                    str(len(hostlist)),
                    "-mca", "routed", "radix:600"]
        if nics:
            cmd += ["-mca", "btl_tcp_if_include", ",".join(nics)]
        rsh_args = []
        if ssh_port:
            rsh_args += ["-p", str(ssh_port)]
        if ssh_identity_file:
            rsh_args += ["-i", ssh_identity_file]
        if rsh_args:
            cmd += ["-mca", "plm_rsh_args", " ".join(rsh_args)]
        for name in env_var_names:
            cmd += ["-x", name]
        if extra_args:
            cmd += list(extra_args)
        return cmd + list(command)
    if impl == MpiImpl.MPICH:
        if ssh_port or ssh_identity_file:
            # Hydra routes ssh options through launcher-exec scripts,
            # not flags; refusing beats a silent default-ssh failure.
            raise ValueError(
                "--ssh-port/--ssh-identity-file are not supported with "
                "a Hydra/MPICH mpirun; configure ssh via ~/.ssh/config "
                "or use the OpenMPI or spawn launcher")
        # Hydra honors host:count in -hosts, preserving the requested
        # per-host slot layout.
        cmd = [mpirun, "-np", str(np),
               "-hosts", ",".join(hostlist)]
        if nics:
            cmd += ["-iface", nics[0]]
        if env_var_names:
            cmd += ["-genvlist", ",".join(env_var_names)]
        if extra_args:
            cmd += list(extra_args)
        return cmd + list(command)
    raise ValueError(f"unknown MPI implementation {impl!r}")
