"""Launcher subsystem: rendezvous server, host/slot allocation, CLI.

Parity: ``horovod/run/`` (horovodrun CLI, gloo_run slot allocation,
RendezvousServer).  The TPU twist: besides ``-H host:slots`` the launcher
can derive world topology from TPU slice metadata (see ``discovery.py``).

``from horovod_tpu.runner.run import run`` is the programmatic entry
point (parity: ``horovod.run.run``) — run a function on N ranks and
collect per-rank results.  (Not re-exported at package level: binding
the name ``run`` on the package would shadow the module for
``import horovod_tpu.runner.run``.)
"""
