"""Launcher subsystem: rendezvous server, host/slot allocation, CLI.

Parity: ``horovod/run/`` (horovodrun CLI, gloo_run slot allocation,
RendezvousServer).  The TPU twist: besides ``-H host:slots`` the launcher
can derive world topology from TPU slice metadata (see ``discovery.py``).
"""
