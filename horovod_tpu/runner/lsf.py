"""LSF allocation introspection + jsrun launch (Summit-style clusters).

Role parity: ``horovod/run/util/lsf.py`` (LSFUtils reads the job's host
allocation from LSB env) and ``run/js_run.py`` (builds one ``jsrun``
invocation instead of per-host ssh).  Redesigned around this stack's
rendezvous: ``jsrun`` fans the job out and sets PMIX env on every task,
workers derive rank/size from it (``runner.discovery.from_mpi_env``) and
rendezvous against the launcher's HTTP server — no erf files and no MPI
linkage needed.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

from horovod_tpu.runner.hosts import HostSlots


def in_lsf_job() -> bool:
    return "LSB_JOBID" in os.environ


def lsf_hosts() -> List[HostSlots]:
    """Hosts and slots of the current LSF allocation.

    Sources, in priority order: ``LSB_DJOB_HOSTFILE`` (one hostname per
    line, repeated per slot), then ``LSB_MCPU_HOSTS`` ("host n host n"
    pairs).  The batch (launch) host contributes no compute slots and is
    dropped when other hosts exist, matching the reference's LSFUtils.
    """
    env = os.environ
    counts: dict = {}
    order: List[str] = []

    hostfile = env.get("LSB_DJOB_HOSTFILE")
    if hostfile and os.path.exists(hostfile):
        with open(hostfile) as f:
            for line in f:
                h = line.strip()
                if not h:
                    continue
                if h not in counts:
                    order.append(h)
                counts[h] = counts.get(h, 0) + 1
    elif env.get("LSB_MCPU_HOSTS"):
        toks = env["LSB_MCPU_HOSTS"].split()
        for h, n in zip(toks[::2], toks[1::2]):
            if h not in counts:
                order.append(h)
            counts[h] = counts.get(h, 0) + int(n)
    else:
        return []

    # First entry is the batch node; it holds the launcher, not workers.
    if len(order) > 1:
        order = order[1:]
    return [HostSlots(h, counts[h]) for h in order]


def jsrun_command(np: int, command: Sequence[str],
                  cpus_per_task: int = 1,
                  extra_args: Optional[Sequence[str]] = None) -> List[str]:
    """One ``jsrun`` line launching ``np`` tasks of ``command``.

    Tasks read rank/size from the PMIX env jsrun sets
    (``discovery.from_mpi_env``).  Rendezvous coordinates and the job
    secret travel in the *process environment* of the jsrun invocation —
    jsrun propagates the submitting environment to tasks — never on the
    (ps-visible) command line.
    """
    cmd = ["jsrun",
           "--np", str(np),
           "--cpu_per_rs", str(max(1, cpus_per_task))]
    if extra_args:
        cmd += list(extra_args)
    return cmd + list(command)
