"""KV client for the rendezvous server.

Parity: ``horovod/run/http/http_client.py`` (read_data_from_kvstore /
put_data_into_kvstore), plus HMAC request signing against the launcher's
job secret (``run/common/util/secret.py`` pattern).  The secret defaults
to the ``HVD_SECRET_KEY`` environment variable — the channel the launcher
ships it to workers on — so every existing call site signs automatically
when a secret is in play.

Every request retries with capped exponential backoff + jitter
(``HVD_KV_RETRIES`` attempts, per-attempt timeout ``HVD_KV_TIMEOUT``):
a rendezvous server that is still binding, restarting, or sheds a
request under load (5xx) costs a delay, not the job.  Client errors
(4xx) are never retried — a 404 is a legitimate "key not there yet"
answer the callers poll on.

When ``HVD_KV_ADDRS`` holds a comma-separated ``host:port`` list the
client treats it as an ordered endpoint set (primary first, warm
standbys after) and rotates to the next endpoint on every retryable
failure, inside the same retry budget.  The HMAC signature covers
method+path+body but never the host, so a failover needs no re-signing.
Unset, behavior is byte-identical to the single-address client.
"""

from __future__ import annotations

import os
import re
import socket
import time
import urllib.error
import urllib.request
import zlib
from typing import List, Optional, Tuple

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.common.retry import retry_call
from horovod_tpu.common.types import FencedError
from horovod_tpu.runner import secret as secret_mod
from horovod_tpu.runner.http_server import EPOCH_HEADER
from horovod_tpu.telemetry import blackbox as _bb
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.utils import env as env_util


def _count_retry(attempt_index: int, exc: BaseException) -> None:
    # Invoked by retry_call before each backoff sleep; a no-op load +
    # None check when telemetry is off.
    _tmx.inc_counter("hvd_kv_retries_total")
    _bb.note("kv.retry", 0, attempt=int(attempt_index),
             error=type(exc).__name__)


def _retryable(e: BaseException) -> bool:
    if isinstance(e, urllib.error.HTTPError):
        return e.code >= 500
    return isinstance(e, (urllib.error.URLError, ConnectionError,
                          socket.timeout, TimeoutError, OSError))


def parse_kv_addrs(spec: str) -> List[Tuple[str, int]]:
    """Parse a comma-separated ``host:port`` endpoint list (the
    ``HVD_KV_ADDRS`` format).  Raises ``ValueError`` with an
    actionable message on any malformed entry — the launcher turns
    that into an exit-2 usage error before a single worker starts."""
    endpoints: List[Tuple[str, int]] = []
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            raise ValueError(
                f"HVD_KV_ADDRS has an empty entry in {spec!r}; expected "
                f"a comma-separated host:port list")
        host, sep, port_s = entry.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"HVD_KV_ADDRS entry {entry!r} is not host:port")
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(
                f"HVD_KV_ADDRS entry {entry!r} has a non-numeric "
                f"port {port_s!r}") from None
        if not 1 <= port <= 65535:
            raise ValueError(
                f"HVD_KV_ADDRS entry {entry!r} has port {port} outside "
                f"1..65535")
        endpoints.append((host, port))
    if not endpoints:
        raise ValueError("HVD_KV_ADDRS is empty")
    return endpoints


class KVClient:
    def __init__(self, host: str, port: int,
                 secret: Optional[str] = None):
        addrs = os.environ.get(env_util.KV_ADDRS, "").strip()
        if addrs:
            self.endpoints = parse_kv_addrs(addrs)
        else:
            self.endpoints = [(host, int(port))]
        self._active = 0
        self.secret = (secret if secret is not None
                       else os.environ.get(secret_mod.ENV_VAR) or None)
        self.attempts = max(1, env_util.get_int("HVD_KV_RETRIES", 4))
        self.timeout = env_util.get_float("HVD_KV_TIMEOUT", 10.0)
        self.retry_base = env_util.get_float("HVD_KV_RETRY_BASE_S", 0.05)
        self.retry_max = env_util.get_float("HVD_KV_RETRY_MAX_S", 2.0)

    @property
    def host(self) -> str:
        return self.endpoints[self._active][0]

    @property
    def port(self) -> int:
        return self.endpoints[self._active][1]

    def _rotate_endpoint(self) -> None:
        # Deterministic failover order: primary, standby 1, standby 2,
        # wrap.  Sticky across calls — once a standby answers, stay on
        # it rather than re-probing the dead primary every request.
        if len(self.endpoints) > 1:
            self._active = (self._active + 1) % len(self.endpoints)

    def _url(self, path: str) -> str:
        return f"http://{self.host}:{self.port}{path}"

    def _request(self, key: str, method: str, body: Optional[bytes] = None,
                 endpoint: str = "/kv/"):
        path = f"{endpoint}{key}"
        req = urllib.request.Request(self._url(path), data=body,
                                     method=method)
        if method in ("PUT", "DELETE") and "elastic/" in key:
            # Epoch fence (docs/fault_tolerance.md): stamp elastic
            # mutations with this process's membership epoch so a
            # zombie's stale write gets a 409 instead of corrupting the
            # re-formed gang's rosters.  Non-elastic processes carry no
            # epoch and never fence.
            epoch = os.environ.get(env_util.ELASTIC_EPOCH, "")
            if epoch:
                req.add_header(EPOCH_HEADER, epoch)
        if self.secret is not None:
            req.add_header(secret_mod.HEADER, secret_mod.sign(
                self.secret, method, path, body or b""))
        return req

    def _with_retry(self, fn, site: str, key: str):
        def attempt():
            _fi.fire(site, key)
            return fn()

        def on_retry(attempt_index, exc):
            _count_retry(attempt_index, exc)
            # A retryable failure on a multi-endpoint client means this
            # endpoint may be dead — the next attempt goes to the next
            # address in the list (no-op for single-address clients).
            self._rotate_endpoint()

        return retry_call(
            attempt, attempts=self.attempts,
            base_delay=self.retry_base, max_delay=self.retry_max,
            is_retryable=_retryable, on_retry=on_retry,
            seed=zlib.crc32(key.encode("utf-8")))

    def _raise_if_fenced(self, e: urllib.error.HTTPError,
                         key: str) -> None:
        """Turn the server's 409 epoch-fence rejection into the typed
        FencedError the elastic wrapper dispatches on (a zombie exits;
        it does NOT re-form)."""
        if e.code != 409:
            return
        try:
            detail = e.read().decode("utf-8", "replace")
        except Exception:
            detail = ""
        m = re.search(r"epoch (\d+) is stale.* epoch (\d+)", detail)
        if m:
            stale, current = int(m.group(1)), int(m.group(2))
        else:
            stale = env_util.get_int(env_util.ELASTIC_EPOCH, 0)
            current = -1
        raise FencedError(f"kv write {key!r}", stale, current) from None

    def put(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode("utf-8")

        def go():
            try:
                with urllib.request.urlopen(
                        self._request(key, "PUT", value),
                        timeout=self.timeout):
                    pass
            except urllib.error.HTTPError as e:
                self._raise_if_fenced(e, key)
                raise

        self._with_retry(go, "kv.put", key)

    def get(self, key: str) -> Optional[str]:
        b = self.get_bytes(key)
        return None if b is None else b.decode("utf-8")

    def get_bytes(self, key: str) -> Optional[bytes]:
        def go():
            try:
                with urllib.request.urlopen(self._request(key, "GET"),
                                            timeout=self.timeout) as r:
                    return r.read()
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return None
                raise

        return self._with_retry(go, "kv.get", key)

    def list(self, prefix: str) -> list:
        """Keys currently stored under ``prefix``, sorted."""
        def go():
            with urllib.request.urlopen(
                    self._request(prefix, "GET", endpoint="/kvlist/"),
                    timeout=self.timeout) as r:
                body = r.read().decode("utf-8")
            return body.split("\n") if body else []

        return self._with_retry(go, "kv.list", prefix)

    def delete(self, key: str) -> None:
        def go():
            try:
                with urllib.request.urlopen(
                        self._request(key, "DELETE"),
                        timeout=self.timeout):
                    pass
            except urllib.error.HTTPError as e:
                self._raise_if_fenced(e, key)
                raise

        self._with_retry(go, "kv.delete", key)

    def wait_get(self, key: str, timeout: float = 60.0,
                 interval: float = 0.05) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = self.get(key)
            if v is not None:
                return v
            time.sleep(interval)
        raise TimeoutError(f"rendezvous key {key!r} not available "
                           f"after {timeout}s")

    def local_address(self) -> Optional[str]:
        """The local interface address that routes to the rendezvous server
        — lets a worker advertise a peer-reachable address without NIC
        configuration (the reference runs a NIC-discovery ring instead,
        run/driver/driver_service.py:128-198)."""
        try:
            s = socket.create_connection((self.host, self.port), timeout=5)
            addr = s.getsockname()[0]
            s.close()
            return addr
        except OSError:
            return None
