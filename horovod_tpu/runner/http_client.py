"""KV client for the rendezvous server.

Parity: ``horovod/run/http/http_client.py`` (read_data_from_kvstore /
put_data_into_kvstore), plus HMAC request signing against the launcher's
job secret (``run/common/util/secret.py`` pattern).  The secret defaults
to the ``HVD_SECRET_KEY`` environment variable — the channel the launcher
ships it to workers on — so every existing call site signs automatically
when a secret is in play.

Every request retries with capped exponential backoff + jitter
(``HVD_KV_RETRIES`` attempts, per-attempt timeout ``HVD_KV_TIMEOUT``):
a rendezvous server that is still binding, restarting, or sheds a
request under load (5xx) costs a delay, not the job.  Client errors
(4xx) are never retried — a 404 is a legitimate "key not there yet"
answer the callers poll on.
"""

from __future__ import annotations

import os
import socket
import time
import urllib.error
import urllib.request
import zlib
from typing import Optional

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.common.retry import retry_call
from horovod_tpu.runner import secret as secret_mod
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.utils import env as env_util


def _count_retry(attempt_index: int, exc: BaseException) -> None:
    # Invoked by retry_call before each backoff sleep; a no-op load +
    # None check when telemetry is off.
    _tmx.inc_counter("hvd_kv_retries_total")


def _retryable(e: BaseException) -> bool:
    if isinstance(e, urllib.error.HTTPError):
        return e.code >= 500
    return isinstance(e, (urllib.error.URLError, ConnectionError,
                          socket.timeout, TimeoutError, OSError))


class KVClient:
    def __init__(self, host: str, port: int,
                 secret: Optional[str] = None):
        self.host = host
        self.port = port
        self.secret = (secret if secret is not None
                       else os.environ.get(secret_mod.ENV_VAR) or None)
        self.attempts = max(1, env_util.get_int("HVD_KV_RETRIES", 4))
        self.timeout = env_util.get_float("HVD_KV_TIMEOUT", 10.0)
        self.retry_base = env_util.get_float("HVD_KV_RETRY_BASE_S", 0.05)
        self.retry_max = env_util.get_float("HVD_KV_RETRY_MAX_S", 2.0)

    def _url(self, path: str) -> str:
        return f"http://{self.host}:{self.port}{path}"

    def _request(self, key: str, method: str, body: Optional[bytes] = None,
                 endpoint: str = "/kv/"):
        path = f"{endpoint}{key}"
        req = urllib.request.Request(self._url(path), data=body,
                                     method=method)
        if self.secret is not None:
            req.add_header(secret_mod.HEADER, secret_mod.sign(
                self.secret, method, path, body or b""))
        return req

    def _with_retry(self, fn, site: str, key: str):
        def attempt():
            _fi.fire(site, key)
            return fn()

        return retry_call(
            attempt, attempts=self.attempts,
            base_delay=self.retry_base, max_delay=self.retry_max,
            is_retryable=_retryable, on_retry=_count_retry,
            seed=zlib.crc32(key.encode("utf-8")))

    def put(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode("utf-8")

        def go():
            with urllib.request.urlopen(self._request(key, "PUT", value),
                                        timeout=self.timeout):
                pass

        self._with_retry(go, "kv.put", key)

    def get(self, key: str) -> Optional[str]:
        b = self.get_bytes(key)
        return None if b is None else b.decode("utf-8")

    def get_bytes(self, key: str) -> Optional[bytes]:
        def go():
            try:
                with urllib.request.urlopen(self._request(key, "GET"),
                                            timeout=self.timeout) as r:
                    return r.read()
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return None
                raise

        return self._with_retry(go, "kv.get", key)

    def list(self, prefix: str) -> list:
        """Keys currently stored under ``prefix``, sorted."""
        def go():
            with urllib.request.urlopen(
                    self._request(prefix, "GET", endpoint="/kvlist/"),
                    timeout=self.timeout) as r:
                body = r.read().decode("utf-8")
            return body.split("\n") if body else []

        return self._with_retry(go, "kv.list", prefix)

    def delete(self, key: str) -> None:
        def go():
            with urllib.request.urlopen(self._request(key, "DELETE"),
                                        timeout=self.timeout):
                pass

        self._with_retry(go, "kv.delete", key)

    def wait_get(self, key: str, timeout: float = 60.0,
                 interval: float = 0.05) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = self.get(key)
            if v is not None:
                return v
            time.sleep(interval)
        raise TimeoutError(f"rendezvous key {key!r} not available "
                           f"after {timeout}s")

    def local_address(self) -> Optional[str]:
        """The local interface address that routes to the rendezvous server
        — lets a worker advertise a peer-reachable address without NIC
        configuration (the reference runs a NIC-discovery ring instead,
        run/driver/driver_service.py:128-198)."""
        try:
            s = socket.create_connection((self.host, self.port), timeout=5)
            addr = s.getsockname()[0]
            s.close()
            return addr
        except OSError:
            return None
