"""KV client for the rendezvous server.

Parity: ``horovod/run/http/http_client.py`` (read_data_from_kvstore /
put_data_into_kvstore).
"""

from __future__ import annotations

import socket
import time
import urllib.error
import urllib.request
from typing import Optional


class KVClient:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port

    def _url(self, key: str) -> str:
        return f"http://{self.host}:{self.port}/kv/{key}"

    def put(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode("utf-8")
        req = urllib.request.Request(
            self._url(key), data=value, method="PUT")
        with urllib.request.urlopen(req, timeout=10):
            pass

    def get(self, key: str) -> Optional[str]:
        try:
            with urllib.request.urlopen(self._url(key), timeout=10) as r:
                return r.read().decode("utf-8")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def get_bytes(self, key: str) -> Optional[bytes]:
        try:
            with urllib.request.urlopen(self._url(key), timeout=10) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def delete(self, key: str) -> None:
        req = urllib.request.Request(self._url(key), method="DELETE")
        with urllib.request.urlopen(req, timeout=10):
            pass

    def wait_get(self, key: str, timeout: float = 60.0,
                 interval: float = 0.05) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = self.get(key)
            if v is not None:
                return v
            time.sleep(interval)
        raise TimeoutError(f"rendezvous key {key!r} not available "
                           f"after {timeout}s")

    def local_address(self) -> Optional[str]:
        """The local interface address that routes to the rendezvous server
        — lets a worker advertise a peer-reachable address without NIC
        configuration (the reference runs a NIC-discovery ring instead,
        run/driver/driver_service.py:128-198)."""
        try:
            s = socket.create_connection((self.host, self.port), timeout=5)
            addr = s.getsockname()[0]
            s.close()
            return addr
        except OSError:
            return None
