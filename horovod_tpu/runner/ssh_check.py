"""ssh reachability pre-checks with an on-disk result cache.

Parity: the reference checks ssh into every remote host before spawning
anything (``run/run.py:597-622`` ``_check_all_hosts_ssh_successful``,
threaded ``ssh <host> true`` probes) and memoizes launcher init checks in
``~/.horovod`` keyed by a hash of (np, hosts, ssh_port) with a staleness
window (``run/util/cache.py:130`` ``Cache``).  Same contract here: an
unreachable host fails the launch fast with a named error *before* any
worker is spawned; repeat launches with the same host set skip the probe
inside the cache window; ``--disable-cache`` forces a fresh probe.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import threading
import time
from typing import List, Optional

# Same staleness window as the reference's CACHE_STALENESS_THRESHOLD_MINUTES.
CACHE_STALENESS_MINUTES = 60.0
_DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".horovod_tpu")


class LaunchCache:
    """Tiny JSON file cache for launcher init checks.

    One file per parameter hash (like the reference's per-hash pickle
    under ``~/.horovod``), holding ``{key: [timestamp, value]}``.
    Corrupt or unreadable cache files are treated as empty — the cache
    must never be able to kill a launch.
    """

    def __init__(self, params_hash: str,
                 cache_dir: Optional[str] = None,
                 staleness_minutes: float = CACHE_STALENESS_MINUTES):
        cache_dir = cache_dir or os.environ.get(
            "HVD_CACHE_DIR", _DEFAULT_CACHE_DIR)
        self._path = os.path.join(cache_dir, f"cache_{params_hash}.json")
        self._window_s = staleness_minutes * 60.0
        self._lock = threading.Lock()

    def _load(self) -> dict:
        try:
            with open(self._path) as f:
                d = json.load(f)
            return d if isinstance(d, dict) else {}
        except (OSError, ValueError):
            return {}

    def get(self, key: str):
        """Cached value, or None if absent/stale."""
        with self._lock:
            entry = self._load().get(key)
        if not entry:
            return None
        ts, value = entry
        if time.time() - ts > self._window_s:
            return None
        return value

    def put(self, key: str, value) -> None:
        with self._lock:
            d = self._load()
            d[key] = [time.time(), value]
            tmp = f"{self._path}.tmp.{os.getpid()}"
            try:
                os.makedirs(os.path.dirname(self._path), exist_ok=True)
                with open(tmp, "w") as f:
                    json.dump(d, f)
                os.replace(tmp, self._path)
            except OSError:
                pass  # a cache write failure must not fail the launch


def params_hash(np: int, hosts: Optional[str],
                ssh_port: Optional[int],
                ssh_identity_file: Optional[str] = None) -> str:
    """Hash of the launch parameters that affect init checks (parity:
    run/run.py:600-607 hashes np + hosts + ssh_port; sha256 here — md5
    is rejected outright on FIPS-mode hosts, and this is a cache key,
    not a compatibility surface).  The identity file is part of the key:
    switching credentials must invalidate a cached reachability verdict
    probed with the old key."""
    params = (f"{np} {hosts or ''} {ssh_port or ''} "
              f"{ssh_identity_file or ''}")
    return hashlib.sha256(params.encode()).hexdigest()


class SSHUnreachableError(RuntimeError):
    """One or more remote hosts did not answer an ssh probe."""


def check_hosts_ssh(
    hostnames: List[str],
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
    cache: Optional[LaunchCache] = None,
    timeout: float = 15.0,
) -> None:
    """Probe ``ssh <host> true`` on every host in parallel; raise
    :class:`SSHUnreachableError` naming the failures.

    A cached success within the staleness window skips the probe for
    that host.  Only successes are cached — an unreachable host is
    re-probed on the next launch (it may have come back).
    """
    to_probe = []
    for h in hostnames:
        if cache is not None and cache.get(f"ssh:{h}") is True:
            continue
        to_probe.append(h)
    if not to_probe:
        return

    failures: dict = {}

    def probe(host: str) -> None:
        cmd = ["ssh", "-o", "StrictHostKeyChecking=no",
               "-o", "BatchMode=yes",
               "-o", f"ConnectTimeout={max(1, int(timeout) - 1)}"]
        if ssh_port:
            cmd += ["-p", str(ssh_port)]
        if ssh_identity_file:
            cmd += ["-i", ssh_identity_file]
        cmd += [host, "true"]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout)
            if proc.returncode != 0:
                failures[host] = (proc.stderr or proc.stdout
                                  or f"rc={proc.returncode}").strip()[-200:]
        except subprocess.TimeoutExpired:
            failures[host] = f"no answer within {timeout}s"
        except OSError as e:  # ssh binary itself missing/broken
            failures[host] = str(e)

    threads = [threading.Thread(target=probe, args=(h,), daemon=True)
               for h in to_probe]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    if failures:
        detail = "; ".join(f"{h}: {msg}" for h, msg in
                           sorted(failures.items()))
        raise SSHUnreachableError(
            f"ssh unreachable on {len(failures)} host(s) — not spawning "
            f"any worker. {detail}")
    if cache is not None:
        for h in to_probe:
            cache.put(f"ssh:{h}", True)
