"""``hvdrun`` — the launcher CLI and programmatic ``run()``.

Parity: ``horovod/run/run.py`` (argparse over every knob, hostfile
support, YAML config, launcher orchestration) and the run-func mode
(run.py:631-657, 702: cloudpickled fn shipped to workers, per-rank results
collected through the KV store).

Usage::

    hvdrun -np 4 python train.py            # 4 local processes
    hvdrun -np 8 -H hostA:4,hostB:4 python train.py
    python -m horovod_tpu.runner.run -np 2 python train.py

    from horovod_tpu.runner import run
    results = run.run(train_fn, np=4)        # list of per-rank returns
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Any, Callable, List, Optional

from horovod_tpu.runner import config_parser
from horovod_tpu.runner.hosts import allocate, parse_hostfile, parse_hosts
from horovod_tpu.runner.http_client import KVClient
from horovod_tpu.runner.http_server import RendezvousServer
from horovod_tpu.runner.launch import launch_workers
from horovod_tpu.runner import secret as secret_mod
from horovod_tpu.version import __version__


def _prog_name() -> str:
    """Reflect the invoked alias (hvdrun or horovodrun) in usage and
    error text; module-mode invocations keep the canonical name."""
    base = os.path.basename(sys.argv[0] or "")
    return base if base in ("hvdrun", "horovodrun") else "hvdrun"


def check_build() -> str:
    """Availability report (parity: ``horovodrun --check-build``,
    run/run.py:116-151 — frameworks / controllers / tensor ops, reshaped
    for this stack's components).  A component counts as available when
    it is built OR buildable on demand (sources + toolchain — the same
    criterion ``native.load`` / ``_native_ops.lib`` apply).  Paths come
    from the loaders themselves, not re-derived here."""
    import importlib.util

    from horovod_tpu import native as native_mod

    def have(mod):
        try:
            return importlib.util.find_spec(mod) is not None
        except Exception:
            return False

    def mark(v):
        return "X" if v else " "

    core = str(native_mod._LIB_PATH)
    csrc = str(native_mod._CSRC_DIR)
    buildable = os.path.isdir(csrc) and _toolchain()
    core_built = os.path.exists(core)
    native_core = core_built or buildable
    # One dlopen serves both probes: the FFI symbol, and the runtime
    # cpuid gate (authoritative for SIMD); cpuinfo flags are the
    # pre-build fallback.
    ffi, simd = False, False
    if core_built:
        try:
            import ctypes

            lib = ctypes.CDLL(core)
            ffi = hasattr(lib, "HvdGroupedAllreduce")
            simd = bool(lib.hvd_simd_available())
        except Exception:
            pass
    else:
        try:
            with open("/proc/cpuinfo") as f:
                flags = f.read()
            simd = "avx2" in flags and "f16c" in flags
        except OSError:
            pass
    if not ffi and buildable and have("jax"):
        ffi = os.path.isfile(os.path.join(csrc, "ffi_bridge.cc"))
    simd_note = ""
    if os.environ.get("HVD_NO_SIMD") == "1":
        simd, simd_note = False, " (disabled by HVD_NO_SIMD=1)"
    # Library dir from the core loader (single source); the tf-ops
    # filename matches tensorflow/_native_ops._SO — not imported here
    # because that package import pulls TensorFlow itself (~seconds),
    # and --check-build must stay fast.
    tf_so = os.path.join(os.path.dirname(core), "libhvd_tf_ops.so")
    tf_kernels = have("tensorflow") and (
        os.path.exists(tf_so)
        or (os.path.isfile(os.path.join(csrc, "tf_ops.cc"))
            and _toolchain()))
    torch_so = os.path.join(os.path.dirname(core),
                            "libhvd_torch_ops.so")
    torch_kernels = have("torch") and (
        os.path.exists(torch_so)
        or (os.path.isfile(os.path.join(csrc, "torch_ops.cc"))
            and _toolchain()))
    return f"""horovod_tpu v{__version__}:

Available Frameworks:
    [{mark(have('jax'))}] JAX (in-graph collectives + engine bridge)
    [{mark(have('tensorflow'))}] TensorFlow
    [{mark(have('torch'))}] PyTorch
    [{mark(have('keras'))}] Keras
    [{mark(have('mxnet'))}] MXNet

Available Engines:
    [{mark(native_core)}] native C++ core (libhvd_core.so)
    [X] Python engine (wire-compatible twin, always available)

Available Native Components:
    [{mark(ffi)}] XLA FFI custom call (jit grouped allreduce)
    [{mark(tf_kernels)}] TensorFlow custom kernels (HvdAllreduce/...)
    [{mark(torch_kernels)}] PyTorch dispatcher ops (torch.ops.hvd.*)
    [{mark(simd)}] SIMD wire codecs (AVX2 + F16C){simd_note}
    [X] XLA/ICI in-graph collectives (psum/all_gather/ppermute)"""


def _toolchain() -> bool:
    import shutil

    return shutil.which(os.environ.get("CXX", "g++")) is not None


class _CheckBuildAction(argparse.Action):
    def __call__(self, parser, namespace, values, option_string=None):
        print(check_build())
        sys.exit(0)


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog=_prog_name(),
        description="Launch a horovod_tpu distributed job.")
    p.add_argument("-v", "--version", action="version",
                   version=__version__)
    p.add_argument("-cb", "--check-build", nargs=0,
                   action=_CheckBuildAction,
                   help="print available frameworks/engines/native "
                        "components and exit")
    p.add_argument("-np", "--num-proc", type=int, required=True,
                   dest="np", help="total number of processes")
    g = p.add_mutually_exclusive_group()
    g.add_argument("-H", "--hosts", dest="hosts",
                   help="host:slots[,host:slots...] (default: localhost)")
    g.add_argument("--hostfile", dest="hostfile",
                   help="path to a hostfile (mpirun 'host slots=N' style)")
    p.add_argument("--ssh-port", type=int, dest="ssh_port")
    p.add_argument("--ssh-identity-file", dest="ssh_identity_file")
    p.add_argument("--network-interface", dest="nics",
                   help="comma-separated NIC name(s); the rendezvous "
                        "binds to and advertises the first one that "
                        "resolves (default: automatic via the default "
                        "route)")
    p.add_argument("--launcher", choices=["spawn", "jsrun", "mpirun"],
                   default="spawn",
                   help="spawn: local subprocess / ssh per slot (default); "
                        "jsrun: one jsrun invocation on an LSF cluster; "
                        "mpirun: one mpirun invocation driving an MPI "
                        "cluster (OpenMPI or Hydra/MPICH; tasks need no "
                        "MPI linkage — rank comes from the env, data "
                        "rides this stack's own mesh) "
                        "(parity: horovodrun's gloo/jsrun/mpirun modes)")
    p.add_argument("--start-timeout", type=int, default=120,
                   dest="start_timeout")
    p.add_argument("--max-restarts", type=int, default=0,
                   dest="max_restarts",
                   help="restart-based elasticity: relaunch the whole "
                        "gang up to N times when any rank fails "
                        "(training scripts resume from their checkpoint; "
                        "the TPU-native form of elastic training — pod "
                        "meshes restart, they do not re-form). Default "
                        "0 keeps the reference's fail-fast contract.")
    el = p.add_argument_group(
        "elastic",
        "in-process elasticity (docs/elastic.md): ranks may die or join "
        "without relaunching the job — survivors roll back to the last "
        "State.commit() and re-form under a new membership epoch.  "
        "Requires the Python engine (set automatically) and a training "
        "script wrapped in @hvd.elastic.run.  Composes with "
        "--max-restarts as the outer fallback: a gang that collapses "
        "below --min-np is relaunched whole.")
    el.add_argument("--min-np", type=int, dest="min_np",
                    help="keep going while at least this many workers "
                         "survive (default: -np)")
    el.add_argument("--max-np", type=int, dest="max_np",
                    help="admit joiners up to this many workers "
                         "(default: -np, i.e. no growth headroom)")
    el.add_argument("--host-discovery-script",
                    dest="host_discovery_script",
                    help="executable printing one 'hostname[:slots]' per "
                         "line; re-polled by the launcher, which starts "
                         "joiner workers on newly discovered hosts "
                         "(absent: membership only shrinks in process, "
                         "and --max-restarts covers full relaunches)")
    p.add_argument("--disable-cache", action="store_true",
                   dest="disable_cache")
    p.add_argument("--output-filename", dest="output_filename")
    p.add_argument("--config-file", dest="config_file")

    tune = p.add_argument_group("tunables")
    tune.add_argument("--fusion-threshold-mb", type=float,
                      dest="fusion_threshold_mb")
    tune.add_argument("--cycle-time-ms", type=float, dest="cycle_time_ms")
    tune.add_argument("--cache-capacity", type=int, dest="cache_capacity")
    tune.add_argument("--hierarchical-allreduce", action="store_true",
                      dest="hierarchical_allreduce")
    tune.add_argument("--hierarchical-allgather", action="store_true",
                      dest="hierarchical_allgather")
    tune.add_argument("--ring-segment-bytes", type=int,
                      dest="ring_segment_bytes",
                      help="segment each ring hop so the next segment's "
                           "receive overlaps the previous segment's "
                           "reduce; 0 disables (autotunable; see "
                           "docs/performance.md)")
    tune.add_argument("--sock-buf-bytes", type=int,
                      dest="sock_buf_bytes",
                      help="SO_SNDBUF/SO_RCVBUF for data-plane sockets "
                           "in bytes; 0 keeps the kernel default (see "
                           "docs/performance.md)")
    tune.add_argument("--collective-timeout", type=float,
                      dest="collective_timeout",
                      help="seconds before an eager collective is "
                           "declared hung: the gang agrees on the "
                           "wedged rank(s) and aborts with a "
                           "CollectiveTimeoutError instead of "
                           "deadlocking; 0 (default) blocks forever "
                           "(see docs/fault_tolerance.md)")
    tune.add_argument("--ctrl-fanout", type=int, dest="ctrl_fanout",
                      help="max children a per-host control-plane "
                           "sub-coordinator folds before the next host "
                           "rank goes direct to the root; 0 (default) "
                           "folds the whole host (see "
                           "docs/fault_tolerance.md)")
    tune.add_argument("--no-shm", action="store_true", dest="no_shm",
                      help="disable the same-host shared-memory "
                           "transport: every peer link uses TCP, the "
                           "pre-shm wire path (escape hatch; see "
                           "docs/performance.md)")
    tune.add_argument("--shm-slot-bytes", type=int,
                      dest="shm_slot_bytes",
                      help="payload bytes per shm ring slot (default "
                           "262144, floor 4096; see "
                           "docs/performance.md)")
    tune.add_argument("--shm-slots", type=int, dest="shm_slots",
                      help="slots per directed shm ring (default 16, "
                           "floor 2; see docs/performance.md)")

    auto = p.add_argument_group("autotune")
    auto.add_argument("--autotune", action="store_true", dest="autotune")
    auto.add_argument("--autotune-log-file", dest="autotune_log_file")

    tl = p.add_argument_group("timeline")
    tl.add_argument("--timeline-filename", dest="timeline_filename")
    tl.add_argument("--timeline-mark-cycles", action="store_true",
                    dest="timeline_mark_cycles")

    st = p.add_argument_group("stall check")
    st.add_argument("--no-stall-check", action="store_true",
                    dest="no_stall_check")
    st.add_argument("--stall-warning-time-seconds", type=float,
                    dest="stall_warning_time_seconds")
    st.add_argument("--stall-shutdown-time-seconds", type=float,
                    dest="stall_shutdown_time_seconds")

    mx = p.add_argument_group("metrics")
    mx.add_argument("--metrics-port", type=int, dest="metrics_port",
                    help="per-worker metrics debug-server base port "
                         "(worker binds port + local_rank; "
                         "see docs/metrics.md)")

    sv = p.add_argument_group("serving")
    sv.add_argument("--serve-port", type=int, dest="serve_port",
                    help="rank-0 inference front-door port for "
                         "horovod_tpu.serving workloads "
                         "(see docs/serving.md)")
    sv.add_argument("--serve-max-batch", type=int, dest="serve_max_batch",
                    help="decode slots per serving batch "
                         "(continuous-batching width)")
    sv.add_argument("--serve-max-queue", type=int, dest="serve_max_queue",
                    help="admission queue bound; beyond it the front "
                         "door sheds with HTTP 503")

    kv = p.add_argument_group(
        "rendezvous availability",
        "surviving the KV store's death (docs/fault_tolerance.md "
        "\"surviving rank 0\"): standbys receive a write-through mirror "
        "of every PUT/DELETE and clients fail over down the endpoint "
        "list inside their normal retry budget.")
    kv.add_argument("--kv-standbys", type=int, dest="kv_standbys",
                    help="start N warm standby KV servers (0..2) next "
                         "to the primary; workers get the full endpoint "
                         "list via HVD_KV_ADDRS and fail over if the "
                         "primary dies")
    kv.add_argument("--kv-addrs", dest="kv_addrs",
                    help="comma-separated host:port list of externally "
                         "managed rendezvous KV endpoints (primary "
                         "first); exported to workers as HVD_KV_ADDRS "
                         "verbatim (mutually exclusive with "
                         "--kv-standbys)")

    p.add_argument("--log-level", dest="log_level",
                   choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                            "FATAL"])
    p.add_argument("--adasum-mode", dest="adasum_mode")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="the training command to run on every slot")
    return p


def _resolve_hosts(args):
    if args.hostfile:
        return parse_hostfile(args.hostfile)
    if args.hosts:
        return parse_hosts(args.hosts)
    # Inside an LSF allocation the job already knows its hosts
    # (parity: run.py LSF autodetect, run/util/lsf.py).
    from horovod_tpu.runner import lsf

    if lsf.in_lsf_job():
        hosts = lsf.lsf_hosts()
        if hosts:
            return hosts
    return parse_hosts(f"localhost:{args.np}")


def _collect_env(args):
    env = {}
    if args.config_file:
        env.update(config_parser.env_from_config_file(args.config_file))
    env.update(config_parser.env_from_args(args))
    return env


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = make_parser().parse_args(argv)
    if not args.command:
        print(f"{_prog_name()}: no command given", file=sys.stderr)
        return 2
    if args.max_restarts < 0:
        print(f"{_prog_name()}: --max-restarts must be >= 0 (there is "
              "no infinite-restart sentinel; pick a bound)",
              file=sys.stderr)
        return 2
    if args.max_restarts and args.launcher in ("jsrun", "mpirun"):
        print(f"{_prog_name()}: --max-restarts is not supported with "
              f"--launcher {args.launcher} (the external scheduler owns "
              "the job lifecycle; use its requeue policy)",
              file=sys.stderr)
        return 2
    if args.metrics_port is not None and \
            not (1 <= args.metrics_port <= 65535):
        print(f"{_prog_name()}: --metrics-port must be in 1..65535 "
              f"(got {args.metrics_port}); each worker binds "
              "metrics-port + local_rank", file=sys.stderr)
        return 2
    if args.serve_port is not None and \
            not (1 <= args.serve_port <= 65535):
        print(f"{_prog_name()}: --serve-port must be in 1..65535 "
              f"(got {args.serve_port})", file=sys.stderr)
        return 2
    for flag, val in (("--serve-max-batch", args.serve_max_batch),
                      ("--serve-max-queue", args.serve_max_queue)):
        if val is not None and val < 1:
            print(f"{_prog_name()}: {flag} must be >= 1 (got {val})",
                  file=sys.stderr)
            return 2
    if args.kv_standbys is not None and not (0 <= args.kv_standbys <= 2):
        print(f"{_prog_name()}: --kv-standbys must be in 0..2 "
              f"(got {args.kv_standbys})", file=sys.stderr)
        return 2
    if args.kv_addrs is not None:
        if args.kv_standbys:
            print(f"{_prog_name()}: --kv-addrs and --kv-standbys are "
                  "mutually exclusive (either the launcher runs the "
                  "standbys or you point at external ones)",
                  file=sys.stderr)
            return 2
        from horovod_tpu.runner.http_client import parse_kv_addrs
        try:
            parse_kv_addrs(args.kv_addrs)
        except ValueError as e:
            print(f"{_prog_name()}: --kv-addrs: {e}", file=sys.stderr)
            return 2
    for flag, val in (("--ring-segment-bytes", args.ring_segment_bytes),
                      ("--sock-buf-bytes", args.sock_buf_bytes),
                      ("--ctrl-fanout", args.ctrl_fanout),
                      ("--collective-timeout", args.collective_timeout)):
        if val is not None and val < 0:
            print(f"{_prog_name()}: {flag} must be >= 0 "
                  f"(got {val}; 0 disables)", file=sys.stderr)
            return 2
    for flag, val, floor in (("--shm-slot-bytes", args.shm_slot_bytes,
                              4096),
                             ("--shm-slots", args.shm_slots, 2)):
        if val is not None and val < floor:
            print(f"{_prog_name()}: {flag} must be >= {floor} "
                  f"(got {val}); use --no-shm to disable the shm "
                  "transport entirely", file=sys.stderr)
            return 2
    # Elastic flags: validate at parse time, before any rendezvous/ssh
    # side effects — a bad floor/ceiling or a missing discovery script
    # must fail in milliseconds, not mid-launch.
    elastic = (args.min_np is not None or args.max_np is not None
               or args.host_discovery_script is not None)
    min_np = args.min_np if args.min_np is not None else args.np
    max_np = args.max_np if args.max_np is not None else args.np
    if elastic:
        if args.launcher in ("jsrun", "mpirun"):
            print(f"{_prog_name()}: elastic flags (--min-np/--max-np/"
                  "--host-discovery-script) are not supported with "
                  f"--launcher {args.launcher} (the external scheduler "
                  "owns process placement; elastic needs the spawn "
                  "launcher's supervision loop)", file=sys.stderr)
            return 2
        if min_np < 1:
            print(f"{_prog_name()}: --min-np must be >= 1 "
                  f"(got {min_np})", file=sys.stderr)
            return 2
        if min_np > args.np:
            print(f"{_prog_name()}: --min-np ({min_np}) cannot exceed "
                  f"-np ({args.np}) — the job starts at -np workers and "
                  "shrinks from there", file=sys.stderr)
            return 2
        if max_np < args.np:
            print(f"{_prog_name()}: --max-np ({max_np}) cannot be below "
                  f"-np ({args.np}) — the job starts at -np workers and "
                  "grows from there", file=sys.stderr)
            return 2
        script = args.host_discovery_script
        if script and not (os.path.isfile(script)
                           and os.access(script, os.X_OK)):
            print(f"{_prog_name()}: --host-discovery-script {script!r} "
                  "is not an executable file", file=sys.stderr)
            return 2
    mpi_impl = None
    if args.launcher == "mpirun":
        # Probe before any rendezvous/ssh side effects: a missing
        # mpirun should fail in milliseconds, not after a NIC ring
        # probe across the cluster.
        from horovod_tpu.runner import mpi

        mpi_impl = mpi.detect_mpi_impl()
        if mpi_impl is None:
            print(f"{_prog_name()}: --launcher mpirun: no usable "
                  "mpirun found on PATH (need OpenMPI or a "
                  "Hydra-family MPICH)", file=sys.stderr)
            return 2
        if mpi_impl == mpi.MpiImpl.MPICH and (
                args.ssh_port or args.ssh_identity_file):
            # Statically decidable: fail before the rendezvous server
            # and the cluster NIC probe, not after.
            print(f"{_prog_name()}: --ssh-port/--ssh-identity-file have "
                  "no Hydra/MPICH mapping; configure ssh via "
                  "~/.ssh/config or use the OpenMPI or spawn launcher",
                  file=sys.stderr)
            return 2
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    hosts = _resolve_hosts(args)
    slots = allocate(hosts, args.np)
    env_extra = _collect_env(args)

    env_extra["HVD_START_TIMEOUT"] = str(args.start_timeout)

    # Per-job secret: signs every rendezvous KV request (parity:
    # run/common/util/secret.py); workers receive it via env.
    job_secret = secret_mod.make_secret()
    env_extra[secret_mod.ENV_VAR] = job_secret

    nic_addr = interface_address_any(args.nics) if args.nics else None
    if args.nics:
        # Workers advertise on the named NIC too (bootstrap_mesh reads
        # HVD_NIC), not just the launcher's rendezvous bind.
        env_extra["HVD_NIC"] = args.nics
    multi_host = any(not _is_local(s.hostname) for s in slots)
    if multi_host and args.launcher == "spawn":
        # Fail fast on unreachable hosts BEFORE starting the rendezvous
        # server or spawning anything (parity: run/run.py:597-622), with
        # repeat launches skipping the probe inside the on-disk cache
        # window (run/util/cache.py).
        from horovod_tpu.runner import ssh_check

        fn_cache = None
        if not args.disable_cache:
            fn_cache = ssh_check.LaunchCache(ssh_check.params_hash(
                args.np, args.hosts or args.hostfile, args.ssh_port,
                args.ssh_identity_file))
        remote = sorted({s.hostname for s in slots
                         if not _is_local(s.hostname)})
        ssh_check.check_hosts_ssh(
            remote, ssh_port=args.ssh_port,
            ssh_identity_file=args.ssh_identity_file, cache=fn_cache)
    server = RendezvousServer(host=nic_addr or "0.0.0.0",
                              secret=job_secret)
    port = server.start()
    # Workers reach the rendezvous at this host; for multi-host jobs they
    # need a routable address, not loopback.
    addr = nic_addr or (_routable_address() if multi_host
                        else "127.0.0.1")
    if multi_host and not nic_addr:
        # No NIC named: ring-probe the hosts for mutually routable
        # interfaces (parity: run/driver/driver_service.py:128-198)
        # instead of trusting the default-route guess on every host.
        try:
            common = probe_common_nics(
                [s.hostname for s in slots], addr, port, job_secret,
                ssh_port=args.ssh_port,
                ssh_identity_file=args.ssh_identity_file)
            env_extra["HVD_NIC"] = ",".join(common)
            # Only re-point the rendezvous at the common NIC when the
            # launcher itself was in the probe ring (has a local slot);
            # on a pure-remote job, a same-named launcher NIC was never
            # validated, while the current addr demonstrably works (the
            # agents just used it).
            if any(_is_local(s.hostname) for s in slots):
                probe_addr = interface_address(common[0])
                if probe_addr:
                    addr = probe_addr
        except Exception as e:  # discovery must never kill the launch
            print(f"{_prog_name()}: NIC ring probe failed ({e}); "
                  "falling back to the default route", file=sys.stderr)
    standbys = []
    if args.kv_standbys:
        # Warm standbys next to the primary: each syncs nothing (the
        # store is empty at launch) and receives a write-through copy
        # of every mutation; workers learn the whole endpoint list.
        for i in range(args.kv_standbys):
            sb = RendezvousServer(host=nic_addr or "0.0.0.0",
                                  secret=job_secret)
            sb.start(name=f"hvd-kv-standby-{i}")
            standbys.append(sb)
        server.set_mirrors([(addr, sb.port) for sb in standbys])
        env_extra["HVD_KV_ADDRS"] = ",".join(
            [f"{addr}:{port}"] + [f"{addr}:{sb.port}" for sb in standbys])
    elif args.kv_addrs is not None:
        env_extra["HVD_KV_ADDRS"] = args.kv_addrs
    output = None
    if args.output_filename:
        output = open(args.output_filename, "w")
    try:
        if args.launcher in ("jsrun", "mpirun"):
            # One external fan-out: tasks get rank/size from the
            # scheduler's env (PMIX_*/OMPI_*/PMI_*, discovery.
            # from_mpi_env) and rendezvous back here.  Coordinates and
            # the job secret ride the launcher's process environment —
            # forwarded by NAME where the tool needs a list (-x /
            # -genvlist) — never values on the ps-visible command line.
            import subprocess

            env = dict(os.environ)
            env.update(env_extra)
            env.update({"HVD_RENDEZVOUS_ADDR": addr,
                        "HVD_RENDEZVOUS_PORT": str(port)})
            if args.launcher == "jsrun":
                from horovod_tpu.runner import lsf

                cmd = lsf.jsrun_command(args.np, command)
            else:  # mpirun (parity: run/mpi_run.py:81-158)
                from horovod_tpu.runner import mpi

                names = sorted(set(env_extra)
                               | {"HVD_RENDEZVOUS_ADDR",
                                  "HVD_RENDEZVOUS_PORT"})
                cmd = mpi.mpirun_command(
                    args.np, slots, command, env_var_names=names,
                    impl=mpi_impl,
                    nics=args.nics.split(",") if args.nics else None,
                    ssh_port=args.ssh_port,
                    ssh_identity_file=args.ssh_identity_file)
            return subprocess.run(
                cmd, env=env, stdout=output or None).returncode
        from horovod_tpu.runner.hosts import HostBlacklist, SlotInfo
        from horovod_tpu.runner.launch import (
            LaunchError,
            launch_workers_elastic,
        )
        from horovod_tpu.utils import env as E

        blacklist = HostBlacklist() if (args.max_restarts or elastic) \
            else None
        if elastic:
            env_extra[E.ELASTIC_MIN_NP] = str(min_np)
            env_extra[E.ELASTIC_MAX_NP] = str(max_np)
            env_extra[E.ELASTIC_EPOCH] = "0"
            # The native engine has no in-process reset path; elastic
            # jobs always run the Python engine.
            env_extra["HVD_TPU_CORE"] = "py"
            # The launcher owns the discovery loop (it must spawn joiner
            # processes on the new hosts); don't also start a notifier
            # driver inside rank 0 — joiners announce themselves.
            env_extra.pop(E.HOST_DISCOVERY_SCRIPT, None)
        for attempt in range(args.max_restarts + 1):
            env_try = dict(env_extra)
            if attempt:
                # Scoped rendezvous keys: the relaunched gang must never
                # read the dead attempt's stale addresses.
                env_try["HVD_RDV_SCOPE"] = f"attempt{attempt}"
                # Skip hosts that keep killing workers, while the rest
                # still cover -np; a cooled-down host is re-probed.
                use_hosts = blacklist.filter_hosts(hosts, args.np)
                skipped = sorted({h.hostname for h in hosts}
                                 - {h.hostname for h in use_hosts})
                if skipped:
                    print(f"{_prog_name()}: skipping blacklisted "
                          f"host(s) {', '.join(skipped)} on relaunch",
                          file=sys.stderr)
                slots = allocate(use_hosts, args.np)
            driver = None
            try:
                if elastic:
                    take_pending = None
                    if args.host_discovery_script:
                        import threading

                        from horovod_tpu.elastic.driver import (
                            ElasticDriver,
                            HostDiscoveryScript,
                        )

                        lock = threading.Lock()
                        known = {s.hostname for s in slots}
                        pending: List[SlotInfo] = []
                        next_rank = [len(slots)]

                        def on_update(ep, added, removed):
                            # Queue joiner slots for each genuinely new
                            # host; the supervision loop spawns them.
                            found = driver.hosts()
                            with lock:
                                for h in added:
                                    if h in known:
                                        continue
                                    known.add(h)
                                    n = found.get(h, 1)
                                    for li in range(n):
                                        pending.append(SlotInfo(
                                            hostname=h,
                                            rank=next_rank[0],
                                            size=0, local_rank=li,
                                            local_size=n,
                                            cross_rank=0, cross_size=0))
                                        next_rank[0] += 1

                        def take_pending():
                            with lock:
                                out = list(pending)
                                pending.clear()
                            return out

                        driver = ElasticDriver(
                            HostDiscoveryScript(
                                args.host_discovery_script),
                            min_np, max_np, blacklist=blacklist,
                            on_hosts_updated=on_update)
                        driver.start()
                    launch_workers_elastic(
                        slots, command, addr, port,
                        min_np=min_np, max_np=max_np,
                        env_extra=env_try,
                        ssh_port=args.ssh_port,
                        ssh_identity_file=args.ssh_identity_file,
                        output=output,
                        new_slots=take_pending,
                        on_failure=blacklist.record_failure)
                else:
                    launch_workers(
                        slots, command, addr, port,
                        env_extra=env_try,
                        ssh_port=args.ssh_port,
                        ssh_identity_file=args.ssh_identity_file,
                        output=output)
                return 0
            except LaunchError as e:
                if blacklist is not None:
                    blacklist.record_failure(e.hostname)
                if attempt >= args.max_restarts:
                    raise
                print(f"{_prog_name()}: rank {e.rank} exited with code "
                      f"{e.returncode}"
                      + (f" on host {e.hostname}" if e.hostname else "")
                      + f"; restarting the job "
                      f"(attempt {attempt + 1}/{args.max_restarts})",
                      file=sys.stderr)
            finally:
                if driver is not None:
                    driver.stop()
        raise AssertionError("unreachable: loop returns or raises")
    finally:
        if output is not None:
            output.close()
        server.stop()
        for sb in standbys:
            sb.stop()


def _is_local(hostname: str) -> bool:
    from horovod_tpu.runner.launch import is_local

    return is_local(hostname)


def probe_common_nics(hostnames: List[str], rdv_addr: str, rdv_port: int,
                      job_secret: str, *,
                      ssh_port: Optional[int] = None,
                      ssh_identity_file: Optional[str] = None,
                      wait_timeout: float = 60.0) -> List[str]:
    """Run one nic_probe agent per unique host through the normal spawn
    path and intersect their routable-interface reports; returns common
    NIC names, non-loopback first.  Raises if no interface is reachable
    from every host."""
    import threading

    from horovod_tpu.runner import nic_probe
    from horovod_tpu.runner.hosts import SlotInfo

    uniq = list(dict.fromkeys(hostnames))
    n = len(uniq)
    agent_slots = [
        SlotInfo(hostname=h, rank=i, size=n, local_rank=0, local_size=1,
                 cross_rank=i, cross_size=n)
        for i, h in enumerate(uniq)]
    kv = KVClient("127.0.0.1", rdv_port, secret=job_secret)
    result: dict = {}

    def _intersect():
        try:
            result["nics"] = nic_probe.common_interfaces(
                kv, n, wait_timeout=wait_timeout)
        except Exception as e:
            result["error"] = e

    t = threading.Thread(target=_intersect, daemon=True)
    t.start()
    with open(os.devnull, "w") as devnull:
        launch_workers(
            agent_slots,
            [sys.executable, "-m", "horovod_tpu.runner.nic_probe"],
            rdv_addr, rdv_port,
            env_extra={secret_mod.ENV_VAR: job_secret},
            ssh_port=ssh_port, ssh_identity_file=ssh_identity_file,
            prefix_output=False, output=devnull)
    t.join(timeout=wait_timeout)
    if "error" in result:
        raise result["error"]
    if "nics" not in result:
        raise TimeoutError("NIC probe intersection timed out")
    return result["nics"]


def _routable_address() -> str:
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))  # no traffic sent; picks the default NIC
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def interface_address(ifname: str) -> Optional[str]:
    """IPv4 address of a named interface (SIOCGIFADDR ioctl — stdlib
    only; the automatic equivalent of the reference's psutil NIC listing.
    The ring-probe counterpart of run/driver/driver_service.py:128-198
    is ``probe_common_nics`` / ``runner.nic_probe``)."""
    import fcntl
    import socket
    import struct

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        packed = struct.pack("256s", ifname.strip().encode()[:15])
        addr = fcntl.ioctl(s.fileno(), 0x8915, packed)[20:24]  # SIOCGIFADDR
        return socket.inet_ntoa(addr)
    except OSError:
        return None
    finally:
        s.close()


def interface_address_any(nics: str) -> Optional[str]:
    """First resolvable address from a comma-separated NIC list; raises
    if the user named interfaces and none of them resolve (silently
    falling back would rendezvous on the wrong network)."""
    names = [n for n in (nics or "").split(",") if n.strip()]
    for n in names:
        addr = interface_address(n)
        if addr:
            return addr
    if names:
        raise ValueError(
            f"--network-interface: none of {names} has an IPv4 address")
    return None


# ---------------------------------------------------------------------------
# programmatic run-func mode
# ---------------------------------------------------------------------------


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    *,
    np: int = 1,
    hosts: Optional[str] = None,
    hostfile: Optional[str] = None,
    env: Optional[dict] = None,
    start_timeout: int = 120,
) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` ranks; returns the list of
    per-rank return values in rank order (parity: horovod.run.run())."""
    import cloudpickle

    if hostfile:
        host_list = parse_hostfile(hostfile)
    elif hosts:
        host_list = parse_hosts(hosts)
    else:
        host_list = parse_hosts(f"localhost:{np}")
    slots = allocate(host_list, np)

    job_secret = secret_mod.make_secret()
    server = RendezvousServer(secret=job_secret)
    port = server.start()
    payload = cloudpickle.dumps((fn, args, kwargs or {}))
    multi_host = any(not _is_local(s.hostname) for s in slots)
    addr = _routable_address() if multi_host else "127.0.0.1"
    kv = KVClient("127.0.0.1", port, secret=job_secret)
    kv.put("runfunc/fn", payload)
    try:
        env_extra = dict(env or {})
        env_extra.setdefault("HVD_START_TIMEOUT", str(start_timeout))
        env_extra[secret_mod.ENV_VAR] = job_secret
        launch_failure = None
        try:
            launch_workers(
                slots,
                [sys.executable, "-m", "horovod_tpu.runner.run_task"],
                addr, port, env_extra=env_extra)
        except Exception as e:
            # Workers post (False, traceback) before exiting non-zero;
            # surface the real exception rather than just the exit code.
            launch_failure = e
        results = []
        for r in range(np):
            blob = server.get(f"runfunc/result/{r}")
            if blob is None:
                if launch_failure is not None:
                    raise launch_failure
                raise RuntimeError(f"rank {r} returned no result")
            ok, value = cloudpickle.loads(blob)
            if not ok:
                raise RuntimeError(f"rank {r} raised:\n{value}")
            results.append(value)
        if launch_failure is not None:
            raise launch_failure
        return results
    finally:
        server.stop()


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
