"""Worker spawning: local subprocesses or ssh, with per-rank env, prefixed
output streaming, and fail-fast teardown.

Parity: ``horovod/run/gloo_run.py:142-259`` (threaded ssh spawn, output
capture to per-rank streams, kill-the-job-if-any-rank-fails —
gloo_run.py:253-259) and ``safe_shell_exec``'s process-group termination.
Local ranks exec directly; remote hosts go through ``ssh`` exactly like the
reference (no MPI anywhere).
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from horovod_tpu.runner.hosts import SlotInfo

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}

# Env vars that must never appear on a (ps-visible) remote command line;
# they are delivered over the ssh process's stdin instead.
SENSITIVE_ENV = ("HVD_SECRET_KEY",)


def _remote_command(env: Dict[str, str], command: Sequence[str]):
    """Build the ssh remote command string.

    Returns ``(remote, stdin_payload)``.  Plain ``HVD_*``-family vars are
    inlined as exports; sensitive ones (``SENSITIVE_ENV``) are read from
    stdin with ``read -rs`` (silent — no pty echo into the captured
    output) so secrets never hit argv, which any local user could read
    via ``ps``/procfs."""
    sensitive = [(k, env[k]) for k in SENSITIVE_ENV if k in env]
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in env.items()
        if k.startswith(("HVD_", "JAX_", "XLA_", "PYTHON"))
        and k not in SENSITIVE_ENV)
    inner = f"cd {shlex.quote(os.getcwd())} && {exports} " + \
        " ".join(shlex.quote(c) for c in command)
    if not sensitive:
        return inner, None
    reads = "; ".join(f"IFS= read -rs {k} && export {k}"
                      for k, _ in sensitive)
    # bash -c: `read -s` is a bash-ism; the user's login shell may be sh.
    remote = "bash -c " + shlex.quote(f"{reads}; {inner}")
    payload = "".join(v + "\n" for _, v in sensitive)
    return remote, payload


def is_local(hostname: str) -> bool:
    import socket

    if hostname in _LOCAL_NAMES:
        return True
    try:
        return hostname in (socket.gethostname(), socket.getfqdn())
    except OSError:
        return False


def worker_env(slot: SlotInfo, rdv_addr: str, rdv_port: int,
               extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Per-slot env block (parity: gloo_run.py:210-215 HOROVOD_RANK/...)."""
    env = dict(os.environ)
    if extra:
        env.update(extra)
    # Make sure workers can import horovod_tpu even when the package is
    # run from a source tree rather than installed (script-mode python
    # does not put the launcher's cwd on sys.path).
    import horovod_tpu

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(horovod_tpu.__file__)))
    pp = env.get("PYTHONPATH", "")
    if pkg_root not in pp.split(os.pathsep):
        env["PYTHONPATH"] = (pkg_root + os.pathsep + pp) if pp else pkg_root
    env.update({
        "HVD_HOSTNAME": slot.hostname,
        "HVD_RANK": str(slot.rank),
        "HVD_SIZE": str(slot.size),
        "HVD_LOCAL_RANK": str(slot.local_rank),
        "HVD_LOCAL_SIZE": str(slot.local_size),
        "HVD_CROSS_RANK": str(slot.cross_rank),
        "HVD_CROSS_SIZE": str(slot.cross_size),
        "HVD_RENDEZVOUS_ADDR": rdv_addr,
        "HVD_RENDEZVOUS_PORT": str(rdv_port),
    })
    return env


def _stream(proc: subprocess.Popen, rank: int, out,
            prefix_output: bool) -> None:
    for raw in iter(proc.stdout.readline, b""):
        line = raw.decode("utf-8", "replace")
        if prefix_output:
            out.write(f"[{rank}]<stdout>: {line}")
        else:
            out.write(line)
        out.flush()


class LaunchError(RuntimeError):
    def __init__(self, rank: int, returncode: int,
                 hostname: Optional[str] = None):
        from horovod_tpu.utils import env as env_util

        # Point the operator straight at the evidence: every rank's
        # flight recorder dumped into HVD_BLACKBOX_DIR on the way down
        # (telemetry/blackbox.py) — tools/hvd_postmortem.py names the
        # first cause from there.
        postmortem = (f"; postmortem: {env_util.blackbox_dir()}"
                      if env_util.blackbox_enabled() else "")
        super().__init__(
            f"worker rank {rank} exited with code {returncode}"
            + (f" on host {hostname}" if hostname else "")
            + postmortem)
        self.rank = rank
        self.returncode = returncode
        self.hostname = hostname


def _spawn_worker(
    slot: SlotInfo,
    command: Sequence[str],
    rdv_addr: str,
    rdv_port: int,
    env_extra: Optional[Dict[str, str]],
    ssh_port: Optional[int],
    ssh_identity_file: Optional[str],
    output,
    prefix_output: bool,
):
    """Start one worker (local exec or ssh) with its streaming thread."""
    env = worker_env(slot, rdv_addr, rdv_port, env_extra)
    stdin_payload = None
    if is_local(slot.hostname):
        argv = list(command)
        popen_env = env
    else:
        # -tt forces a remote pty so killing the local ssh client
        # HUPs the remote process group — fail-fast teardown reaches
        # remote workers, not just the local ssh processes.
        ssh_cmd = ["ssh", "-tt", "-o", "StrictHostKeyChecking=no"]
        if ssh_port:
            ssh_cmd += ["-p", str(ssh_port)]
        if ssh_identity_file:
            ssh_cmd += ["-i", ssh_identity_file]
        # Only HVD_* vars cross the ssh boundary (the reference passes
        # an explicit env list too, mpi_run.py -x); secrets go over
        # stdin, never argv.
        remote, stdin_payload = _remote_command(env, command)
        argv = ssh_cmd + [slot.hostname, remote]
        popen_env = dict(os.environ)
    proc = subprocess.Popen(
        argv, env=popen_env,
        stdin=subprocess.PIPE if stdin_payload else None,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, start_new_session=True)
    if stdin_payload:
        proc.stdin.write(stdin_payload.encode())
        proc.stdin.flush()
        proc.stdin.close()
    t = threading.Thread(target=_stream,
                         args=(proc, slot.rank, output, prefix_output),
                         daemon=True)
    t.start()
    return proc, t


def launch_workers(
    slots: Sequence[SlotInfo],
    command: Sequence[str],
    rdv_addr: str,
    rdv_port: int,
    *,
    env_extra: Optional[Dict[str, str]] = None,
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
    prefix_output: bool = True,
    output=None,
    kill_timeout: float = 5.0,
) -> None:
    """Run ``command`` on every slot; block until all exit.

    Any non-zero exit terminates the whole job (SIGTERM, then SIGKILL
    after ``kill_timeout``) and raises LaunchError for the first failure —
    the reference launcher's fail-fast contract (gloo_run.py:253-259).
    """
    output = output or sys.stdout
    procs: List[subprocess.Popen] = []
    threads: List[threading.Thread] = []

    for slot in slots:
        proc, t = _spawn_worker(slot, command, rdv_addr, rdv_port,
                                env_extra, ssh_port, ssh_identity_file,
                                output, prefix_output)
        procs.append(proc)
        threads.append(t)

    failure: Optional[LaunchError] = None
    alive = set(range(len(procs)))
    while alive and failure is None:
        for i in list(alive):
            rc = procs[i].poll()
            if rc is None:
                continue
            alive.discard(i)
            if rc != 0:
                failure = LaunchError(slots[i].rank, rc,
                                      hostname=slots[i].hostname)
                break
        time.sleep(0.05)

    if failure is not None:
        _terminate(procs, kill_timeout)
        for t in threads:
            t.join(timeout=2)
        raise failure

    for p in procs:
        p.wait()
    for t in threads:
        t.join(timeout=2)


def launch_workers_elastic(
    slots: Sequence[SlotInfo],
    command: Sequence[str],
    rdv_addr: str,
    rdv_port: int,
    *,
    min_np: int,
    max_np: int,
    env_extra: Optional[Dict[str, str]] = None,
    ssh_port: Optional[int] = None,
    ssh_identity_file: Optional[str] = None,
    prefix_output: bool = True,
    output=None,
    kill_timeout: float = 5.0,
    new_slots: Optional[Callable[[], List[SlotInfo]]] = None,
    on_failure: Optional[Callable[[str], None]] = None,
) -> None:
    """Elastic supervision: a dying worker does NOT kill the job.

    The in-process gang re-forms around failures (``elastic/run.py``),
    so the launcher's job is only to (a) keep supervising survivors,
    (b) spawn joiner processes on hosts ``new_slots()`` reports (fed by
    the discovery driver), capped at ``max_np`` live workers, and
    (c) declare the job failed only when fewer than ``min_np`` workers
    finished cleanly — the same floor the gang itself enforces.

    ``on_failure(hostname)`` fires per non-zero exit (blacklist feed).
    Joiners still pending once every original worker has exited are
    torn down and not counted as failures.
    """
    output = output or sys.stdout
    entries: List[dict] = []

    def _spawn(slot: SlotInfo, joiner: bool) -> None:
        extra = dict(env_extra or {})
        if joiner:
            extra["HVD_ELASTIC_JOINER"] = "1"
        proc, t = _spawn_worker(slot, command, rdv_addr, rdv_port,
                                extra, ssh_port, ssh_identity_file,
                                output, prefix_output)
        entries.append({"slot": slot, "proc": proc, "thread": t,
                        "joiner": joiner, "rc": None})

    for slot in slots:
        _spawn(slot, joiner=False)

    successes = 0
    first_failure: Optional[LaunchError] = None
    while True:
        live = [e for e in entries if e["rc"] is None]
        if not live:
            break
        for e in live:
            rc = e["proc"].poll()
            if rc is None:
                continue
            e["rc"] = rc
            if rc == 0:
                successes += 1
            else:
                slot = e["slot"]
                if first_failure is None:
                    first_failure = LaunchError(slot.rank, rc,
                                                hostname=slot.hostname)
                if on_failure is not None:
                    on_failure(slot.hostname)
                from horovod_tpu.utils import env as env_util
                pm = (f"; postmortem: {env_util.blackbox_dir()}"
                      if env_util.blackbox_enabled() else "")
                print(f"hvdrun: worker rank {slot.rank} on "
                      f"{slot.hostname} exited with code {rc}; the gang "
                      f"re-forms in process (elastic mode){pm}",
                      file=sys.stderr)
        originals_done = all(e["rc"] is not None for e in entries
                             if not e["joiner"])
        if originals_done:
            # Nobody left to admit a pending joiner — reap stragglers.
            stragglers = [e["proc"] for e in entries
                          if e["joiner"] and e["rc"] is None]
            if stragglers:
                _terminate(stragglers, kill_timeout)
                for e in entries:
                    if e["joiner"] and e["rc"] is None:
                        e["rc"] = e["proc"].poll()
            break
        if new_slots is not None:
            live_count = sum(1 for e in entries if e["rc"] is None)
            for slot in new_slots():
                if live_count >= max_np:
                    break
                _spawn(slot, joiner=True)
                live_count += 1
        time.sleep(0.05)

    for e in entries:
        e["thread"].join(timeout=2)
    if successes < min_np:
        raise first_failure if first_failure is not None else LaunchError(
            slots[0].rank if slots else 0, 1)


def _terminate(procs: List[subprocess.Popen], kill_timeout: float) -> None:
    for p in procs:
        if p.poll() is None:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
    deadline = time.monotonic() + kill_timeout
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs):
            return
        time.sleep(0.1)
    for p in procs:
        if p.poll() is None:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
