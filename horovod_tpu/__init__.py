"""horovod_tpu: a TPU-native distributed training framework with the
capabilities of Horovod v0.19.1.

Public API parity: ``import horovod_tpu as hvd`` gives the classic surface
(``hvd.init/rank/size/allreduce/allgather/broadcast/join/...``,
``DistributedOptimizer``, ``Compression``) — see
``horovod/common/basics.py`` and per-framework ``mpi_ops.py`` in the
reference.  TPU-native extensions live in ``horovod_tpu.parallel`` (device
meshes, in-graph collectives, hierarchical ICI/DCN reduction, sequence
parallelism) and ``horovod_tpu.ops`` (XLA + Pallas data plane).
"""

import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    # Honor an explicitly-requested JAX platform *in-process*.  Some
    # environments install a sitecustomize that registers an out-of-tree
    # PJRT plugin and force-selects it through jax.config — which silently
    # overrides the JAX_PLATFORMS env var.  Launched workers (and tests)
    # rely on that env var, so restore the user's choice before any
    # backend initializes.  Only acts when the config's first-priority
    # platform actually differs from the env's (so an env that itself
    # names the plugin platform is left untouched), and is a no-op once a
    # backend is live.
    try:
        import jax as _jax

        _want = _os.environ["JAX_PLATFORMS"]
        _have = _jax.config.jax_platforms or ""
        if _have.split(",")[0].strip() != _want.split(",")[0].strip():
            _jax.config.update("jax_platforms", _want)
    except Exception:  # backend already initialized, or no jax — leave it
        pass

from horovod_tpu.version import __version__  # noqa: F401

from horovod_tpu.basics import (  # noqa: F401
    cache_stats,
    cross_rank,
    cross_size,
    cuda_built,
    gloo_built,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    metrics_snapshot,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    rocm_built,
    shutdown,
    size,
    xla_built,
)
from horovod_tpu.common.types import (  # noqa: F401
    RanksFailedError,
    ReduceOp,
    ReplicaDivergenceError,
)
from horovod_tpu.ops.compression import Compression  # noqa: F401
from horovod_tpu.process_sets import ProcessSet  # noqa: F401
from horovod_tpu.ops.eager import (  # noqa: F401
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    broadcast_object,
    broadcast_parameters,
    grouped_allreduce,
    join,
    poll,
    reducescatter,
    reducescatter_async,
    sparse_allreduce,
    synchronize,
)
from horovod_tpu.parallel.optimizer import (  # noqa: F401
    DistributedOptimizer,
    allreduce_gradients,
    distributed_grad,
    distributed_value_and_grad,
)
from horovod_tpu import data  # noqa: F401  (sharded sampling + prefetch)
from horovod_tpu import elastic  # noqa: F401  (commit/rollback + re-form)
from horovod_tpu import integrity  # noqa: F401  (data-plane integrity)
from horovod_tpu import telemetry  # noqa: F401  (metrics registry/export)
from horovod_tpu.parallel.multihost import (  # noqa: F401
    init_jax_distributed,
)

# ReduceOp constants at top level, Horovod-style (basics.py:29-31).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
