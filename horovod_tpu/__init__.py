"""horovod_tpu: a TPU-native distributed training framework with the
capabilities of Horovod v0.19.1.

Public API parity: ``import horovod_tpu as hvd`` gives the classic surface
(``hvd.init/rank/size/allreduce/allgather/broadcast/join/...``,
``DistributedOptimizer``, ``Compression``) — see
``horovod/common/basics.py`` and per-framework ``mpi_ops.py`` in the
reference.  TPU-native extensions live in ``horovod_tpu.parallel`` (device
meshes, in-graph collectives, hierarchical ICI/DCN reduction, sequence
parallelism) and ``horovod_tpu.ops`` (XLA + Pallas data plane).
"""

from horovod_tpu.version import __version__  # noqa: F401

from horovod_tpu.basics import (  # noqa: F401
    cache_stats,
    cross_rank,
    cross_size,
    cuda_built,
    gloo_built,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    rocm_built,
    shutdown,
    size,
    xla_built,
)
from horovod_tpu.common.types import ReduceOp  # noqa: F401
from horovod_tpu.ops.compression import Compression  # noqa: F401
from horovod_tpu.ops.eager import (  # noqa: F401
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    broadcast_object,
    broadcast_parameters,
    grouped_allreduce,
    join,
    poll,
    synchronize,
)
from horovod_tpu.parallel.optimizer import (  # noqa: F401
    DistributedOptimizer,
    allreduce_gradients,
    distributed_grad,
    distributed_value_and_grad,
)

# ReduceOp constants at top level, Horovod-style (basics.py:29-31).
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
