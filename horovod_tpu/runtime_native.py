"""Python facade over the native C++ engine (``csrc/``).

``NativeEngine`` exposes the same interface as ``runtime_py.PyEngine`` —
``allreduce_async``/``allgather_async``/``broadcast_async``/
``alltoall_async``/``poll``/``synchronize``/``barrier``/``join``/
``shutdown`` — so ``horovod_tpu.ops.eager`` is engine-agnostic.  The two
engines speak the same wire protocol (csrc/wire.cc ≡ common/wire.py) and
run the same ring algorithms, so a job may mix them
(``HVD_TPU_CORE=py`` on some ranks) and still reduce bit-identically;
the multi-process tests exercise exactly that.

Buffer ownership: allreduce/broadcast run **in place** on the enqueue-side
numpy buffer (zero copy, like the reference's in-place torch path,
mpi_ops_v2.cc DoAllreduce with output == input); this class keeps the array
alive until its handle completes.  Allgather/alltoall outputs are sized by
negotiation, so the core owns them until ``synchronize`` copies them out.
"""

from __future__ import annotations

import ctypes
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.common.types import (
    DataType,
    ReduceOp,
    RequestType,
    StatusType,
    dtype_from_numpy,
)
from horovod_tpu.runner.discovery import block_topology_ok
from horovod_tpu.runtime_py import _np_dtype
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import timeline as timeline_mod


@dataclass
class _HandleMeta:
    kind: RequestType
    array: np.ndarray  # enqueue-side buffer, kept alive until completion
    dtype: DataType
    shape: tuple


class NativeEngine:
    """ctypes driver for the C++ engine; see module docstring."""

    def __init__(self, rank, size, local_rank, local_size,
                 cross_rank, cross_size, rdv_addr, rdv_port):
        from horovod_tpu import native
        from horovod_tpu.bootstrap import bootstrap_mesh

        # The recovery ladder (CRC trailers, NACK retransmit; see
        # csrc/wire.h) is a Python-engine data plane.  Refusing the knob
        # before any rendezvous traffic keeps the failure loud: a native
        # rank silently joining a CRC-armed gang would reduce peers'
        # 8-byte trailers as payload.
        from horovod_tpu.utils import env as _env_util

        if _env_util.wire_crc():
            raise RuntimeError(
                "HVD_WIRE_CRC=1 is not supported by the native engine; "
                "unset it or run the Python engine (HVD_TPU_CORE=py)")
        self._lib = native.load()
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size
        self.is_homogeneous = True
        self.native_fallback_reason = None
        # The native core writes the timeline itself (csrc/timeline.cc);
        # a Python writer here would clobber the same file.
        self.timeline = timeline_mod.Timeline()

        # shm_capable=False: the C++ core speaks TCP only, and the
        # published host record keeps every peer (including Python
        # engines on the same host) on the socket path against us.
        data, ctrl_sock, ctrl_socks, _kv, _prefix = bootstrap_mesh(
            rank, size, rdv_addr, rdv_port, shm_capable=False)

        # Hand the connected fds to the core, which owns them from now on.
        data_fds = (ctypes.c_int32 * size)(*[-1] * size)
        ctrl_fds = (ctypes.c_int32 * size)(*[-1] * size)
        for r, s in data.items():
            data_fds[r] = s.detach()
        if rank == 0:
            for r, s in ctrl_socks.items():
                ctrl_fds[r] = s.detach()
        elif ctrl_sock is not None:
            ctrl_fds[0] = ctrl_sock.detach()

        rc = self._lib.hvd_create(
            rank, size, local_rank, local_size, cross_rank, cross_size,
            data_fds, ctrl_fds,
            env_util.cycle_time_ms() / 1e3,
            env_util.fusion_threshold_bytes(),
            env_util.get_float(env_util.STALL_CHECK_TIME, 60.0),
            env_util.get_float(env_util.STALL_SHUTDOWN_TIME, 0.0),
            1 if env_util.get_bool(env_util.STALL_CHECK_DISABLE, False)
            else 0,
            env_util.get_int(env_util.CACHE_CAPACITY, 1024),
            1 if env_util.get_bool(env_util.HIERARCHICAL_ALLREDUCE, False)
            else 0,
            1 if env_util.get_bool(env_util.HIERARCHICAL_ALLGATHER, False)
            else 0,
            *self._autotune_args(
                block_topology_ok(rank, size, local_rank, local_size,
                                  cross_rank, cross_size)),
            (env_util.get_str(env_util.TIMELINE).encode() or None)
            if rank == 0 else None,
            1 if env_util.get_bool(env_util.TIMELINE_MARK_CYCLES, False)
            else 0)
        if rc != 0:
            raise OSError(self._lib.hvd_last_error().decode())

        self._meta: Dict[int, _HandleMeta] = {}
        self._meta_lock = threading.Lock()
        self._shutdown = False

        # Sets constructed before the engine existed (the coordinator and
        # non-members need the registry for lookup/skip, not just members
        # at enqueue time).
        from horovod_tpu import process_sets as _ps

        for sid, ranks in _ps.snapshot().items():
            self.register_process_set(sid, ranks)

    def register_process_set(self, set_id, ranks):
        arr = (ctypes.c_int32 * len(ranks))(*ranks)
        self._lib.hvd_register_process_set(set_id, arr, len(ranks))

    @staticmethod
    def _autotune_args(hierarchical_ok: bool = False):
        """hvd_create's autotune tail, from the shared env policy (single
        source: autotune.parameter_manager.autotune_options_from_env)."""
        from horovod_tpu.autotune.parameter_manager import (
            autotune_options_from_env,
        )

        opts = autotune_options_from_env(hierarchical_ok)
        if opts is None:
            return (0, 0, 0, 0, 0, 0, 0, 0, 0.0, None)
        return (1,
                1 if opts["tune_fusion"] else 0,
                1 if opts["tune_cycle"] else 0,
                1 if opts["tune_cache"] else 0,
                1 if opts["tune_hier_allreduce"] else 0,
                1 if opts["tune_hier_allgather"] else 0,
                opts["warmup_samples"], opts["max_samples"],
                opts["sample_duration_s"],
                opts["log_path"].encode() if opts["log_path"] else None)

    # -- enqueue -----------------------------------------------------------

    def _dims(self, arr: np.ndarray):
        nd = max(arr.ndim, 1)
        dims = (ctypes.c_int64 * nd)(*(arr.shape or (1,)))
        return nd if arr.ndim else 0, dims

    def _raise_enqueue_error(self):
        msg = self._lib.hvd_last_error().decode()
        # Argument errors mirror the Python engine's ValueError surface.
        if any(k in msg for k in ("same name", "out of range", "splits",
                                  "divisible", "per participant")):
            raise ValueError(msg)
        raise RuntimeError(msg)


    def _ps_args(self, process_set):
        """Validate a ProcessSet and return the (id, size) C-API args.
        (Registration with the C++ core happens at ProcessSet
        construction / engine startup, not per call.)"""
        if process_set is None:
            return 0, 0
        return process_set.validate(self.rank, self.size)

    def allreduce_async(self, name, array, op=ReduceOp.SUM,
                        prescale=1.0, postscale=1.0, process_set=None):
        arr = np.ascontiguousarray(array)
        if arr is array:  # in-place op: never clobber the caller's array
            arr = arr.copy()
        dt = dtype_from_numpy(arr.dtype)
        nd, dims = self._dims(arr)
        ps_id, ps_size = self._ps_args(process_set)
        h = self._lib.hvd_allreduce_async(
            name.encode(), arr.ctypes.data, nd if arr.ndim else 0, dims,
            int(dt), int(op), prescale, postscale, ps_id, ps_size)
        if h < 0:
            self._raise_enqueue_error()
        with self._meta_lock:
            self._meta[h] = _HandleMeta(
                RequestType.ALLREDUCE, arr, dt, arr.shape)
        return h

    def allgather_async(self, name, array, process_set=None):
        arr = np.ascontiguousarray(array)
        dt = dtype_from_numpy(arr.dtype)
        nd, dims = self._dims(arr)
        ps_id, ps_size = self._ps_args(process_set)
        h = self._lib.hvd_allgather_async(
            name.encode(), arr.ctypes.data, nd if arr.ndim else 0, dims,
            int(dt), ps_id, ps_size)
        if h < 0:
            self._raise_enqueue_error()
        with self._meta_lock:
            self._meta[h] = _HandleMeta(
                RequestType.ALLGATHER, arr, dt, arr.shape)
        return h

    def reducescatter_async(self, name, array, op=ReduceOp.SUM,
                            process_set=None):
        arr = np.ascontiguousarray(array)
        if arr.ndim == 0:
            raise ValueError(
                "reducescatter needs at least one dimension to scatter "
                "over (got a scalar)")
        dt = dtype_from_numpy(arr.dtype)
        nd, dims = self._dims(arr)
        ps_id, ps_size = self._ps_args(process_set)
        h = self._lib.hvd_reducescatter_async(
            name.encode(), arr.ctypes.data, nd, dims, int(dt), int(op),
            ps_id, ps_size)
        if h < 0:
            self._raise_enqueue_error()
        with self._meta_lock:
            self._meta[h] = _HandleMeta(
                RequestType.REDUCESCATTER, arr, dt, arr.shape)
        return h

    def broadcast_async(self, name, array, root_rank=0,
                        process_set=None):
        arr = np.ascontiguousarray(array)
        if arr is array:
            arr = arr.copy()
        dt = dtype_from_numpy(arr.dtype)
        nd, dims = self._dims(arr)
        ps_id, ps_size = self._ps_args(process_set)
        if process_set is not None and \
                root_rank not in process_set.ranks:
            raise ValueError(
                f"broadcast root rank {root_rank} (global) is not a "
                f"member of {process_set}")
        h = self._lib.hvd_broadcast_async(
            name.encode(), arr.ctypes.data, nd if arr.ndim else 0, dims,
            int(dt), root_rank, ps_id, ps_size)
        if h < 0:
            self._raise_enqueue_error()
        with self._meta_lock:
            self._meta[h] = _HandleMeta(
                RequestType.BROADCAST, arr, dt, arr.shape)
        return h

    def alltoall_async(self, name, array, splits: Optional[List[int]] = None,
                       process_set=None):
        arr = np.ascontiguousarray(array)
        dt = dtype_from_numpy(arr.dtype)
        nd, dims = self._dims(arr)
        ps_id, ps_size = self._ps_args(process_set)
        if splits is not None:
            splits = [int(s) for s in splits]
            if sum(splits) != (arr.shape[0] if arr.ndim else 0):
                raise ValueError("splits must sum to dim 0")
            carr = (ctypes.c_int64 * len(splits))(*splits)
            h = self._lib.hvd_alltoall_async(
                name.encode(), arr.ctypes.data, nd, dims, int(dt), carr,
                len(splits), ps_id, ps_size)
        else:
            h = self._lib.hvd_alltoall_async(
                name.encode(), arr.ctypes.data, nd, dims, int(dt), None,
                0, ps_id, ps_size)
        if h < 0:
            self._raise_enqueue_error()
        with self._meta_lock:
            self._meta[h] = _HandleMeta(
                RequestType.ALLTOALL, arr, dt, arr.shape)
        return h

    # -- completion --------------------------------------------------------

    def poll(self, handle: int) -> bool:
        rc = self._lib.hvd_poll(handle)
        if rc < 0:
            raise ValueError(f"unknown handle {handle}")
        return rc == 1

    def synchronize(self, handle: int, timeout=None):
        st = StatusType(self._lib.hvd_wait(handle))
        with self._meta_lock:
            meta = self._meta.pop(handle, None)
        if st != StatusType.OK:
            msg = self._lib.hvd_handle_error(handle).decode()
            self._lib.hvd_release(handle)
            raise RuntimeError(msg or "collective failed")
        try:
            if meta is None:
                return None
            if meta.kind in (RequestType.ALLREDUCE, RequestType.BROADCAST):
                return meta.array  # reduced/received in place
            np_dt = _np_dtype(meta.dtype)
            nbytes = self._lib.hvd_result_nbytes(handle)
            src = self._lib.hvd_result_data(handle)
            if nbytes <= 0 or src is None:
                out = np.zeros((0,) + meta.shape[1:], np_dt)
            else:
                buf = ctypes.string_at(src, nbytes)
                out = np.frombuffer(buf, dtype=np_dt).copy()
                rest = meta.shape[1:]
                out = out.reshape((-1,) + rest)
            if meta.kind == RequestType.ALLTOALL:
                cap = self.size
                sp = (ctypes.c_int64 * cap)()
                n = self._lib.hvd_result_splits(handle, sp, cap)
                return out, [int(sp[i]) for i in range(max(n, 0))]
            return out
        finally:
            self._lib.hvd_release(handle)

    # -- blocking ops ------------------------------------------------------

    def barrier(self, process_set=None):
        ps_id, ps_size = self._ps_args(process_set)
        rc = self._lib.hvd_barrier(ps_id, ps_size)
        if rc != 0:
            raise RuntimeError(self._lib.hvd_last_error().decode())

    def cache_stats(self):
        out = (ctypes.c_int64 * 5)()
        self._lib.hvd_cache_stats(out)
        return {"hits": out[0], "misses": out[1], "evictions": out[2],
                "size": out[3], "capacity": out[4]}

    def join(self) -> int:
        return self._lib.hvd_join()

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        self._lib.hvd_shutdown()
        self.timeline.shutdown()
