"""Continuous-batching admission scheduler (rank 0 only).

The scheduler owns the request lifecycle on the coordinator: HTTP
handler threads ``submit()`` prompts into a bounded FIFO queue, the
serving loop moves queued requests into free decode slots at token
boundaries (``take_admissions``), appends sampled tokens
(``on_token``), and completes or replays them.  Worker ranks never see
this class — they reconstruct identical slot state from the broadcast
deltas (loop.py).

Thread-safety: handler threads and the serving-loop thread share one
lock; completion is signalled per-request through an Event the handler
blocks on.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from horovod_tpu.telemetry import registry as _tmx

# Completed requests kept around for join-by-id (a client re-POSTing an
# id after a leader fail-over must get the finished answer, not a
# duplicate decode).  Bounded so serving forever never grows memory.
_RECENT_CAP = 256


class QueueFull(Exception):
    """Admission queue is at HVD_SERVE_MAX_QUEUE — shed (HTTP 503)."""


class Request:
    """One /generate request through its life: queued -> active (slot
    assigned) -> done.  ``tokens`` holds only the generated tail, never
    the prompt."""

    def __init__(self, req_id: str, prompt: List[int], max_new: int):
        self.id = req_id
        self.prompt = prompt
        self.max_new = max_new
        self.tokens: List[int] = []
        self.slot: Optional[int] = None
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.t_submit = time.monotonic()
        self.t_first_token: Optional[float] = None
        # Bumped on each replay admission: a re-formed gang decodes the
        # request from the prompt again (at-least-once), so the token
        # tail is rebuilt from scratch.
        self.attempts = 0


class Scheduler:
    def __init__(self, max_batch: int, max_queue: int, cache_len: int):
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.cache_len = cache_len
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._slots: List[Optional[Request]] = [None] * max_batch
        self._ids = itertools.count()
        self._completed = 0
        self._recent: "OrderedDict[str, Request]" = OrderedDict()
        # Monotonic stamp of the last gang-confirmed decode step, fed
        # by the serving loop (loop.py reuses the latency read it
        # already takes).  /stats derives last_step_age_s from it so an
        # external router can spot a wedged gang before clients time
        # out; 0.0 = no step confirmed yet this incarnation.
        self._last_step_t = 0.0

    def _find(self, req_id: str) -> Optional[Request]:
        """A live or recently-completed request with this id, else None.
        Caller holds the lock."""
        for r in self._queue:
            if r.id == req_id:
                return r
        for r in self._slots:
            if r is not None and r.id == req_id:
                return r
        return self._recent.get(req_id)

    # -- handler-thread side -------------------------------------------

    def submit(self, prompt: List[int], max_new: int,
               req_id: Optional[str] = None) -> Request:
        """Queue a request; raises ValueError on an unservable shape and
        QueueFull when the admission queue is at its bound.

        ``req_id`` (optional, client-supplied) makes the submit
        idempotent: when a request with that id is already queued,
        active, or recently completed, the existing Request is returned
        instead of a duplicate — the re-POST a client issues after a
        leader fail-over joins the shadow-replayed original."""
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new > self.cache_len:
            raise ValueError(
                f"prompt + max_new_tokens ({len(prompt) + max_new}) "
                f"exceeds the serving cache length ({self.cache_len})")
        with self._lock:
            if req_id is not None:
                existing = self._find(req_id)
                if existing is not None:
                    return existing
            if len(self._queue) >= self.max_queue:
                raise QueueFull(
                    f"admission queue full ({self.max_queue})")
            req = Request(req_id or f"r{next(self._ids)}",
                          list(prompt), max_new)
            self._queue.append(req)
            _tmx.set_gauge("hvd_serve_queue_depth", len(self._queue))
        return req

    # -- leader fail-over (promoted rank) -------------------------------

    def adopt_shadow(self, entries: List[Tuple[int, Dict]]) -> int:
        """Seed a fresh scheduler (on a worker just promoted to rank 0)
        with the dead leader's in-flight slot table, reconstructed from
        the broadcast delta frames: ``entries`` is a ``(slot, {"id",
        "prompt", "max_new", ...})`` list.  Each becomes a queued
        Request with ``attempts=1`` — the lost incarnation's decode was
        attempt 1, so the replay the new leader admits reports
        ``attempts >= 2`` (at-least-once, like requeue_inflight).
        Returns how many were adopted."""
        adopted = 0
        with self._lock:
            for slot, st in sorted(entries, key=lambda e: e[0]):
                if self._find(st["id"]) is not None:
                    continue  # already known (e.g. client re-POST won)
                req = Request(st["id"], list(st["prompt"]),
                              int(st["max_new"]))
                req.attempts = 1
                self._queue.append(req)
                adopted += 1
            if adopted:
                _tmx.set_gauge("hvd_serve_queue_depth", len(self._queue))
        for _ in range(adopted):
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("replayed",))
        return adopted

    # -- serving-loop side ---------------------------------------------

    def take_admissions(self) -> List[Tuple[int, Request]]:
        """Move queued requests into free slots (FIFO, as many as fit);
        returns the (slot, request) pairs admitted this step."""
        out: List[Tuple[int, Request]] = []
        with self._lock:
            for slot in range(self.max_batch):
                if self._slots[slot] is not None or not self._queue:
                    continue
                req = self._queue.popleft()
                req.slot = slot
                req.attempts += 1
                self._slots[slot] = req
                out.append((slot, req))
            if out:
                _tmx.set_gauge("hvd_serve_queue_depth", len(self._queue))
                _tmx.set_gauge("hvd_serve_batch_occupancy",
                               self.active_count())
        return out

    def on_token(self, slot: int, token: int) -> Request:
        """Append one sampled token to the slot's request (first token
        records TTFT)."""
        with self._lock:
            req = self._slots[slot]
            assert req is not None, f"token for empty slot {slot}"
            if not req.tokens:
                req.t_first_token = time.monotonic()
                _tmx.observe("hvd_serve_ttft_seconds",
                             req.t_first_token - req.t_submit)
            req.tokens.append(token)
        return req

    def complete(self, slot: int) -> None:
        """Retire the slot's request and wake its handler thread."""
        with self._lock:
            req = self._slots[slot]
            assert req is not None, f"complete() on empty slot {slot}"
            self._slots[slot] = None
            self._completed += 1
            self._recent[req.id] = req
            while len(self._recent) > _RECENT_CAP:
                self._recent.popitem(last=False)
            _tmx.set_gauge("hvd_serve_batch_occupancy",
                           self.active_count())
        _tmx.inc_counter("hvd_serve_requests_total", labels=("ok",))
        req.done.set()

    def requeue_inflight(self) -> int:
        """At-least-once replay after a gang re-form: every active
        request goes back to the FRONT of the queue (original admission
        order) with its token tail cleared — the re-formed gang decodes
        it from the prompt again.  Returns how many were requeued."""
        with self._lock:
            inflight = [r for r in self._slots if r is not None]
            inflight.sort(key=lambda r: r.t_submit)
            for req in reversed(inflight):
                req.tokens = []
                req.slot = None
                self._queue.appendleft(req)
            self._slots = [None] * self.max_batch
            if inflight:
                _tmx.set_gauge("hvd_serve_queue_depth", len(self._queue))
                _tmx.set_gauge("hvd_serve_batch_occupancy", 0)
        for _ in inflight:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("replayed",))
        return len(inflight)

    def fail_all(self, reason: str) -> None:
        """Unrecoverable serving failure: error out every queued and
        active request so no handler thread blocks forever."""
        with self._lock:
            pending = [r for r in self._slots if r is not None]
            pending.extend(self._queue)
            self._queue.clear()
            self._slots = [None] * self.max_batch
        for req in pending:
            req.error = reason
            req.done.set()

    # -- introspection ---------------------------------------------------

    def active_count(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    def active_slots(self) -> Dict[int, Request]:
        with self._lock:
            return {i: r for i, r in enumerate(self._slots)
                    if r is not None}

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._queue) or \
                any(r is not None for r in self._slots)

    def note_step(self, t: float) -> None:
        """The serving loop confirmed a decode step at monotonic time
        ``t`` (a read the loop already took for its latency metric)."""
        self._last_step_t = t

    def stats(self) -> Dict[str, float]:
        now = time.monotonic()
        with self._lock:
            oldest = min((r.t_submit for r in self._queue), default=now)
            out = {
                "queued": len(self._queue),
                "active": sum(1 for r in self._slots if r is not None),
                "slots": self.max_batch,
                "completed": self._completed,
                # Staleness surface for external probes: how long since
                # the gang last stepped, and how long the oldest queued
                # request has been starving.
                "last_step_age_s": round(
                    now - self._last_step_t, 3)
                    if self._last_step_t else 0.0,
                "oldest_queued_age_s": round(now - oldest, 3),
            }
        _tmx.set_gauge("hvd_serve_last_step_age_seconds",
                       out["last_step_age_s"])
        _tmx.set_gauge("hvd_serve_oldest_queued_age_seconds",
                       out["oldest_queued_age_s"])
        # SLO rollups from the registry's serve histograms (the same
        # bucket math the gang aggregator uses), when telemetry is on.
        if _tmx.enabled():
            hists = _tmx.snapshot().get("histograms", {})
            for metric, key in (("hvd_serve_ttft_seconds", "ttft"),
                                ("hvd_serve_token_latency_seconds",
                                 "step")):
                h = hists.get(metric)
                if h and h.get("count"):
                    out[f"{key}_p50_ms"] = round(
                        1e3 * _tmx.histogram_quantile(h, 0.50), 3)
                    out[f"{key}_p99_ms"] = round(
                        1e3 * _tmx.histogram_quantile(h, 0.99), 3)
        return out
