"""Rank-0 HTTP front door for the serving gang.

Same ThreadingHTTPServer shape as the metrics debug server
(telemetry/server.py) and the rendezvous server: HTTP/1.1 keep-alive,
silent request logging, chaos-shed hook first.  ``POST /generate``
blocks the handler thread until the scheduler completes (or fails) the
request; ``GET /stats`` and ``GET /health`` answer immediately.

Shedding is explicit and typed: the ``serve.admit`` chaos site or a
full admission queue answers 503 (the client's signal to back off or
go to another replica), a malformed body 400, and a request that
outlives ``timeout_s`` 504 — the handler gives up, the request itself
stays admitted (at-least-once, not exactly-once).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.serving.scheduler import QueueFull, Scheduler
from horovod_tpu.telemetry import registry as _tmx


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    scheduler: Scheduler = None  # class attrs installed by FrontDoor
    timeout_s: float = 120.0

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _chaos_unavailable(self) -> bool:
        try:
            _fi.fire("serve.admit", f"{self.command} {self.path}")
        except _fi.InjectedFault:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("shed",))
            self._send(503, b"", "text/plain")
            return True
        return False

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj).encode("utf-8"),
                   "application/json")

    def do_GET(self):
        if self._chaos_unavailable():
            return
        if self.path == "/health":
            self._send(200, b"ok", "text/plain")
            return
        if self.path == "/stats":
            self._send_json(200, self.scheduler.stats())
            return
        self._send(404, b"", "text/plain")

    def do_POST(self):
        if self._chaos_unavailable():
            return
        if self.path != "/generate":
            self._send(404, b"", "text/plain")
            return
        try:
            n = int(self.headers.get("Content-Length", "0"))
            body = json.loads(self.rfile.read(n) or b"{}")
            prompt = [int(t) for t in body["prompt"]]
            max_new = int(body.get("max_new_tokens", 16))
        except (ValueError, KeyError, TypeError, json.JSONDecodeError):
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("error",))
            self._send_json(400, {"error": "bad request body"})
            return
        try:
            req = self.scheduler.submit(prompt, max_new)
        except QueueFull as e:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("shed",))
            self._send_json(503, {"error": str(e)})
            return
        except ValueError as e:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("error",))
            self._send_json(400, {"error": str(e)})
            return
        if not req.done.wait(self.timeout_s):
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("error",))
            self._send_json(504, {"error": "request timed out",
                                  "id": req.id})
            return
        if req.error is not None:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("error",))
            self._send_json(500, {"error": req.error, "id": req.id})
            return
        import time

        now = time.monotonic()
        self._send_json(200, {
            "id": req.id,
            "tokens": req.tokens,
            "attempts": req.attempts,
            "ttft_ms": round((req.t_first_token - req.t_submit) * 1e3, 3)
            if req.t_first_token else None,
            "latency_ms": round((now - req.t_submit) * 1e3, 3),
        })


class FrontDoor:
    """Threaded /generate endpoint on rank 0; ``start()`` returns the
    bound port.  Survives gang re-forms — the scheduler (and the
    handler threads parked on request Events) belong to the process,
    not to an engine incarnation."""

    def __init__(self, scheduler: Scheduler, *, host: str = "0.0.0.0",
                 port: int = 0, timeout_s: float = 120.0):
        handler = type("_BoundHandler", (_Handler,),
                       {"scheduler": scheduler, "timeout_s": timeout_s})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-serve-http",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()
