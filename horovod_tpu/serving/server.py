"""Per-rank HTTP front door for the serving gang.

Same ThreadingHTTPServer shape as the metrics debug server
(telemetry/server.py) and the rendezvous server: HTTP/1.1 keep-alive,
silent request logging, chaos-shed hook first.  ``POST /generate``
blocks the handler thread until the scheduler completes (or fails) the
request; ``GET /stats`` and ``GET /health`` answer immediately.

Every rank runs one door for the life of the process; its role is
dynamic.  On the leader (``door.scheduler`` set) requests are admitted
locally.  On followers (``door.scheduler is None``) the door is a thin
forwarding proxy: the body is relayed to the current leader's door
(address learned from the serve-delta frames / the elastic-scoped KV
key) and the answer streamed back — so clients keep one stable
endpoint per rank across leader re-elections.

Shedding is explicit and typed: the ``serve.admit`` chaos site or a
full admission queue answers 503 (the client's signal to back off or
go to another replica), a malformed body 400, and a request that
outlives ``timeout_s`` 504 — the handler gives up, the request itself
stays admitted (at-least-once, not exactly-once).  A follower whose
leader is unknown or unreachable also answers 503 — retryable, the
re-election publishes a fresh address within the client's backoff.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.serving.scheduler import QueueFull, Scheduler
from horovod_tpu.telemetry import registry as _tmx


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    door: "FrontDoor" = None  # class attr installed by FrontDoor

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _chaos_unavailable(self) -> bool:
        try:
            _fi.fire("serve.admit", f"{self.command} {self.path}")
        except _fi.InjectedFault:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("shed",))
            self._send(503, b"", "text/plain")
            return True
        return False

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj).encode("utf-8"),
                   "application/json")

    def do_GET(self):
        if self._chaos_unavailable():
            return
        if self.path == "/health":
            self._send(200, b"ok", "text/plain")
            return
        if self.path == "/stats":
            scheduler = self.door.scheduler
            if scheduler is None:
                self._send_json(200, {
                    "role": "follower",
                    "leader": self.door.leader_addr() or None,
                })
                return
            stats = scheduler.stats()
            stats["role"] = "leader"
            self._send_json(200, stats)
            return
        self._send(404, b"", "text/plain")

    def do_POST(self):
        if self._chaos_unavailable():
            return
        if self.path != "/generate":
            self._send(404, b"", "text/plain")
            return
        n = int(self.headers.get("Content-Length", "0"))
        raw = self.rfile.read(n)
        scheduler = self.door.scheduler
        if scheduler is None:
            self._forward(raw)
            return
        try:
            body = json.loads(raw or b"{}")
            prompt = [int(t) for t in body["prompt"]]
            max_new = int(body.get("max_new_tokens", 16))
            req_id = body.get("id")
            if req_id is not None and (not isinstance(req_id, str)
                                       or not req_id):
                raise ValueError("id must be a non-empty string")
        except (ValueError, KeyError, TypeError, json.JSONDecodeError):
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("error",))
            self._send_json(400, {"error": "bad request body"})
            return
        try:
            req = scheduler.submit(prompt, max_new, req_id=req_id)
        except QueueFull as e:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("shed",))
            self._send_json(503, {"error": str(e)})
            return
        except ValueError as e:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("error",))
            self._send_json(400, {"error": str(e)})
            return
        if not req.done.wait(self.door.timeout_s):
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("error",))
            self._send_json(504, {"error": "request timed out",
                                  "id": req.id})
            return
        if req.error is not None:
            _tmx.inc_counter("hvd_serve_requests_total",
                             labels=("error",))
            self._send_json(500, {"error": req.error, "id": req.id})
            return
        import time

        now = time.monotonic()
        self._send_json(200, {
            "id": req.id,
            "tokens": req.tokens,
            "attempts": req.attempts,
            "ttft_ms": round((req.t_first_token - req.t_submit) * 1e3, 3)
            if req.t_first_token else None,
            "latency_ms": round((now - req.t_submit) * 1e3, 3),
        })

    # -- follower: proxy to the current leader --------------------------

    def _forward(self, raw: bytes) -> None:
        """Relay the POST body to the leader's /generate and stream the
        answer back.  One refresh+retry on a dead leader address (the
        re-elected leader republishes under the KV key); still
        unreachable -> 503, the retryable answer."""
        addr = self.door.leader_addr()
        for attempt in (0, 1):
            if attempt:
                addr = self.door.leader_addr(refresh=True)
            if not addr or addr == self.door.advertised_addr():
                # Unknown leader, or a stale pointer at ourselves while
                # we hold no scheduler: nothing to proxy to yet.
                continue
            try:
                req = urllib.request.Request(
                    f"http://{addr}/generate", data=raw, method="POST",
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(
                        req, timeout=self.door.timeout_s) as r:
                    self._send(r.status, r.read(),
                               r.headers.get("Content-Type",
                                             "application/json"))
                return
            except urllib.error.HTTPError as e:
                # The leader answered (400/503/...): relay its verdict.
                self._send(e.code, e.read(),
                           e.headers.get("Content-Type",
                                         "application/json"))
                return
            except (urllib.error.URLError, ConnectionError, OSError):
                continue
        _tmx.inc_counter("hvd_serve_requests_total", labels=("shed",))
        self._send_json(503, {"error": "serving leader unreachable; "
                                       "retry after re-election"})


class FrontDoor:
    """Threaded /generate endpoint, one per rank; ``start()`` returns
    the bound port.  Survives gang re-forms — the scheduler (and the
    handler threads parked on request Events) belong to the process,
    not to an engine incarnation.  ``scheduler`` is mutable: flipping it
    from None to a live Scheduler promotes the door from forwarding
    follower to admitting leader (and back is never needed — a demoted
    leader is a dead process).

    ``leader_addr_fn(refresh)``: returns the current leader's
    ``host:port`` or None; ``refresh=True`` asks for an authoritative
    re-read (the KV key) rather than the frame-cached value."""

    def __init__(self, scheduler: Optional[Scheduler], *,
                 host: str = "0.0.0.0", port: int = 0,
                 timeout_s: float = 120.0,
                 leader_addr_fn:
                 Optional[Callable[..., Optional[str]]] = None,
                 advertise_host: str = "127.0.0.1"):
        self.scheduler = scheduler
        self.timeout_s = timeout_s
        self._leader_addr_fn = leader_addr_fn
        self._advertise_host = advertise_host
        handler = type("_BoundHandler", (_Handler,), {"door": self})
        try:
            self._httpd = ThreadingHTTPServer((host, port), handler)
        except OSError:
            if port == 0:
                raise
            # Configured port taken (several ranks of one host): an
            # ephemeral port keeps the door up; the launcher/KV carries
            # the real address to clients.
            self._httpd = ThreadingHTTPServer((host, 0), handler)
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def leader_addr(self, refresh: bool = False) -> Optional[str]:
        if self._leader_addr_fn is None:
            return None
        return self._leader_addr_fn(refresh=refresh)

    def advertised_addr(self) -> str:
        return f"{self._advertise_host}:{self.port}"

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-serve-http",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()
