"""Per-rank continuous-batching decode state.

One :class:`DecodeEngine` lives on every rank of the serving gang and
holds the slot-batched KV caches ([L, max_batch, cache_len, H, HD]), the
per-slot current token and position vectors, and the jit-ed step
(models/transformer.decode_step, donated caches — the update is
in-place, no per-step reallocation).  The per-slot math is bit-identical
to the single-request ``generate`` path, so a slot's output never
depends on what its neighbors are decoding (pinned by
tests/test_serving.py oracles).

Long-context KV shards over the mesh via the model's KV_CACHE_SPEC
(heads over ``tp``) — the same ``parallel/`` mesh-spec plumbing training
uses, applied with ``filter_spec`` so a spec axis missing from the mesh
degrades to replication.

Prefill compiles once per distinct prompt length (the serving analogue
of generate()'s per-shape compile).  Greedy sampling only: determinism
is what lets every rank step without exchanging tokens and lets a
re-formed gang replay a request to the identical completion.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.models import transformer as T


class DecodeEngine:
    def __init__(self, params, cfg: T.TransformerConfig, *,
                 max_batch: int, cache_len: Optional[int] = None,
                 mesh=None):
        if cfg.n_experts:
            raise NotImplementedError(
                "serving supports dense-FFN configs (same contract as "
                "models.transformer.generate)")
        self.params = params
        self.cfg = cfg
        self.max_batch = max_batch
        self.cache_len = cache_len or cfg.max_seq_len
        self.mesh = mesh
        L, H, HD = cfg.n_layers, cfg.n_heads, cfg.head_dim
        shape = (L, max_batch, self.cache_len, H, HD)
        self.ks = jnp.zeros(shape, cfg.compute_dtype)
        self.vs = jnp.zeros(shape, cfg.compute_dtype)
        if mesh is not None:
            from horovod_tpu.parallel.mesh import sharding_for

            sharding = sharding_for(mesh, T.KV_CACHE_SPEC)
            self.ks = jax.device_put(self.ks, sharding)
            self.vs = jax.device_put(self.vs, sharding)
        self.tok = jnp.zeros((max_batch,), jnp.int32)
        self.pos = jnp.zeros((max_batch,), jnp.int32)
        self._step = jax.jit(partial(T.decode_step, cfg=cfg),
                             donate_argnums=(3, 4))
        self._prefills: Dict[int, object] = {}  # prompt len -> jit fn

    def prefill(self, slot: int, prompt: List[int]) -> int:
        """Run the prompt through the model, install its K/V into the
        slot's cache lane, and return the first sampled (greedy) token.
        The slot is live from the next step() on."""
        fn = self._prefills.get(len(prompt))
        if fn is None:
            fn = jax.jit(partial(T.prefill_request, cfg=self.cfg,
                                 cache_len=self.cache_len))
            self._prefills[len(prompt)] = fn
        logits, ks1, vs1 = fn(self.params,
                              jnp.asarray(prompt, jnp.int32))
        self.ks = self.ks.at[:, slot].set(ks1[:, 0])
        self.vs = self.vs.at[:, slot].set(vs1[:, 0])
        first = int(jnp.argmax(logits))
        self.tok = self.tok.at[slot].set(first)
        self.pos = self.pos.at[slot].set(len(prompt))
        return first

    def clear(self, slot: int) -> None:
        """Retire a slot.  The cache lane is left as-is — the position
        mask hides it, and the next admission's prefill overwrites it."""
        self.tok = self.tok.at[slot].set(0)
        self.pos = self.pos.at[slot].set(0)

    def step(self) -> np.ndarray:
        """One decode step for the whole batch; returns the [max_batch]
        greedy next-token vector (free slots compute harmless garbage —
        rows are independent)."""
        logits, self.ks, self.vs = self._step(
            self.params, self.tok, self.pos, self.ks, self.vs)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        self.tok = nxt
        # Clamp so an idle slot parked at the cap can never scatter out
        # of bounds; an active slot retires before reaching it.
        self.pos = jnp.minimum(self.pos + 1, self.cache_len - 1)
        return np.asarray(nxt)
