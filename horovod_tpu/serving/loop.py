"""The gang-wide serving loop: lockstep continuous-batching decode.

One :class:`ServingLoop` runs on every rank (``run()`` blocks for the
life of the deployment).  Rank 0 drives: it drains the scheduler's
admission queue into free slots at each token boundary, encodes the
batch delta as one ``TAG_SERVE`` frame (common/wire.py ServeDelta) and
pushes it to every rank over the control channel
(``runtime_py.serve_broadcast``) — including itself, so coordinator and
workers execute the identical ``_apply_frame`` path.  Every rank then
prefills the admitted prompts, steps the shared jit-ed decode function,
and retires finished slots.  Greedy decode is deterministic, so
retirements need no broadcast: every rank computes the same tokens and
drops the same slots.

Robustness is composed from the existing machinery, not rebuilt:

* Each step ends in a tiny token-agreement allreduce
  (``__serve.confirm``, MAX over the next-token vector).  That gives the
  PR-6 collective deadline a data-plane op to bound — a rank wedged in
  the ring trips the hop deadline, the gang-wide abort agreement names
  it, and the survivors raise :class:`CollectiveTimeoutError` out of
  this loop.  It also feeds the per-step straggler detector (a
  chaos-delayed rank is consistently last into the negotiation and gets
  a STRAGGLER timeline record), and doubles as a determinism check:
  if any rank's tokens differ from the gang max, greedy lockstep has
  diverged and the step fails loudly rather than serving garbage.
* The epoch body is wrapped in ``@hvd.elastic.run``: on an abort the
  gang re-forms in process, a fresh :class:`DecodeEngine` is built
  against the new world, and rank 0 requeues every in-flight request at
  the front of the queue (``Scheduler.requeue_inflight``) — requests are
  replayed from their prompts, at-least-once, to the bit-identical
  completion (greedy).  The HTTP front door and its parked handler
  threads belong to the process, so clients only observe added latency.

A rank that stalls *outside* the data plane (``serve.step`` chaos site,
kind=stall) is invisible to the collective deadline — it never submits,
so there is no hung collective to abort, only the coordinator's stalled-
tensor warnings (see docs/serving.md for why that distinction matters).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.common import wire
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.serving.decode import DecodeEngine
from horovod_tpu.serving.scheduler import Scheduler
from horovod_tpu.serving.server import FrontDoor
from horovod_tpu.telemetry import blackbox as _bb
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils.logging import get_logger


class ServingLoop:
    """Continuous-batching inference over the live gang.

    ``run()`` initializes (if needed), starts the rank-0 front door, and
    blocks serving until ``stop()`` — surviving rank failures via
    elastic re-forms along the way.  Knobs default from the
    ``HVD_SERVE_*`` environment (utils/env.py; set by ``hvdrun
    --serve-port/--serve-max-batch/--serve-max-queue``).
    """

    def __init__(self, params, cfg, *,
                 max_batch: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 port: Optional[int] = None,
                 host: str = "0.0.0.0",
                 cache_len: Optional[int] = None,
                 mesh=None,
                 eos_id: Optional[int] = None,
                 request_timeout_s: float = 120.0,
                 recv_timeout_s: float = 1.0,
                 idle_poll_s: float = 0.002,
                 on_ready: Optional[Callable[[int], None]] = None):
        self.params = params
        self.cfg = cfg
        self.max_batch = max_batch or env_util.serve_max_batch()
        self.max_queue = max_queue or env_util.serve_max_queue()
        self.port = env_util.serve_port() if port is None else port
        self.host = host
        self.cache_len = cache_len or cfg.max_seq_len
        self.mesh = mesh
        self.eos_id = eos_id
        self.request_timeout_s = request_timeout_s
        self.recv_timeout_s = recv_timeout_s
        self.idle_poll_s = idle_poll_s
        self.on_ready = on_ready
        self.scheduler: Optional[Scheduler] = None
        self._door: Optional[FrontDoor] = None
        self._stop = threading.Event()
        # slot -> {"id", "prompt", "max_new", "remaining"}.  Every rank
        # derives the same dict from the same frame stream — this IS the
        # follower's shadow of the leader's in-flight table: a rank
        # promoted to 0 by a re-form seeds its fresh scheduler from it
        # (prompt + max_new are all a replay needs; greedy decode
        # rebuilds the token tail bit-identically).
        self._slots: Dict[int, Dict] = {}
        # Leader front-door address ("host:port") as last seen in a
        # serve-delta frame; authoritative copy lives under the
        # elastic-scoped KV key serve/leader.
        self._known_leader: Optional[str] = None
        self._elastic_ctx = None
        self.log = get_logger(0)

    # -- lifecycle -------------------------------------------------------

    def stop(self) -> None:
        """Ask the loop to drain and exit: rank 0 finishes every queued
        and active request, then broadcasts a stop frame."""
        self._stop.set()

    def run(self) -> None:
        """Serve until ``stop()``.  Blocks; re-forms the gang in process
        on rank failure (``@hvd.elastic.run`` semantics)."""
        from horovod_tpu import basics, elastic

        os.environ.setdefault("HVD_TPU_CORE", "py")
        if not basics.is_initialized():
            basics.init()
        try:
            if basics.size() == 1 and \
                    not os.environ.get("HVD_RENDEZVOUS_ADDR"):
                # Single process, no launcher: there is no gang to
                # re-form (and no KV store for the elastic protocol),
                # so run one incarnation directly.
                import types

                self._epoch_body(types.SimpleNamespace(
                    serve_generation=0))
            else:
                state = elastic.ObjectState(serve_generation=0)
                elastic.run(self._epoch_body)(state)
        finally:
            if self._door is not None:
                self._door.stop()
                self._door = None
            if self.scheduler is not None:
                self.scheduler.fail_all("serving loop exited")

    # -- one gang incarnation -------------------------------------------

    def _epoch_body(self, state) -> None:
        from horovod_tpu import basics

        eng = basics._runtime
        if eng is None or not hasattr(eng, "serve_broadcast"):
            raise RuntimeError(
                "serving requires the Python engine (HVD_TPU_CORE=py)")
        self.log = get_logger(basics.rank())
        self._elastic_ctx = getattr(state, "_elastic_ctx", None)
        engine = DecodeEngine(self.params, self.cfg,
                              max_batch=self.max_batch,
                              cache_len=self.cache_len, mesh=self.mesh)
        # The previous incarnation's in-flight table survives the reset
        # here: on a promoted rank it seeds the fresh scheduler below.
        shadow = sorted(self._slots.items())
        self._slots = {}
        leader = basics.rank() == 0
        promoted = leader and self.scheduler is None
        if promoted:
            # Seed the fresh scheduler from the shadow BEFORE the front
            # door flips to leader role: a client re-POSTing an in-flight
            # id during the window must join the adopted request (and see
            # its attempts>1), not race it as a fresh admission.
            self.scheduler = Scheduler(self.max_batch, self.max_queue,
                                       self.cache_len)
            if shadow:
                adopted = self.scheduler.adopt_shadow(shadow)
                self.log.info(
                    "promoted to serving leader: adopted %d in-flight "
                    "request(s) from the shadow slot table", adopted)
        self._ensure_front_door(leader=leader)
        if leader:
            state.serve_generation += 1
            replayed = self.scheduler.requeue_inflight()
            if replayed:
                self.log.info(
                    "re-formed gang (generation %d): replaying %d "
                    "in-flight request(s) from their prompts",
                    state.serve_generation, replayed)
            self._publish_leader()
            self._drive(eng, engine)
        else:
            self._follow(eng, engine)

    def _ensure_front_door(self, leader: bool = True) -> None:
        """Bind this rank's front door once per process (its port is
        stable across re-elections).  The leader's door admits into the
        local scheduler; a follower's door forwards to the current
        leader.  A follower promoted by a re-form flips its existing
        door to leader role in place — clients keep the same endpoint."""
        if leader and self.scheduler is None:
            self.scheduler = Scheduler(self.max_batch, self.max_queue,
                                       self.cache_len)
        if self._door is None:
            self._door = FrontDoor(
                self.scheduler if leader else None, host=self.host,
                port=self.port,
                timeout_s=self.request_timeout_s,
                leader_addr_fn=self._leader_addr,
                advertise_host=self._advertise_host())
            door_port = self._door.start()
            if leader:
                self.port = door_port
            self.log.info(
                "serving front door listening on :%d (%s)", door_port,
                "leader" if leader else "forwarding to leader")
            if self.on_ready is not None:
                self.on_ready(door_port)
        elif leader and self._door.scheduler is None:
            self._door.scheduler = self.scheduler
            self.port = self._door.port
            self.log.info("front door :%d promoted to serving leader",
                          self.port)

    # -- leader address: publish + resolve -------------------------------

    def _advertise_host(self) -> str:
        ctx = self._elastic_ctx
        if ctx is not None:
            addr = ctx.kv.local_address()
            if addr:
                return addr
        return "127.0.0.1"

    def _leader_self_addr(self) -> str:
        return f"{self._advertise_host()}:{self.port}"

    def _publish_leader(self) -> None:
        """Rank 0: publish this door's address under the elastic-scoped
        KV key so follower doors (and late joiners) can resolve the
        leader even before the first delta frame of the epoch."""
        self._known_leader = self._leader_self_addr()
        ctx = self._elastic_ctx
        if ctx is not None:
            try:
                ctx.kv.put(ctx.key("serve/leader"), self._known_leader)
            except Exception:
                # KV briefly unreachable (e.g. failing over to a
                # standby): the delta frames still carry the address.
                self.log.warning("could not publish serving leader "
                                 "address to the KV store")

    def _leader_addr(self, refresh: bool = False) -> Optional[str]:
        """Follower doors resolve the current leader here: the cached
        frame-carried address normally, the KV key on ``refresh`` (a
        forward just failed — re-election may have moved the leader)."""
        if refresh:
            ctx = self._elastic_ctx
            if ctx is not None:
                try:
                    v = ctx.kv.get(ctx.key("serve/leader"))
                except Exception:
                    v = None
                if v:
                    self._known_leader = v
        return self._known_leader

    # -- rank 0: drive ---------------------------------------------------

    def _drive(self, eng, engine: DecodeEngine) -> None:
        seq = 0
        while True:
            stopping = self._stop.is_set() and not self.scheduler.has_work()
            admissions = self.scheduler.take_admissions()
            if not stopping and not admissions and not self._slots:
                time.sleep(self.idle_poll_s)  # idle: no frame, no step
                continue
            seq += 1
            payload = wire.encode_serve_delta(
                seq, stopping,
                [(slot, r.id, r.max_new, r.prompt)
                 for slot, r in admissions],
                eng.epoch, leader_addr=self._known_leader or "")
            eng.serve_broadcast(payload)
            frame = eng.serve_recv(timeout=self.recv_timeout_s)
            if frame is None:  # own frame is in the inbox unless dying
                if self._engine_dying(eng):
                    return
                continue
            if self._apply_frame(frame, eng, engine, rank0=True):
                return

    # -- workers: follow -------------------------------------------------

    def _follow(self, eng, engine: DecodeEngine) -> None:
        while True:
            frame = eng.serve_recv(timeout=self.recv_timeout_s)
            if frame is None:
                if self._engine_dying(eng):
                    return
                continue  # plain timeout: keep listening
            if self._apply_frame(frame, eng, engine, rank0=False):
                return

    @staticmethod
    def _engine_dying(eng) -> bool:
        """None from serve_recv: timeout (keep going), clean shutdown
        (exit), or abort.  A lost-coordinator abort is re-raised as the
        RuntimeError the elastic wrapper maps to a rank-0 failure."""
        if getattr(eng, "_aborted", False):
            raise RuntimeError(
                getattr(eng, "_abort_reason", None) or "engine aborted")
        return eng._shutdown_flag.is_set() or \
            eng._shutdown_requested.is_set()

    # -- the lockstep step (identical on every rank) ---------------------

    def _apply_frame(self, frame, eng, engine, *, rank0: bool) -> bool:
        seq, stopping, admissions, epoch, leader_addr = \
            wire.decode_serve_delta_ex(frame)
        if epoch != eng.epoch:
            return False  # stale frame from a previous incarnation
        if leader_addr and not rank0:
            self._known_leader = leader_addr
        if stopping:
            return True
        # Chaos: a mid-decode stall/delay on this rank, fired before any
        # device work so the step's collective shows the gap.
        _fi.fire("serve.step", str(seq))
        tr = getattr(eng, "_tracer", None)
        ta0 = time.monotonic_ns() if tr is not None else 0
        t0 = time.monotonic()
        for slot, req_id, max_new, prompt in admissions:
            first = engine.prefill(slot, prompt)
            self._slots[slot] = {"id": req_id, "prompt": list(prompt),
                                 "max_new": max_new,
                                 "remaining": max_new}
            self._emit(slot, first, engine, rank0)
        if self._slots:
            toks = engine.step()
            tc0 = time.monotonic_ns() if tr is not None else 0
            self._confirm(toks)
            if tr is not None:
                # The agreement allreduce's own collective spans share
                # this step's wall window; the serve.confirm span ties
                # them to the TAG_SERVE seq that caused them.
                tr.span("serve.confirm", tc0, time.monotonic_ns(),
                        step=seq, slots=len(self._slots))
            for slot in sorted(self._slots):
                self._emit(slot, int(toks[slot]), engine, rank0)
            # Step confirm on the flight recorder: reuses the tracer's
            # post-confirm read when tracing, untimed otherwise (ring
            # order still sequences it against failure events).
            _bb.note("serve.confirm", tc0, step=seq,
                     slots=len(self._slots))
            if rank0:
                t1 = time.monotonic()
                _tmx.observe("hvd_serve_token_latency_seconds", t1 - t0)
                # Staleness surface for /stats last_step_age_s — the
                # same clock read the latency observe just took.
                self.scheduler.note_step(t1)
        if tr is not None:
            tr.span("serve.apply", ta0, time.monotonic_ns(), step=seq,
                    admitted=len(admissions))
        return False

    def _emit(self, slot: int, token: int, engine: DecodeEngine,
              rank0: bool) -> None:
        st = self._slots[slot]
        if rank0:
            self.scheduler.on_token(slot, token)
        st["remaining"] -= 1
        if st["remaining"] <= 0 or \
                (self.eos_id is not None and token == self.eos_id):
            engine.clear(slot)
            del self._slots[slot]
            if rank0:
                self.scheduler.complete(slot)

    def _confirm(self, toks: np.ndarray) -> None:
        """Token-agreement allreduce: the step's data-plane op (deadline
        + straggler surface) and the greedy-lockstep determinism check."""
        from horovod_tpu.ops import eager

        local = np.asarray(toks, dtype=np.float64)
        agreed = eager.allreduce(local, op=ReduceOp.MAX,
                                 name="__serve.confirm")
        if not np.array_equal(np.asarray(agreed), local):
            raise RuntimeError(
                "serving token divergence: this rank's greedy tokens "
                "differ from the gang's — lockstep decode is broken "
                "(non-deterministic kernels or mismatched params?)")
