"""horovod_tpu.serving — continuous-batching LM inference on the gang.

The north star serves heavy traffic, not just training throughput: this
package turns the flagship transformer's KV-cache decode loop
(models/transformer.py) into a served workload with a latency SLO.

Shape of the system (docs/serving.md):

* Rank 0 runs the HTTP front door (``POST /generate`` / ``GET /stats``,
  server.py) and the admission :class:`Scheduler` (scheduler.py), which
  packs prompts into the running batch at token boundaries —
  join-at-prefill, retire-at-EOS/max-len, per-slot position tracking.
* Each decode step, rank 0 broadcasts the batch delta over the control
  channel (TAG_SERVE, runtime_py.serve_broadcast) so EVERY rank steps
  the same jit-ed decode function (:class:`DecodeEngine`, decode.py) in
  lockstep; decode is greedy, so all ranks compute identical tokens and
  retire identical slots without further coordination.
* Robustness composes with the existing machinery instead of being
  rebuilt: each step's token-agreement allreduce gives the PR-6
  collective deadline a data-plane op to bound and feeds the straggler
  detector; on a gang abort the loop re-forms via ``@hvd.elastic.run``
  and replays in-flight requests from their prompts (at-least-once,
  loop.py).
"""

from horovod_tpu.serving.decode import DecodeEngine
from horovod_tpu.serving.loop import ServingLoop
from horovod_tpu.serving.scheduler import QueueFull, Request, Scheduler
from horovod_tpu.serving.server import FrontDoor

__all__ = [
    "DecodeEngine",
    "FrontDoor",
    "QueueFull",
    "Request",
    "Scheduler",
    "ServingLoop",
]
