"""JAX-native training-loop helpers mirroring the reference's Keras
callbacks (SURVEY.md §2.4 callbacks row) for users writing raw
JAX/optax loops:

* ``metric_average`` — epoch-end metric averaging across ranks
  (MetricAverageCallback).
* ``warmup_schedule`` — LR warmup from the single-worker value to
  size× over N steps (LearningRateWarmupCallback's recipe as an optax
  schedule, composable with optax.join_schedules).
* ``schedule_with_multipliers`` — epoch-ranged LR multipliers
  (LearningRateScheduleCallback) as an optax schedule.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu import basics
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.ops import eager as _eager


def metric_average(value, name: str):
    """Average a scalar (or array) metric across all ranks; returns a
    float for scalars (parity: _keras/callbacks.py:46-84)."""
    out = _eager.allreduce(np.asarray(value, np.float64),
                           op=ReduceOp.AVERAGE, name=f"metric.{name}")
    return float(out) if np.ndim(out) == 0 or np.size(out) == 1 else out


def warmup_schedule(base_lr: float, warmup_steps: int,
                    size: Optional[int] = None) -> Callable:
    """optax schedule: lr(step) ramps base_lr → base_lr*size over
    warmup_steps, then stays at base_lr*size (parity: the gradual
    warmup of _keras/callbacks.py:162-200)."""
    import jax.numpy as jnp

    n = basics.size() if size is None else size

    def schedule(step):
        frac = jnp.minimum(step / max(1, warmup_steps), 1.0)
        return base_lr * (frac * (n - 1) + 1)

    return schedule


def schedule_with_multipliers(
        base_lr: float,
        multipliers: Sequence[Tuple[int, float]],
        steps_per_epoch: int) -> Callable:
    """optax schedule from (start_epoch, multiplier) pairs — the classic
    ImageNet staircase the reference's examples build from
    LearningRateScheduleCallback stacks."""
    import jax.numpy as jnp

    starts = jnp.asarray([e * steps_per_epoch for e, _ in multipliers])
    # multiplier 1.0 applies before the first boundary
    mults = jnp.asarray([1.0] + [m for _, m in multipliers])

    def schedule(step):
        idx = jnp.clip(jnp.sum(step >= starts), 0, len(mults) - 1)
        return base_lr * mults[idx]

    return schedule
