"""Core message / status / shape types for the coordination plane.

Parity reference (behavior only): ``horovod/common/message.h:27-192`` and
``horovod/common/common.h:140-200`` in the reference tree. The reference
serializes with FlatBuffers; we use a self-describing little-endian binary
encoding (see ``wire.py``) because the controller messages are tiny (tens of
bytes) and a hand-rolled codec removes the flatc build dependency while
keeping the C++ core and Python in lockstep via a shared layout spec.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Sequence


class DataType(enum.IntEnum):
    """Wire dtype tags.  Mirrors the *set* of types the reference negotiates
    (message.h:27-38) plus bfloat16, which is the native TPU accumulation
    format and therefore first-class here."""

    UINT8 = 0
    INT8 = 1
    UINT16 = 2
    INT16 = 3
    INT32 = 4
    INT64 = 5
    FLOAT16 = 6
    FLOAT32 = 7
    FLOAT64 = 8
    BOOL = 9
    BFLOAT16 = 10
    # TPU-native 8-bit wire formats (beyond the reference, which stops at
    # fp16): OCP FP8 — e4m3fn for gradients, e5m2 (truncated fp16) for
    # range-heavy tensors.  Ring hops accumulate via fp32 like half.cc.
    FLOAT8_E4M3 = 11
    FLOAT8_E5M2 = 12

    @property
    def itemsize(self) -> int:
        return _ITEMSIZE[self]


_ITEMSIZE = {
    DataType.UINT8: 1,
    DataType.INT8: 1,
    DataType.UINT16: 2,
    DataType.INT16: 2,
    DataType.INT32: 4,
    DataType.INT64: 8,
    DataType.FLOAT16: 2,
    DataType.FLOAT32: 4,
    DataType.FLOAT64: 8,
    DataType.BOOL: 1,
    DataType.BFLOAT16: 2,
    DataType.FLOAT8_E4M3: 1,
    DataType.FLOAT8_E5M2: 1,
}

_NUMPY_NAMES = {
    DataType.UINT8: "uint8",
    DataType.INT8: "int8",
    DataType.UINT16: "uint16",
    DataType.INT16: "int16",
    DataType.INT32: "int32",
    DataType.INT64: "int64",
    DataType.FLOAT16: "float16",
    DataType.FLOAT32: "float32",
    DataType.FLOAT64: "float64",
    DataType.BOOL: "bool",
    DataType.BFLOAT16: "bfloat16",
    DataType.FLOAT8_E4M3: "float8_e4m3fn",
    DataType.FLOAT8_E5M2: "float8_e5m2",
}


def dtype_from_numpy(np_dtype) -> DataType:
    name = str(np_dtype)
    for k, v in _NUMPY_NAMES.items():
        if v == name:
            return k
    raise ValueError(f"horovod_tpu does not support dtype {name!r}")


def dtype_to_numpy_name(dt: DataType) -> str:
    return _NUMPY_NAMES[dt]


class ReduceOp(enum.IntEnum):
    """Reduction semantics carried in the request.

    Average / Sum / Adasum / Min / Max / Product.  The reference exposes
    Average, Sum, Adasum (``horovod/common/operations.cc`` C API constants,
    surfaced via basics.py:29-31); the extra lattice ops are free on the
    XLA path so we expose them too.
    """

    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


class RequestType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ALLTOALL = 4
    BARRIER = 5
    REDUCESCATTER = 6


class ResponseType(enum.IntEnum):
    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ALLTOALL = 4
    BARRIER = 5
    REDUCESCATTER = 6
    ERROR = 7
    # Coordinator liveness extension (PyEngine only, gated behind
    # HVD_HEARTBEAT_TIMEOUT > 0): announces dead-rank eviction.  The
    # evicted global ranks ride ``tensor_sizes`` — the existing Response
    # wire layout carries it unchanged, so csrc/wire.h stays in sync.
    EVICT = 8


class RanksFailedError(RuntimeError):
    """Raised by the enqueue API after the coordinator evicted dead ranks.

    In-flight collectives complete on the survivors (zero stand-ins via
    the Join machinery); the *next* submitted op raises this so the
    training loop can checkpoint and exit for a ``--max-restarts``
    relaunch."""

    def __init__(self, ranks):
        self.ranks = sorted(int(r) for r in ranks)
        super().__init__(
            f"rank(s) {self.ranks} stopped responding and were evicted; "
            f"surviving ranks completed in-flight collectives — "
            f"checkpoint and restart (hvdrun --max-restarts relaunches "
            f"automatically)")


class ReplicaDivergenceError(RanksFailedError):
    """The replica-divergence audit found rank(s) whose replicated state
    no longer bit-matches the gang's (silent corruption: a flipped bit,
    a non-deterministic kernel, bad HBM).

    Subclasses :class:`RanksFailedError` with ``.ranks`` = the deviant
    rank(s), so ``@hvd.elastic.run`` treats it exactly like a dead rank:
    the deviants are evicted, the survivors roll back to the last commit
    and re-form.  Every rank computes the identical verdict from the
    same allgathered digests, so the deviant evicts *itself* (it exits
    instead of re-joining) while the survivors agree on the new world.
    """

    def __init__(self, ranks, leaf_path: str = "",
                 digests=None):
        self.leaf_path = leaf_path
        self.digests = dict(digests or {})
        RuntimeError.__init__(self)  # skip RanksFailedError's message
        self.ranks = sorted(int(r) for r in ranks)
        detail = f" (first divergent leaf: {leaf_path})" if leaf_path \
            else ""
        self.args = (
            f"replica state diverged on rank(s) {self.ranks}{detail}; "
            f"the replicated parameters no longer bit-match across the "
            f"gang — evict the deviant rank(s) and restore survivors "
            f"from the last commit/checkpoint",)


class CollectiveTimeoutError(RanksFailedError):
    """A collective blew past ``HVD_COLLECTIVE_TIMEOUT`` and the gang
    agreed to abort it.

    The rank that timed out locally reports the peer it was blocked on
    to the coordinator over the still-live control channel; the
    coordinator confirms with a probe round and broadcasts a verdict
    naming the wedged rank(s), so every survivor raises this *same*
    exception for the *same* step (mirroring the non-finite agreement
    of ``horovod_tpu.integrity``).

    Subclasses :class:`RanksFailedError` with ``.ranks`` = the wedged
    rank(s), so ``@hvd.elastic.run`` treats a hung rank exactly like a
    dead one: evict, re-form, and replay the aborted fused batch from
    the retained inputs (``ops.fusion_buffer``).
    """

    def __init__(self, ranks, tensor_name: str = "",
                 timeout_s: float = 0.0):
        self.tensor_name = tensor_name
        self.timeout_s = float(timeout_s)
        RuntimeError.__init__(self)  # skip RanksFailedError's message
        self.ranks = sorted(int(r) for r in ranks)
        detail = f" during {tensor_name!r}" if tensor_name else ""
        self.args = (
            f"collective timed out after {self.timeout_s:g}s{detail}: "
            f"the gang agreed rank(s) {self.ranks} are wedged (hung, "
            f"not dead — heartbeats alone cannot catch this); evict "
            f"the wedged rank(s) and replay the aborted batch",)


class FencedError(RuntimeError):
    """A stale-epoch actor was rejected by the current gang incarnation.

    Raised on a **zombie** — a rank that was evicted (long GC pause,
    network blip, chaos stall) while the survivors re-formed at a newer
    membership epoch — when it wakes up and tries to write into the new
    gang: a control frame gets a ``TAG_FENCE`` reply from the
    coordinator, a KV write under ``elastic/*`` gets HTTP 409 from the
    rendezvous server.  Deliberately NOT a :class:`RanksFailedError`
    subclass: the elastic wrapper re-forms on those, but a fenced rank
    has no seat in the new world — it must exit, and the typed class is
    how the training loop tells "my peers died, re-form" apart from
    "I am the zombie, stop".
    """

    def __init__(self, what: str, stale_epoch: int, current_epoch: int):
        self.what = what
        self.stale_epoch = int(stale_epoch)
        self.current_epoch = int(current_epoch)
        super().__init__(
            f"fenced {what}: this rank is at membership epoch "
            f"{self.stale_epoch} but the gang re-formed at epoch "
            f"{self.current_epoch}; this process was evicted and has no "
            f"seat in the new world — exit instead of corrupting it")


class StatusType(enum.IntEnum):
    OK = 0
    UNKNOWN_ERROR = 1
    PRECONDITION_ERROR = 2
    ABORTED = 3
    INVALID_ARGUMENT = 4
    IN_PROGRESS = 5


@dataclass
class Status:
    """Operation outcome delivered to completion callbacks.

    Parity: ``horovod/common/common.h:90-138`` (Status with OK / Aborted /
    PreconditionError / InvalidArgument constructors)."""

    type: StatusType = StatusType.OK
    reason: str = ""
    # Optional typed exception: when set, HandleManager.wait re-raises
    # THIS object instead of wrapping ``reason`` in a bare RuntimeError,
    # so gang-agreed failures (CollectiveTimeoutError, ...) keep their
    # class — ``@hvd.elastic.run`` dispatches on it.  Python-side only;
    # never serialized (csrc/wire.h carries reason strings as before).
    exc: Optional[BaseException] = None

    @staticmethod
    def ok() -> "Status":
        return Status(StatusType.OK)

    @staticmethod
    def aborted(reason: str) -> "Status":
        return Status(StatusType.ABORTED, reason)

    @staticmethod
    def precondition_error(reason: str) -> "Status":
        return Status(StatusType.PRECONDITION_ERROR, reason)

    @staticmethod
    def invalid_argument(reason: str) -> "Status":
        return Status(StatusType.INVALID_ARGUMENT, reason)

    @staticmethod
    def unknown_error(reason: str) -> "Status":
        return Status(StatusType.UNKNOWN_ERROR, reason)

    @staticmethod
    def in_progress() -> "Status":
        return Status(StatusType.IN_PROGRESS)

    def ok_(self) -> bool:
        return self.type == StatusType.OK

    def in_progress_(self) -> bool:
        return self.type == StatusType.IN_PROGRESS


@dataclass(frozen=True)
class TensorShape:
    """Immutable shape; parity: common.h TensorShape (dims + num_elements)."""

    dims: tuple

    def __init__(self, dims: Sequence[int] = ()):  # allow TensorShape([2,3])
        object.__setattr__(self, "dims", tuple(int(d) for d in dims))

    @property
    def num_elements(self) -> int:
        n = 1
        for d in self.dims:
            n *= d
        return n

    @property
    def rank(self) -> int:
        return len(self.dims)

    def __str__(self) -> str:
        return "[" + ", ".join(str(d) for d in self.dims) + "]"


@dataclass
class Request:
    """What one rank wants to do with one named tensor.

    Parity: message.h:47-100 (request_rank, request_type, tensor_type,
    tensor_name, root_rank, device, tensor_shape) with `prescale_factor` /
    `postscale_factor` from the v2 torch path folded in, since the XLA
    backend applies them inside the fused reduction.
    """

    request_rank: int = 0
    request_type: RequestType = RequestType.ALLREDUCE
    tensor_type: DataType = DataType.FLOAT32
    tensor_name: str = ""
    root_rank: int = -1
    device: str = "cpu"
    tensor_shape: TensorShape = field(default_factory=TensorShape)
    reduce_op: ReduceOp = ReduceOp.SUM
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # Process-set scoping (beyond the reference; the project added
    # process sets post-v0.19): 0 = the global set.  The id is a stable
    # hash of the member ranks and ``process_set_size`` lets the
    # coordinator wait for exactly the members without a registration
    # round-trip.
    process_set_id: int = 0
    process_set_size: int = 0


@dataclass
class Response:
    """What every rank must now execute, in identical order.

    Parity: message.h:132-192 (response_type, tensor_names, error_message,
    devices, tensor_sizes).  ``tensor_names`` > 1 means the entries were
    fused into one collective launch."""

    response_type: ResponseType = ResponseType.ERROR
    tensor_names: List[str] = field(default_factory=list)
    error_message: str = ""
    devices: List[str] = field(default_factory=list)
    # Dtype of the (fused) entries; lets a Joined rank allocate zero
    # stand-ins from the response alone (parity: tensor_queue.cc:97-113).
    tensor_type: DataType = DataType.FLOAT32
    # For allgather: first-dimension sizes gathered from every rank, ordered
    # by rank, one block per tensor.  For allreduce: total byte size of each
    # fused tensor (used to slice the fusion buffer).
    tensor_sizes: List[int] = field(default_factory=list)
    # Allreduce execution parameters, negotiated from the (matching)
    # requests.  Carried in the response so (a) fusion only merges
    # allreduces with identical semantics and (b) joined ranks' zero
    # stand-ins reduce with the right op.
    reduce_op: ReduceOp = ReduceOp.SUM
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # For allreduce: the exact negotiated dims of each fused tensor, one
    # per tensor_names entry.  Authoritative on every rank (including
    # joined ranks executing zero stand-ins), which keeps response-cache
    # parameters coherent without relying on rank-local request state.
    tensor_shapes: List["TensorShape"] = field(default_factory=list)
    # Process-set scoping: non-member ranks skip the response entirely
    # (0 = the global set, everyone executes).
    process_set_id: int = 0

    def add_tensor_name(self, name: str) -> None:
        self.tensor_names.append(name)
