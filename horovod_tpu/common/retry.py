"""Retry with exponential backoff + deterministic jitter.

The control plane's cold paths (rendezvous KV requests, mesh connect)
face transient failure as a matter of course at fleet scale — a KV server
that is still binding, a peer that has not called listen yet, a dropped
SYN.  Single-try semantics turn each of those into a job failure; this
module gives them the standard remedy: capped exponential backoff with
jitter so a gang of workers retrying in lockstep does not thundering-herd
the endpoint they are waiting on.

Jitter is drawn from a ``random.Random`` seeded per call (default: from
the attempt site), keeping chaos tests deterministic.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


def backoff_delays(attempts: int, base_delay: float, max_delay: float,
                   jitter: float, seed: int = 0):
    """The delay sequence ``retry_call`` sleeps between attempts:
    ``min(max_delay, base * 2**i) * (1 + U(0, jitter))``, deterministic
    under ``seed``."""
    rng = random.Random(seed)
    out = []
    for i in range(max(0, attempts - 1)):
        d = min(max_delay, base_delay * (2.0 ** i))
        out.append(d * (1.0 + rng.random() * jitter))
    return out


def retry_call(
    fn: Callable[[], T],
    *,
    attempts: int = 4,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    jitter: float = 0.5,
    is_retryable: Callable[[BaseException], bool] = lambda e: True,
    deadline: Optional[float] = None,
    seed: int = 0,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
) -> T:
    """Call ``fn`` up to ``attempts`` times with exponential backoff.

    ``is_retryable`` filters which exceptions are worth another attempt;
    anything else propagates immediately.  ``deadline`` (monotonic
    timestamp) caps total time regardless of attempts left.  The final
    failure re-raises the last exception.
    """
    delays = backoff_delays(attempts, base_delay, max_delay, jitter, seed)
    last: Optional[BaseException] = None
    for i in range(attempts):
        try:
            return fn()
        except BaseException as e:  # noqa: B036 — filtered below
            if not is_retryable(e):
                raise
            last = e
            if i >= attempts - 1:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if on_retry is not None:
                on_retry(i + 1, e)
            d = delays[i]
            if deadline is not None:
                d = min(d, max(0.0, deadline - time.monotonic()))
            time.sleep(d)
    assert last is not None
    raise last
