"""Response cache: steady-state bypass of full request negotiation.

Role parity: ``horovod/common/response_cache.cc/.h`` — an LRU cache of
previously negotiated ALLREDUCE responses, kept byte-identical on every
rank so that in steady state a rank only has to tell the coordinator "bit
p is ready" instead of re-serializing the full Request, and the
coordinator only has to broadcast "execute bits p1..pk" instead of full
Response lists.

The reference synchronizes cache bits with an MPI/Gloo bitvector
allreduce (``response_cache.h:45-167``, ``controller.cc:171-200``).  Our
controller is a star over TCP, so the protocol is adapted: hit events
``(name, position)`` ride the worker→coordinator request frame, the
coordinator *synthesizes* the full Request from its own (coherent) cache
entry and feeds it through the ordinary message table, and when every
contributing rank hit, the coordinator broadcasts just the position.
Any divergence (eviction in flight, shape change) degrades to the
explicit negotiated path or a RESEND instruction — never to corruption.

Coherence argument: every rank executes the same response stream in the
same order; every cache mutation (insert, in-place update, LRU touch,
eviction) happens at response-execution time from response-carried data
only (``Response.tensor_shapes`` holds the negotiated dims, so even a
joined rank executing zero stand-ins caches identical parameters).
Hence position assignment, LRU order, and eviction choice are identical
on all ranks without extra synchronization.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Optional, Tuple

from horovod_tpu.common.types import (
    ReduceOp,
    Request,
    RequestType,
    Response,
    ResponseType,
    TensorShape,
)

# Classification results (parity: response_cache.h CacheState).
MISS = 0
HIT = 1
INVALID = 2  # name cached but parameters changed → renegotiate


def _params_of_request(req: Request) -> tuple:
    return (int(req.tensor_type), tuple(req.tensor_shape.dims),
            int(req.reduce_op), req.prescale_factor, req.postscale_factor,
            req.device)


class _Entry:
    __slots__ = ("name", "position", "response", "params")

    def __init__(self, name: str, position: int, response: Response,
                 params: tuple):
        self.name = name
        self.position = position
        self.response = response
        self.params = params


class ResponseCache:
    """LRU cache of single-tensor ALLREDUCE responses, position-addressed.

    Positions are dense small integers reused after eviction so the wire
    encoding stays compact (parity: the reference's fixed-width cache
    bitvector).  The entry dict doubles as the LRU order (front = least
    recently used), giving O(1) touch/evict via ``move_to_end``.
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._by_pos: Dict[int, _Entry] = {}
        self._free_positions: list = []
        self._next_position = 0
        # stats
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def __len__(self) -> int:
        return len(self._entries)

    # -- classification (background-thread pop path) ----------------------

    def classify(self, req: Request) -> Tuple[int, int]:
        """Returns (state, position).  Only ALLREDUCE is cacheable — the
        reference likewise caches only allreduce responses (allgather
        output sizes vary per step)."""
        if not self.enabled or req.request_type != RequestType.ALLREDUCE \
                or req.process_set_id:
            # Process-set ops bypass the cache: positions must stay
            # coherent on EVERY rank, and non-members never see the
            # set's traffic.
            return MISS, -1
        ent = self._entries.get(req.tensor_name)
        if ent is None:
            self.misses += 1
            return MISS, -1
        if ent.params != _params_of_request(req):
            return INVALID, ent.position
        self.hits += 1
        return HIT, ent.position

    # -- lookups ----------------------------------------------------------

    def get_by_position(self, pos: int) -> Optional[Response]:
        ent = self._by_pos.get(pos)
        return ent.response if ent is not None else None

    def name_at(self, pos: int) -> Optional[str]:
        ent = self._by_pos.get(pos)
        return ent.name if ent is not None else None

    def position_of(self, name: str) -> int:
        ent = self._entries.get(name)
        return ent.position if ent is not None else -1

    def synthesize_request(self, pos: int, rank: int) -> Optional[Request]:
        """Rebuild the full Request a hit event stands for, from the
        coordinator's own cache entry (coherent with the sender's)."""
        ent = self._by_pos.get(pos)
        if ent is None:
            return None
        (ttype, dims, rop, pre, post, device) = ent.params
        return Request(
            request_rank=rank,
            request_type=RequestType.ALLREDUCE,
            tensor_type=ent.response.tensor_type,
            tensor_name=ent.name,
            device=device,
            tensor_shape=TensorShape(list(dims)),
            reduce_op=ReduceOp(rop),
            prescale_factor=pre,
            postscale_factor=post,
        )

    def touch(self, pos: int) -> None:
        ent = self._by_pos.get(pos)
        if ent is not None:
            self._entries.move_to_end(ent.name)

    # -- population (response-execution path) -----------------------------

    def put(self, resp: Response) -> None:
        """Cache each tensor of an executed ALLREDUCE response as its own
        single-tensor response.  Exact dims come from the negotiated
        ``resp.tensor_shapes`` — response-carried, so identical on every
        rank regardless of local request state."""
        if not self.enabled or resp.response_type != ResponseType.ALLREDUCE \
                or resp.error_message or resp.process_set_id:
            return
        have_shapes = len(resp.tensor_shapes) == len(resp.tensor_names)
        for i, name in enumerate(resp.tensor_names):
            shape = resp.tensor_shapes[i] if have_shapes \
                else TensorShape([resp.tensor_sizes[i]])
            single = Response(
                response_type=ResponseType.ALLREDUCE,
                tensor_type=resp.tensor_type,
                tensor_names=[name],
                devices=list(resp.devices),
                tensor_sizes=[resp.tensor_sizes[i]],
                reduce_op=resp.reduce_op,
                prescale_factor=resp.prescale_factor,
                postscale_factor=resp.postscale_factor,
                tensor_shapes=[shape],
            )
            params = (int(resp.tensor_type), tuple(shape.dims),
                      int(resp.reduce_op), resp.prescale_factor,
                      resp.postscale_factor,
                      resp.devices[0] if resp.devices else "cpu")
            self._put_one(name, single, params)

    def _put_one(self, name: str, resp: Response, params: tuple) -> None:
        ent = self._entries.get(name)
        if ent is not None:
            # In-place update keeps the position stable (shape changes
            # re-cache under the same position).
            ent.response = resp
            ent.params = params
            self._entries.move_to_end(name)
            return
        if len(self._entries) >= self.capacity:
            _victim, vent = self._entries.popitem(last=False)
            del self._by_pos[vent.position]
            self._free_positions.append(vent.position)
            self.evictions += 1
        if self._free_positions:
            pos = self._free_positions.pop(0)
        else:
            pos = self._next_position
            self._next_position += 1
        ent = _Entry(name, pos, resp, params)
        self._entries[name] = ent
        self._by_pos[pos] = ent

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "size": len(self._entries),
                "capacity": self.capacity}
