"""Deterministic fault injection for chaos testing.

A process-global *fault plan* names injection **sites** threaded through
the control plane (socket helpers, rendezvous KV client/server, bootstrap,
the PyEngine loops) and describes what to do when execution passes one:
drop the operation (raise), delay it, raise an arbitrary error, or kill
the process outright.  Faults can be one-shot (``times`` / ``after``) or
probabilistic (``prob`` under a fixed ``seed``) — both deterministic, so
multi-process chaos scenarios replay exactly.

The plan comes from the ``HOROVOD_FAULT_PLAN`` environment variable
(inline JSON, or a path to a JSON file) or from :func:`configure`.  With
no plan set, every :func:`fire` call is a single module-global ``None``
check — no allocation, no locking, no time lookup — so production code
pays nothing for carrying the hooks (pinned by tests/test_chaos.py).

Plan format::

    {"seed": 123, "faults": [
        {"site": "kv.put", "kind": "error", "times": 3},
        {"site": "sock.connect", "kind": "delay", "delay_s": 0.2,
         "prob": 0.5},
        {"site": "train.step", "kind": "kill", "after": 2},
        {"site": "ctrl.worker.send", "kind": "drop", "match": "req"}
    ]}

Fault fields:

* ``site``   — exact injection-site name (required).
* ``kind``   — ``drop`` | ``error`` (both raise :class:`InjectedFault`,
  a ``ConnectionError`` so existing network error handling engages),
  ``delay`` (sleep ``delay_s``), ``kill`` (``os._exit(137)``, the
  SIGKILL-style death a supervisor sees).
* ``match``  — substring that must appear in the call's ``detail``.
* ``times``  — fire at most this many times (default: unlimited).
* ``after``  — skip the first N matching passes (default 0).
* ``prob``   — fire with this probability, drawn from a PRNG seeded by
  the plan ``seed`` (default: always fire).
* ``delay_s``— sleep duration for ``kind: delay`` (default 0.1).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import List, Optional

ENV_VAR = "HOROVOD_FAULT_PLAN"


class InjectedFault(ConnectionError):
    """An artificial failure raised at a fault-injection site."""


class _Fault:
    __slots__ = ("site", "kind", "match", "times", "after", "prob",
                 "delay_s", "hits", "fired")

    def __init__(self, spec: dict):
        self.site = spec["site"]
        self.kind = spec.get("kind", "error")
        if self.kind not in ("drop", "error", "delay", "kill"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        self.match = spec.get("match")
        self.times = spec.get("times")
        self.after = int(spec.get("after", 0))
        self.prob = spec.get("prob")
        self.delay_s = float(spec.get("delay_s", 0.1))
        self.hits = 0    # matching passes seen
        self.fired = 0   # faults actually injected


class _Plan:
    def __init__(self, spec: dict):
        self.faults: List[_Fault] = [
            _Fault(f) for f in spec.get("faults", [])]
        self.rng = random.Random(spec.get("seed", 0))
        self.lock = threading.Lock()


# None = fault injection disabled; the single hot-path flag.
_PLAN: Optional[_Plan] = None


def fire(site: str, detail: str = "") -> None:
    """Injection-site hook.  No-op (one global load + ``is`` check) unless
    a fault plan is active and names ``site``."""
    plan = _PLAN
    if plan is None:
        return
    _fire_slow(plan, site, detail)


def _fire_slow(plan: _Plan, site: str, detail: str) -> None:
    for f in plan.faults:
        if f.site != site:
            continue
        if f.match is not None and f.match not in detail:
            continue
        with plan.lock:
            f.hits += 1
            if f.hits <= f.after:
                continue
            if f.times is not None and f.fired >= f.times:
                continue
            if f.prob is not None and plan.rng.random() >= f.prob:
                continue
            f.fired += 1
        if f.kind == "delay":
            time.sleep(f.delay_s)
            continue
        if f.kind == "kill":
            os._exit(137)
        raise InjectedFault(
            f"injected {f.kind} at {site!r}"
            + (f" ({detail})" if detail else ""))


def configure(spec: Optional[dict]) -> None:
    """Install a fault plan programmatically (``None`` clears it)."""
    global _PLAN
    _PLAN = _Plan(spec) if spec else None


def clear() -> None:
    configure(None)


def active() -> bool:
    return _PLAN is not None


def _load_from_env() -> None:
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return
    raw = raw.strip()
    if not raw.startswith("{"):
        with open(raw) as fh:
            raw = fh.read()
    configure(json.loads(raw))


_load_from_env()
