"""Deterministic fault injection for chaos testing.

A process-global *fault plan* names injection **sites** threaded through
the control plane (socket helpers, rendezvous KV client/server, bootstrap,
the PyEngine loops) and describes what to do when execution passes one:
drop the operation (raise), delay it, raise an arbitrary error, or kill
the process outright.  Faults can be one-shot (``times`` / ``after``) or
probabilistic (``prob`` under a fixed ``seed``) — both deterministic, so
multi-process chaos scenarios replay exactly.

The plan comes from the ``HOROVOD_FAULT_PLAN`` environment variable
(inline JSON, a path to a JSON file, or the seedable
``random:<seed>:<rate>`` shorthand that sweeps the transient data-plane
fault kinds — see :func:`random_schedule`) or from :func:`configure`.  With
no plan set, every :func:`fire` call is a single module-global ``None``
check — no allocation, no locking, no time lookup — so production code
pays nothing for carrying the hooks (pinned by tests/test_chaos.py).

Plan format::

    {"seed": 123, "faults": [
        {"site": "kv.put", "kind": "error", "times": 3},
        {"site": "sock.connect", "kind": "delay", "delay_s": 0.2,
         "prob": 0.5},
        {"site": "train.step", "kind": "kill", "after": 2},
        {"site": "ctrl.worker.send", "kind": "drop", "match": "req"}
    ]}

Fault fields:

* ``site``   — exact injection-site name (required).
* ``kind``   — ``drop`` | ``error`` (both raise :class:`InjectedFault`,
  a ``ConnectionError`` so existing network error handling engages),
  ``delay`` (sleep ``delay_s``), ``kill`` (``os._exit(137)``, the
  SIGKILL-style death a supervisor sees), ``corrupt`` (data-plane
  poisoning: fires only at :func:`should_corrupt` sites, where the
  call site itself applies the corruption — NaN gradients, a flipped
  bit, a torn checkpoint file), ``stall`` (sleep ``stall_s`` —
  hour-scale by default, i.e. an "indefinite" hang: a GC pause, a
  wedged peer, a partition that heals — then continue normally),
  ``halfopen`` (sleep ``stall_s`` then raise, modeling a half-open TCP
  connection whose blackholed writes the kernel eventually errors).
* ``match``  — substring that must appear in the call's ``detail``.
* ``times``  — fire at most this many times (default: unlimited).
* ``after``  — skip the first N matching passes (default 0).
* ``prob``   — fire with this probability, drawn from a PRNG seeded by
  the plan ``seed`` (default: always fire).
* ``delay_s``— sleep duration for ``kind: delay`` (default 0.1).
* ``stall_s``— hang duration for ``stall`` / ``halfopen`` (default
  3600 — "forever" at test scale, yet the injected sleeper thread
  still unwinds instead of leaking for the life of the process).
* ``groups`` — for ``kind: partition`` only: two rank lists.  The fault
  drops (raises :class:`InjectedFault` for) every frame whose
  (this-process rank, ``detail`` peer rank) pair crosses the two
  groups, in BOTH directions — a network partition between host
  groups, not a single dead link.  This-process rank comes from
  ``HVD_RANK``; when ``detail`` names the sender itself (the
  ``ctrl.worker.send`` convention) the remote is the root, rank 0.
  Frames within one group never fire, so each side keeps running and
  independently concludes the other side died — exactly the split the
  elastic quorum gate must resolve.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import List, Optional

ENV_VAR = "HOROVOD_FAULT_PLAN"

# Canonical injection-site registry: every site literal passed to
# :func:`fire` / :func:`should_corrupt` anywhere in the package (plus the
# documented user-level sites, like the ``train.step`` a training script
# fires itself) must be listed here, and every entry must appear in the
# docs/fault_tolerance.md site table — enforced by
# tools/check_fault_sites.py (wired as tests/test_fault_sites.py).
KNOWN_SITES = {
    # control plane (fire)
    "sock.send": "mesh data-socket send",
    "sock.recv": "mesh data-socket recv",
    "sock.connect": "mesh bootstrap connect",
    "kv.put": "rendezvous KV client put",
    "kv.get": "rendezvous KV client get",
    "kv.delete": "rendezvous KV client delete",
    "kv.server.request": "rendezvous server request handling",
    "kv.mirror": "rendezvous primary->standby write-through mirroring",
    "metrics.server.request": "metrics debug-server request handling",
    "agg.scrape": "gang aggregator per-rank snapshot read (KV entry + "
                  "HTTP scrape fallback; detail = the rank)",
    "bootstrap.start": "worker bootstrap entry",
    "bootstrap.accept": "mesh listener accept loop",
    "engine.cycle": "PyEngine background cycle",
    "ctrl.worker.send": "worker->coordinator control send",
    "ctrl.coord.send": "coordinator->worker control send",
    "ctrl.subcoord.send": "sub-coordinator control forward (TREE_UP "
                          "aggregate to root / routed frame to a child)",
    "ctrl.reparent": "orphaned child's TAG_REPARENT adoption back to "
                     "the root after its sub-coordinator died",
    "sock.stall": "data-plane ring-hop receive (hang simulation)",
    "sock.halfopen": "persistent sender thread send (half-open sim)",
    "sock.corrupt": "flip one wire byte of a ladder data frame (CRC)",
    "sock.reset": "hard-reset a ladder data socket mid-collective",
    "shm.lost": "shm ring faults mid-gang (reader gone / attach lost)",
    "shm.stall": "data-plane shm ring receive (hang simulation)",
    "shm.attach": "shm segment attach during transport pairing",
    "trace.emit": "trace span-file write (a dropped/failed write must "
                  "never affect training)",
    "blackbox.dump": "flight-recorder dump at a terminal failure (a "
                     "failed dump must never mask the original error)",
    "train.step": "user-level per-step site (training scripts)",
    "serve.admit": "serving front-door admission (HTTP 503 shedding)",
    "serve.step": "serving decode step, every rank (stall/delay sim)",
    # data plane (should_corrupt)
    "grad.nonfinite": "poison local gradients with NaN (eager guard)",
    "state.bitflip": "flip one bit of the audited replica state",
    "ckpt.corrupt": "corrupt one file of a just-written checkpoint",
}


def known_sites() -> dict:
    """Copy of the site registry (site name -> short description)."""
    return dict(KNOWN_SITES)


class InjectedFault(ConnectionError):
    """An artificial failure raised at a fault-injection site."""


class _Fault:
    __slots__ = ("site", "kind", "match", "times", "after", "prob",
                 "delay_s", "stall_s", "groups", "hits", "fired")

    def __init__(self, spec: dict):
        self.site = spec["site"]
        self.kind = spec.get("kind", "error")
        if self.kind not in ("drop", "error", "delay", "kill", "corrupt",
                             "stall", "halfopen", "partition"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        self.match = spec.get("match")
        self.times = spec.get("times")
        self.after = int(spec.get("after", 0))
        self.prob = spec.get("prob")
        self.delay_s = float(spec.get("delay_s", 0.1))
        self.stall_s = float(spec.get("stall_s", 3600.0))
        groups = spec.get("groups")
        if self.kind == "partition":
            if (not isinstance(groups, (list, tuple)) or len(groups) != 2
                    or not all(isinstance(g, (list, tuple))
                               for g in groups)):
                raise ValueError(
                    "partition fault needs groups: [[ranks...], "
                    "[ranks...]]")
            groups = (frozenset(int(r) for r in groups[0]),
                      frozenset(int(r) for r in groups[1]))
        self.groups = groups
        self.hits = 0    # matching passes seen
        self.fired = 0   # faults actually injected


class _Plan:
    def __init__(self, spec: dict):
        self.faults: List[_Fault] = [
            _Fault(f) for f in spec.get("faults", [])]
        self.rng = random.Random(spec.get("seed", 0))
        self.lock = threading.Lock()


# None = fault injection disabled; the single hot-path flag.
_PLAN: Optional[_Plan] = None


def fire(site: str, detail: str = "") -> None:
    """Injection-site hook.  No-op (one global load + ``is`` check) unless
    a fault plan is active and names ``site``."""
    plan = _PLAN
    if plan is None:
        return
    _fire_slow(plan, site, detail)


def _matches_and_arms(plan: _Plan, f: _Fault, detail: str) -> bool:
    """Shared pass/fire bookkeeping for one site-matched fault."""
    if f.match is not None and f.match not in detail:
        return False
    with plan.lock:
        f.hits += 1
        if f.hits <= f.after:
            return False
        if f.times is not None and f.fired >= f.times:
            return False
        if f.prob is not None and plan.rng.random() >= f.prob:
            return False
        f.fired += 1
    return True


def _partition_crosses(f: _Fault, detail: str) -> bool:
    """True when this frame crosses the partition's two groups: the
    local process rank (HVD_RANK) on one side, the peer rank named by
    ``detail`` on the other.  Sites that pass the sender's OWN rank as
    detail (ctrl.worker.send, a sub-coordinator's TREE_UP) are talking
    to the root — rank 0 stands in as the remote."""
    try:
        me = int(os.environ.get("HVD_RANK", "0"))
        other = int(detail)
    except ValueError:
        return False  # non-rank detail: not a peer-addressed frame
    if other == me:
        other = 0
    g0, g1 = f.groups
    return (me in g0 and other in g1) or (me in g1 and other in g0)


def _fire_slow(plan: _Plan, site: str, detail: str) -> None:
    for f in plan.faults:
        if f.site != site or f.kind == "corrupt":
            # corrupt faults only arm at should_corrupt() sites — a
            # fire() site cannot apply a data corruption.
            continue
        if f.kind == "partition" and not _partition_crosses(f, detail):
            # Same-side traffic flows; only cross-group frames are cut
            # (and only those count against times/prob bookkeeping).
            continue
        if not _matches_and_arms(plan, f, detail):
            continue
        if f.kind == "delay":
            time.sleep(f.delay_s)
            continue
        if f.kind == "stall":
            time.sleep(f.stall_s)
            continue
        if f.kind == "halfopen":
            time.sleep(f.stall_s)
            raise InjectedFault(
                f"injected halfopen at {site!r}"
                + (f" ({detail})" if detail else ""))
        if f.kind == "kill":
            os._exit(137)
        raise InjectedFault(
            f"injected {f.kind} at {site!r}"
            + (f" ({detail})" if detail else ""))


def should_corrupt(site: str, detail: str = "") -> bool:
    """Data-corruption hook.  Returns True when an armed ``corrupt``
    fault names ``site`` — the call site then applies the actual
    corruption (it knows what a NaN gradient / flipped bit / torn file
    looks like).  Same zero-cost contract as :func:`fire` when no plan
    is active."""
    plan = _PLAN
    if plan is None:
        return False
    for f in plan.faults:
        if f.site != site or f.kind != "corrupt":
            continue
        if _matches_and_arms(plan, f, detail):
            return True
    return False


def configure(spec: Optional[dict]) -> None:
    """Install a fault plan programmatically (``None`` clears it)."""
    global _PLAN
    _PLAN = _Plan(spec) if spec else None


def clear() -> None:
    configure(None)


def active() -> bool:
    return _PLAN is not None


# The transient fault kinds the `random:` schedule sweeps — exactly the
# faults the recovery ladder (docs/fault_tolerance.md) must self-heal
# without an eviction.  sock.corrupt is a `corrupt` kind (the ladder
# sender flips a wire byte); the other two are `error` kinds whose
# InjectedFault the ladder treats as a dead socket / dead segment.
RANDOM_SCHEDULE_FAULTS = (
    ("sock.corrupt", "corrupt"),
    ("sock.reset", "error"),
    ("shm.lost", "error"),
)


def random_schedule(seed: int, rate: float) -> dict:
    """Expand ``random:<seed>:<rate>`` into a plan spec: each transient
    fault kind fires independently with probability ``rate`` per pass,
    from one PRNG seeded with ``seed`` — deterministic, so a chaos soak
    replays exactly under the same plan string."""
    return {"seed": int(seed), "faults": [
        {"site": site, "kind": kind, "prob": float(rate)}
        for site, kind in RANDOM_SCHEDULE_FAULTS]}


def _load_from_env() -> None:
    raw = os.environ.get(ENV_VAR)
    if not raw:
        return
    raw = raw.strip()
    if raw.startswith("random:"):
        # Seedable randomized chaos soak: "random:<seed>:<rate>".
        _, seed, rate = raw.split(":")
        configure(random_schedule(int(seed), float(rate)))
        return
    if not raw.startswith("{"):
        with open(raw) as fh:
            raw = fh.read()
    configure(json.loads(raw))


_load_from_env()
