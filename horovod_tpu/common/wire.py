"""Binary wire codec for controller messages.

Role parity: ``horovod/common/wire/message.fbs`` + ``message.cc`` (the
reference serializes Request/Response lists with FlatBuffers).  We use a
hand-rolled little-endian encoding instead: messages are tens of bytes, the
schema is stable, and one codec spec shared by this file and the C++ core
(``csrc/wire.h``) avoids a flatc build step.  THE TWO MUST MATCH — any
change here must be mirrored in csrc/wire.h.

Layout (all integers little-endian):

  varstr   := u32 len, bytes
  Request  := u8 request_type, i32 request_rank, u8 tensor_type,
              varstr tensor_name, i32 root_rank, varstr device,
              u8 reduce_op, f64 prescale, f64 postscale,
              u8 ndim, i64 dims[ndim],
              i32 process_set_id, i32 process_set_size
  CacheHit := varstr name, u32 position
  RequestList  := u8 shutdown, u32 n, Request[n],
                  u32 n_hits, CacheHit[n_hits],
                  [ u32 epoch ]                   # optional trailer
  Response := u8 response_type, u8 tensor_type, u32 n_names,
              varstr[n_names], varstr error_message,
              u32 n_devices, varstr[n_devices],
              u32 n_sizes, i64 sizes[n_sizes],
              u8 reduce_op, f64 prescale, f64 postscale,
              u32 n_shapes, { u8 ndim, i64 dims[ndim] }[n_shapes],
              i32 process_set_id
  ResponseList := u8 shutdown, u32 n, Response[n],
                  u32 n_hit_positions, u32 pos[n_hit_positions],
                  u32 n_resend, varstr resend_names[n_resend],
                  u8 has_params,
                  [ i64 fusion_threshold, f64 cycle_time_s,
                    u8 cache_enabled, u8 hierarchical_allreduce,
                    u8 hierarchical_allgather,
                    i64 ring_segment_bytes ],  # iff has_params
                  [ u32 epoch ]                   # optional trailer

The ``epoch`` trailer is the **membership epoch** of the sender's gang
incarnation (``horovod_tpu.elastic``): each elastic re-form bumps it, and
a receiver drops any list frame stamped with a different epoch — a stale
in-flight frame from a previous incarnation (e.g. a zombie rank that was
evicted but not dead) aborts deterministically instead of corrupting the
new gang's negotiation.  It is a *trailer* so the layout stays
backward/forward compatible: decoders that predate it (the C++ core
before csrc/wire.cc grew the mirror) ignore trailing bytes, and a frame
without the trailer decodes as epoch 0 — the only epoch the native
engine may run at (elastic requires the Python engine).

``has_params`` carries the autotuner's knob broadcast (parity: rank 0
tuning + Params bcast, ``parameter_manager.cc`` via ``controller.cc:33-47``);
workers must apply it before executing the same frame's cached hits so
hit fusion stays coherent.

The cache fields carry the response-cache fast path (parity:
``horovod/common/response_cache.h:45-167`` — there a fixed-width
bitvector allreduced across ranks; here explicit hit events up to the
coordinator and hit positions back down, see
``horovod_tpu/common/response_cache.py``).

Collective-abort agreement payloads (Python engine only — ridden on the
``TAG_ABORT_REPORT`` / ``TAG_PROBE_ACK`` / ``TAG_ABORT_VERDICT``
control tags, which like ``TAG_HEARTBEAT`` do not exist in
csrc/sockets.h; the native engine never negotiates a collective
timeout, so these codecs need no C++ mirror — only the tag-number
reservation is noted in csrc/wire.h):

  AbortReport  := varstr tensor_name, i32 suspect_rank, u32 epoch
  ProbeAck     := u8 busy, f64 busy_seconds, u32 epoch
  AbortVerdict := varstr tensor_name, u32 n, i32 ranks[n], u32 epoch

Trace clock-sync payloads (Python engine only, like the abort tags —
ridden on ``TAG_CLOCK_PING`` / ``TAG_CLOCK_PONG``, reserved as tags
14/15 in csrc/wire.h; frames only flow when ``HVD_TRACE`` is set, so a
traced gang must be all-Python — docs/timeline.md "Gang-wide tracing"):

  ClockPing := i64 t0_ns, u32 epoch      # worker's monotonic clock
  ClockPong := i64 t0_ns, i64 t_coord_ns, u32 epoch   # t0 echoed back

The worker timestamps the ping (``t0_ns``), the coordinator answers
from its ctrl recv thread with its own monotonic read, and the worker
computes ``offset = t_coord − (t0 + t1)/2`` at receive time ``t1`` —
the NTP midpoint method, accurate to half the control-channel RTT.

Flight-recorder dump pull (Python engine only — ridden on
``TAG_BLACKBOX`` / ``TAG_BLACKBOX_DUMP``, reserved as tags 16/17 in
csrc/wire.h; after an abort verdict the coordinator pulls each live
worker's in-memory ring so one archive survives a dead disk —
docs/fault_tolerance.md "the black box"):

  BlackboxRequest := u32 epoch
  BlackboxDump    := i32 rank, u32 epoch, u32 len, bytes blob[len]

The blob is the UTF-8 JSON dump document (``telemetry/blackbox.py``
schema ``hvd-blackbox-v1``), byte-identical to what the worker would
write to its own ``blackbox_rank<r>.json``.

Recovery-ladder framing (``HVD_WIRE_CRC=1`` only — docs/fault_tolerance.md
"recovery ladder"; tag numbers 11-13 and the trailer layout are reserved
in csrc/wire.h, which the native engine must mirror before it can join a
CRC-armed gang):

  DataTrailer := u32 seq, u32 crc        # appended to every data frame;
                                         # crc = CRC-32 (zlib polynomial
                                         # 0xEDB88320) over payload, then
                                         # over the 4 seq bytes
  Nack        := u32 expected_seq        # TAG_NACK: receiver -> sender
  Resume      := i32 rank, u32 expected_seq, u32 epoch   # TAG_RESUME
  Failover    := i32 rank, u32 expected_seq, u32 epoch   # TAG_FAILOVER

The trailer rides INSIDE the frame payload (header length includes it),
so CRC-off peers and CRC-on peers are wire-incompatible by construction
— the knob must be gang-wide, like ``HVD_COLLECTIVE_TIMEOUT``.  CRC-32
with the zlib polynomial is the deliberate checksum choice: zlib.crc32
runs at C speed in every CPython (no extra dependency), and the csrc
mirror uses the same table-driven polynomial.
"""

from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

from horovod_tpu.common.types import (
    DataType,
    ReduceOp,
    Request,
    RequestType,
    Response,
    ResponseType,
    TensorShape,
)


def _pack_str(buf: bytearray, s: str) -> None:
    b = s.encode("utf-8")
    buf += struct.pack("<I", len(b))
    buf += b


def _unpack_str(data: bytes, off: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    s = data[off:off + n].decode("utf-8")
    return s, off + n


def encode_request(req: Request, buf: bytearray) -> None:
    buf += struct.pack("<BiB", int(req.request_type), req.request_rank,
                       int(req.tensor_type))
    _pack_str(buf, req.tensor_name)
    buf += struct.pack("<i", req.root_rank)
    _pack_str(buf, req.device)
    buf += struct.pack("<Bdd", int(req.reduce_op), req.prescale_factor,
                       req.postscale_factor)
    dims = req.tensor_shape.dims
    buf += struct.pack("<B", len(dims))
    for d in dims:
        buf += struct.pack("<q", d)
    buf += struct.pack("<ii", req.process_set_id, req.process_set_size)


def decode_request(data: bytes, off: int) -> Tuple[Request, int]:
    rtype, rrank, ttype = struct.unpack_from("<BiB", data, off)
    off += struct.calcsize("<BiB")
    name, off = _unpack_str(data, off)
    (root,) = struct.unpack_from("<i", data, off)
    off += 4
    device, off = _unpack_str(data, off)
    rop, pre, post = struct.unpack_from("<Bdd", data, off)
    off += struct.calcsize("<Bdd")
    (ndim,) = struct.unpack_from("<B", data, off)
    off += 1
    dims = []
    for _ in range(ndim):
        (d,) = struct.unpack_from("<q", data, off)
        off += 8
        dims.append(d)
    ps_id, ps_size = struct.unpack_from("<ii", data, off)
    off += 8
    return Request(
        request_rank=rrank,
        request_type=RequestType(rtype),
        tensor_type=DataType(ttype),
        tensor_name=name,
        root_rank=root,
        device=device,
        tensor_shape=TensorShape(dims),
        reduce_op=ReduceOp(rop),
        prescale_factor=pre,
        postscale_factor=post,
        process_set_id=ps_id,
        process_set_size=ps_size,
    ), off


def encode_request_list(reqs: List[Request], shutdown: bool = False,
                        cache_hits: List[Tuple[str, int]] = (),
                        epoch: int = 0) -> bytes:
    buf = bytearray()
    buf += struct.pack("<BI", 1 if shutdown else 0, len(reqs))
    for r in reqs:
        encode_request(r, buf)
    buf += struct.pack("<I", len(cache_hits))
    for name, pos in cache_hits:
        _pack_str(buf, name)
        buf += struct.pack("<I", pos)
    buf += struct.pack("<I", epoch)
    return bytes(buf)


def decode_request_list(
        data: bytes) -> Tuple[List[Request], bool, List[Tuple[str, int]],
                              int]:
    shutdown, n = struct.unpack_from("<BI", data, 0)
    off = struct.calcsize("<BI")
    out = []
    for _ in range(n):
        r, off = decode_request(data, off)
        out.append(r)
    (n_hits,) = struct.unpack_from("<I", data, off)
    off += 4
    hits = []
    for _ in range(n_hits):
        name, off = _unpack_str(data, off)
        (pos,) = struct.unpack_from("<I", data, off)
        off += 4
        hits.append((name, pos))
    epoch = 0
    if off + 4 <= len(data):  # pre-trailer encoders stop here
        (epoch,) = struct.unpack_from("<I", data, off)
    return out, bool(shutdown), hits, epoch


def encode_response(resp: Response, buf: bytearray) -> None:
    buf += struct.pack("<BBI", int(resp.response_type),
                       int(resp.tensor_type), len(resp.tensor_names))
    for nm in resp.tensor_names:
        _pack_str(buf, nm)
    _pack_str(buf, resp.error_message)
    buf += struct.pack("<I", len(resp.devices))
    for d in resp.devices:
        _pack_str(buf, d)
    buf += struct.pack("<I", len(resp.tensor_sizes))
    for s in resp.tensor_sizes:
        buf += struct.pack("<q", s)
    buf += struct.pack("<Bdd", int(resp.reduce_op), resp.prescale_factor,
                       resp.postscale_factor)
    buf += struct.pack("<I", len(resp.tensor_shapes))
    for shape in resp.tensor_shapes:
        dims = shape.dims
        buf += struct.pack("<B", len(dims))
        for d in dims:
            buf += struct.pack("<q", d)
    buf += struct.pack("<i", resp.process_set_id)


def decode_response(data: bytes, off: int) -> Tuple[Response, int]:
    rtype, ttype, n_names = struct.unpack_from("<BBI", data, off)
    off += struct.calcsize("<BBI")
    names = []
    for _ in range(n_names):
        nm, off = _unpack_str(data, off)
        names.append(nm)
    err, off = _unpack_str(data, off)
    (n_dev,) = struct.unpack_from("<I", data, off)
    off += 4
    devices = []
    for _ in range(n_dev):
        d, off = _unpack_str(data, off)
        devices.append(d)
    (n_sizes,) = struct.unpack_from("<I", data, off)
    off += 4
    sizes = []
    for _ in range(n_sizes):
        (s,) = struct.unpack_from("<q", data, off)
        off += 8
        sizes.append(s)
    rop, pre, post = struct.unpack_from("<Bdd", data, off)
    off += struct.calcsize("<Bdd")
    (n_shapes,) = struct.unpack_from("<I", data, off)
    off += 4
    shapes = []
    for _ in range(n_shapes):
        (ndim,) = struct.unpack_from("<B", data, off)
        off += 1
        dims = []
        for _ in range(ndim):
            (d,) = struct.unpack_from("<q", data, off)
            off += 8
            dims.append(d)
        shapes.append(TensorShape(dims))
    (ps_id,) = struct.unpack_from("<i", data, off)
    off += 4
    return Response(
        response_type=ResponseType(rtype),
        tensor_type=DataType(ttype),
        tensor_names=names,
        error_message=err,
        devices=devices,
        tensor_sizes=sizes,
        reduce_op=ReduceOp(rop),
        prescale_factor=pre,
        postscale_factor=post,
        tensor_shapes=shapes,
        process_set_id=ps_id,
    ), off


def encode_response_list(resps: List[Response], shutdown: bool = False,
                         hit_positions: List[int] = (),
                         resend_names: List[str] = (),
                         params: Optional[Tuple[int, float, bool,
                                                bool, bool, int]] = None,
                         epoch: int = 0) -> bytes:
    """``params``: (fusion_threshold, cycle_time_s, cache_enabled,
    hierarchical_allreduce, hierarchical_allgather, ring_segment_bytes)
    knob broadcast from the autotuner, or None.  A 5-tuple is accepted
    for callers predating the segment knob (encoded as 0)."""
    buf = bytearray()
    buf += struct.pack("<BI", 1 if shutdown else 0, len(resps))
    for r in resps:
        encode_response(r, buf)
    buf += struct.pack("<I", len(hit_positions))
    for p in hit_positions:
        buf += struct.pack("<I", p)
    buf += struct.pack("<I", len(resend_names))
    for nm in resend_names:
        _pack_str(buf, nm)
    if params is None:
        buf += struct.pack("<B", 0)
    else:
        fusion, cycle_s, cache_on, hier_ar, hier_ag = params[:5]
        segment = params[5] if len(params) > 5 else 0
        buf += struct.pack("<BqdBBBq", 1, fusion, cycle_s,
                           1 if cache_on else 0, 1 if hier_ar else 0,
                           1 if hier_ag else 0, segment)
    buf += struct.pack("<I", epoch)
    return bytes(buf)


def decode_response_list(data: bytes) -> Tuple[
        List[Response], bool, List[int], List[str],
        Optional[Tuple[int, float, bool, bool, bool, int]], int]:
    shutdown, n = struct.unpack_from("<BI", data, 0)
    off = struct.calcsize("<BI")
    out = []
    for _ in range(n):
        r, off = decode_response(data, off)
        out.append(r)
    (n_hits,) = struct.unpack_from("<I", data, off)
    off += 4
    hits = []
    for _ in range(n_hits):
        (p,) = struct.unpack_from("<I", data, off)
        off += 4
        hits.append(p)
    (n_resend,) = struct.unpack_from("<I", data, off)
    off += 4
    resend = []
    for _ in range(n_resend):
        nm, off = _unpack_str(data, off)
        resend.append(nm)
    (has_params,) = struct.unpack_from("<B", data, off)
    off += 1
    params = None
    if has_params:
        fusion, cycle_s, cache_on, hier_ar, hier_ag, segment = \
            struct.unpack_from("<qdBBBq", data, off)
        off += struct.calcsize("<qdBBBq")
        params = (fusion, cycle_s, bool(cache_on), bool(hier_ar),
                  bool(hier_ag), segment)
    epoch = 0
    if off + 4 <= len(data):  # pre-trailer encoders stop here
        (epoch,) = struct.unpack_from("<I", data, off)
    return out, bool(shutdown), hits, resend, params, epoch


# -- collective-abort agreement payloads (docs/fault_tolerance.md) -----


def encode_abort_report(tensor_name: str, suspect_rank: int,
                        epoch: int = 0) -> bytes:
    """Worker -> coordinator: a local hop timeout during
    ``tensor_name``, blocked on ``suspect_rank`` (-1 = unknown)."""
    buf = bytearray()
    _pack_str(buf, tensor_name)
    buf += struct.pack("<iI", suspect_rank, epoch)
    return bytes(buf)


def decode_abort_report(data: bytes) -> Tuple[str, int, int]:
    name, off = _unpack_str(data, 0)
    suspect, epoch = struct.unpack_from("<iI", data, off)
    return name, suspect, epoch


def encode_probe_ack(busy: bool, busy_seconds: float,
                     epoch: int = 0) -> bytes:
    """Worker -> coordinator: probe answer.  ``busy`` = a collective is
    executing right now; ``busy_seconds`` = for how long."""
    return struct.pack("<BdI", 1 if busy else 0, busy_seconds, epoch)


def decode_probe_ack(data: bytes) -> Tuple[bool, float, int]:
    busy, busy_seconds, epoch = struct.unpack_from("<BdI", data, 0)
    return bool(busy), busy_seconds, epoch


def encode_abort_verdict(tensor_name: str, ranks,
                         epoch: int = 0) -> bytes:
    """Coordinator -> workers: the gang-agreed wedged rank set for the
    collective named ``tensor_name``."""
    buf = bytearray()
    _pack_str(buf, tensor_name)
    ranks = sorted(int(r) for r in ranks)
    buf += struct.pack("<I", len(ranks))
    for r in ranks:
        buf += struct.pack("<i", r)
    buf += struct.pack("<I", epoch)
    return bytes(buf)


def decode_abort_verdict(data: bytes) -> Tuple[str, List[int], int]:
    name, off = _unpack_str(data, 0)
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    ranks = []
    for _ in range(n):
        (r,) = struct.unpack_from("<i", data, off)
        off += 4
        ranks.append(r)
    (epoch,) = struct.unpack_from("<I", data, off)
    return name, ranks, epoch


# -- trace clock sync (docs/timeline.md "Gang-wide tracing") ------------


def encode_clock_ping(t0_ns: int, epoch: int = 0) -> bytes:
    """Worker -> coordinator: this rank's monotonic clock, now."""
    return struct.pack("<qI", t0_ns, epoch)


def decode_clock_ping(data: bytes) -> Tuple[int, int]:
    t0_ns, epoch = struct.unpack_from("<qI", data, 0)
    return t0_ns, epoch


def encode_clock_pong(t0_ns: int, t_coord_ns: int,
                      epoch: int = 0) -> bytes:
    """Coordinator -> worker: the ping's t0 echoed back plus the
    coordinator's own monotonic clock at answer time."""
    return struct.pack("<qqI", t0_ns, t_coord_ns, epoch)


def decode_clock_pong(data: bytes) -> Tuple[int, int, int]:
    t0_ns, t_coord_ns, epoch = struct.unpack_from("<qqI", data, 0)
    return t0_ns, t_coord_ns, epoch


# -- flight-recorder dump pull (docs/fault_tolerance.md "black box") ----


def encode_blackbox_request(epoch: int = 0) -> bytes:
    """Coordinator -> worker (TAG_BLACKBOX): send me your flight-recorder
    ring.  Sent after an abort-verdict broadcast so the archive on the
    coordinator's disk covers ranks whose own dump may never land."""
    return struct.pack("<I", epoch)


def decode_blackbox_request(data: bytes) -> int:
    (epoch,) = struct.unpack_from("<I", data, 0)
    return epoch


def encode_blackbox_dump(rank: int, epoch: int, blob: bytes) -> bytes:
    """Worker -> coordinator (TAG_BLACKBOX_DUMP): the serialized dump
    document (UTF-8 JSON, the same bytes ``blackbox_rank<r>.json`` would
    hold)."""
    return struct.pack("<iII", rank, epoch, len(blob)) + blob


def decode_blackbox_dump(data: bytes) -> Tuple[int, int, bytes]:
    rank, epoch, n = struct.unpack_from("<iII", data, 0)
    return rank, epoch, bytes(data[12:12 + n])


# -- recovery-ladder framing (docs/fault_tolerance.md) ------------------
#
# Every data frame on a CRC-armed link carries an 8-byte trailer: the
# link-local send sequence number and a CRC-32 over payload-then-seq.
# The receiver validates before any byte reaches the reduction, NACKs
# the expected seq on mismatch, and the sender replays from its
# retained copy (utils/ladder.py).

_TRAILER = struct.Struct("<II")
TRAILER_BYTES = _TRAILER.size


class WireCorruptionError(ConnectionError):
    """A data frame failed CRC validation (or the ladder exhausted its
    retransmit/reconnect budget trying to heal a link).  Carries the
    peer rank and hop phase like :class:`~ops.cpu_backend.HopTimeout`,
    so the engine can feed the same gang-wide abort agreement."""

    def __init__(self, peer: int, cause: str):
        super().__init__(
            f"data-plane link to rank {peer} is corrupt past the "
            f"recovery ladder ({cause})")
        self.peer = int(peer)
        self.phase = "recv"
        self.cause = cause


def data_crc(payload, seq: int) -> int:
    """CRC-32 (zlib polynomial) over the payload bytes then the packed
    seq — covering the seq binds the checksum to the frame's position in
    the stream, so a replayed-but-stale frame can never validate."""
    crc = zlib.crc32(payload)
    return zlib.crc32(struct.pack("<I", seq & 0xFFFFFFFF), crc)


def pack_trailer(payload, seq: int) -> bytes:
    return _TRAILER.pack(seq & 0xFFFFFFFF, data_crc(payload, seq))


def split_trailer(frame: memoryview) -> Tuple[memoryview, int, int]:
    """Split a trailered data frame into (payload_view, seq, crc); the
    caller validates ``crc == data_crc(payload_view, seq)``."""
    if len(frame) < TRAILER_BYTES:
        raise ValueError("data frame shorter than its CRC trailer")
    body = frame[:-TRAILER_BYTES]
    seq, crc = _TRAILER.unpack(frame[-TRAILER_BYTES:])
    return body, seq, crc


def encode_nack(expected_seq: int) -> bytes:
    return struct.pack("<I", expected_seq & 0xFFFFFFFF)


def decode_nack(data: bytes) -> int:
    return struct.unpack_from("<I", data, 0)[0]


def encode_resume(rank: int, expected_seq: int, epoch: int = 0) -> bytes:
    """Both RESUME (post-reconnect) and FAILOVER (shm->TCP demotion)
    ride this payload: who is speaking, the next data seq they expect
    to receive, and their membership epoch (stale-incarnation guard)."""
    return struct.pack("<iII", rank, expected_seq & 0xFFFFFFFF, epoch)


def decode_resume(data: bytes) -> Tuple[int, int, int]:
    return struct.unpack_from("<iII", data, 0)


# -- serving admission broadcast (docs/serving.md) ----------------------
#
# One frame per continuous-batching decode step: rank 0's scheduler tells
# the gang which requests join which slots THIS step.  Retirements are
# not carried — decode is deterministic (greedy), so every rank retires
# the same slot at the same token on its own.


def encode_serve_delta(seq: int, stop: bool, admissions,
                       epoch: int = 0, leader_addr: str = "") -> bytes:
    """Coordinator -> workers: step ``seq``'s batch delta.
    ``admissions``: iterable of (slot, request_id, max_new_tokens,
    prompt_tokens) with ``prompt_tokens`` an iterable of ints.
    ``leader_addr`` (``host:port`` of the leader's front door, "" =
    unknown) rides as a trailer AFTER the epoch so pre-trailer decoders
    — which stop reading at the epoch — still parse the frame."""
    buf = bytearray()
    buf += struct.pack("<QBI", seq, 1 if stop else 0, len(admissions))
    for slot, req_id, max_new, prompt in admissions:
        buf += struct.pack("<II", slot, max_new)
        _pack_str(buf, req_id)
        prompt = [int(t) for t in prompt]
        buf += struct.pack(f"<I{len(prompt)}I", len(prompt), *prompt)
    buf += struct.pack("<I", epoch)
    _pack_str(buf, leader_addr)
    return bytes(buf)


def decode_serve_delta(data: bytes):
    """Returns (seq, stop, admissions, epoch) — the encode_serve_delta
    arguments, with each admission as (slot, request_id, max_new_tokens,
    prompt_tokens list)."""
    return decode_serve_delta_ex(data)[:4]


def decode_serve_delta_ex(data: bytes):
    """Returns (seq, stop, admissions, epoch, leader_addr); a frame
    from an encoder without the leader trailer yields ``""``."""
    seq, stop, n = struct.unpack_from("<QBI", data, 0)
    off = struct.calcsize("<QBI")
    admissions = []
    for _ in range(n):
        slot, max_new = struct.unpack_from("<II", data, off)
        off += 8
        req_id, off = _unpack_str(data, off)
        (plen,) = struct.unpack_from("<I", data, off)
        off += 4
        prompt = list(struct.unpack_from(f"<{plen}I", data, off))
        off += 4 * plen
        admissions.append((slot, req_id, max_new, prompt))
    (epoch,) = struct.unpack_from("<I", data, off)
    off += 4
    leader_addr = ""
    if off < len(data):
        leader_addr, off = _unpack_str(data, off)
    return seq, bool(stop), admissions, epoch, leader_addr


# -- hierarchical control tree (docs/fault_tolerance.md "Hierarchical
#    control plane, fencing, and quorum") --------------------------------
#
# Layout (little-endian, like everything above; values reserved in
# csrc/wire.h — the native engine refuses the tags cleanly and never
# joins a tree):
#
#   TreeUp   := u32 epoch, u32 n, { i32 rank, u8 tag, varstr payload }[n]
#   TreeDown := i32 target_rank (-1 = every child), u8 tag, varstr payload
#   Reparent := i32 rank, i32 old_parent, u32 epoch
#   Fence    := u32 stale_epoch, u32 current_epoch
#
# TreeUp is tag-transparent: a sub-coordinator folds whatever frames its
# children sent it (TAG_REQUEST_LIST ready ticks, TAG_HEARTBEAT, probe
# acks) into one aggregate, and the root dispatches each entry exactly
# as if it had arrived on that rank's own control socket.  TreeDown
# routes a root frame (TAG_PROBE today) through the sub-coordinator to
# one child or to the whole host.


def encode_tree_up(entries, epoch: int = 0) -> bytes:
    """Sub-coordinator -> root: ``entries`` = [(rank, tag, payload)]."""
    buf = bytearray(struct.pack("<II", epoch, len(entries)))
    for rank, tag, payload in entries:
        buf += struct.pack("<iBI", rank, tag, len(payload))
        buf += payload
    return bytes(buf)


def decode_tree_up(data: bytes):
    """Returns ``(entries, epoch)`` with entries = [(rank, tag, payload)]."""
    epoch, n = struct.unpack_from("<II", data, 0)
    off = 8
    entries = []
    for _ in range(n):
        rank, tag, plen = struct.unpack_from("<iBI", data, off)
        off += 9
        entries.append((rank, tag, bytes(data[off:off + plen])))
        off += plen
    return entries, epoch


def encode_tree_down(target_rank: int, tag: int, payload: bytes) -> bytes:
    """Root -> sub-coordinator: forward ``(tag, payload)`` to
    ``target_rank`` (-1 = every child on that host)."""
    return struct.pack("<iBI", target_rank, tag, len(payload)) + payload


def decode_tree_down(data: bytes):
    """Returns ``(target_rank, tag, payload)``."""
    target, tag, plen = struct.unpack_from("<iBI", data, 0)
    return target, tag, bytes(data[9:9 + plen])


def encode_reparent(rank: int, old_parent: int, epoch: int = 0) -> bytes:
    """Orphaned child -> root: my sub-coordinator ``old_parent`` died;
    route my control traffic directly from now on."""
    return struct.pack("<iiI", rank, old_parent, epoch)


def decode_reparent(data: bytes):
    """Returns ``(rank, old_parent, epoch)``."""
    return struct.unpack_from("<iiI", data, 0)


# -- epoch fence (docs/fault_tolerance.md "epoch fencing") ---------------


def encode_fence(stale_epoch: int, current_epoch: int) -> bytes:
    """Coordinator -> a sender whose control frame carried a stale
    membership epoch: you were evicted/re-formed away; exit."""
    return struct.pack("<II", stale_epoch, current_epoch)


def decode_fence(data: bytes):
    """Returns ``(stale_epoch, current_epoch)``."""
    return struct.unpack_from("<II", data, 0)
