"""Common core types shared by every layer of horovod_tpu.

Python-visible mirror of the native core's type system (see ``csrc/``).
Behavioral parity target: the reference's ``horovod/common/common.h`` and
``horovod/common/message.h`` (DataType enum at message.h:27-38, Request at
message.h:47-100, Response at message.h:132-192) — re-designed, not copied:
the wire format here is a compact little-endian struct encoding rather than
FlatBuffers, and device identity is a JAX platform string rather than a CUDA
device ordinal.
"""

from horovod_tpu.common.types import (  # noqa: F401
    DataType,
    ReduceOp,
    Request,
    RequestType,
    Response,
    ResponseType,
    Status,
    StatusType,
    TensorShape,
)
