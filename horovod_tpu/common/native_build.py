"""Shared build-on-demand machinery for framework custom-op libraries.

The TF kernels (``csrc/tf_ops.cc``) and torch dispatcher ops
(``csrc/torch_ops.cc``) follow one pattern: compile against the
installed framework's headers, link ``libhvd_core.so`` with an
``$ORIGIN`` rpath, publish atomically (gangs race to build), and track
staleness against every header the kernels transitively include.  One
implementation here; the per-framework loaders supply only flags and
the ``load`` call.
"""

from __future__ import annotations

import os
import subprocess
from typing import Sequence

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB_DIR = os.path.join(_PKG_DIR, "_lib")
CORE_SO = os.path.join(LIB_DIR, "libhvd_core.so")
CSRC_DIR = os.path.normpath(os.path.join(_PKG_DIR, os.pardir, "csrc"))

# Everything the framework-op translation units may include; a change
# in ANY of these (enum values in types.h especially) must force a
# rebuild or a stale library would map wire enums wrongly.
_DEP_HEADERS = ("engine.h", "types.h", "kernels.h", "wire.h",
                "sockets.h", "timeline.h", "autotune.h")


def cxx() -> str:
    return os.environ.get("CXX", "g++")


def needs_build(src: str, so: str) -> bool:
    if not os.path.isfile(src):
        return False  # wheel install: use the prebuilt .so or fall back
    if not os.path.exists(so):
        return True
    deps = [src, CORE_SO]
    deps += [os.path.join(CSRC_DIR, h) for h in _DEP_HEADERS]
    newest = max(os.path.getmtime(p) for p in deps if os.path.exists(p))
    return os.path.getmtime(so) < newest


def build(src: str, so: str, extra_flags: Sequence[str],
          extra_links: Sequence[str]) -> None:
    """Compile ``src`` into ``so`` linking the engine core.  Gang-safe:
    compile to a per-pid temp, publish with an atomic rename."""
    tmp = f"{so}.tmp.{os.getpid()}"
    cmd = [cxx(), "-O2", "-std=c++17", "-fPIC", "-w",
           f"-I{CSRC_DIR}", *extra_flags,
           "-shared", src,
           f"-L{os.path.dirname(CORE_SO)}", "-l:libhvd_core.so",
           "-Wl,-rpath,$ORIGIN", *extra_links,
           "-o", tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=600)
        if r.returncode != 0:
            raise RuntimeError(
                f"build of {os.path.basename(so)} failed: "
                f"{r.stderr[-800:]}")
        os.replace(tmp, so)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def native_engine_active() -> bool:
    """Common precondition: the C++ engine the kernels enqueue into is
    live in this process (re-checked per call — never latched)."""
    try:
        from horovod_tpu import basics
        from horovod_tpu.runtime_native import NativeEngine

        return isinstance(basics._engine(), NativeEngine)
    except Exception:
        return False
