"""Distributed input pipeline: rank-sharded sampling + device prefetch.

The reference has no loader of its own — its examples lean on
``torch.utils.data.distributed.DistributedSampler`` (
``examples/pytorch_imagenet_resnet50.py:112-130,177``: one shard per
rank, reshuffled per epoch via ``set_epoch``) and hand-rolled
rank-slicing in the TF/Keras examples.  A user switching from the
reference needs that idiom as a first-class surface, so this module
provides it framework-neutrally, plus the piece a TPU actually needs
that GPU loaders get for free from CUDA streams: **asynchronous
host→device transfer** overlapping the training step
(:func:`prefetch_to_device`), which hides dispatch/PCIe (or tunnel)
latency behind compute.

Composition::

    sampler = ShardedSampler(len(ds), rank=hvd.rank(), size=hvd.size())
    for epoch in range(epochs):
        sampler.set_epoch(epoch)
        for xb, yb in prefetch_to_device(
                batches(ds, sampler, batch_size=64)):
            state, loss = train_step(state, xb, yb)

Everything is plain numpy until :func:`prefetch_to_device`, so the
pipeline also serves the eager engines' numpy workers unchanged.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, Sequence, Tuple

import numpy as np

__all__ = [
    "ShardedSampler",
    "ArrayDataset",
    "batches",
    "prefetch_to_device",
]


class ShardedSampler:
    """Deterministic per-rank index shard with per-epoch reshuffling.

    Semantics follow the reference examples' ``DistributedSampler``
    usage: every rank sees ``ceil(n / size)`` indices (the tail is
    padded by wrapping, so all ranks take the same number of steps and
    collectives stay aligned), the permutation is seeded by
    ``(seed, epoch)`` identically on every rank, and each rank takes a
    strided slice of it.  Call :meth:`set_epoch` before each epoch or
    every epoch repeats epoch 0's order.

    With ``drop_last=True`` the global sample count is truncated to a
    multiple of ``size`` instead of padded.
    """

    def __init__(self, n_samples: int, rank: int, size: int, *,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False):
        if not 0 <= rank < size:
            raise ValueError(f"rank {rank} outside [0, {size})")
        if n_samples <= 0:
            raise ValueError("empty dataset")
        self.n_samples = int(n_samples)
        self.rank = int(rank)
        self.size = int(size)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.drop_last = bool(drop_last)
        self._epoch = 0
        if drop_last:
            self._per_rank = self.n_samples // self.size
            if self._per_rank == 0:
                raise ValueError(
                    f"{n_samples} samples over {size} ranks with "
                    "drop_last leaves rank shards empty")
        else:
            self._per_rank = -(-self.n_samples // self.size)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)

    def __len__(self) -> int:
        return self._per_rank

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            order = np.random.RandomState(
                (self.seed * 1_000_003 + self._epoch) % (2 ** 31)
            ).permutation(self.n_samples)
        else:
            order = np.arange(self.n_samples)
        total = self._per_rank * self.size
        if total > self.n_samples:  # pad by wrapping, reference-style
            order = np.concatenate([order, order[: total - self.n_samples]])
        else:
            order = order[:total]
        return iter(order[self.rank:total:self.size].tolist())


class ArrayDataset:
    """Tuple-of-arrays dataset: ``ds[i] -> (arrays[0][i], ...)``."""

    def __init__(self, *arrays: np.ndarray):
        if not arrays:
            raise ValueError("need at least one array")
        n = len(arrays[0])
        for a in arrays[1:]:
            if len(a) != n:
                raise ValueError("arrays disagree on length")
        self.arrays = tuple(np.asarray(a) for a in arrays)

    def __len__(self) -> int:
        return len(self.arrays[0])

    def batch(self, idx: Sequence[int]) -> Tuple[np.ndarray, ...]:
        ix = np.asarray(idx)
        return tuple(a[ix] for a in self.arrays)

    @staticmethod
    def from_parquet(paths, columns: Sequence[str]) -> "ArrayDataset":
        """Load parquet files (a path, glob, or list) into memory as one
        dataset — the plain-files twin of the Spark estimators' shard
        store (``spark/store.py`` writes exactly these).  Each column
        becomes one array with its stored dtype preserved (Arrow-native
        conversion, no Python-object hop); list-valued columns reshape
        to ``[rows, width]`` (one nesting level, rows must agree on
        width)."""
        import glob as globlib
        import os

        import pyarrow.parquet as pq

        if isinstance(paths, (str, bytes, os.PathLike)):
            pattern = os.fsdecode(paths)
            matched = sorted(globlib.glob(pattern))
            if matched:
                paths = matched
            elif globlib.has_magic(pattern):
                raise FileNotFoundError(
                    f"glob {pattern!r} matched no files")
            else:
                paths = [pattern]
        tables = [pq.read_table(p, columns=list(columns)) for p in paths]
        cols = []
        for name in columns:
            parts = [_arrow_column_to_numpy(t[name]) for t in tables]
            cols.append(np.concatenate(parts) if len(parts) > 1
                        else parts[0])
        return ArrayDataset(*cols)


def _arrow_column_to_numpy(chunked) -> np.ndarray:
    """Arrow column → numpy, dtype-preserving.  Fixed-width list columns
    reshape from their flattened values buffer (a float32 list column
    comes back float32 — ``to_pylist`` widened it to float64 and paid an
    O(n) Python-object conversion)."""
    import pyarrow as pa

    arrs = []
    for chunk in chunked.chunks:
        t = chunk.type
        if pa.types.is_list(t) or pa.types.is_large_list(t) \
                or pa.types.is_fixed_size_list(t):
            values = chunk.flatten().to_numpy(zero_copy_only=False)
            n = len(chunk)
            if n == 0:
                arrs.append(values.reshape(0, -1))
                continue
            width, rem = divmod(len(values), n)
            if rem:
                raise ValueError(
                    "ragged list column: rows must agree on width")
            arrs.append(values.reshape(n, width))
        else:
            arrs.append(chunk.to_numpy(zero_copy_only=False))
    if not arrs:
        return np.empty((0,))
    return np.concatenate(arrs) if len(arrs) > 1 else arrs[0]


def batches(dataset, sampler: ShardedSampler, batch_size: int, *,
            drop_remainder: bool = True) -> Iterator[Tuple[np.ndarray, ...]]:
    """Yields host-side batches of ``dataset`` in ``sampler`` order.

    ``dataset`` needs ``batch(list_of_indices)`` (:class:`ArrayDataset`)
    or plain ``__getitem__`` over which samples are stacked.
    ``drop_remainder=True`` (default) keeps batch shapes static — one
    compiled program under ``jit``, no retrace on the last batch.
    """
    buf: list = []
    take = getattr(dataset, "batch", None)
    for i in sampler:
        buf.append(i)
        if len(buf) == batch_size:
            yield take(buf) if take else _stack(dataset, buf)
            buf = []
    if buf and not drop_remainder:
        yield take(buf) if take else _stack(dataset, buf)


def _stack(dataset, idx):
    rows = [dataset[i] for i in idx]
    if isinstance(rows[0], tuple):
        return tuple(np.stack(col) for col in zip(*rows))
    return np.stack(rows)


def prefetch_to_device(it: Iterable, *, buffer_size: int = 2,
                       sharding=None) -> Iterator:
    """Moves batches to device ``buffer_size`` ahead of consumption.

    A daemon thread pulls from ``it`` and starts the host→device
    transfer (``jax.device_put`` is asynchronous); by the time the
    training loop asks for the next batch its transfer has been
    overlapping the previous step's compute.  ``sharding`` (e.g. a
    ``NamedSharding`` over the dp axis) places each leaf; default is
    the default device.

    On hosts where jax is unavailable (numpy-only eager workers) the
    iterator passes batches through untouched.
    """
    try:
        import jax
    except Exception:
        yield from it
        return

    if buffer_size < 1:
        raise ValueError("buffer_size must be >= 1")

    def put(batch):
        if sharding is not None:
            return jax.tree.map(
                lambda a: jax.device_put(a, sharding), batch)
        return jax.tree.map(jax.device_put, batch)

    q: queue.Queue = queue.Queue(maxsize=buffer_size)
    stop = threading.Event()  # consumer gone — producer must exit

    class _Err:
        def __init__(self, exc):
            self.exc = exc

    _END = object()

    def send(item) -> bool:
        """Blocking put that gives up when the consumer has left."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for batch in it:
                if not send(put(batch)):
                    return
        except Exception as e:  # surfaced on the consumer side
            send(_Err(e))
        else:
            send(_END)

    threading.Thread(target=producer, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, _Err):
                raise item.exc
            yield item
    finally:
        # Early exit (break / generator close): wake a producer blocked
        # in put() and drop any buffered device batches.
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
