"""Decoder-only Transformer LM — the flagship multi-axis-parallel model.

The reference framework is data-parallel only (SURVEY.md §2.8); a complete
TPU framework must also scale model size (tp), sequence length (sp), and
experts (ep).  This model is built so that every one of those axes is a
*sharding decision*, not a code path:

* Layers are stacked along a leading axis and iterated with ``lax.scan`` —
  one compiled layer body regardless of depth (and the natural substrate
  for pipeline parallelism: split the stacked axis over the ``pp`` mesh
  axis, see ``horovod_tpu.parallel.pipeline``).
* ``param_specs(config)`` gives a PartitionSpec pytree: attention heads and
  FFN hidden dim sharded over ``tp`` (Megatron layout: column-parallel in,
  row-parallel out — XLA inserts exactly the two psums per block), experts
  over ``ep``.
* Activations carry ``P('dp', 'sp', None)`` constraints: batch over data
  ranks, sequence over the sp axis.  Attention under GSPMD all-gathers K/V
  over sp; the ring-attention path (``horovod_tpu.parallel.ring_attention``)
  replaces that with neighbor ``ppermute`` exchanges when activated.
* bf16 compute, fp32 params/norms, RoPE positions, pre-RMSNorm blocks,
  causal masking via static ``lax`` ops only — no dynamic shapes anywhere.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int = 2048
    n_experts: int = 0          # 0 → dense FFN; >0 → Switch-style MoE
    capacity_factor: float = 1.25
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    compute_dtype: Any = jnp.bfloat16
    # "dense": GSPMD attention (XLA all-gathers K/V over sp);
    # "ring": blockwise ring attention via ppermute over the sp ring;
    # "ulysses": all-to-all head exchange (see parallel/ring_attention.py);
    # "flash": Pallas blockwise flash-attention kernel
    #   (ops/pallas_attention.py) — O(S) memory, MXU-tiled; used when the
    #   mesh has no tp/sp sharding to partition across (falls back to
    #   dense under GSPMD sharding, where XLA cannot split a pallas_call).
    attn_impl: str = "dense"
    # Rematerialize each layer in the backward pass (jax.checkpoint).
    # Costs ~1 extra forward of compute for O(1)-layer activation
    # memory; turn off when the model fits without it.
    remat: bool = True

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def _normal(key, shape, std):
    return jax.random.normal(key, shape, jnp.float32) * std


def init(rng, cfg: TransformerConfig) -> Params:
    k = iter(jax.random.split(rng, 16))
    L, D, H, HD, F = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                      cfg.head_dim, cfg.d_ff)
    std = 0.02
    out_std = std / math.sqrt(2 * L)
    layer: Params = {
        "ln1": jnp.ones((L, D), jnp.float32),
        "ln2": jnp.ones((L, D), jnp.float32),
        "wq": _normal(next(k), (L, D, H, HD), std),
        "wk": _normal(next(k), (L, D, H, HD), std),
        "wv": _normal(next(k), (L, D, H, HD), std),
        "wo": _normal(next(k), (L, H, HD, D), out_std),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        layer["router"] = _normal(next(k), (L, D, E), std)
        layer["w_in"] = _normal(next(k), (L, E, D, F), std)
        layer["w_gate"] = _normal(next(k), (L, E, D, F), std)
        layer["w_out"] = _normal(next(k), (L, E, F, D), out_std)
    else:
        layer["w_in"] = _normal(next(k), (L, D, F), std)
        layer["w_gate"] = _normal(next(k), (L, D, F), std)
        layer["w_out"] = _normal(next(k), (L, F, D), out_std)
    return {
        "embed": _normal(next(k), (cfg.vocab_size, D), std),
        "layers": layer,
        "ln_f": jnp.ones((D,), jnp.float32),
    }


def param_specs(cfg: TransformerConfig) -> Params:
    """PartitionSpec pytree (Megatron tp layout + ep experts).

    The leading stacked-layer axis is left unsharded here; the pipeline
    wrapper reshards it over ``pp`` when pipelining is on.
    """
    layer: Params = {
        "ln1": P(None, None),
        "ln2": P(None, None),
        "wq": P(None, None, "tp", None),
        "wk": P(None, None, "tp", None),
        "wv": P(None, None, "tp", None),
        "wo": P(None, "tp", None, None),
    }
    if cfg.n_experts:
        layer["router"] = P(None, None, None)
        layer["w_in"] = P(None, "ep", None, "tp")
        layer["w_gate"] = P(None, "ep", None, "tp")
        layer["w_out"] = P(None, "ep", "tp", None)
    else:
        layer["w_in"] = P(None, None, "tp")
        layer["w_gate"] = P(None, None, "tp")
        layer["w_out"] = P(None, "tp", None)
    return {
        "embed": P("tp", None),
        "layers": layer,
        "ln_f": P(None),
    }


ACT_SPEC = P("dp", "sp", None)  # [batch, seq, d_model]


# ---------------------------------------------------------------------------
# apply
# ---------------------------------------------------------------------------


def _constrain(x, spec: Optional[P], mesh):
    """Apply a sharding constraint, keeping only axes present in ``mesh``.

    ``mesh`` is threaded explicitly (static Python value) instead of read
    from ambient context so the model works under plain ``jit`` with
    ``in_shardings`` on any JAX version.
    """
    if spec is None or mesh is None:
        return x
    from horovod_tpu.parallel.mesh import filter_spec

    fixed = filter_spec(spec, mesh)
    if all(ax is None for ax in fixed):
        return x
    return lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, fixed))


def _rmsnorm(x, g):
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (y * g).astype(x.dtype)


def _rope(x, theta: float, pos=None):
    """Rotary embedding over head_dim pairs; x: [B, S, H, HD].
    ``pos``: optional [S] absolute positions (decode steps rotate a
    single new token at its true position); default ``arange(S)``."""
    B, S, H, HD = x.shape
    half = HD // 2
    freqs = jnp.exp(
        -math.log(theta) * jnp.arange(half, dtype=jnp.float32) / half)
    if pos is None:
        pos = jnp.arange(S, dtype=jnp.float32)
    else:
        pos = pos.astype(jnp.float32)
    ang = pos[:, None] * freqs[None, :]          # [S, half]
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


def _attention(x, lp, cfg: TransformerConfig, mesh=None):
    B, S, D = x.shape
    dtype = cfg.compute_dtype
    q = jnp.einsum("bsd,dhk->bshk", x, lp["wq"].astype(dtype))
    kk = jnp.einsum("bsd,dhk->bshk", x, lp["wk"].astype(dtype))
    v = jnp.einsum("bsd,dhk->bshk", x, lp["wv"].astype(dtype))
    q = _rope(q, cfg.rope_theta)
    kk = _rope(kk, cfg.rope_theta)

    if cfg.attn_impl not in ("dense", "ring", "ulysses", "flash"):
        raise ValueError(
            f"attn_impl must be dense/ring/ulysses/flash, "
            f"got {cfg.attn_impl!r}")
    use_sp = (cfg.attn_impl in ("ring", "ulysses") and mesh is not None
              and mesh.shape.get("sp", 1) > 1)
    use_flash = (cfg.attn_impl == "flash"
                 and (mesh is None
                      or max(mesh.shape.get("tp", 1),
                             mesh.shape.get("sp", 1)) == 1))
    if use_flash:
        from horovod_tpu.ops.pallas_attention import flash_attention

        if mesh is not None and mesh.shape.get("dp", 1) > 1:
            # A pallas_call has no GSPMD partitioning rule, so under a
            # dp-sharded batch the kernel must run per-shard: wrap it in
            # a manual-dp shard_map (tp/sp are 1 here by the guard).
            from jax.sharding import PartitionSpec as _P

            from horovod_tpu.parallel.shard import shard_map as _shmap

            ctx = _shmap(
                lambda a, b, c: flash_attention(a, b, c, causal=True),
                mesh, axis_names=frozenset({"dp"}),
                in_specs=(_P("dp"), _P("dp"), _P("dp")),
                out_specs=_P("dp"), check_vma=False)(q, kk, v)
        else:
            ctx = flash_attention(q, kk, v, causal=True)
    elif use_sp:
        # Sequence-parallel attention: K/V never gather; blocks rotate the
        # sp ring (ring) or heads exchange via all-to-all (ulysses).
        from horovod_tpu.parallel import ring_attention as ra

        ctx = ra.make_sharded_attention(
            mesh, impl=cfg.attn_impl, axis="sp", causal=True,
            head_axis="tp")(q, kk, v)
    else:
        scale = 1.0 / math.sqrt(cfg.head_dim)
        logits = jnp.einsum("bshk,bthk->bhst", q, kk).astype(jnp.float32)
        logits *= scale
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        logits = jnp.where(mask[None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
        ctx = jnp.einsum("bhst,bthk->bshk", probs, v)
    return jnp.einsum("bshk,hkd->bsd", ctx, lp["wo"].astype(dtype))


def _dense_ffn(x, lp, dtype):
    h = jnp.einsum("bsd,df->bsf", x, lp["w_in"].astype(dtype))
    g = jnp.einsum("bsd,df->bsf", x, lp["w_gate"].astype(dtype))
    h = h * jax.nn.silu(g)
    return jnp.einsum("bsf,fd->bsd", h, lp["w_out"].astype(dtype))


def _moe_ffn(x, lp, cfg: TransformerConfig):
    """Switch-style top-1 MoE with static capacity.

    Dispatch/combine are dense einsums against one-hot masks — fully static
    shapes, so XLA shards the expert dimension over ``ep`` and turns the
    einsums into all-to-alls.  Re-derivation of the standard Switch layer
    (public Mesh-TF/Flaxformer pattern), not a port.
    """
    B, S, D = x.shape
    E = cfg.n_experts
    dtype = cfg.compute_dtype
    C = max(1, int(cfg.capacity_factor * S * B / E))

    xf = x.reshape(B * S, D)
    router_logits = (xf.astype(jnp.float32)
                     @ lp["router"].astype(jnp.float32))      # [T, E]
    gates = jax.nn.softmax(router_logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)                    # [T]
    gate = jnp.max(gates, axis=-1)                             # [T]
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, E]
    # Position of each token within its expert's capacity buffer.
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0            # [T, E]
    keep = (pos < C) & (onehot > 0)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                            dtype=jnp.float32) * keep[..., None]
    dispatch = pos_oh                                           # [T, E, C]
    combine = dispatch * gate[:, None, None]                    # [T, E, C]

    xe = jnp.einsum("tec,td->ecd", dispatch.astype(dtype), xf)  # [E, C, D]
    h = jnp.einsum("ecd,edf->ecf", xe, lp["w_in"].astype(dtype))
    g = jnp.einsum("ecd,edf->ecf", xe, lp["w_gate"].astype(dtype))
    h = h * jax.nn.silu(g)
    ye = jnp.einsum("ecf,efd->ecd", h, lp["w_out"].astype(dtype))
    y = jnp.einsum("tec,ecd->td", combine.astype(dtype), ye)
    # Auxiliary load-balancing loss (Switch eq. 4).
    density = jnp.mean(onehot, axis=0)
    density_proxy = jnp.mean(gates, axis=0)
    aux = E * jnp.sum(density * density_proxy)
    return y.reshape(B, S, D), aux


def _layer(x, lp, cfg: TransformerConfig, mesh):
    y = _attention(_rmsnorm(x, lp["ln1"]), lp, cfg, mesh)
    x = _constrain(x + y, ACT_SPEC, mesh)
    h = _rmsnorm(x, lp["ln2"])
    if cfg.n_experts:
        y, aux = _moe_ffn(h, lp, cfg)
    else:
        y, aux = _dense_ffn(h, lp, cfg.compute_dtype), 0.0
    x = _constrain(x + y, ACT_SPEC, mesh)
    return x, aux


def apply(params: Params, tokens, cfg: TransformerConfig,
          *, mesh=None, remat: Optional[bool] = None):
    """Forward pass.  ``tokens``: [B, S] int32.  Returns
    ``(logits_fp32, aux_loss)``.  ``remat`` defaults to ``cfg.remat``."""
    if remat is None:
        remat = cfg.remat
    dtype = cfg.compute_dtype
    x = params["embed"].astype(dtype)[tokens]
    x = _constrain(x, ACT_SPEC, mesh)

    layer_fn = _layer
    if remat:
        layer_fn = jax.checkpoint(_layer, static_argnums=(2, 3))

    def body(carry, lp):
        h, aux_sum = carry
        h, aux = layer_fn(h, lp, cfg, mesh)
        return (h, aux_sum + aux), None

    (x, aux), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)),
                           params["layers"])
    x = _rmsnorm(x, params["ln_f"])
    return vocab_projection(x, params["embed"]), aux


def vocab_projection(x, embed):
    """Final [B,S,D] → [B,S,V] projection: compute-dtype inputs on the
    MXU, f32 accumulation (an f32xf32 dot here ran at the MXU's
    multi-pass fp32 rate and was the single hottest op of the step).
    Shared with the pipelined path (parallel/pipeline.py)."""
    return jnp.einsum("bsd,vd->bsv", x, embed.astype(x.dtype),
                      preferred_element_type=jnp.float32)


def softmax_xent(logits, targets):
    """Mean softmax cross-entropy in logsumexp form: one pass over the
    [B, S, V] logits instead of materializing a full log_softmax tensor
    of the same size (identical math:
    -logp[target] = lse(logits) - logits[target])."""
    lse = jax.nn.logsumexp(logits, axis=-1)
    target_logit = jnp.take_along_axis(logits, targets[..., None],
                                       axis=-1)[..., 0]
    return jnp.mean(lse - target_logit)


def loss_fn(params, tokens, targets, cfg: TransformerConfig,
            *, mesh=None, aux_weight: float = 0.01):
    logits, aux = apply(params, tokens, cfg, mesh=mesh)
    return softmax_xent(logits, targets) + aux_weight * aux


# ---------------------------------------------------------------------------
# Autoregressive generation (KV cache)
# ---------------------------------------------------------------------------
#
# The reference is a training framework with no inference path; a complete
# model family needs one.  Decode is the classic two-phase shape: one
# prefill pass caches every layer's rotated K/V for the prompt, then a
# lax.scan emits one token per step, attending a single query against the
# cache — O(S) per token instead of O(S^2) recompute.  Dense single-host
# math (generation batches are small; the parallel axes exist for
# training).


def _attention_cached(x, lp, cfg, k_cache, v_cache, pos):
    """One token's attention against the cache.

    x: [B, 1, D]; k/v_cache: [B, Smax, H, HD] (valid through ``pos``);
    ``pos``: scalar index of THIS token.  Returns (out [B, 1, D],
    updated caches)."""
    dtype = cfg.compute_dtype
    q = jnp.einsum("bsd,dhk->bshk", x, lp["wq"].astype(dtype))
    k = jnp.einsum("bsd,dhk->bshk", x, lp["wk"].astype(dtype))
    v = jnp.einsum("bsd,dhk->bshk", x, lp["wv"].astype(dtype))
    p = jnp.full((1,), pos)
    q = _rope(q, cfg.rope_theta, pos=p)
    k = _rope(k, cfg.rope_theta, pos=p)
    k_cache = lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))
    scale = 1.0 / math.sqrt(cfg.head_dim)
    logits = jnp.einsum("bshk,bthk->bhst", q, k_cache
                        ).astype(jnp.float32) * scale
    Smax = k_cache.shape[1]
    valid = jnp.arange(Smax) <= pos
    logits = jnp.where(valid[None, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
    ctx = jnp.einsum("bhst,bthk->bshk", probs, v_cache)
    return (jnp.einsum("bshk,hkd->bsd", ctx, lp["wo"].astype(dtype)),
            k_cache, v_cache)


def _prefill(params, tokens, cfg, Smax):
    """Forward over the prompt, returning next-token logits for the last
    position and per-layer K/V caches [L, B, Smax, H, HD]."""
    dtype = cfg.compute_dtype
    B, S = tokens.shape
    x = params["embed"].astype(dtype)[tokens]

    scale = 1.0 / math.sqrt(cfg.head_dim)
    tri = jnp.tril(jnp.ones((S, S), jnp.bool_))

    def body(h, lp):
        # Per-layer math of _layer with the projections computed ONCE,
        # attention inlined densely, and the rotated K/V captured for
        # the cache (so decode and training can't desynchronize on the
        # projection/RoPE recipe).
        y = _rmsnorm(h, lp["ln1"])
        q = jnp.einsum("bsd,dhk->bshk", y, lp["wq"].astype(dtype))
        k = jnp.einsum("bsd,dhk->bshk", y, lp["wk"].astype(dtype))
        v = jnp.einsum("bsd,dhk->bshk", y, lp["wv"].astype(dtype))
        q = _rope(q, cfg.rope_theta)
        k = _rope(k, cfg.rope_theta)
        logits = jnp.einsum("bshk,bthk->bhst", q, k
                            ).astype(jnp.float32) * scale
        logits = jnp.where(tri[None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
        ctx = jnp.einsum("bhst,bthk->bshk", probs, v)
        h = h + jnp.einsum("bshk,hkd->bsd", ctx, lp["wo"].astype(dtype))
        h = h + _dense_ffn(_rmsnorm(h, lp["ln2"]), lp, dtype)
        pad = [(0, 0), (0, Smax - S), (0, 0), (0, 0)]
        return h, (jnp.pad(k, pad), jnp.pad(v, pad))

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    x = _rmsnorm(x, params["ln_f"])
    logits = vocab_projection(x[:, -1:], params["embed"])[:, 0]
    return logits, ks, vs


def generate(params, prompt, cfg: TransformerConfig, *,
             max_new_tokens: int, temperature: float = 0.0,
             rng=None, cache_len: Optional[int] = None):
    """Autoregressive decode.  ``prompt``: [B, S0] int32.  Returns
    [B, S0 + max_new_tokens] (prompt + generated).  ``temperature=0``
    is greedy argmax; otherwise softmax sampling with ``rng``.

    ``cache_len`` pins the KV-cache length (default: exactly
    ``S0 + max_new_tokens``).  The extra positions are masked out, but
    the cache length still shapes XLA's reduction tree — callers that
    compare against a fixed-length serving cache (serving/decode.py)
    pass the serving length here to keep the comparison bit-exact.

    Dense-FFN configs only (``n_experts=0``) — MoE routing under a
    one-token capacity is a different decode design.
    """
    if cfg.n_experts:
        raise NotImplementedError(
            "generate() supports dense-FFN configs; MoE decode needs "
            "per-step routing with capacity 1")
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs rng")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, "
                         f"got {max_new_tokens}")
    B, S0 = prompt.shape
    Smax = S0 + max_new_tokens
    if Smax > cfg.max_seq_len:
        raise ValueError(
            f"prompt + new tokens ({Smax}) exceeds max_seq_len "
            f"({cfg.max_seq_len})")
    if cache_len is not None:
        if cache_len < Smax:
            raise ValueError(
                f"cache_len ({cache_len}) is shorter than prompt + new "
                f"tokens ({Smax})")
        Smax = cache_len
    dtype = cfg.compute_dtype
    logits0, ks, vs = _prefill(params, prompt, cfg, Smax)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def sample(logits, key):
        if temperature > 0.0:
            return jax.random.categorical(key, logits / temperature,
                                          axis=-1)
        return jnp.argmax(logits, axis=-1)

    def step(carry, key):
        tok, pos, ks, vs = carry
        x = params["embed"].astype(dtype)[tok[:, None]]

        def layer(h, layer_in):
            lp, k_c, v_c = layer_in
            y = _rmsnorm(h, lp["ln1"])
            attn, k_c, v_c = _attention_cached(y, lp, cfg, k_c, v_c, pos)
            h = h + attn
            h = h + _dense_ffn(_rmsnorm(h, lp["ln2"]), lp, dtype)
            return h, (k_c, v_c)

        x, (ks, vs) = lax.scan(layer, x, (params["layers"], ks, vs))
        x = _rmsnorm(x, params["ln_f"])
        logits = vocab_projection(x, params["embed"])[:, 0]
        nxt = sample(logits, key).astype(prompt.dtype)
        return (nxt, pos + 1, ks, vs), nxt

    keys = jax.random.split(rng, max_new_tokens)
    first = sample(logits0, keys[0]).astype(prompt.dtype)
    if max_new_tokens == 1:
        return jnp.concatenate([prompt, first[:, None]], axis=1)
    (_, _, _, _), rest = lax.scan(
        step, (first, jnp.asarray(S0), ks, vs), keys[1:])
    out = jnp.concatenate(
        [prompt, first[:, None], rest.swapaxes(0, 1)], axis=1)
    return out


# ---------------------------------------------------------------------------
# Continuous-batching decode (horovod_tpu.serving)
# ---------------------------------------------------------------------------
#
# generate() decodes one request at a time: every row of the batch shares a
# single scalar position.  A serving batch is ragged — each slot joined at
# a different step and sits at its own offset in the KV cache — so these
# entry points carry a per-slot position VECTOR.  The per-row math is that
# of _attention_cached exactly (same einsums, same mask construction, same
# f32 softmax), which is what keeps a continuously batched decode
# bit-identical to the single-request generate() oracle: rows never mix,
# so a slot's output depends only on its own cache lane.


KV_CACHE_SPEC = P(None, None, None, "tp", None)  # [L, B, Smax, H, HD]


def _attention_cached_slots(x, lp, cfg, k_cache, v_cache, pos):
    """One token per slot against the cache, at per-slot positions.

    x: [B, 1, D]; k/v_cache: [B, Smax, H, HD]; ``pos``: [B] int32, the
    absolute position of THIS token in each slot.  Returns
    (out [B, 1, D], updated caches)."""
    dtype = cfg.compute_dtype
    B = x.shape[0]
    q = jnp.einsum("bsd,dhk->bshk", x, lp["wq"].astype(dtype))
    k = jnp.einsum("bsd,dhk->bshk", x, lp["wk"].astype(dtype))
    v = jnp.einsum("bsd,dhk->bshk", x, lp["wv"].astype(dtype))
    # _rope with a per-row angle: ang[b] = pos[b] * freqs — the scalar-pos
    # rotation of _attention_cached applied row-wise.
    half = cfg.head_dim // 2
    freqs = jnp.exp(
        -math.log(cfg.rope_theta)
        * jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[:, None] * freqs[None, :]   # [B, half]
    cos = jnp.cos(ang)[:, None, None, :]
    sin = jnp.sin(ang)[:, None, None, :]

    def rot(t):
        t1, t2 = t[..., :half], t[..., half:]
        tf1, tf2 = t1.astype(jnp.float32), t2.astype(jnp.float32)
        return jnp.concatenate(
            [tf1 * cos - tf2 * sin, tf2 * cos + tf1 * sin], axis=-1
        ).astype(t.dtype)

    q = rot(q)
    k = rot(k)
    rows = jnp.arange(B)
    k_cache = k_cache.at[rows, pos].set(k[:, 0])
    v_cache = v_cache.at[rows, pos].set(v[:, 0])
    scale = 1.0 / math.sqrt(cfg.head_dim)
    logits = jnp.einsum("bshk,bthk->bhst", q, k_cache
                        ).astype(jnp.float32) * scale
    Smax = k_cache.shape[1]
    valid = jnp.arange(Smax)[None, :] <= pos[:, None]         # [B, Smax]
    logits = jnp.where(valid[:, None, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
    ctx = jnp.einsum("bhst,bthk->bshk", probs, v_cache)
    return (jnp.einsum("bshk,hkd->bsd", ctx, lp["wo"].astype(dtype)),
            k_cache, v_cache)


def decode_step(params, tok, pos, ks, vs, cfg: TransformerConfig):
    """One continuous-batching step: embed ``tok`` [B], attend each slot
    at its own ``pos`` [B], return (next-token logits [B, V] f32, updated
    caches [L, B, Smax, H, HD]).  The layer body is generate()'s step
    with _attention_cached swapped for the per-slot-position variant.
    Dense-FFN configs only (same contract as generate())."""
    dtype = cfg.compute_dtype
    x = params["embed"].astype(dtype)[tok[:, None]]

    def layer(h, layer_in):
        lp, k_c, v_c = layer_in
        y = _rmsnorm(h, lp["ln1"])
        attn, k_c, v_c = _attention_cached_slots(y, lp, cfg, k_c, v_c, pos)
        h = h + attn
        h = h + _dense_ffn(_rmsnorm(h, lp["ln2"]), lp, dtype)
        return h, (k_c, v_c)

    x, (ks, vs) = lax.scan(layer, x, (params["layers"], ks, vs))
    x = _rmsnorm(x, params["ln_f"])
    logits = vocab_projection(x, params["embed"])[:, 0]
    return logits, ks, vs


def prefill_request(params, prompt, cfg: TransformerConfig, cache_len: int):
    """Prefill ONE request.  ``prompt``: [S0] int32.  Returns
    (next-token logits [V] f32, per-layer K/V [L, 1, cache_len, H, HD])
    ready to be written into a serving batch's slot lane."""
    logits, ks, vs = _prefill(params, prompt[None], cfg, cache_len)
    return logits[0], ks, vs
