"""Model zoo: benchmark-parity models (ResNet family, MNIST convnet — the
reference's example/benchmark configs, SURVEY.md §6) plus the flagship
multi-axis-parallel Transformer LM for the long-context path.  All pure
functional JAX: ``init`` returns param pytrees, ``apply``/``loss_fn`` are
jit-compatible pure functions."""

from horovod_tpu.models import mnist, resnet, transformer  # noqa: F401
from horovod_tpu.models.resnet import (  # noqa: F401
    ResNetConfig,
    resnet18_config,
    resnet50_config,
    resnet101_config,
    resnet152_config,
)
from horovod_tpu.models.transformer import TransformerConfig  # noqa: F401
