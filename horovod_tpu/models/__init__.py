"""Model zoo for benchmarks and parity configs.

Mirrors the reference's benchmark surface (SURVEY.md §6: ResNet-50
synthetic benchmark, MNIST examples) plus a transformer for the
long-context / sequence-parallel path.
"""
