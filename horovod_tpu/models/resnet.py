"""ResNet v1.5 family in pure functional JAX — the benchmark-parity model.

The reference's headline numbers are ResNet-50 synthetic-benchmark
images/sec (``examples/tensorflow2_synthetic_benchmark.py:30-45``, batch 32,
``applications.ResNet50``) and ResNet-101 scaling efficiency
(``docs/benchmarks.rst:8-13``).  This module provides the same model family,
built TPU-first:

* NHWC layout with channel counts that are multiples of 128 in the deep
  stages — convs lower to MXU matmuls with full tiles.
* bf16 compute / fp32 params + fp32 batch-norm statistics: the standard
  TPU mixed-precision recipe (params stay fp32 so allreduce numerics can
  hit the 1e-6 gate against the CPU oracle in fp32).
* No Python objects in the forward path: params are a pytree of arrays,
  ``apply`` is a pure function — jit/pjit/grad compose freely.
* Batch norm is folded into functional form with state threaded explicitly
  (training mode returns updated running stats), so the whole train step is
  one compiled XLA program.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


@dataclass(frozen=True)
class ResNetConfig:
    """Stage layout per the classic v1 family.  ``basic=True`` selects the
    two-conv basic block (ResNet-18/34); False the 1-3-1 bottleneck."""

    blocks: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    num_classes: int = 1000
    basic: bool = False
    compute_dtype: Any = jnp.bfloat16
    # Run the stem as a 4x4 conv over a 2x2 space-to-depth transform of
    # the input (12 channels instead of 3) — mathematically equivalent to
    # the 7x7 stride-2 conv (weights are rearranged at apply time; the
    # parameter stays the canonical [7,7,3,w] tensor so checkpoints are
    # layout-independent), but it feeds the MXU 4x the input channels.
    # A 3-in-channel conv wastes most of each 128-lane contraction tile;
    # this is the standard TPU ResNet stem rewrite.
    stem_s2d: bool = True
    # Rematerialize each residual block in the backward pass
    # (jax.checkpoint): stores only block inputs instead of every
    # intermediate activation — the standard HBM-for-FLOPs trade that
    # unlocks large batches (e.g. 256x224x224) on one chip.
    remat: bool = False

    @property
    def bottleneck(self) -> bool:
        return not self.basic


def resnet50_config(num_classes: int = 1000, **kw) -> ResNetConfig:
    return ResNetConfig(blocks=(3, 4, 6, 3), num_classes=num_classes, **kw)


def resnet101_config(num_classes: int = 1000, **kw) -> ResNetConfig:
    return ResNetConfig(blocks=(3, 4, 23, 3), num_classes=num_classes, **kw)


def resnet152_config(num_classes: int = 1000, **kw) -> ResNetConfig:
    return ResNetConfig(blocks=(3, 8, 36, 3), num_classes=num_classes, **kw)


def resnet18_config(num_classes: int = 1000, **kw) -> ResNetConfig:
    return ResNetConfig(blocks=(2, 2, 2, 2), num_classes=num_classes,
                        basic=True, **kw)


def _is_basic(cfg: ResNetConfig) -> bool:
    return cfg.basic


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def _conv_init(key, kh, kw, cin, cout):
    # He-normal fan-out, the torchvision/Keras ResNet default.
    fan_out = kh * kw * cout
    std = math.sqrt(2.0 / fan_out)
    return jax.random.normal(key, (kh, kw, cin, cout), jnp.float32) * std


def _bn_init(c):
    return {
        "scale": jnp.ones((c,), jnp.float32),
        "bias": jnp.zeros((c,), jnp.float32),
    }


def _bn_state(c):
    return {
        "mean": jnp.zeros((c,), jnp.float32),
        "var": jnp.ones((c,), jnp.float32),
    }


def init(rng, config: ResNetConfig) -> Tuple[Params, Params]:
    """Returns ``(params, batch_stats)`` pytrees."""
    keys = iter(jax.random.split(rng, 512))
    params: Params = {}
    stats: Params = {}

    params["stem_conv"] = _conv_init(next(keys), 7, 7, 3, config.width)
    params["stem_bn"] = _bn_init(config.width)
    stats["stem_bn"] = _bn_state(config.width)

    cin = config.width
    expansion = 1 if _is_basic(config) else 4
    for si, nblocks in enumerate(config.blocks):
        cmid = config.width * (2 ** si)
        cout = cmid * expansion
        for bi in range(nblocks):
            name = f"stage{si}_block{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            blk: Params = {}
            bst: Params = {}
            if _is_basic(config):
                blk["conv1"] = _conv_init(next(keys), 3, 3, cin, cmid)
                blk["bn1"] = _bn_init(cmid)
                bst["bn1"] = _bn_state(cmid)
                blk["conv2"] = _conv_init(next(keys), 3, 3, cmid, cout)
                blk["bn2"] = _bn_init(cout)
                bst["bn2"] = _bn_state(cout)
            else:
                blk["conv1"] = _conv_init(next(keys), 1, 1, cin, cmid)
                blk["bn1"] = _bn_init(cmid)
                bst["bn1"] = _bn_state(cmid)
                blk["conv2"] = _conv_init(next(keys), 3, 3, cmid, cmid)
                blk["bn2"] = _bn_init(cmid)
                bst["bn2"] = _bn_state(cmid)
                blk["conv3"] = _conv_init(next(keys), 1, 1, cmid, cout)
                blk["bn3"] = _bn_init(cout)
                bst["bn3"] = _bn_state(cout)
            if bi == 0 and (cin != cout or stride != 1):
                blk["proj_conv"] = _conv_init(next(keys), 1, 1, cin, cout)
                blk["proj_bn"] = _bn_init(cout)
                bst["proj_bn"] = _bn_state(cout)
            params[name] = blk
            stats[name] = bst
            cin = cout

    head_std = 1.0 / math.sqrt(cin)
    params["head_w"] = jax.random.uniform(
        next(keys), (cin, config.num_classes), jnp.float32,
        -head_std, head_std)
    params["head_b"] = jnp.zeros((config.num_classes,), jnp.float32)
    return params, stats


# ---------------------------------------------------------------------------
# apply
# ---------------------------------------------------------------------------

_BN_MOMENTUM = 0.9
_BN_EPS = 1e-5


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    kh = w.shape[0]
    pad = (kh - 1) // 2
    return lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype),
        window_strides=(stride, stride),
        padding=[(pad, pad), (pad, pad)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _stem_s2d_conv(images, w, dtype):
    """The 7x7 stride-2 stem as an equivalent 4x4 stride-1 conv on a 2x2
    space-to-depth input.

    Derivation: with the input padded by 4 (not the usual 3) on every
    spatial edge and the kernel zero-padded to 8x8 at the top-left, the
    stride-2 conv output is ``out[p] = sum_u xpad[2p+u] * w8[u]``
    (u = 0..7, w8[0] = 0, w8[u] = w[u-1]).  Splitting u = 2k + d maps
    every tap onto the space-to-depth grid ``x2[p+k, d-block]`` — a 4x4
    stride-1 VALID conv over 4x the channels.  The output is sliced to
    ceil(H/2) (the VALID conv yields one extra row/col from the pad-4).
    """
    n, h, wd, c = images.shape
    x = jnp.pad(images, ((0, 0), (4, 4), (4, 4), (0, 0)))
    hp, wp = h + 8, wd + 8
    # s2d: x2[n, i, j, (dy*2+dx)*c + ch] = x[n, 2i+dy, 2j+dx, ch]
    x = x.reshape(n, hp // 2, 2, wp // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, hp // 2, wp // 2, 4 * c)
    # kernel: w8[2k+d, 2l+e, ch, o] -> ws[k, l, (d*2+e)*c + ch, o]
    w8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
    cout = w.shape[-1]
    ws = w8.reshape(4, 2, 4, 2, c, cout)
    ws = ws.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c, cout)
    y = lax.conv_general_dilated(
        x.astype(dtype), ws.astype(dtype),
        window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return y[:, : (h + 1) // 2, : (wd + 1) // 2, :]


def _bn(x, p, s, train: bool):
    """Functional batch-norm; statistics in fp32, normalization applied in
    the activation dtype.  Returns (y, new_state).

    The mean/var reductions stay fp32 (bf16 accumulation of squared sums
    is unusable), but the per-element normalization is a single fused
    multiply-add ``x * inv + shift`` with the fp32 scalars folded and cast
    once — in bf16 this halves the HBM bytes of every BN in the network
    versus upcasting the whole activation tensor to fp32."""
    if train:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        new_s = {
            "mean": _BN_MOMENTUM * s["mean"] + (1 - _BN_MOMENTUM) * mean,
            "var": _BN_MOMENTUM * s["var"] + (1 - _BN_MOMENTUM) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = lax.rsqrt(var + _BN_EPS) * p["scale"]
    shift = p["bias"] - mean * inv
    y = x * inv.astype(x.dtype) + shift.astype(x.dtype)
    return y, new_s


def _block(x, blk, bst, stride, basic, train, dtype):
    out_stats = {}
    shortcut = x
    if "proj_conv" in blk:
        shortcut = _conv(x, blk["proj_conv"], stride, dtype)
        shortcut, out_stats["proj_bn"] = _bn(
            shortcut, blk["proj_bn"], bst["proj_bn"], train)
    if basic:
        y = _conv(x, blk["conv1"], stride, dtype)
        y, out_stats["bn1"] = _bn(y, blk["bn1"], bst["bn1"], train)
        y = jax.nn.relu(y)
        y = _conv(y, blk["conv2"], 1, dtype)
        y, out_stats["bn2"] = _bn(y, blk["bn2"], bst["bn2"], train)
    else:
        y = _conv(x, blk["conv1"], 1, dtype)
        y, out_stats["bn1"] = _bn(y, blk["bn1"], bst["bn1"], train)
        y = jax.nn.relu(y)
        # v1.5: stride lives on the 3x3, not the 1x1.
        y = _conv(y, blk["conv2"], stride, dtype)
        y, out_stats["bn2"] = _bn(y, blk["bn2"], bst["bn2"], train)
        y = jax.nn.relu(y)
        y = _conv(y, blk["conv3"], 1, dtype)
        y, out_stats["bn3"] = _bn(y, blk["bn3"], bst["bn3"], train)
    return jax.nn.relu(y + shortcut), out_stats


def apply(params: Params, batch_stats: Params, images,
          config: ResNetConfig, train: bool = False):
    """Forward pass.  ``images``: [N, H, W, 3] float.  Returns
    ``(logits_fp32, new_batch_stats)``."""
    dtype = config.compute_dtype
    basic = _is_basic(config)
    new_stats: Params = {}

    if (config.stem_s2d and images.shape[1] % 2 == 0
            and images.shape[2] % 2 == 0):
        x = _stem_s2d_conv(images, params["stem_conv"], dtype)
    else:
        x = _conv(images, params["stem_conv"], 2, dtype)
    x, new_stats["stem_bn"] = _bn(
        x, params["stem_bn"], batch_stats["stem_bn"], train)
    x = jax.nn.relu(x)
    x = lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
        [(0, 0), (1, 1), (1, 1), (0, 0)])

    block_fn = _block
    if config.remat:
        # Static args (stride/basic/train/dtype) stay python-level;
        # only the array args are checkpointed.
        block_fn = jax.checkpoint(_block, static_argnums=(3, 4, 5, 6))

    cin = config.width
    expansion = 1 if basic else 4
    for si, nblocks in enumerate(config.blocks):
        for bi in range(nblocks):
            name = f"stage{si}_block{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            x, new_stats[name] = block_fn(
                x, params[name], batch_stats[name], stride, basic,
                train, dtype)

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["head_w"] + params["head_b"]
    return logits, new_stats


def loss_fn(params, batch_stats, images, labels, config: ResNetConfig):
    """Softmax cross-entropy; the synthetic-benchmark objective."""
    logits, new_stats = apply(params, batch_stats, images, config,
                              train=True)
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
    return loss, new_stats
