"""Small MNIST convnet — the parity twin of the reference's MNIST examples
(``examples/tensorflow2_mnist.py``, ``examples/pytorch_mnist.py``: two convs
+ two dense layers).  Functional JAX, bf16 compute / fp32 params."""

from __future__ import annotations

import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Params = Dict[str, Any]


def init(rng) -> Params:
    k1, k2, k3, k4 = jax.random.split(rng, 4)

    def conv(key, kh, kw, cin, cout):
        std = math.sqrt(2.0 / (kh * kw * cout))
        return jax.random.normal(key, (kh, kw, cin, cout),
                                 jnp.float32) * std

    def dense(key, fin, fout):
        std = math.sqrt(2.0 / fin)
        return jax.random.normal(key, (fin, fout), jnp.float32) * std

    return {
        "conv1": conv(k1, 3, 3, 1, 32),
        "b1": jnp.zeros((32,), jnp.float32),
        "conv2": conv(k2, 3, 3, 32, 64),
        "b2": jnp.zeros((64,), jnp.float32),
        "fc1": dense(k3, 7 * 7 * 64, 128),
        "fb1": jnp.zeros((128,), jnp.float32),
        "fc2": dense(k4, 128, 10),
        "fb2": jnp.zeros((10,), jnp.float32),
    }


def apply(params: Params, images, dtype=jnp.bfloat16):
    """``images``: [N, 28, 28, 1] float in [0, 1].  Returns fp32 logits."""
    x = images.astype(dtype)

    def conv(x, w, stride=1):
        return lax.conv_general_dilated(
            x, w.astype(dtype), (stride, stride),
            [(1, 1), (1, 1)], dimension_numbers=("NHWC", "HWIO", "NHWC"))

    x = jax.nn.relu(conv(x, params["conv1"]) + params["b1"].astype(dtype))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1),
                          (1, 2, 2, 1), "VALID")
    x = jax.nn.relu(conv(x, params["conv2"]) + params["b2"].astype(dtype))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1),
                          (1, 2, 2, 1), "VALID")
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"].astype(dtype)
                    + params["fb1"].astype(dtype))
    logits = (x.astype(jnp.float32) @ params["fc2"] + params["fb2"])
    return logits


def loss_fn(params: Params, images, labels) -> jnp.ndarray:
    logits = apply(params, images)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
