"""Process sets: collectives over subgroups of ranks.

Beyond the v0.19 reference (the project added process sets later):
``ProcessSet([0, 2])`` scopes an eager collective to a subset of ranks —

    ps = hvd.ProcessSet([0, 2])
    if ps.included():
        out = hvd.allreduce(x, process_set=ps)

Design (TPU-first redesign, not a port):

* A set's identity is a **stable hash of its sorted member ranks** — no
  registration round-trip; every rank that constructs the same member
  list gets the same id.  Requests carry ``(id, size)``, so the
  coordinator can wait for exactly the members without global state.
* ``ProcessSet`` must be constructed identically on **every** rank
  (members and non-members), like the reference requires: the response
  stream reaches all ranks, and non-members need the registry to know
  to skip a set's responses.
* The data plane reuses the full TCP mesh — subgroup rings walk the
  member list in sorted order over the existing peer sockets, with the
  same chunk math as the global ring (mixed native/py bit-compatible).
* Every data op takes ``process_set=`` (allreduce/grouped/allgather/
  broadcast/reducescatter/alltoall) and ``barrier(process_set=...)``
  synchronizes just the members; only ``join`` stays global-set-only.
  The in-graph regime expresses subgroups as mesh axes instead
  (docs/parallelism.md).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

_lock = threading.Lock()
_registry: Dict[int, List[int]] = {}

GLOBAL_ID = 0


def _set_id(ranks: Sequence[int]) -> int:
    """FNV-1a over the member ranks, folded to a positive int32 != 0."""
    h = 2166136261
    for r in ranks:
        for b in int(r).to_bytes(4, "little", signed=False):
            h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    h &= 0x7FFFFFFF
    return h or 1


class ProcessSet:
    """A fixed subgroup of global ranks (sorted, duplicates removed).

    Construct on every rank with the same member list.  Pass via the
    ``process_set=`` argument of eager collectives."""

    def __init__(self, ranks: Sequence[int]):
        members = sorted({int(r) for r in ranks})
        if not members:
            raise ValueError("a process set needs at least one rank")
        if members[0] < 0:
            raise ValueError(f"negative rank in process set: {members}")
        self.ranks: List[int] = members
        self.process_set_id = _set_id(members)
        with _lock:
            prev = _registry.get(self.process_set_id)
            if prev is not None and prev != members:
                raise ValueError(
                    f"process-set id collision: ranks {members} hash to "
                    f"id {self.process_set_id}, already registered for "
                    f"ranks {prev}.  Set ids are a 31-bit hash of the "
                    "member list, so distinct sets can (rarely) collide; "
                    "requests would be routed to the wrong subgroup.  "
                    "Re-partition one of the two subgroups (any change "
                    "to its member list picks a new id), or call "
                    "process_sets.reset() if the colliding set belongs "
                    "to a previous world that no longer exists.")
            _registry[self.process_set_id] = members
        # The native engine keeps its own registry (the C++ coordinator
        # and the skip path consult it); tell it about this set if it is
        # already running — NativeEngine syncs the snapshot otherwise.
        from horovod_tpu import basics

        eng = basics._runtime
        if eng is not None and hasattr(eng, "register_process_set"):
            eng.register_process_set(self.process_set_id, members)

    def size(self) -> int:
        return len(self.ranks)

    def rank(self) -> int:
        """This process's rank within the set, or -1 if not a member."""
        from horovod_tpu import basics

        try:
            return self.ranks.index(basics.rank())
        except ValueError:
            return -1

    def included(self) -> bool:
        return self.rank() >= 0

    def validate(self, rank: int, world_size: int):
        """Shared enqueue-side validation for every engine; returns the
        (id, size) request fields."""
        if rank not in self.ranks:
            raise ValueError(f"rank {rank} is not a member of {self}")
        if self.ranks[-1] >= world_size:
            raise ValueError(
                f"{self} has ranks outside the world [0, {world_size})")
        return self.process_set_id, len(self.ranks)

    def __repr__(self) -> str:
        return f"ProcessSet(ranks={self.ranks}, id={self.process_set_id})"


def ranks_of(set_id: int) -> Optional[List[int]]:
    """Member ranks of a registered set (None if unknown here)."""
    if set_id == GLOBAL_ID:
        return None
    with _lock:
        return _registry.get(set_id)


def snapshot() -> Dict[int, List[int]]:
    """All registered sets (engine-creation sync)."""
    with _lock:
        return dict(_registry)


def reset() -> None:
    """Forget all registered sets.  Called by the elastic re-form path
    (ranks are renumbered, so old member lists are meaningless) and by
    tests."""
    with _lock:
        _registry.clear()
