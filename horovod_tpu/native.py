"""Loader for the native coordination core (``csrc/`` → libhvd_core.so).

Builds the shared library on demand with the system toolchain when the
sources are newer than the binary (mirroring the reference's extension
build, but without requiring an install step), then binds the C API via
ctypes.  Role parity: ``horovod/common/basics.py`` loading
``mpi_lib_v2``/ctypes symbols from operations.cc:650-788.

The build is guarded by an ``fcntl`` lock so concurrently launched worker
processes do not race the compiler.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
from pathlib import Path
from typing import Optional

_PKG_DIR = Path(__file__).resolve().parent
_LIB_PATH = _PKG_DIR / "_lib" / "libhvd_core.so"
_CSRC_DIR = _PKG_DIR.parent / "csrc"

_lib: Optional[ctypes.CDLL] = None

_SOURCES = ("wire.cc", "sockets.cc", "kernels.cc", "autotune.cc",
            "timeline.cc", "engine.cc", "c_api.cc")
# In the build only when jaxlib's FFI headers are present (the Makefile's
# conditional SRCS) — tracked for staleness only in that configuration,
# or _needs_build would stay True forever on FFI-off hosts.
_FFI_SOURCE = "ffi_bridge.cc"
_HEADERS = ("types.h", "wire.h", "sockets.h", "kernels.h", "autotune.h",
            "timeline.h", "engine.h")
_FFI_ON_STAMP = _CSRC_DIR / ".ffi_on.stamp"
_FFI_OFF_STAMP = _CSRC_DIR / ".ffi_off.stamp"


class NativeUnavailable(ImportError):
    pass


def _ffi_include_dir() -> str:
    """jaxlib's XLA FFI header dir, located WITHOUT importing jax.

    Numpy-only eager workers load this module on startup; importing jax
    here would cost them seconds.  ``find_spec`` reads package metadata
    only, and the header path is stable within a jaxlib install
    (``jax.ffi.include_dir()`` resolves to the same directory).
    """
    try:
        import importlib.util

        spec = importlib.util.find_spec("jaxlib")
        if spec is None or not spec.origin:
            return ""
        inc = Path(spec.origin).parent / "include"
        if (inc / "xla" / "ffi" / "api" / "ffi.h").is_file():
            return str(inc)
    except Exception:
        pass
    return ""


def _needs_build() -> bool:
    if not _CSRC_DIR.is_dir():
        return False  # installed artifact only; use the .so as shipped
    if not _LIB_PATH.exists():
        return True
    # The stamps record whether the XLA FFI handlers compiled into the
    # current .so (csrc/Makefile manages them).  If availability changed
    # — or the lib predates the stamp mechanism — relink.
    want_on = bool(_ffi_include_dir())
    if want_on != _FFI_ON_STAMP.exists() or (
            not want_on) != _FFI_OFF_STAMP.exists():
        return True
    sources = _SOURCES + ((_FFI_SOURCE,) if want_on else ())
    lib_mtime = _LIB_PATH.stat().st_mtime
    for f in sources + _HEADERS:
        p = _CSRC_DIR / f
        if p.exists() and p.stat().st_mtime > lib_mtime:
            return True
    return False


def build_if_needed() -> None:
    """Build libhvd_core.so via the one build recipe: ``csrc/Makefile``.

    The Makefile decides whether the XLA custom-call handlers
    (ffi_bridge.cc) compile in; this loader only supplies the header
    location so the probe needn't import jax (the Makefile's own
    fallback probe shells out to ``python3 -c "import jax.ffi ..."``).
    setup.py drives the same Makefile for wheels, so lazy source builds
    and packaged builds cannot drift.
    """
    if not _needs_build():
        return
    _LIB_PATH.parent.mkdir(parents=True, exist_ok=True)
    lock_path = _LIB_PATH.parent / ".build.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if not _needs_build():  # built while we waited on the lock
                return
            cmd = ["make", "-C", str(_CSRC_DIR),
                   f"JAX_INC={_ffi_include_dir()}"]
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True)
            except OSError as e:
                raise NativeUnavailable(
                    "native core build needs the `make` binary on PATH "
                    f"(csrc/Makefile is the one build recipe): {e}")
            if proc.returncode != 0:
                raise NativeUnavailable(
                    f"native core build failed:\n{proc.stdout}"
                    f"\n{proc.stderr}")
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.hvd_create.argtypes = [
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_int32), c.POINTER(c.c_int32),
        c.c_double, c.c_int64, c.c_double, c.c_double, c.c_int, c.c_int64,
        c.c_int, c.c_int,                          # hierarchical ar/ag
        c.c_int, c.c_int, c.c_int, c.c_int,        # autotune, tune f/c/c
        c.c_int, c.c_int,                          # tune hier ar/ag
        c.c_int, c.c_int, c.c_double,
        c.c_char_p, c.c_char_p, c.c_int,
    ]
    lib.hvd_create.restype = c.c_int
    lib.hvd_cache_stats.argtypes = [c.POINTER(c.c_int64)]
    lib.hvd_cache_stats.restype = None
    lib.hvd_shutdown.argtypes = []
    lib.hvd_shutdown.restype = None
    lib.hvd_is_aborted.restype = c.c_int
    lib.hvd_last_error.restype = c.c_char_p
    lib.hvd_allreduce_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.c_int, c.c_double, c.c_double, c.c_int, c.c_int,
    ]
    lib.hvd_allreduce_async.restype = c.c_int64
    lib.hvd_allgather_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.c_int, c.c_int,
    ]
    lib.hvd_allgather_async.restype = c.c_int64
    lib.hvd_broadcast_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.c_int, c.c_int, c.c_int,
    ]
    lib.hvd_broadcast_async.restype = c.c_int64
    lib.hvd_alltoall_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.POINTER(c.c_int64), c.c_int, c.c_int, c.c_int,
    ]
    lib.hvd_alltoall_async.restype = c.c_int64
    lib.hvd_reducescatter_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.c_int, c.c_int, c.c_int,
    ]
    lib.hvd_reducescatter_async.restype = c.c_int64
    lib.hvd_register_process_set.argtypes = [
        c.c_int, c.POINTER(c.c_int32), c.c_int,
    ]
    lib.hvd_register_process_set.restype = c.c_int64
    lib.hvd_poll.argtypes = [c.c_int64]
    lib.hvd_poll.restype = c.c_int
    lib.hvd_wait.argtypes = [c.c_int64]
    lib.hvd_wait.restype = c.c_int
    lib.hvd_handle_error.argtypes = [c.c_int64]
    lib.hvd_handle_error.restype = c.c_char_p
    lib.hvd_result_nbytes.argtypes = [c.c_int64]
    lib.hvd_result_nbytes.restype = c.c_int64
    lib.hvd_result_data.argtypes = [c.c_int64]
    lib.hvd_result_data.restype = c.c_void_p
    lib.hvd_result_splits.argtypes = [
        c.c_int64, c.POINTER(c.c_int64), c.c_int]
    lib.hvd_result_splits.restype = c.c_int
    lib.hvd_release.argtypes = [c.c_int64]
    lib.hvd_release.restype = None
    lib.hvd_barrier.argtypes = [c.c_int, c.c_int]
    lib.hvd_barrier.restype = c.c_int
    lib.hvd_join.restype = c.c_int
    return lib


def load() -> ctypes.CDLL:
    """Build (if needed) and load the native core; raises NativeUnavailable
    when no toolchain/binary is available so callers can fall back to the
    Python engine."""
    global _lib
    if _lib is not None:
        return _lib
    if os.environ.get("HVD_TPU_CORE", "").lower() in ("py", "python"):
        raise NativeUnavailable("HVD_TPU_CORE forces the Python engine")
    try:
        build_if_needed()
    except (OSError, subprocess.SubprocessError) as e:
        raise NativeUnavailable(f"cannot build native core: {e}")
    if not _LIB_PATH.exists():
        raise NativeUnavailable(f"native core not built: {_LIB_PATH}")
    _lib = _bind(ctypes.CDLL(str(_LIB_PATH)))
    return _lib
