"""Loader for the native coordination core (``csrc/`` → libhvd_core.so).

Builds the shared library on demand with the system toolchain when the
sources are newer than the binary (mirroring the reference's extension
build, but without requiring an install step), then binds the C API via
ctypes.  Role parity: ``horovod/common/basics.py`` loading
``mpi_lib_v2``/ctypes symbols from operations.cc:650-788.

The build is guarded by an ``fcntl`` lock so concurrently launched worker
processes do not race the compiler.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
from pathlib import Path
from typing import Optional

_PKG_DIR = Path(__file__).resolve().parent
_LIB_PATH = _PKG_DIR / "_lib" / "libhvd_core.so"
_CSRC_DIR = _PKG_DIR.parent / "csrc"

_lib: Optional[ctypes.CDLL] = None

_SOURCES = ("wire.cc", "sockets.cc", "kernels.cc", "autotune.cc",
            "timeline.cc", "engine.cc", "c_api.cc")
_HEADERS = ("types.h", "wire.h", "sockets.h", "kernels.h", "autotune.h",
            "timeline.h", "engine.h")


class NativeUnavailable(ImportError):
    pass


def _needs_build() -> bool:
    if not _CSRC_DIR.is_dir():
        return False  # installed artifact only; use the .so as shipped
    if not _LIB_PATH.exists():
        return True
    lib_mtime = _LIB_PATH.stat().st_mtime
    for f in _SOURCES + _HEADERS:
        p = _CSRC_DIR / f
        if p.exists() and p.stat().st_mtime > lib_mtime:
            return True
    return False


def build_if_needed() -> None:
    if not _needs_build():
        return
    _LIB_PATH.parent.mkdir(parents=True, exist_ok=True)
    lock_path = _LIB_PATH.parent / ".build.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if not _needs_build():  # built while we waited on the lock
                return
            cmd = [
                os.environ.get("CXX", "g++"), "-O2", "-std=c++17", "-fPIC",
                "-Wall", "-pthread", "-shared",
            ] + [str(_CSRC_DIR / s) for s in _SOURCES] + [
                "-o", str(_LIB_PATH),
            ]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                raise NativeUnavailable(
                    f"native core build failed:\n{proc.stderr}")
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.hvd_create.argtypes = [
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_int32), c.POINTER(c.c_int32),
        c.c_double, c.c_int64, c.c_double, c.c_double, c.c_int, c.c_int64,
        c.c_int, c.c_int,                          # hierarchical ar/ag
        c.c_int, c.c_int, c.c_int, c.c_int,        # autotune, tune f/c/c
        c.c_int, c.c_int,                          # tune hier ar/ag
        c.c_int, c.c_int, c.c_double,
        c.c_char_p, c.c_char_p, c.c_int,
    ]
    lib.hvd_create.restype = c.c_int
    lib.hvd_cache_stats.argtypes = [c.POINTER(c.c_int64)]
    lib.hvd_cache_stats.restype = None
    lib.hvd_shutdown.argtypes = []
    lib.hvd_shutdown.restype = None
    lib.hvd_is_aborted.restype = c.c_int
    lib.hvd_last_error.restype = c.c_char_p
    lib.hvd_allreduce_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.c_int, c.c_double, c.c_double, c.c_int, c.c_int,
    ]
    lib.hvd_allreduce_async.restype = c.c_int64
    lib.hvd_allgather_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.c_int, c.c_int,
    ]
    lib.hvd_allgather_async.restype = c.c_int64
    lib.hvd_broadcast_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.c_int, c.c_int, c.c_int,
    ]
    lib.hvd_broadcast_async.restype = c.c_int64
    lib.hvd_alltoall_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.POINTER(c.c_int64), c.c_int, c.c_int, c.c_int,
    ]
    lib.hvd_alltoall_async.restype = c.c_int64
    lib.hvd_reducescatter_async.argtypes = [
        c.c_char_p, c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.c_int,
        c.c_int, c.c_int, c.c_int,
    ]
    lib.hvd_reducescatter_async.restype = c.c_int64
    lib.hvd_register_process_set.argtypes = [
        c.c_int, c.POINTER(c.c_int32), c.c_int,
    ]
    lib.hvd_register_process_set.restype = c.c_int64
    lib.hvd_poll.argtypes = [c.c_int64]
    lib.hvd_poll.restype = c.c_int
    lib.hvd_wait.argtypes = [c.c_int64]
    lib.hvd_wait.restype = c.c_int
    lib.hvd_handle_error.argtypes = [c.c_int64]
    lib.hvd_handle_error.restype = c.c_char_p
    lib.hvd_result_nbytes.argtypes = [c.c_int64]
    lib.hvd_result_nbytes.restype = c.c_int64
    lib.hvd_result_data.argtypes = [c.c_int64]
    lib.hvd_result_data.restype = c.c_void_p
    lib.hvd_result_splits.argtypes = [
        c.c_int64, c.POINTER(c.c_int64), c.c_int]
    lib.hvd_result_splits.restype = c.c_int
    lib.hvd_release.argtypes = [c.c_int64]
    lib.hvd_release.restype = None
    lib.hvd_barrier.argtypes = [c.c_int, c.c_int]
    lib.hvd_barrier.restype = c.c_int
    lib.hvd_join.restype = c.c_int
    return lib


def load() -> ctypes.CDLL:
    """Build (if needed) and load the native core; raises NativeUnavailable
    when no toolchain/binary is available so callers can fall back to the
    Python engine."""
    global _lib
    if _lib is not None:
        return _lib
    if os.environ.get("HVD_TPU_CORE", "").lower() in ("py", "python"):
        raise NativeUnavailable("HVD_TPU_CORE forces the Python engine")
    try:
        build_if_needed()
    except (OSError, subprocess.SubprocessError) as e:
        raise NativeUnavailable(f"cannot build native core: {e}")
    if not _LIB_PATH.exists():
        raise NativeUnavailable(f"native core not built: {_LIB_PATH}")
    _lib = _bind(ctypes.CDLL(str(_LIB_PATH)))
    return _lib
