"""Pipeline parallelism over the ``pp`` mesh axis.

The reference framework has **no** pipeline parallelism (SURVEY.md §2.8 —
its only scaling axis is the batch); this module is part of the TPU-native
multi-axis extension promised in ``models/transformer.py``.

Design — GPipe microbatch pipelining, built from the same primitives as the
rest of the stack:

* The transformer stacks layers on a leading axis and iterates them with
  ``lax.scan`` (models/transformer.py) — so pipelining is a *sharding
  decision on that axis*: each ``pp`` stage holds ``n_layers / pp``
  contiguous layers (its "cell").
* The batch is split into M microbatches; a ``lax.scan`` over
  ``M + P - 1`` ticks advances the pipeline.  Every tick each stage
  applies its cell, then activations rotate one stage down the ring via
  ``lax.ppermute`` — the same neighbor-exchange primitive ring attention
  uses.  Stage 0 feeds microbatches in; stage P-1 collects outputs.  The
  classic GPipe bubble is the ``(P-1) / (M+P-1)`` idle fraction.
* Backward is ``jax.grad`` straight through the schedule (GPipe
  semantics: all forwards, then all backwards, with per-cell activation
  rematerialization via ``jax.checkpoint``).  A hand-interleaved 1F1B
  schedule trades peak memory for the same bubble; under XLA the remat
  scan gives most of that back without a second schedule.
* Only ``pp`` is a *manual* axis (``shard_map(axis_names={'pp'})``);
  ``dp``/``tp``/``ep`` stay in GSPMD "auto" mode, so Megatron tensor
  sharding and MoE expert all-to-alls compose with pipelining unchanged.
  (``sp`` ring attention runs its own shard_map and is used in
  non-pipelined steps; inside a pipeline cell attention is GSPMD-dense.)

Numerics: with dense FFN the pipelined forward is exactly the layer scan
re-bracketed, so outputs match the non-pipelined ``tfm.apply`` to float
round-off (the test pins this).  MoE aux-loss and capacity are computed
per *microbatch* when pipelined — the standard semantic shift of
microbatching, documented here rather than hidden.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from horovod_tpu.ops.collective import _one_axis_size
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import filter_spec
from horovod_tpu.parallel.shard import shard_map
from horovod_tpu.parallel.train import _step0


def pipeline_param_specs(cfg: tfm.TransformerConfig):
    """``tfm.param_specs`` with the stacked-layer axis sharded over ``pp``."""
    specs = tfm.param_specs(cfg)

    def reshard(spec: P) -> P:
        return P("pp", *spec[1:])

    specs["layers"] = jax.tree.map(
        reshard, specs["layers"], is_leaf=lambda x: isinstance(x, P))
    return specs


def gpipe(stage_fn, x_mb, *, axis: str = "pp"):
    """Run ``stage_fn`` over microbatches through the ``axis`` ring.

    Call inside a shard_map body where ``axis`` is manual.  ``x_mb`` is
    ``[M, ...]`` microbatched input, present on every stage (only stage
    0's copy is consumed).  ``stage_fn(x) -> (y, aux)`` applies this
    stage's cell.  Returns ``([M, ...] outputs, total_aux)``, both
    replicated across the ``axis`` ring.
    """
    n_stages = _one_axis_size(axis)
    stage = lax.axis_index(axis)
    n_micro = x_mb.shape[0]
    ticks = n_micro + n_stages - 1
    ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        buf, out, aux_sum = carry
        feed = x_mb[jnp.clip(t, 0, n_micro - 1)]
        inp = jnp.where(stage == 0, feed, buf)
        y, aux = stage_fn(inp)
        # Stage P-1 finished microbatch t-(P-1) this tick.
        mb = t - (n_stages - 1)
        write = (stage == n_stages - 1) & (mb >= 0)
        out = jnp.where(write, out.at[jnp.clip(mb, 0, n_micro - 1)].set(y),
                        out)
        # Rotate activations one stage down the ring.  Bubble ticks carry
        # garbage that the feed/write gating above keeps out of results.
        buf = lax.ppermute(y, axis, ring)
        valid = (t >= stage) & (t - stage < n_micro)
        aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
        return (buf, out, aux_sum), None

    # The carry becomes pp-varying after one tick (each stage holds its
    # own activations), so it must *start* varying for scan's type check.
    # jax < 0.5 has no varying-manual-axes typing (no lax.pcast) and no
    # such check: the seed is used as-is there.
    _pcast = getattr(lax, "pcast", lambda a, _axis, to: a)
    carry0 = jax.tree.map(
        lambda a: _pcast(a, axis, to="varying"),
        (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb),
         jnp.zeros((), jnp.float32)))
    (_, out, aux_sum), _ = lax.scan(tick, carry0, jnp.arange(ticks))
    # Results live on the last stage; replicate them ring-wide (masked
    # psum — the same lowering ops.collective.broadcast uses).
    out = lax.psum(jnp.where(stage == n_stages - 1, out,
                             jnp.zeros_like(out)), axis)
    aux = lax.psum(aux_sum, axis)
    return out, aux


def pipeline_apply(params, tokens, cfg: tfm.TransformerConfig, mesh,
                   *, n_microbatches: Optional[int] = None,
                   remat: bool = True):
    """Pipelined forward of the stacked-layer transformer.

    ``params`` laid out per :func:`pipeline_param_specs` (stacked-layer
    axis over ``pp``).  Returns ``(logits_fp32, aux)`` like ``tfm.apply``.
    """
    pp = mesh.shape.get("pp", 1)
    if pp <= 1:
        return tfm.apply(params, tokens, cfg, mesh=mesh, remat=remat)
    if cfg.n_layers % pp:
        raise ValueError(
            f"n_layers={cfg.n_layers} must divide over pp={pp}")
    M = n_microbatches or pp
    B = tokens.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    if cfg.attn_impl == "flash" and mesh.shape.get("dp", 1) > 1:
        # Inside the pipeline body dp stays GSPMD-auto, and a pallas_call
        # cannot be partitioned by GSPMD — use dense attention there.
        import dataclasses

        cfg = dataclasses.replace(cfg, attn_impl="dense")

    layer_fn = tfm._layer
    if remat:
        layer_fn = jax.checkpoint(tfm._layer, static_argnums=(2, 3))

    def body(params, tokens):
        dtype = cfg.compute_dtype
        # Embedding runs replicated on every stage (cheap next to a cell).
        x = params["embed"].astype(dtype)[tokens]
        S, D = x.shape[1], x.shape[2]
        x_mb = x.reshape(M, B // M, S, D)

        def stage_fn(h):
            def layer_body(carry, lp):
                h, aux_sum = carry
                h, aux = layer_fn(h, lp, cfg, None)
                return (h, aux_sum + aux), None

            (h, aux), _ = lax.scan(
                layer_body, (h, jnp.zeros((), jnp.float32)),
                params["layers"])
            return h, aux

        out, aux = gpipe(stage_fn, x_mb, axis="pp")
        # gpipe sums aux over microbatches; the per-microbatch MoE
        # load-balance statistic is scale-free (~the full-batch value), so
        # average to keep the loss independent of the n_microbatches
        # throughput knob.
        aux = aux / M
        x = out.reshape(B, S, D)
        x = tfm._rmsnorm(x, params["ln_f"])
        return tfm.vocab_projection(x, params["embed"]), aux

    specs = pipeline_param_specs(cfg)
    # Only pp placement is named here; dp/tp/ep stay GSPMD-auto.
    pp_only = jax.tree.map(
        lambda s: P(*[ax if ax == "pp" else None for ax in s]),
        specs, is_leaf=lambda x: isinstance(x, P))
    # check_vma stays ON (unlike the full-manual collectives wrapper):
    # partial-manual shard_map only admits unmentioned-axis out_specs when
    # replication over pp is provable, which the masked-psum broadcast at
    # the end of gpipe() establishes.
    sharded = shard_map(
        body, mesh=mesh, axis_names=frozenset({"pp"}),
        in_specs=(pp_only, P()), out_specs=(P(), P()), check_vma=True)
    return sharded(params, tokens)


def pipeline_loss_fn(params, tokens, targets, cfg, mesh,
                     *, n_microbatches=None, aux_weight: float = 0.01):
    logits, aux = pipeline_apply(params, tokens, cfg, mesh,
                                 n_microbatches=n_microbatches)
    return tfm.softmax_xent(logits, targets) + aux_weight * aux


class PipelineTrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def make_pipeline_train_step(
    cfg: tfm.TransformerConfig,
    mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    *,
    n_microbatches: Optional[int] = None,
):
    """Pipelined twin of ``train.make_transformer_train_step``: params are
    born sharded over pp (stacked-layer axis) × tp/ep; the whole GPipe
    schedule jits as one program and autodiff provides the backward
    pipeline."""
    if optimizer is None:
        optimizer = optax.adamw(1e-3, weight_decay=0.01)
    from horovod_tpu.parallel.train import _opt_shardings

    specs = pipeline_param_specs(cfg)
    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, filter_spec(s, mesh)), specs,
        is_leaf=lambda x: isinstance(x, P))
    data_sharding = NamedSharding(mesh, filter_spec(P("dp", None), mesh))

    def init_fn(rng) -> PipelineTrainState:
        params = jax.jit(lambda k: tfm.init(k, cfg),
                         out_shardings=param_shardings)(rng)
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=_opt_shardings(optimizer, params,
                                         param_shardings))(params)
        return PipelineTrainState(params, opt_state,
                                  _step0(mesh))

    def _step(state: PipelineTrainState, tokens, targets):
        loss, grads = jax.value_and_grad(pipeline_loss_fn)(
            state.params, tokens, targets, cfg, mesh,
            n_microbatches=n_microbatches)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return PipelineTrainState(params, opt_state, state.step + 1), loss

    step_fn = jax.jit(
        _step,
        in_shardings=(None, data_sharding, data_sharding),
        donate_argnums=(0,),
    )
    return step_fn, init_fn
