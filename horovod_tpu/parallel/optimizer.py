"""Distributed optimizer / gradient wrappers for JAX (optax).

Parity: ``horovod/tensorflow/__init__.py:266-311`` (_DistributedOptimizer),
``:474-531`` (DistributedGradientTape) and the torch hook-based optimizer
(``torch/__init__.py:127-221``), re-imagined for JAX's functional style:

* ``DistributedOptimizer(inner)`` returns an ``optax.GradientTransformation``
  that all-reduces gradients before applying the inner transformation.
* ``distributed_grad(fun)`` is the DistributedGradientTape analog: the
  returned grad function all-reduces the gradients it produces.

Both work in two regimes:
* **in-graph** (default, TPU path): pass ``axis=`` mesh axis name(s); the
  allreduce lowers to one fused XLA all-reduce inside the jitted step
  (tensor fusion via ``grouped_allreduce`` — one collective per dtype).
* **eager**: ``axis=None`` outside jit uses the process-group engine
  (host-network collectives, the classic Horovod regime).

``backward_passes_per_step`` accumulates gradients locally and reduces only
every Nth step (parity: torch/__init__.py:100-125); in-graph it uses a
counter in the optimizer state with ``lax.cond``-free arithmetic gating so
the program stays trace-stable.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.common.types import ReduceOp
from horovod_tpu.ops import collective as C
from horovod_tpu.ops.compression import Compression


def _allreduce_grads_ingraph(grads, op, axis, compression,
                             hierarchical=False, outer_axis="dcn"):
    # Fuse across leaves: compress first, group by dtype inside
    # grouped_allreduce, decompress after.
    leaves, treedef = jax.tree.flatten(grads)
    comp = [compression.compress(g) for g in leaves]
    reduced = C.grouped_allreduce([c for c, _ in comp], op=op, axis=axis,
                                  hierarchical=hierarchical,
                                  outer_axis=outer_axis)
    out = [compression.decompress(r, ctx)
           for r, (_, ctx) in zip(reduced, comp)]
    return jax.tree.unflatten(treedef, out)


def _allreduce_grads_eager(grads, op, compression):
    from horovod_tpu.ops import eager

    leaves, treedef = jax.tree.flatten(grads)
    if any(eager._is_traced(g) for g in leaves):
        # Inside jit: one host callback enqueues the whole group into
        # the engine (controller fusion on the compiled path) — the
        # bridge regime, ops/bridge.py.
        from horovod_tpu.ops import bridge

        return jax.tree.unflatten(treedef, list(bridge.grouped_allreduce(
            tuple(leaves), name="grad", op=op, compression=compression)))
    handles = []
    for i, g in enumerate(leaves):
        handles.append(eager.allreduce_async(
            g, name=f"grad.{i}", op=op, compression=compression))
    return jax.tree.unflatten(
        treedef, [eager.synchronize(h) for h in handles])


def allreduce_gradients(grads, *, op: ReduceOp = ReduceOp.AVERAGE,
                        axis=("dp",), compression=Compression.none,
                        hierarchical: bool = False,
                        outer_axis: str = "dcn"):
    """All-reduce a pytree of gradients (in-graph when ``axis`` given).

    ``hierarchical=True`` routes the fused buffers through
    RS(ICI)→AR(DCN)→AG(ICI) — requires both the ``axis`` (inner) and a
    ``dcn`` outer axis in the active mesh (the in-graph analog of
    ``HVD_HIERARCHICAL_ALLREDUCE``)."""
    if axis is None:
        if hierarchical:
            raise ValueError(
                "hierarchical=True is an in-graph (mesh-axis) option; "
                "the eager regime's two-level mode is the engine-side "
                "HVD_HIERARCHICAL_ALLREDUCE knob")
        return _allreduce_grads_eager(grads, op, compression)
    return _allreduce_grads_ingraph(grads, op, axis, compression,
                                    hierarchical, outer_axis)


class _AccumState(NamedTuple):
    counter: jnp.ndarray
    acc: Any
    inner: Any


def _is_float(leaf) -> bool:
    return jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)


def _guarded_ingraph(inner, *, op, axis, compression, hierarchical,
                     outer_axis, policy):
    """In-graph non-finite guard: the flag agreement and the gradient
    allreduce both execute unconditionally (XLA collectives cannot be
    data-dependent); the *application* is masked.  With policy ``skip``
    a bad step leaves params and inner state bit-identical; with
    ``zero`` non-finite entries reduce as zeros.  Counters ride the
    optimizer state (integrity.nonfinite.GuardState / stats())."""
    from horovod_tpu.integrity import nonfinite as _nf

    # The flag agreement must span the FULL gradient-reduction set: with
    # hierarchical=True the gradients reduce across the inner axes AND
    # outer_axis (DCN), and a NaN agreed only within one slice would
    # skip the step there while the other slices apply it — silently
    # forking the replicas.
    flag_axes = (axis,) if isinstance(axis, str) else tuple(axis)
    if hierarchical and outer_axis not in flag_axes:
        flag_axes = flag_axes + (outer_axis,)

    def init_fn(params):
        return _nf.GuardState(jnp.zeros((), jnp.int32),
                              jnp.zeros((), jnp.int32),
                              inner.init(params))

    def update_fn(grads, state, params=None, **extra):
        finite = jnp.array(True)
        for leaf in jax.tree.leaves(grads):
            if _is_float(leaf):
                finite = jnp.logical_and(finite,
                                         jnp.all(jnp.isfinite(leaf)))
        flag = jnp.where(finite, 0, 1).astype(jnp.int32)
        bad = C.allreduce(flag, op=ReduceOp.MAX, axis=flag_axes)
        is_bad = bad > 0

        def reduce_and_apply(tree, inner_state):
            reduced = allreduce_gradients(
                tree, op=op, axis=axis, compression=compression,
                hierarchical=hierarchical, outer_axis=outer_axis)
            return inner.update(reduced, inner_state, params, **extra)

        nonfinite_steps = state.nonfinite_steps + bad
        consecutive = jnp.where(is_bad, state.consecutive + 1, 0)

        if policy == "zero":
            safe = jax.tree.map(
                lambda g: jnp.where(jnp.isfinite(g), g, jnp.zeros_like(g))
                if _is_float(g) else g, grads)
            updates, inner_state = reduce_and_apply(safe, state.inner)
            return updates, _nf.GuardState(nonfinite_steps, consecutive,
                                           inner_state)

        # skip: zero the whole tree on a bad step (jnp.where, never a
        # multiply — NaN * 0 is NaN) so the unconditional reduce and
        # inner update stay finite, then discard their results.
        safe = jax.tree.map(
            lambda g: jnp.where(is_bad, jnp.zeros_like(g), g), grads)
        updates, inner_state = reduce_and_apply(safe, state.inner)
        gated = jax.tree.map(
            lambda u: jnp.where(is_bad, jnp.zeros_like(u), u), updates)
        picked = jax.tree.map(
            lambda new, old: jnp.where(is_bad, old, new),
            inner_state, state.inner)
        return gated, _nf.GuardState(nonfinite_steps, consecutive, picked)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(
    inner: optax.GradientTransformation,
    *,
    op: ReduceOp = ReduceOp.AVERAGE,
    axis: Union[str, Sequence[str], None] = ("dp",),
    compression=Compression.none,
    backward_passes_per_step: int = 1,
    hierarchical: bool = False,
    outer_axis: str = "dcn",
    nonfinite_policy: Optional[str] = None,
    nonfinite_guard=None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so updates see globally-reduced gradients.

    ``hierarchical=True`` (in-graph regime only) reduces the fused
    gradient buffers RS(inner/ICI)->AR(outer/DCN)->AG(inner/ICI);
    ``axis`` must name exactly the inner and ``outer_axis`` axes.

    ``nonfinite_policy`` (default: ``HVD_NONFINITE_POLICY``, then
    ``off``) arms the non-finite gradient guard: a 1-element
    MAX-allreduce agrees a per-step any-NaN/Inf flag so every rank
    skips (``skip``), sanitizes (``zero``) or — eager regime only —
    raises on (``raise``) the *same* step.  ``off`` adds zero extra
    collectives.  Pass ``nonfinite_guard`` (a
    :class:`~horovod_tpu.integrity.nonfinite.NonFiniteGuard`) to keep a
    handle on the eager guard's counters.  Composes with
    ``backward_passes_per_step == 1`` only.  The eager guard inspects
    gradients host-side: call the guarded step outside ``jit`` (the
    bridge's traced-leaf path does not compose with a guard; the guard
    raises a clear error on traced leaves).
    """
    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    from horovod_tpu.integrity import nonfinite as _nf

    guard = nonfinite_guard
    policy = guard.policy if guard is not None \
        else _nf.resolve_policy(nonfinite_policy)
    if policy != "off":
        if backward_passes_per_step != 1:
            raise ValueError(
                "the non-finite gradient guard composes with "
                "backward_passes_per_step == 1 only; accumulate at the "
                "data-loader level to combine them")
        if axis is not None:
            if policy == "raise":
                raise ValueError(
                    "nonfinite_policy 'raise' needs host control flow "
                    "and is eager-only (axis=None); in-graph use 'skip' "
                    "and watch integrity.nonfinite_stats(opt_state)")
            if guard is not None:
                raise ValueError(
                    "nonfinite_guard is the eager-regime (axis=None) "
                    "hook; in-graph counters live in the optimizer "
                    "state (integrity.nonfinite_stats)")
        elif guard is None:
            guard = _nf.NonFiniteGuard(policy)

    if backward_passes_per_step == 1:
        if policy != "off" and axis is not None:
            return _guarded_ingraph(
                inner, op=op, axis=axis, compression=compression,
                hierarchical=hierarchical, outer_axis=outer_axis,
                policy=policy)

        def init_fn(params):
            return inner.init(params)

        def update_fn(grads, state, params=None, **extra):
            if guard is not None:
                grads, skip = guard.intercept(grads)
                if skip:
                    return jax.tree.map(jnp.zeros_like, grads), state
            reduced = allreduce_gradients(
                grads, op=op, axis=axis, compression=compression,
                hierarchical=hierarchical, outer_axis=outer_axis)
            return inner.update(reduced, state, params, **extra)

        return optax.GradientTransformation(init_fn, update_fn)

    n = backward_passes_per_step

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return _AccumState(jnp.zeros((), jnp.int32), zeros,
                           inner.init(params))

    def update_fn(grads, state, params=None, **extra):
        counter = state.counter + 1
        acc = jax.tree.map(lambda a, g: a + g, state.acc, grads)
        do_reduce = counter >= n

        def reduce_branch(acc_tree):
            scaled = jax.tree.map(lambda a: a / n, acc_tree)
            return allreduce_gradients(
                scaled, op=op, axis=axis, compression=compression,
                hierarchical=hierarchical, outer_axis=outer_axis)

        if axis is None:
            # Eager regime: python control flow is fine.
            if bool(do_reduce):
                reduced = reduce_branch(acc)
                updates, inner_state = inner.update(
                    reduced, state.inner, params, **extra)
                new_state = _AccumState(
                    jnp.zeros((), jnp.int32),
                    jax.tree.map(jnp.zeros_like, acc), inner_state)
                return updates, new_state
            zero_updates = jax.tree.map(jnp.zeros_like, grads)
            return zero_updates, _AccumState(counter, acc, state.inner)

        # In-graph: both branches must trace; collective ops must execute
        # unconditionally (XLA collectives cannot be data-dependent), so we
        # reduce every step but only *apply* on the Nth — the reduce of a
        # masked accumulator is the price of trace stability.  For real
        # skip-step savings use backward_passes_per_step at the data-loader
        # level or run the eager regime.
        reduced = reduce_branch(acc)
        updates, inner_state = inner.update(
            reduced, state.inner, params, **extra)
        gate = (counter >= n).astype(jnp.float32)
        gated = jax.tree.map(lambda u: u * gate.astype(u.dtype), updates)
        new_counter = jnp.where(do_reduce, 0, counter)
        new_acc = jax.tree.map(
            lambda a: a * (1.0 - gate).astype(a.dtype), acc)
        # Inner optimizer state advances only on apply steps.
        def pick(new, old):
            return jax.tree.map(
                lambda x, y: jnp.where(do_reduce, x, y), new, old)
        return gated, _AccumState(new_counter, new_acc,
                                  pick(inner_state, state.inner))

    return optax.GradientTransformation(init_fn, update_fn)


def distributed_grad(fun, *, op: ReduceOp = ReduceOp.AVERAGE,
                     axis: Union[str, Sequence[str], None] = ("dp",),
                     compression=Compression.none,
                     argnums=0, has_aux: bool = False):
    """DistributedGradientTape analog: grad-of-``fun`` with the gradients
    all-reduced across ``axis`` (parity: tensorflow/__init__.py:474-531)."""
    gfun = jax.grad(fun, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        if has_aux:
            grads, aux = gfun(*args, **kwargs)
            return allreduce_gradients(
                grads, op=op, axis=axis, compression=compression), aux
        grads = gfun(*args, **kwargs)
        return allreduce_gradients(
            grads, op=op, axis=axis, compression=compression)

    return wrapped


def distributed_value_and_grad(fun, *, op: ReduceOp = ReduceOp.AVERAGE,
                               axis: Union[str, Sequence[str], None] = ("dp",),
                               compression=Compression.none,
                               argnums=0, has_aux: bool = False):
    vgfun = jax.value_and_grad(fun, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        val, grads = vgfun(*args, **kwargs)
        return val, allreduce_gradients(
            grads, op=op, axis=axis, compression=compression)

    return wrapped
