"""Multi-host GSPMD bootstrap: ``jax.distributed`` from the launcher.

The eager engine already spans hosts (TCP mesh via the rendezvous); this
module gives the COMPILED regime the same reach: under ``hvdrun``, each
process calls :func:`init_jax_distributed` and its local chips join one
global ``jax.devices()`` view, so ``Mesh``/``pjit`` programs — and every
in-graph collective in ``ops.collective`` — span hosts with XLA inserting
the cross-host transfers (ICI within a slice, DCN across).  Role parity:
the reference's NCCL/MPI backend is what let one training job span
hosts; here that job is a GSPMD program and the launcher supplies the
coordination ``jax.distributed`` needs (coordinator address via the same
HMAC-signed rendezvous KV the engine bootstraps through).

Usage (inside a program launched by ``hvdrun -np N``)::

    import horovod_tpu as hvd
    hvd.init()
    hvd.init_jax_distributed()      # local chips join the global mesh
    # jax.device_count() == chips across ALL hosts from here on

Single-process runs are a no-op, so the same script works under plain
``python``.
"""

from __future__ import annotations

import os
import socket
from typing import Optional

_initialized = False


def init_jax_distributed(timeout: float = 120.0) -> None:
    """Join this process's devices into one global JAX view.

    Must run before the first JAX backend touch in this process (jax
    requires ``distributed.initialize`` pre-backend-init).  Rank 0
    binds a free port for the coordination service and publishes it on
    the launcher's rendezvous KV; other ranks block on that key.
    Idempotent; no-op for single-process jobs or when the launcher env
    is absent.
    """
    global _initialized
    if _initialized:
        return
    # Rank/size come from the initialized runtime, which already ran the
    # full discovery chain (HVD_* env, OMPI/PMIx/Slurm/JSM — so mpirun
    # and srun launches work here too, not just hvdrun's spawn mode).
    from horovod_tpu import basics

    if basics.is_initialized():
        rank, size = basics.rank(), basics.size()
    else:
        rank = int(os.environ.get("HVD_RANK", "0"))
        size = int(os.environ.get("HVD_SIZE", "1"))
    if size <= 1:
        return
    rdv_addr = os.environ.get("HVD_RENDEZVOUS_ADDR")
    rdv_port = os.environ.get("HVD_RENDEZVOUS_PORT")
    if not rdv_addr or not rdv_port:
        raise RuntimeError(
            "init_jax_distributed needs the launcher rendezvous "
            "(HVD_RENDEZVOUS_ADDR/PORT); run under hvdrun or export "
            "them manually")

    from horovod_tpu.runner.http_client import KVClient

    kv = KVClient(rdv_addr, int(rdv_port))
    scope = os.environ.get("HVD_RDV_SCOPE", "")
    key = f"hvd/{scope}/jax_coordinator" if scope else "hvd/jax_coordinator"

    if rank == 0:
        coord = f"{_my_addr(kv)}:{_free_port()}"
        kv.put(key, coord)
    else:
        try:
            coord = kv.wait_get(key, timeout=timeout)
        except TimeoutError as e:
            raise RuntimeError(
                "timed out waiting for the jax.distributed coordinator "
                "address on the rendezvous KV (did rank 0 call "
                "init_jax_distributed?)") from e

    import jax

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=size, process_id=rank,
                               initialization_timeout=int(timeout))
    _initialized = True


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _my_addr(kv) -> str:
    """The address peers can reach this host at — same policy as the
    engine bootstrap (bootstrap.py:58-67): the launcher-probed NIC list
    wins, else learn the address from the route the rendezvous
    connection takes."""
    my_host = None
    nic = os.environ.get("HVD_NIC")
    if nic:
        from horovod_tpu.runner.run import interface_address_any

        try:
            my_host = interface_address_any(nic)
        except ValueError:
            my_host = None  # NIC list from another host; fall back
    return my_host or kv.local_address() or "127.0.0.1"
