"""Sharded training-step builders.

Where the reference bolts distribution onto framework optimizers
(``_DistributedOptimizer`` re-running allreduce per gradient,
``tensorflow/__init__.py:266-311``), the TPU-native shape is: declare
parameter/data shardings over a ``Mesh``, jit the whole step, and let XLA
insert the gradient all-reduces — they come out fused and overlapped with
the backward pass, which is what Horovod's background thread + fusion
buffer worked hard to approximate.

Two regimes are exposed:

* ``make_*_train_step(mesh=...)`` — GSPMD/pjit: params replicated over
  ``dp``/``dcn`` and sharded over ``tp``/``ep`` per the model's
  ``param_specs``; batch sharded over ``dp`` (and ``sp`` for sequences).
  Gradient reduction is implicit.
* the optimizer wrappers in ``horovod_tpu.parallel.optimizer`` — explicit
  Horovod-style allreduce, for code that wants the classic contract.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.models import mnist as mnist_model
from horovod_tpu.models import resnet as resnet_model
from horovod_tpu.models import transformer as tfm
from horovod_tpu.parallel.mesh import filter_spec


def _sharding(mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, filter_spec(spec, mesh))


def _replicated(mesh):
    return NamedSharding(mesh, P())


def _batch_spec(mesh, *axes) -> P:
    """P over whichever of ``axes`` exist in the mesh (rest None)."""
    return filter_spec(P(*axes), mesh)


def _step0(mesh):
    """Mesh-replicated zero step counter.  A plain ``jnp.zeros(())`` is an
    uncommitted single-device array — fine until a checkpoint restore
    commits it, at which point jit rejects the mixed device sets; placing
    it on the mesh up front keeps init and restored states identical."""
    return jax.device_put(jnp.zeros((), jnp.int32), _replicated(mesh))


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


# ---------------------------------------------------------------------------
# Transformer (flagship: dp × tp × sp × ep)
# ---------------------------------------------------------------------------


def make_transformer_train_step(
    cfg: tfm.TransformerConfig,
    mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    *,
    zero1: bool = False,
):
    """Returns ``(step_fn, init_fn)``.

    ``init_fn(rng) -> TrainState`` places params with tp/ep shardings;
    ``step_fn(state, tokens, targets) -> (state, loss)`` is jit-compiled
    over the mesh.  Batch layout: tokens/targets ``[B, S]`` sharded
    ``P('dp', 'sp')``.

    ``zero1=True`` additionally shards the optimizer state over the
    ``dp`` axis (ZeRO stage 1, GSPMD-style: the moments' shardings get
    ``dp`` on their first free dimension and XLA turns the gradient
    sync into reduce-scatter + sharded update + allgather instead of
    allreduce + replicated update — same math, 1/dp the adam-moment
    memory per chip).  The reference has no optimizer-state sharding
    (DP replicates everything); this is TPU-native headroom for large
    models.
    """
    if optimizer is None:
        optimizer = optax.adamw(1e-3, weight_decay=0.01)
    specs = tfm.param_specs(cfg)
    param_shardings = jax.tree.map(
        lambda s: _sharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))
    data_sharding = NamedSharding(mesh, _batch_spec(mesh, "dp", "sp"))
    zero_axis = "dp" if zero1 and mesh.shape.get("dp", 1) > 1 else None
    abstract_params = jax.eval_shape(
        lambda: tfm.init(jax.random.PRNGKey(0), cfg))
    opt_shardings = _opt_shardings(optimizer, abstract_params,
                                   param_shardings, zero_axis=zero_axis)
    if zero1:
        # The degradation cases must be loud: asking for ZeRO-1 and
        # getting replicated state is a silent 0x memory saving.
        from horovod_tpu.utils.logging import get_logger

        if zero_axis is None:
            get_logger().warning(
                "zero1=True but the mesh has no dp axis > 1; optimizer "
                "state stays replicated")
        else:
            n_sharded = sum(
                zero_axis in (s.spec or ())
                for s in jax.tree.leaves(
                    opt_shardings,
                    is_leaf=lambda x: isinstance(x, NamedSharding)))
            if n_sharded == 0:
                get_logger().warning(
                    "zero1=True but no optimizer-state dimension is "
                    "divisible by dp=%d; state stays replicated",
                    mesh.shape["dp"])

    def init_fn(rng) -> TrainState:
        # Params are born sharded: jit-with-out_shardings means no device
        # ever holds the full unsharded model (tp/ep exist because it
        # wouldn't fit).
        params = jax.jit(
            lambda k: tfm.init(k, cfg),
            out_shardings=param_shardings)(rng)
        opt_state = jax.jit(
            optimizer.init, out_shardings=opt_shardings)(params)
        return TrainState(params, opt_state, _step0(mesh))

    def _step(state: TrainState, tokens, targets):
        loss, grads = jax.value_and_grad(tfm.loss_fn)(
            state.params, tokens, targets, cfg, mesh=mesh)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    if zero_axis is not None:
        # Pin the ZeRO placement through the step so the sharded
        # moments never silently collapse back to replicated (XLA's
        # propagation would otherwise be free to choose).
        rep = NamedSharding(mesh, P())
        state_shardings = TrainState(param_shardings, opt_shardings, rep)
        step_fn = jax.jit(
            _step,
            in_shardings=(state_shardings, data_sharding, data_sharding),
            out_shardings=(state_shardings, rep),
            donate_argnums=(0,),
        )
    else:
        step_fn = jax.jit(
            _step,
            in_shardings=(None, data_sharding, data_sharding),
            donate_argnums=(0,),
        )
    return step_fn, init_fn


def _zero1_augment(sharding, shape, axis):
    """Put ``axis`` on the first free, divisible dimension of a
    param-mirroring leaf's sharding (ZeRO-1: shard the moments over
    data-parallel replicas).  Leaves with no eligible dimension keep the
    param's sharding (replicated over ``axis``)."""
    mesh = sharding.mesh
    n = mesh.shape[axis]
    spec = list(sharding.spec) + [None] * (len(shape) - len(sharding.spec))
    for i, (ax, dim) in enumerate(zip(spec, shape)):
        if ax is None and dim % n == 0 and dim >= n:
            spec[i] = axis
            return NamedSharding(mesh, P(*spec))
    return sharding


def _opt_shardings(optimizer, params, param_shardings, zero_axis=None):
    """Optimizer-state shardings: state leaves that mirror a param (adam
    moments — their tree path ends with the param's path and the shape
    matches) get that param's sharding; everything else is replicated.
    Path-suffix matching is exact per position, so two params with equal
    shapes but different specs can't collide.  ``zero_axis`` additionally
    shards the param-mirroring leaves over that mesh axis (ZeRO-1)."""
    from jax.tree_util import keystr, tree_flatten_with_path

    shapes = jax.eval_shape(optimizer.init, params)
    param_paths = tree_flatten_with_path(params)[0]
    flat_shard = jax.tree.flatten(param_shardings)[0]
    suffixes = [(keystr(path), leaf.shape, s)
                for (path, leaf), s in zip(param_paths, flat_shard)]
    mesh_rep = flat_shard[0].mesh if flat_shard else None

    def pick(path, leaf):
        ps = keystr(path)
        for suf, shape, s in suffixes:
            if ps.endswith(suf) and leaf.shape == shape:
                if zero_axis is not None:
                    return _zero1_augment(s, shape, zero_axis)
                return s
        return NamedSharding(mesh_rep, P())

    return jax.tree_util.tree_map_with_path(pick, shapes)


# ---------------------------------------------------------------------------
# ResNet / MNIST (pure data parallel over dp [+ dcn])
# ---------------------------------------------------------------------------


class ResNetState(NamedTuple):
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray


def make_resnet_train_step(
    cfg: resnet_model.ResNetConfig,
    mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
):
    """Data-parallel ResNet step: params replicated, batch over dp (+dcn).

    BN statistics are cross-replica-averaged like the reference's
    examples do with ``hvd.allreduce`` on metrics — here it's a psum XLA
    inserts from the replicated out-sharding of ``batch_stats``.
    """
    if optimizer is None:
        optimizer = optax.sgd(0.1, momentum=0.9)
    rep = _replicated(mesh)
    data_sharding = NamedSharding(mesh, _batch_spec(mesh, "dp"))

    def init_fn(rng) -> ResNetState:
        params, stats = resnet_model.init(rng, cfg)
        params = jax.device_put(params, rep)
        stats = jax.device_put(stats, rep)
        opt_state = jax.device_put(optimizer.init(params), rep)
        return ResNetState(params, stats, opt_state,
                           _step0(mesh))

    def _step(state: ResNetState, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            resnet_model.loss_fn, has_aux=True)(
                state.params, state.batch_stats, images, labels, cfg)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return ResNetState(params, new_stats, opt_state,
                           state.step + 1), loss

    step_fn = jax.jit(
        _step,
        in_shardings=(None, data_sharding, data_sharding),
        donate_argnums=(0,),
    )
    return step_fn, init_fn


def make_resnet_train_step_hvd(
    cfg: resnet_model.ResNetConfig,
    mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    *,
    axis=("dp",),
):
    """Classic-Horovod-contract ResNet step: the whole step runs inside
    ``shard_map`` and gradient reduction is an *explicit*
    ``grouped_allreduce`` (via ``DistributedOptimizer``), not a sharding
    XLA infers — the analog of the reference benchmark always training
    through ``hvd.DistributedOptimizer``
    (examples/tensorflow2_synthetic_benchmark.py:119-130).

    Pass ``optimizer`` already wrapped in
    :func:`horovod_tpu.parallel.optimizer.DistributedOptimizer` (with
    matching ``axis``) to control op/compression; a default SGD wrapper is
    built otherwise.  BN statistics and the reported loss are
    cross-replica averaged.
    """
    from horovod_tpu.ops import collective as C
    from horovod_tpu.parallel import optimizer as opt_mod
    from horovod_tpu.parallel.shard import shard_map

    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    axes = tuple(a for a in axes if a in mesh.axis_names)
    if optimizer is None:
        optimizer = opt_mod.DistributedOptimizer(
            optax.sgd(0.1, momentum=0.9), axis=axes)
    rep = _replicated(mesh)
    # All data-parallel axes gang up on dim 0 (batch).  P(*axes) would
    # instead spread them across dims — sharding image height over the
    # second axis (caught by the hier-ici-dcn dryrun mesh).
    batch_p = filter_spec(P(axes), mesh) if axes else P()

    def init_fn(rng) -> ResNetState:
        params, stats = resnet_model.init(rng, cfg)
        params = jax.device_put(params, rep)
        stats = jax.device_put(stats, rep)
        opt_state = jax.device_put(optimizer.init(params), rep)
        return ResNetState(params, stats, opt_state,
                           _step0(mesh))

    def body(state: ResNetState, images, labels):
        (loss, new_stats), grads = jax.value_and_grad(
            resnet_model.loss_fn, has_aux=True)(
                state.params, state.batch_stats, images, labels, cfg)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        if axes:
            new_stats = jax.tree.map(
                lambda s: C.allreduce(s, axis=axes), new_stats)
            loss = C.allreduce(loss, axis=axes)
        return ResNetState(params, new_stats, opt_state,
                           state.step + 1), loss

    sharded = shard_map(
        body, mesh,
        in_specs=(P(), batch_p, batch_p),
        out_specs=(P(), P()),
    )
    step_fn = jax.jit(sharded, donate_argnums=(0,))
    return step_fn, init_fn


def make_mnist_train_step(mesh, optimizer=None):
    if optimizer is None:
        optimizer = optax.adam(1e-3)
    rep = _replicated(mesh)
    data_sharding = NamedSharding(mesh, _batch_spec(mesh, "dp"))

    def init_fn(rng) -> TrainState:
        params = jax.device_put(mnist_model.init(rng), rep)
        opt_state = jax.device_put(optimizer.init(params), rep)
        return TrainState(params, opt_state, _step0(mesh))

    def _step(state: TrainState, images, labels):
        loss, grads = jax.value_and_grad(mnist_model.loss_fn)(
            state.params, images, labels)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    step_fn = jax.jit(
        _step,
        in_shardings=(None, data_sharding, data_sharding),
        donate_argnums=(0,),
    )
    return step_fn, init_fn
