"""Version-portable ``shard_map`` wrapper.

JAX moved ``shard_map`` from ``jax.experimental`` to ``jax.shard_map`` and
added varying-manual-axes (VMA) replication checking; collective-heavy
bodies (all_gather outputs consumed as replicated) frequently defeat the
static inference, so we default ``check_vma=False`` — the collectives in
``horovod_tpu.ops.collective`` define their own replication semantics.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax


def _resolve():
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn  # type: ignore
    return fn


_SHARD_MAP = _resolve()
_PARAMS = set(inspect.signature(_SHARD_MAP).parameters)


def shard_map(f, mesh, in_specs, out_specs, **kwargs: Any):
    """``shard_map(f, mesh, in_specs, out_specs)`` with VMA checking off
    unless explicitly requested.  Accepts the current keyword surface on
    every supported jax: ``check_vma`` maps to the older ``check_rep``,
    and partial-manual ``axis_names`` maps to the pre-0.5 ``auto``
    complement (the axes left automatic)."""
    check = kwargs.pop("check_vma", kwargs.pop("check_rep", False))
    if "check_vma" in _PARAMS:
        kwargs["check_vma"] = check
    elif "check_rep" in _PARAMS:
        kwargs["check_rep"] = check
    manual = kwargs.pop("axis_names", None)
    jit_wrap = False
    if manual is not None:
        if "axis_names" in _PARAMS:
            kwargs["axis_names"] = frozenset(manual)
        elif "auto" in _PARAMS:
            kwargs["auto"] = \
                frozenset(mesh.axis_names) - frozenset(manual)
            # pre-0.5 partial-auto only exists on the jit lowering path
            # (the eager impl and the replication checker both raise
            # NotImplementedError for it)
            kwargs["check_rep"] = False
            jit_wrap = bool(kwargs["auto"])
    mapped = _SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, **kwargs)
    return jax.jit(mapped) if jit_wrap else mapped
