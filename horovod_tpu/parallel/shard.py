"""Version-portable ``shard_map`` wrapper.

JAX moved ``shard_map`` from ``jax.experimental`` to ``jax.shard_map`` and
added varying-manual-axes (VMA) replication checking; collective-heavy
bodies (all_gather outputs consumed as replicated) frequently defeat the
static inference, so we default ``check_vma=False`` — the collectives in
``horovod_tpu.ops.collective`` define their own replication semantics.
"""

from __future__ import annotations

import inspect
from typing import Any

import jax


def _resolve():
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn  # type: ignore
    return fn


_SHARD_MAP = _resolve()
_PARAMS = set(inspect.signature(_SHARD_MAP).parameters)


def shard_map(f, mesh, in_specs, out_specs, **kwargs: Any):
    """``shard_map(f, mesh, in_specs, out_specs)`` with VMA checking off
    unless explicitly requested."""
    if "check_vma" in _PARAMS:
        kwargs.setdefault("check_vma", False)
    elif "check_rep" in _PARAMS:
        kwargs.setdefault("check_rep", False)
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
