"""Sequence parallelism: ring attention and Ulysses head-exchange.

The reference has no sequence/context parallelism (SURVEY.md §5 long-context
row: absent; scaling axis is the batch).  A complete TPU framework needs
long-context support as a first-class citizen, and the ICI torus is built
for it:

* **Ring attention** (`ring_attention`): K/V blocks rotate around the ``sp``
  ring via ``lax.ppermute`` (one ICI-neighbor hop per step); each hop's
  local attention runs the Pallas flash kernel
  (``ops.pallas_attention.flash_attention_lse`` — MXU-tiled, O(block)
  score memory) and hops compose exactly through logsumexp weights, fp32.
  Communication is overlapped by XLA: the next block transfers while the
  current one is being used — the TPU-native equivalent of what the
  reference's background thread + streams did for allreduce overlap.
* **Ulysses** (`ulysses_attention`): one ``all_to_all`` turns
  sequence-sharding into head-sharding, full attention runs locally per
  head group, a second ``all_to_all`` restores sequence-sharding.  Cheaper
  for moderate sequence lengths; requires ``heads % sp_size == 0``.

Both are written for use inside ``shard_map`` bodies (axis names, like
``horovod_tpu.ops.collective``); ``make_sharded_attention`` wraps one in
``shard_map`` over a mesh for direct use.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.collective import _one_axis_size
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.shard import shard_map


def _combine_partials(o1, lse1, o2, lse2):
    """Exactly merge two partial attentions over disjoint key sets.

    ``o_i`` are normalized partial outputs [B, S, H, D]; ``lse_i`` their
    per-query logsumexps [B, S, H] (``-inf`` marks an empty/skipped key
    set).  Standard logsumexp composition, fp32."""
    m = jnp.maximum(lse1, lse2)
    # Guard the fully-masked query rows (both -inf): weights become 0/0
    # otherwise; such rows keep -inf lse and a zero output.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.exp(lse1 - m_safe)
    w2 = jnp.exp(lse2 - m_safe)
    tot = w1 + w2
    norm = jnp.where(tot > 0.0, tot, 1.0)
    o = (o1.astype(jnp.float32) * (w1 / norm)[..., None]
         + o2.astype(jnp.float32) * (w2 / norm)[..., None])
    lse = m + jnp.log(norm)
    return o, lse


def ring_attention(q, k, v, axis: str = "sp", causal: bool = True):
    """Blockwise ring attention over the ``axis`` ring (inside shard_map).

    q/k/v: [B, S_local, H, D] — the local sequence shard.  Returns the
    attention output [B, S_local, H, D] in q's dtype.

    Each hop's local block runs the Pallas flash kernel
    (``ops.pallas_attention.flash_attention_lse`` — MXU-tiled, O(block)
    score memory) and hops compose exactly via logsumexp weights
    (:func:`_combine_partials`); K/V rotate one ICI neighbor per step
    via ``lax.ppermute``, which XLA overlaps with the current hop's
    compute.  The result is exact — identical to full attention on the
    gathered sequence up to fp accumulation order.  This is the
    ring-flash composition: the kernel's (o, lse) pair is the per-hop
    partial, the ring is the reduction tree.
    """
    from horovod_tpu.ops.pallas_attention import flash_attention_lse

    n = _one_axis_size(axis)
    my = lax.axis_index(axis)
    B, S, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]  # send to next neighbor

    # Step 0 is the self-block (no hop): causal triangle when causal.
    # Partials are fp32 end-to-end (the kernel emits fp32, the combine
    # runs fp32), so no per-hop rounding enters the composition.
    o, lse = flash_attention_lse(q, k, v, causal=causal, scale=scale)

    def body(step, carry):
        k_cur, v_cur, o, lse = carry
        k_cur = lax.ppermute(k_cur, axis, perm)
        v_cur = lax.ppermute(v_cur, axis, perm)
        # After `step` hops we hold the block of rank (my - step) mod n.
        owner = (my - step) % n
        o_hop, lse_hop = flash_attention_lse(q, k_cur, v_cur,
                                             causal=False, scale=scale)
        if causal:
            # owner > my holds future tokens: the hop contributes
            # nothing (lse -inf zeroes its combination weight).
            lse_hop = jnp.where(owner < my, lse_hop, -jnp.inf)
        o, lse = _combine_partials(o, lse, o_hop, lse_hop)
        return k_cur, v_cur, o, lse

    _, _, o, lse = lax.fori_loop(1, n, body, (k, v, o, lse))
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, axis: str = "sp", causal: bool = True):
    """Ulysses sequence parallelism: all-to-all head exchange (inside
    shard_map).  q/k/v: [B, S_local, H, D] with H divisible by the axis
    size; returns [B, S_local, H, D]."""
    n = _one_axis_size(axis)
    B, S, H, D = q.shape
    if H % n != 0:
        raise ValueError(f"heads {H} not divisible by axis size {n}")

    def seq_to_heads(x):
        # [B, S_local, H, D] -> [B, S_global, H/n, D]
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    Sg = qg.shape[1]
    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((Sg, Sg), jnp.bool_))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(vg.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vg)
    return heads_to_seq(out)


def full_attention(q, k, v, causal: bool = True):
    """Single-device reference attention (the oracle for tests)."""
    B, S, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def make_sharded_attention(mesh, impl: str = "ring", axis: str = "sp",
                           causal: bool = True,
                           head_axis: Optional[str] = None):
    """Wrap ring/ulysses attention in shard_map over ``mesh``.

    Returns ``fn(q, k, v) -> out`` taking/returning global [B, S, H, D]
    arrays sequence-sharded over ``axis``, batch over ``dp`` when the mesh
    has it, and heads over ``head_axis`` when given (tensor parallelism
    composed with sequence parallelism).
    """
    fns = {"ring": ring_attention, "ulysses": ulysses_attention}
    if impl not in fns:
        raise ValueError(f"impl must be one of {sorted(fns)}")
    if head_axis is not None and head_axis not in mesh.shape:
        head_axis = None
    inner = functools.partial(fns[impl], axis=axis, causal=causal)
    batch_ax = "dp" if "dp" in mesh.shape else None
    spec = P(batch_ax, axis, head_axis, None)

    def fn(q, k, v):
        return shard_map(inner, mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)

    return fn
